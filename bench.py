"""Throughput benchmark suite — the round's real-TPU evidence, in one run.

Driver contract: ``python bench.py`` prints a JSON line
``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}``.

On a live TPU the default run is a PHASED SUITE, each phase in its own
subprocess (one chip claim at a time; a wedged phase is killed without
taking the parent down):

  1. train-tiny       — headline: donated train step, ProGen-tiny (README
                        example config, BASELINE.md config 1), bf16,
                        reference recipe 4x4. tokens/sec/chip + MFU.
                        The headline JSON line is printed (and flushed) the
                        moment this phase finishes — insurance against a
                        later phase wedging the relay.
  2. kernel-w256/512  — Pallas local-attention kernel vs the XLA path,
                        fwd+bwd, Mosaic-compiled (VERDICT round-2 item 2),
                        including on-chip max-abs-error vs the golden.
  3. train-tiny-pallas— the flagship with use_pallas_attn + scan_layers
                        (one scanned body = few Mosaic instances; the
                        unrolled stack's 12+ separate remote kernel
                        compiles blew a 720s timeout in round 3). Its
                        controlled comparison is train-tiny-scan, the XLA
                        twin with the same layer structure — train-tiny
                        (phase 1) differs in two variables.
  4. train-long8k[-xla]— long-context config (8192/512, remat+scan),
                        Pallas per its TOML vs forced-XLA, side by side.
  5. train-default / train-base — remaining BASELINE.md configs.
  6. large-projection — ProGen-large (1.2B) HBM/flops sharding study
                        (single chip can't hold 1.2B x 16B/param; the
                        study reports the v5e-64 plan instead), no chip.

Every phase result is appended to BENCH_DETAIL.json as it lands. At the
end one FINAL line (same headline metric/value + per-phase summary) is
printed — drivers that parse the last line get the rich record, drivers
that parse the first still get the headline.

vs_baseline: the reference publishes no numbers (BASELINE.md), so the
denominator is this repo's own newest prior-round TPU record when present,
else 1.0 (the value itself establishes the baseline).

MFU: profiling.flops_per_token (PaLM convention, SGU spatial mix charged
by actual per-token work) / per-device peak (v5e 197 TFLOP/s bf16).

Off-TPU (dead relay / CPU host): a tiny functional smoke with a DISTINCT
metric name, so a fallback number can never pollute the TPU baseline
chain. A dead axon relay makes backend init HANG — hence the timed
subprocess probe before anything touches jax.devices().

Extra CLIs:
  python bench.py kernel           — kernel phases only, one line.
  python bench.py --config base    — one train phase in-process, one line.
"""

from __future__ import annotations

import glob
import json
import os
import subprocess
import sys
import time
from pathlib import Path

import numpy as np

_REPO = Path(__file__).resolve().parent
_DETAIL_PATH = _REPO / "BENCH_DETAIL.json"
_LOG_DIR = _REPO / "runs" / "bench_logs"


_WATCHDOG = None  # phase-child stall watchdog; beaten by _mark

# phase-child goodput ledger: the _phase entry point owns one per run and
# benches credit compile/step/checkpoint time through _account; the phase
# result then carries a ``goodput`` report (and a ``goodput`` event lands
# in the phase child's events.jsonl) so a slow bench is attributable —
# compile-bound vs step-bound vs checkpoint-bound — straight from the JSON
_PHASE_LEDGER = None


def _account(bucket: str, seconds) -> None:
    if _PHASE_LEDGER is not None and seconds is not None:
        _PHASE_LEDGER.account(bucket, float(seconds))


def _mark(msg: str) -> None:
    """Progress marker on stderr (streamed to the phase log by the
    orchestrator): when a phase is timeout-killed, the trail shows how far
    it got — init, compile, or iteration N. Doubles as the stall-watchdog
    heartbeat in phase children, so "marks stopped" is exactly the
    condition that triggers a stack dump."""
    print(f"[bench-mark +{time.perf_counter() - _T0:.1f}s] {msg}",
          file=sys.stderr, flush=True)
    if _WATCHDOG is not None:
        _WATCHDOG.beat()


_T0 = time.perf_counter()


def _value_fence(out) -> None:
    """Force every leaf of ``out`` to finish executing by READING a value
    back to the host. ``jax.block_until_ready`` is not a reliable fence
    over the axon relay — in round 3 it returned after dispatch-ack,
    timing dispatch rate instead of compute (implied device FLOP/s ~9x a
    v5e's physical peak). A host read cannot complete before the device
    work it depends on, whatever the transport. Scalars are fetched
    directly; arrays are reduced on device first so only 4 bytes move."""
    import jax
    import jax.numpy as jnp

    total = None
    for leaf in jax.tree_util.tree_leaves(out):
        s = (
            leaf.astype(jnp.float32)
            if getattr(leaf, "ndim", 0) == 0
            else jnp.sum(leaf.astype(jnp.float32))
        )
        total = s if total is None else total + s
    if total is not None:
        float(total)  # ONE host round-trip for the whole tree


def _hbm_stats() -> dict:
    """Per-device memory stats where the backend exposes them (TPU does;
    CPU returns nothing) — peak HBM in use is the per-config memory
    evidence next to each throughput row. Reads through the shared
    telemetry gauge helper; output keys stay the legacy bench-schema
    names that ADVICE/VERDICT parsers grep for."""
    from progen_tpu.telemetry import hbm_gauges

    g = hbm_gauges()
    out = {}
    if "hbm/peak_gb" in g:
        out["peak_hbm_gb"] = round(g["hbm/peak_gb"], 2)
    if "hbm/limit_gb" in g:
        out["hbm_limit_gb"] = round(g["hbm/limit_gb"], 2)
    return out


def _suspect_fields(flops: float, seconds: float, peak: float) -> dict:
    """Honesty-guard fields for ANY timed phase: implied device FLOP/s and
    a flag when it exceeds physical peak — a number past peak means the
    measurement (not the chip) is broken and must not be read as real."""
    implied = flops / max(seconds, 1e-12)
    return {
        "implied_device_tflops": round(implied / 1e12, 1),
        "timing_suspect": bool(implied > 1.1 * peak),
    }

# (name, timeout_sec) in execution order; budget cuts from the tail.
# Ordering is wedge-risk-driven: both round-3 relay deaths were caused by
# the timeout-kill of a phase subprocess (decode-tiny in run a,
# train-tiny-pallas in run b), and everything AFTER the wedge was lost.
# So: headline first, then the phases that have already proven fast and
# safe, then all remaining XLA-only phases, and the Pallas-in-train-step
# phases (slow whole-program Mosaic+XLA compiles, the current kill risk)
# at the very end alongside decode-tiny.
_PHASES = (
    # headline FIRST: nothing may run before it whose timeout-kill could
    # wedge the relay and cost the round's one number
    ("train-tiny", 720),
    ("calib-matmul", 300),  # fence calibration: known-FLOPs matmul chain
    ("train-tiny-bs32", 420),  # ceiling companion: bs=32, no accum
    ("train-tiny-scan", 720),  # XLA twin of train-tiny-pallas's structure
    ("kernel-w256", 420),
    ("kernel-w512", 420),
    # long8k-shape kernel row (w=512 n=8192 bh=16): runs BEFORE the long8k
    # train phases so their policy lookup is backed by a measurement at the
    # shape they actually run, writing ops/pallas_policy.json on a clean run
    ("kernel-w512-n8192", 600),
    # fused layer kernels (standalone Mosaic compiles like kernel-w*,
    # not the slow whole-program train-step embedding): writes the
    # layer_entries policy rows the fused-flag train runs read
    ("kernel-fused-w256", 420),
    ("kernel-fused-w512", 420),
    ("train-default", 600),
    ("train-base", 720),
    ("train-long8k-xla", 1080),
    ("sgu-mix", 420),
    ("train-long8k", 1500),
    ("train-tiny-pallas", 1500),
    ("decode-tiny", 600),
    # serving engine under staggered arrivals (steady-state tokens/s +
    # TTFT); two jits only, shapes shared with decode-tiny's policy
    ("decode-serve", 600),
    # admission stall under mixed traffic: decode ITL p99 while a long
    # prompt admits, monolithic vs chunked, plus the prefix-cache TTFT
    # speedup — the two gated serving ratios (bench.py gate --metric
    # serve_admit_stall_ratio / serve_prefix_cache_speedup)
    ("decode-admit-stall", 600),
    # framed-TCP loopback vs unix socket on real serve subprocesses
    # (pinned to CPU: host-side transport parity, no chip claim) — the
    # gated serve_transport_parity ratio
    ("transport-overhead", 600),
    # armed vs disarmed flight recorder on real serve subprocesses
    # (pinned to CPU: host-side forensics parity, no chip claim) — the
    # gated flight_overhead_ratio; the always-on black box must stay
    # within ~1% of free
    ("flight-overhead", 600),
    # int8 weight-quantized decode vs fp on the same params (quant
    # compile cost rides the engine build; two decode jits total)
    ("decode-int8", 600),
    # protein-design workloads: bulk scoring throughput (bucketed
    # compile-once score_step) and the vmapped L x 20 mutant scan
    ("batch-score", 600),
    ("mutagenesis", 600),
    # sustained base run: 100+ steps + async ckpt + exactness-checked
    # restore (the production-claim proxy); long, so late in the order
    ("sustain-base", 1200),
    ("profile-tiny", 420),  # artifact-only; last, fully expendable
)

# per-config bench recipes: (grad_accum, micro_batch, iters)
_RECIPES = {
    "tiny": (4, 4, 10),      # reference train recipe, train.py:38-43
    "default": (4, 4, 10),
    "base": (2, 4, 6),
    "long8k": (1, 2, 5),
    "smoke": (2, 2, 3),      # CPU-fallback functional smoke
}


def _probe_platform(timeout: float = 180.0) -> str | None:
    """Probe backend init in a SUBPROCESS and report its platform: a dead
    axon relay makes jax.devices() hang (not raise), which would swallow
    the whole bench. Returns "tpu"/"cpu"/... on success, None on a dead or
    erroring backend. One probe serves both liveness and platform (the
    probe process releases any chip claim on exit)."""
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            timeout=timeout,
            capture_output=True,
            text=True,
        )
    except subprocess.TimeoutExpired:
        return None
    return r.stdout.strip() if r.returncode == 0 else None


def _tpu_probe_ok(timeout: float = 180.0) -> bool:
    return _probe_platform(timeout) is not None


# the axon relay's PJRT client is libtpu underneath and should report
# "tpu"; accept the registration name too in case the plugin surfaces it
_TPU_PLATFORMS = ("tpu", "axon")


def _is_tpu_platform(platform: str | None) -> bool:
    return platform in _TPU_PLATFORMS


def _force_cpu():
    import jax
    import jax._src.xla_bridge as xb

    jax.config.update("jax_platforms", "cpu")
    xb._backend_factories.pop("axon", None)


def _device_or_cpu_fallback():
    """jax.devices() with a CPU fallback when the TPU backend is
    unreachable; the 'platform' key in the emitted JSON distinguishes the
    outcomes."""
    import jax

    if not _tpu_probe_ok():
        _force_cpu()
        return jax.devices()
    try:
        return jax.devices()
    except RuntimeError:
        jax.config.update("jax_platforms", "cpu")
        return jax.devices()


def _prior_round_value() -> float | None:
    best = None
    for path in sorted(glob.glob(str(_REPO / "BENCH_r*.json"))):
        try:
            rec = json.loads(open(path).read())
        except (OSError, json.JSONDecodeError):
            continue
        parsed = rec.get("parsed") if isinstance(rec, dict) else None
        if not isinstance(parsed, dict):
            continue
        if (
            parsed.get("metric", "").startswith("train_tokens")
            and parsed.get("platform", "tpu") == "tpu"
        ):
            best = parsed.get("value", best)
        elif isinstance(parsed.get("last_tpu_record"), dict):
            # a dead-relay round: its fallback record carries the newest
            # archived honest TPU headline, keeping the vs_baseline chain
            # unbroken across rounds without a live chip
            best = parsed["last_tpu_record"].get("value", best)
    return best


# "smoke" pseudo-config: functional check at CPU-feasible shapes (the full
# tiny config is minutes/step on a 1-core fallback host)
_SMOKE_CONFIG = dict(
    num_tokens=256, dim=64, depth=2, heads=2, dim_head=32, window_size=32,
    seq_len=128, global_mlp_depth=1, ff_mult=2, dtype="float32",
)


def _load_config(name: str, **overrides):
    from progen_tpu.config import ProGenConfig, load_toml_config

    if name == "smoke":
        kwargs = dict(_SMOKE_CONFIG)
    else:
        toml = _REPO / "configs" / "model" / f"{name}.toml"
        kwargs = load_toml_config(str(toml))
    kwargs.update(overrides)
    kwargs.setdefault("dtype", "bfloat16")
    return ProGenConfig.from_dict(kwargs)


# --------------------------------------------------------------------------
# phases (each runs in its own process via `bench.py _phase <name>`)
# --------------------------------------------------------------------------


def _train_bench(config_name: str, *, use_pallas=None, recipe=None,
                 phase_suffix: str = "", profile_dir: str | None = None,
                 extra_overrides: dict | None = None) -> dict:
    """One measured train-step benchmark for a named config. Returns the
    result dict (also JSON-printed by the _phase entry point). ``recipe``
    overrides the (grad_accum, micro_batch, iters) table — used by the
    ceiling phases that lift the reference-parity batch. ``profile_dir``
    wraps the timed loop in a jax.profiler trace (the profile phase)."""
    import contextlib

    import jax

    from progen_tpu import profiling
    from progen_tpu.models.progen import ProGen
    from progen_tpu.parallel.partition import make_mesh, put_batch
    from progen_tpu.training.optimizer import make_optimizer
    from progen_tpu.training.step import compile_train_step, init_train_state

    overrides = dict(extra_overrides or {})
    if use_pallas is not None:
        overrides["use_pallas_attn"] = use_pallas
    config = _load_config(config_name, **overrides)
    grad_accum, micro_bs, n_iters = recipe or _RECIPES[config_name]

    n_chips = len(jax.devices())
    _mark(f"devices ok: {n_chips} chip(s)")
    micro_bs *= n_chips
    mesh = make_mesh()
    model = ProGen(config)
    optimizer = make_optimizer()
    state, shardings = init_train_state(
        model, optimizer, jax.random.PRNGKey(0), config.seq_len, mesh=mesh
    )
    _mark("train state initialized")
    step = compile_train_step(model, optimizer, state, shardings, mesh)

    rng = np.random.default_rng(0)
    batch = rng.integers(
        1, config.num_tokens, size=(grad_accum, micro_bs, config.seq_len + 1)
    ).astype(np.int32)

    with mesh:
        device_batch = put_batch(batch, mesh, accum_axis=True)
        _mark("batch on device; compiling train step")
        t0 = time.perf_counter()
        # AOT-compile ONCE and run the same executable for warmup, timing,
        # and cost_analysis — .lower().compile() does NOT share the traced
        # jit call's executable cache, so mixing the two paths would
        # compile the step twice inside the phase timeout
        compiled = step.lower(state, device_batch).compile()
        state, metrics = compiled(state, device_batch)  # warmup
        # _value_fence rationale: the loss read cannot complete before the
        # step has run (and, via the donated state chain, every step
        # before it)
        _value_fence(metrics["loss"])
        compile_s = time.perf_counter() - t0
        _account("compile", compile_s)
        _mark(f"compile+first step done in {compile_s:.1f}s; timing "
              f"{n_iters} iters")

        tracing = (
            jax.profiler.trace(profile_dir)
            if profile_dir
            else contextlib.nullcontext()
        )
        t0 = time.perf_counter()
        with tracing:
            for _ in range(n_iters):
                state, metrics = compiled(state, device_batch)
            loss_val = float(metrics["loss"])
        dt = time.perf_counter() - t0
        _account("step", dt)
        _mark(f"timed loop done in {dt:.1f}s")

    tokens_per_step = grad_accum * micro_bs * config.seq_len
    per_chip = tokens_per_step * n_iters / dt / n_chips
    peak = profiling.peak_flops(jax.devices()[0])
    per_chip_flops = per_chip * profiling.flops_per_token(config)
    mfu = per_chip_flops / peak

    # XLA's own accounting for the compiled step: how many FLOPs/bytes the
    # schedule actually executes vs the PaLM-convention model count — the
    # ratio localizes an MFU gap (masked-window attention waste, remat
    # recompute, optimizer elementwise traffic) without a trace viewer.
    xla_cost = None
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        model_flops_step = profiling.flops_per_token(config) * tokens_per_step
        xla_flops = float(ca.get("flops", 0.0))
        xla_bytes = float(ca.get("bytes accessed", 0.0))
        if xla_flops > 0:
            xla_cost = {
                "flops_per_step": xla_flops,
                "bytes_accessed_per_step": xla_bytes,
                "arithmetic_intensity": round(xla_flops / xla_bytes, 1)
                if xla_bytes else None,
                # >1.0 means XLA schedules more FLOPs than the model
                # convention counts (bwd of fwd-only ops, masked waste…)
                "flops_vs_model_count": round(
                    xla_flops / model_flops_step, 3
                ),
            }
    except Exception as e:  # diagnostic only: never fail a timed phase
        _mark(f"cost_analysis unavailable: {e!r}")
    # which measured kernel combo this config's attention actually traced
    # under (ADVICE r3: make the silently-applied policy visible per phase)
    attn_policy = None
    if config.use_pallas_attn:
        from progen_tpu.ops.pallas_attention import policy_decision

        attn_policy = policy_decision(
            config.window_size, n=config.seq_len, bh=micro_bs * config.heads
        )
    # ADVICE r5: the compiled-path ring shard_map check_vma outcome (one
    # evidence record per configuration) rides the phase row, and an
    # on-chip outcome is persisted into the policy table so CPU sessions
    # can read what the compiled TPU path accepted
    from progen_tpu.parallel.ring_attention import (
        record_ring_vma_policy,
        ring_vma_events,
    )

    ring_evs = ring_vma_events()
    if ring_evs and jax.devices()[0].platform == "tpu":
        record_ring_vma_policy(ring_evs[-1])
    return {
        "phase": f"train-{config_name}"
        + ("-pallas" if use_pallas else "-xla" if use_pallas is False else "")
        + phase_suffix,
        **({"ring_check_vma": ring_evs[-1]} if ring_evs else {}),
        "config": config_name,
        "tokens_per_sec_per_chip": round(per_chip, 1),
        "mfu": round(mfu, 4),
        "step_ms": round(1000 * dt / n_iters, 1),
        "compile_s": round(compile_s, 1),
        "num_params": state.num_params(),
        "batch": f"{grad_accum}x{micro_bs}x{config.seq_len}",
        "dtype": config.dtype,
        "use_pallas_attn": config.use_pallas_attn,
        "scan_layers": config.scan_layers,
        "loss": round(loss_val, 4),
        "chips": n_chips,
        **({"attn_policy": attn_policy} if attn_policy else {}),
        **({"xla_cost": xla_cost} if xla_cost else {}),
        **_suspect_fields(per_chip_flops, 1.0, peak),  # per_chip_flops is /s
        **_hbm_stats(),
        "platform": jax.devices()[0].platform,
    }


def _price_kernel_combos(fwd_cands: dict, bwd_only: dict, t_xb: float):
    """Pick the deployed (fwd, bwd) kernel combo by pricing the FULL grid,
    each candidate with the forward time of the forward impl it ACTUALLY
    pairs (t_xf for xla-fwd combos, the g-batched fwd time for pallas_gN)
    — a global argmin, so near-tie winners aren't decided greedily on the
    forward alone.

    fwd_cands: {"xla": t_xf, "pallas_g1": t, "pallas_g<N>": t, ...} fwd
      times (s). bwd_only: {impl: t} pallas backward-only costs (the
      measured grad pipelines are pallas-g1-fwd + that bwd, so bwd-only =
      t_pb[impl] - t_pf). t_xb: the PLAIN XLA autodiff grad pipeline
      (fwd+bwd total).

    Special cases: fwd=xla + bwd=xla is plain local_attention by the model
    dispatch (no custom-VJP recompute), priced at t_xb; a bwd="xla" escape
    hatch under a pallas-fwd custom VJP re-runs the whole XLA forward
    inside the backward (~t_xb on top of the deployed forward, not
    t_xb - t_xf).

    Returns (best_fwd_key, fwd_win, bwd_win)."""
    combos = {("xla", "xla"): t_xb}
    for fkey, ftime in fwd_cands.items():
        for impl, bcost in bwd_only.items():
            combos[(fkey, impl)] = ftime + bcost
        if fkey != "xla":
            combos[(fkey, "xla")] = ftime + t_xb
    best_fwd_key, bwd_win = min(combos, key=combos.get)
    return best_fwd_key, ("xla" if best_fwd_key == "xla" else "pallas"), bwd_win


def _kernel_bench(window: int, n: int = 1024) -> dict:
    """Pallas windowed-attention kernel vs the XLA path, fwd+bwd, at the
    flagship shapes. On TPU the kernel is Mosaic-COMPILED (interpret only
    off-TPU) and the on-chip error vs the XLA golden is recorded — the
    non-interpret correctness evidence VERDICT round-2 asked for.

    A clean on-chip run WRITES its winners into the measured policy table
    (ops/pallas_policy.json, record_policy_entry) keyed by the measured
    (window, n, batch*heads) — so `use_pallas_attn` configs downstream in
    the same suite (train-long8k runs AFTER kernel-w512-n8192) pick their
    impls from evidence at their own shapes, not an extrapolation."""
    import jax
    import jax.numpy as jnp

    from progen_tpu.ops.attention import local_attention
    from progen_tpu.ops.pallas_attention import pallas_local_attention

    # phase label = the SCHEDULED name (requested shape), so resume
    # bookkeeping matches even when the off-TPU smoke shrinks the shapes
    phase_name = f"kernel-w{window}" + (f"-n{n}" if n != 1024 else "")
    on_tpu = _is_tpu_platform(jax.devices()[0].platform)
    if on_tpu:
        # n=1024: the tiny/default train shapes (bh=128). n=8192: the
        # long8k shapes — batch shrinks to the long8k recipe's micro-batch
        # so bh matches what the train step actually runs (bh=16).
        b, h, d = (16, 8, 64) if n <= 2048 else (2, 8, 64)
        iters_f, iters_b = 20, 10
        w = window
    else:
        # interpret-mode Pallas is minutes/call at TPU shapes — keep the
        # off-TPU path a functional smoke, not a perf claim
        b, h, n, d = 2, 2, 128, 32
        iters_f, iters_b = 2, 1
        w = min(window, 32)
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (b, h, n, d), jnp.bfloat16) for kk in ks)

    def time_fn(fn, iters):
        out = fn(q, k, v)  # compile
        _value_fence(out)
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(q, k, v)
        _value_fence(out)  # in-order device stream: all iters must finish
        return (time.perf_counter() - t0) / iters, out

    xla_fwd = jax.jit(lambda q, k, v: local_attention(q, k, v, window_size=w))
    pl_fwd = jax.jit(
        lambda q, k, v: pallas_local_attention(q, k, v, w, None, not on_tpu)
    )
    xla_bwd = jax.jit(
        jax.grad(lambda q, k, v: local_attention(q, k, v, window_size=w)
                 .astype(jnp.float32).sum(), argnums=(0, 1, 2))
    )

    def pl_bwd(impl):
        return jax.jit(
            jax.grad(
                lambda q, k, v: pallas_local_attention(
                    q, k, v, w, None, not on_tpu, impl
                ).astype(jnp.float32).sum(),
                argnums=(0, 1, 2),
            )
        )

    t_xf, o_x = time_fn(xla_fwd, iters_f)
    t_pf, o_p = time_fn(pl_fwd, iters_f)
    fwd_err = float(
        jnp.abs(o_x.astype(jnp.float32) - o_p.astype(jnp.float32)).max()
    )
    # forward bh_block variants: g batch-heads per program (fatter blocks,
    # fewer programs — the small-window perf lever). VMEM caps g at w=512.
    from progen_tpu.ops.pallas_attention import _safe_bh_block

    fwd_ms_g = {}
    timed_gs = {1}  # the plain pallas row above is g=1
    for g_try in (4, 8):
        g_eff = _safe_bh_block(g_try, b * h, w)  # VMEM cap / divisibility
        if g_eff in timed_gs:  # e.g. w=512 caps 8 -> 4: don't re-time
            continue
        timed_gs.add(g_eff)
        pl_fwd_g = jax.jit(
            lambda q, k, v, g_=g_eff: pallas_local_attention(
                q, k, v, w, None, not on_tpu, "kv", g_
            )
        )
        t_g, o_g = time_fn(pl_fwd_g, iters_f)
        err_g = float(
            jnp.abs(o_x.astype(jnp.float32) - o_g.astype(jnp.float32)).max()
        )
        fwd_ms_g[f"pallas_g{g_eff}"] = {  # label = EFFECTIVE g
            "ms": round(t_g * 1e3, 3),
            "max_err": err_g,
        }
    t_xb, g_x = time_fn(xla_bwd, iters_b)
    # both pallas backwards: kv (combined-in-register) vs halo (f32
    # scratch + shifted add) — the on-chip winner informs the default
    t_pb = {}
    bwd_err = {}
    bwd_impls = ["kv", "halo"]
    # batched kv variants (same lever as the forward's bh_block; VMEM cap
    # uses n_probs=2 — two probability tensors live per program)
    timed_bwd_gs = {1}
    for g_try in (4, 8):
        g_eff = _safe_bh_block(g_try, b * h, w, n_probs=2)
        if g_eff not in timed_bwd_gs:
            timed_bwd_gs.add(g_eff)
            bwd_impls.append(f"kv_g{g_eff}")
    for impl in bwd_impls:
        t_pb[impl], g_p = time_fn(pl_bwd(impl), iters_b)
        bwd_err[impl] = max(
            float(
                jnp.abs(a.astype(jnp.float32) - b_.astype(jnp.float32)).max()
            )
            for a, b_ in zip(g_x, g_p)
        )
    best = min(t_pb, key=t_pb.get)
    from progen_tpu import profiling as _prof

    peak = _prof.peak_flops(jax.devices()[0])
    # score + value einsums, 2 FLOP/MAC, ctx = 2w per query
    fwd_flops = 2 * 2 * b * h * n * (2 * w) * d
    bwd_flops = 2 * fwd_flops  # dq,dk,dv reuse both einsums (lower bound)
    t_pf_best = min([t_pf] + [v["ms"] / 1e3 for v in fwd_ms_g.values()])
    fwd_guard = _suspect_fields(fwd_flops, min(t_xf, t_pf_best), peak)
    bwd_guard = _suspect_fields(bwd_flops, min(t_xb, *t_pb.values()), peak)
    suspect = fwd_guard["timing_suspect"] or bwd_guard["timing_suspect"]

    fwd_cands = {"xla": t_xf, "pallas_g1": t_pf,
                 # fwd_ms_g keys are already "pallas_g<N>"
                 **{k: v["ms"] / 1e3 for k, v in fwd_ms_g.items()}}
    bwd_only = {impl: max(t - t_pf, 1e-9) for impl, t in t_pb.items()}
    best_fwd_key, fwd_win, bwd_win = _price_kernel_combos(
        fwd_cands, bwd_only, t_xb
    )
    policy_entry = {
        "window": w, "n": n, "bh": b * h,
        "fwd": fwd_win,
        "bwd": bwd_win,  # "xla" / "kv" / "halo" / "kv_g<N>"
        "bh_block": (1 if best_fwd_key in ("xla", "pallas_g1")
                     else int(best_fwd_key.rsplit("_g", 1)[1])),
    }
    # never adopt a fast-but-WRONG kernel: the policy only learns from
    # runs whose on-chip error vs the XLA golden is within bf16 tolerance
    # (r3b honest runs measured 2.0e-3 fwd / 1.6e-2 bwd)
    max_bwd_err = max(bwd_err.values()) if bwd_err else 0.0
    numerics_ok = fwd_err <= 1e-2 and max_bwd_err <= 5e-2
    policy_recorded = False
    if on_tpu and not suspect and numerics_ok:
        from progen_tpu.ops.pallas_attention import record_policy_entry

        record_policy_entry({
            **policy_entry,
            "fwd_ms": {k: round(v * 1e3, 3) for k, v in fwd_cands.items()},
            "bwd_ms": {"xla_full": round(t_xb * 1e3, 3),
                       **{k: round(v * 1e3, 3)
                          for k, v in bwd_only.items()}},
            "source": f"bench {phase_name}"
                      + time.strftime(" %Y-%m-%d", time.gmtime()),
        })
        policy_recorded = True
    return {
        "phase": phase_name,
        "fwd_ms": {
            "xla": round(t_xf * 1e3, 3),
            "pallas": round(t_pf * 1e3, 3),
            **{k: v["ms"] for k, v in fwd_ms_g.items()},
        },
        "fwd_bh_block_err": {k: v["max_err"] for k, v in fwd_ms_g.items()},
        "bwd_ms": {
            "xla": round(t_xb * 1e3, 3),
            **{f"pallas_{impl}": round(t * 1e3, 3)
               for impl, t in t_pb.items()},
        },
        "fwd_speedup": round(t_xf / t_pf_best, 2),  # best pallas variant
        "bwd_speedup": round(t_xb / t_pb[best], 2),
        "bwd_best_impl": best,
        "fwd_max_abs_err": fwd_err,
        "bwd_max_abs_err": bwd_err,  # per impl: a regression in the
                                     # slower one must stay visible
        "shape": f"b{b} h{h} n{n} d{d} w{w} bf16",
        "policy_entry": policy_entry,
        "policy_recorded": policy_recorded,
        "policy_numerics_ok": numerics_ok,
        "timing_suspect": suspect,
        "implied_device_tflops": {
            "fwd_fastest": fwd_guard["implied_device_tflops"],
            "bwd_fastest": bwd_guard["implied_device_tflops"],
        },
        "mosaic_compiled": on_tpu,
        "platform": jax.devices()[0].platform,
    }


def _sgu_mix_bench() -> dict:
    """Dense tril-masked vs recursive block-triangular SGU mix at the
    long8k shapes, fwd+bwd — isolates the sgu_block_size optimization
    (the long8k train phases both run with it on)."""
    import jax
    import jax.numpy as jnp

    from progen_tpu.ops.sgu import causal_sgu_mix

    on_tpu = _is_tpu_platform(jax.devices()[0].platform)
    n, d_half, b = (8192, 1024, 2) if on_tpu else (256, 64, 1)
    block = 1024 if on_tpu else 32
    iters = 10 if on_tpu else 3
    gate = jax.random.normal(jax.random.PRNGKey(0), (b, n, d_half),
                             jnp.bfloat16)
    w = jax.random.normal(jax.random.PRNGKey(1), (n, n), jnp.float32) / n
    bias = jnp.ones((n, 1), jnp.float32)

    def timed(block_size, bwd):
        if bwd:
            fn = jax.jit(
                jax.grad(
                    lambda g, w: causal_sgu_mix(g, w, bias, block_size)
                    .astype(jnp.float32).sum(),
                    argnums=(0, 1),
                )
            )
        else:
            fn = jax.jit(
                lambda g, w: causal_sgu_mix(g, w, bias, block_size)
            )
        _value_fence(fn(gate, w))  # compile
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(gate, w)
        _value_fence(out)
        return (time.perf_counter() - t0) / iters

    t_dense_f, t_block_f = timed(0, False), timed(block, False)
    t_dense_b, t_block_b = timed(0, True), timed(block, True)
    from progen_tpu import profiling as _prof

    peak = _prof.peak_flops(jax.devices()[0])
    dense_fwd_flops = 2 * b * n * n * d_half  # (n,n) mix, 2 FLOP/MAC
    guard = _suspect_fields(
        dense_fwd_flops, min(t_dense_f, t_block_f / 0.6), peak
    )  # blocked does ~0.6x dense MACs at these shapes
    return {
        "phase": "sgu-mix",
        "timing_suspect": guard["timing_suspect"],
        "implied_device_tflops": guard["implied_device_tflops"],
        "shape": f"b{b} n{n} d{d_half} block{block}",
        "fwd_ms": {
            "dense": round(t_dense_f * 1e3, 3),
            "blocked": round(t_block_f * 1e3, 3),
        },
        "bwd_ms": {
            "dense": round(t_dense_b * 1e3, 3),
            "blocked": round(t_block_b * 1e3, 3),
        },
        "fwd_speedup": round(t_dense_f / t_block_f, 2),
        "bwd_speedup": round(t_dense_b / t_block_b, 2),
        "platform": jax.devices()[0].platform,
    }


def _fused_kernel_bench(block: int) -> dict:
    """Fused Pallas layer kernels (ops/pallas_layers.py) vs their
    unfused XLA references, fwd+bwd: the shift->norm halo kernel and the
    SGU mix+gate kernel that keeps the normalized gate VMEM-resident
    across norm/causal-mix/gating and skips the structurally-zero upper
    triangle in-grid. On TPU a clean run (numerics pass, timings not
    suspect) writes the measured winners into pallas_policy.json's
    layer_entries; off-TPU the kernels run in interpret mode — a
    functional smoke whose timings are never policy evidence."""
    import jax
    import jax.numpy as jnp

    from progen_tpu.ops.pallas_layers import (
        LAYER_PALLAS_OK,
        fused_norm_shift,
        fused_sgu_mix_gate,
        norm_shift_reference,
        record_layer_policy_entry,
        sgu_mix_gate_reference,
    )

    phase = f"kernel-fused-w{block}"
    if not LAYER_PALLAS_OK:
        return {"phase": phase,
                "error": "pallas layer-kernel API unavailable on this jax"}

    on_tpu = _is_tpu_platform(jax.devices()[0].platform)
    interpret = not on_tpu
    if on_tpu:
        b, n, d, d_half, iters, bn = 4, 1024, 512, 1024, 10, block
    else:  # smoke shapes: interpret mode is minutes/iter at TPU shapes
        b, n, d, d_half, iters, bn = 2, 128, 64, 64, 3, min(block, 32)
    eps = 1e-5
    kx, kxg, kg, kw = jax.random.split(jax.random.PRNGKey(0), 4)
    x = jax.random.normal(kx, (b, n, d), jnp.bfloat16)
    scale = jnp.full((d,), 1.1, jnp.float32)
    xg = jax.random.normal(kxg, (b, n, d_half), jnp.bfloat16)
    gate = jax.random.normal(kg, (b, n, d_half), jnp.bfloat16)
    gscale = jnp.full((d_half,), 0.9, jnp.float32)
    w = jax.random.normal(kw, (n, n), jnp.float32) / n
    bias = jnp.ones((n, 1), jnp.float32)
    _mark(f"{phase}: b{b} n{n} d{d} dh{d_half} bn{bn} "
          f"interpret={interpret}")

    def ns_fused(x, s):
        return fused_norm_shift(x, s, eps, bn, interpret, "bfloat16")

    def ns_ref(x, s):
        return norm_shift_reference(x, s, eps, "bfloat16")

    def sgu_fused(x, g, w, s):
        return fused_sgu_mix_gate(x, g, w, bias, s, eps, bn, interpret,
                                  "bfloat16")

    def sgu_ref(x, g, w, s):
        return sgu_mix_gate_reference(x, g, w, bias, s, eps, "bfloat16")

    def timed(fn, *args, bwd=False):
        if bwd:
            def loss(*a):
                return fn(*a).astype(jnp.float32).sum()

            run = jax.jit(jax.grad(loss, argnums=tuple(range(len(args)))))
        else:
            run = jax.jit(fn)
        t0 = time.perf_counter()
        out = run(*args)
        _value_fence(out)
        _account("compile", time.perf_counter() - t0)
        t0 = time.perf_counter()
        for _ in range(iters):
            out = run(*args)
        _value_fence(out)
        dt = time.perf_counter() - t0
        _account("step", dt)
        return dt / iters

    # numerics BEFORE timing: a fast wrong kernel must never become a
    # policy winner (bf16 paths are bit-identical by construction; the
    # tolerance covers f32-accumulation reassociation only)
    err_ns = float(jnp.max(jnp.abs(
        ns_fused(x, scale).astype(jnp.float32)
        - ns_ref(x, scale).astype(jnp.float32)
    )))
    err_sgu = float(jnp.max(jnp.abs(
        sgu_fused(xg, gate, w, gscale).astype(jnp.float32)
        - sgu_ref(xg, gate, w, gscale).astype(jnp.float32)
    )))
    numerics_ok = err_ns <= 0.05 and err_sgu <= 0.05

    t_ns_ref_f = timed(ns_ref, x, scale)
    t_ns_fused_f = timed(ns_fused, x, scale)
    t_ns_ref_b = timed(ns_ref, x, scale, bwd=True)
    t_ns_fused_b = timed(ns_fused, x, scale, bwd=True)
    _mark(f"{phase}: norm_shift timed "
          f"(fwd {t_ns_ref_f * 1e3:.2f} -> {t_ns_fused_f * 1e3:.2f} ms)")
    t_sgu_ref_f = timed(sgu_ref, xg, gate, w, gscale)
    t_sgu_fused_f = timed(sgu_fused, xg, gate, w, gscale)
    t_sgu_ref_b = timed(sgu_ref, xg, gate, w, gscale, bwd=True)
    t_sgu_fused_b = timed(sgu_fused, xg, gate, w, gscale, bwd=True)
    _mark(f"{phase}: sgu timed "
          f"(fwd {t_sgu_ref_f * 1e3:.2f} -> {t_sgu_fused_f * 1e3:.2f} ms)")

    from progen_tpu import profiling as _prof

    peak = _prof.peak_flops(jax.devices()[0])
    dense_flops = 2 * b * n * n * d_half  # dense (n, n) mix, 2 FLOP/MAC
    guard = _suspect_fields(
        dense_flops, min(t_sgu_ref_f, t_sgu_fused_f / 0.5), peak
    )  # fused does ~0.5x dense MACs (tril-only grid)

    policy_written = False
    if on_tpu and numerics_ok and not guard["timing_suspect"]:
        record_layer_policy_entry({
            "kind": "norm_shift", "n": n, "d": d,
            "impl": "pallas" if t_ns_fused_f <= t_ns_ref_f else "xla",
            "block": bn,
            "fwd_ms": {"xla": round(t_ns_ref_f * 1e3, 3),
                       "pallas": round(t_ns_fused_f * 1e3, 3)},
            "bwd_ms": {"xla": round(t_ns_ref_b * 1e3, 3),
                       "pallas": round(t_ns_fused_b * 1e3, 3)},
            "source": phase,
        })
        record_layer_policy_entry({
            "kind": "sgu_mix", "n": n, "d": d_half,
            "impl": "pallas" if t_sgu_fused_f <= t_sgu_ref_f else "xla",
            "block": bn,
            "fwd_ms": {"xla": round(t_sgu_ref_f * 1e3, 3),
                       "pallas": round(t_sgu_fused_f * 1e3, 3)},
            "bwd_ms": {"xla": round(t_sgu_ref_b * 1e3, 3),
                       "pallas": round(t_sgu_fused_b * 1e3, 3)},
            "source": phase,
        })
        policy_written = True

    return {
        "phase": phase,
        "timing_suspect": guard["timing_suspect"],
        "implied_device_tflops": guard["implied_device_tflops"],
        "shape": f"b{b} n{n} d{d} dh{d_half} bn{bn}",
        "interpret": interpret,
        # headline speedups = the SGU kernel (the O(n^2) one): the
        # main() summary contract for kernel phases reads these keys
        "fwd_speedup": round(t_sgu_ref_f / t_sgu_fused_f, 2),
        "bwd_speedup": round(t_sgu_ref_b / t_sgu_fused_b, 2),
        "norm_shift": {
            "fwd_ms": {"xla": round(t_ns_ref_f * 1e3, 3),
                       "pallas": round(t_ns_fused_f * 1e3, 3)},
            "bwd_ms": {"xla": round(t_ns_ref_b * 1e3, 3),
                       "pallas": round(t_ns_fused_b * 1e3, 3)},
            "fwd_speedup": round(t_ns_ref_f / t_ns_fused_f, 2),
            "bwd_speedup": round(t_ns_ref_b / t_ns_fused_b, 2),
            "max_abs_err": err_ns,
        },
        "sgu_mix": {
            "fwd_ms": {"xla": round(t_sgu_ref_f * 1e3, 3),
                       "pallas": round(t_sgu_fused_f * 1e3, 3)},
            "bwd_ms": {"xla": round(t_sgu_ref_b * 1e3, 3),
                       "pallas": round(t_sgu_fused_b * 1e3, 3)},
            "fwd_speedup": round(t_sgu_ref_f / t_sgu_fused_f, 2),
            "bwd_speedup": round(t_sgu_ref_b / t_sgu_fused_b, 2),
            "max_abs_err": err_sgu,
        },
        "numerics_ok": numerics_ok,
        "policy_written": policy_written,
        "platform": jax.devices()[0].platform,
        **_hbm_stats(),
    }


def _calib_bench() -> dict:
    """Fence calibration: a chained bf16 matmul with KNOWN FLOPs. Each
    iteration consumes the previous result, so even a dispatch-ack
    transport must execute the whole chain before the final value fetch.
    On a real v5e the 4096-cube matmul should land at a large fraction of
    the 197 bf16 TFLOP/s peak — and NEVER above it. This is the on-chip
    proof that the suite's timing methodology measures compute, not
    dispatch (the round-3 block_until_ready failure mode)."""
    import jax
    import jax.numpy as jnp

    from progen_tpu import profiling

    on_tpu = _is_tpu_platform(jax.devices()[0].platform)
    n = 4096 if on_tpu else 256
    chain_len, iters = 8, 10

    @jax.jit
    def chain(x, b):
        for _ in range(chain_len):
            x = x @ b
        return x

    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    a = jax.random.normal(k1, (n, n), jnp.bfloat16)
    # 1/sqrt(n) keeps the chain magnitude-STABLE (variance-preserving):
    # a 1/n scale underflows bf16 to exact zeros ~21 multiplies in, and a
    # zero-operand chain is a weaker proof that compute actually ran
    b = jax.random.normal(k2, (n, n), jnp.bfloat16) / jnp.sqrt(
        jnp.float32(n)
    ).astype(jnp.bfloat16)
    _value_fence(chain(a, b))  # compile
    t0 = time.perf_counter()
    x = a
    for _ in range(iters):
        x = chain(x, b)
    _value_fence(x)
    dt = time.perf_counter() - t0

    flops = iters * chain_len * 2 * n**3
    peak = profiling.peak_flops(jax.devices()[0])
    achieved = flops / dt
    return {
        "phase": "calib-matmul",
        "shape": f"{n}x{n} bf16, chain {chain_len} x {iters} iters",
        "achieved_tflops": round(achieved / 1e12, 1),
        "peak_tflops": round(peak / 1e12, 1),
        "mxu_efficiency": round(achieved / peak, 3),
        "timing_suspect": bool(achieved > 1.1 * peak),
        "platform": jax.devices()[0].platform,
    }


def _sustain_bench() -> dict:
    """Sustained training on the ~205M base config with a mid-run async
    checkpoint and an exactness-checked restore — the closest this
    single-chip box gets to the production claim: steady-state
    tokens/sec/chip over 100+ steps under real HBM pressure, checkpoint
    machinery engaged, resume continuing the identical loss trajectory
    (ref train.py:179-222 is the loop this hardens). Artifact:
    runs/sustain_base_metrics.jsonl (per-chunk timings + losses)."""
    import shutil

    import jax

    from progen_tpu import profiling
    from progen_tpu.checkpoint import (
        Package,
        get_checkpoint_fns,
        sharded_abstract_state,
    )
    from progen_tpu.models.progen import ProGen
    from progen_tpu.parallel.partition import make_mesh, put_batch
    from progen_tpu.training.optimizer import make_optimizer
    from progen_tpu.training.step import (
        abstract_train_state,
        compile_train_step,
        init_train_state,
        train_state_shardings,
    )

    on_tpu = _is_tpu_platform(jax.devices()[0].platform)
    if on_tpu:
        config = _load_config("base")
        grad_accum, micro_bs = _RECIPES["base"][:2]
        target_steps, ckpt_at, resume_steps, chunk = 120, 60, 10, 10
    else:
        config = _load_config("smoke")
        grad_accum, micro_bs = 2, 2
        target_steps, ckpt_at, resume_steps, chunk = 8, 4, 2, 2
    deadline = float(os.environ.get("BENCH_PHASE_DEADLINE_SEC", 1170))
    t_start = time.perf_counter()

    mesh = make_mesh()
    model = ProGen(config)
    optimizer = make_optimizer()
    state, shardings = init_train_state(
        model, optimizer, jax.random.PRNGKey(0), config.seq_len, mesh=mesh
    )
    _mark("sustain: state initialized")
    step = compile_train_step(model, optimizer, state, shardings, mesh)

    # rotating synthetic batches: zero host input cost, deterministic
    # stream so the post-restore step can replay the EXACT batch the
    # original trajectory saw (turning resume into an on-chip exactness
    # check, not just liveness)
    rng = np.random.default_rng(0)
    n_rot = 4
    host_batches = [
        rng.integers(1, config.num_tokens,
                     size=(grad_accum, micro_bs, config.seq_len + 1)
                     ).astype(np.int32)
        for _ in range(n_rot)
    ]

    ckpt_dir = _REPO / "runs" / "sustain_ckpt"
    shutil.rmtree(ckpt_dir, ignore_errors=True)
    reset_ckpt, get_last, save_ckpt = get_checkpoint_fns(
        str(ckpt_dir), keep_last_n=2, async_save=True
    )

    metrics_path = _LOG_DIR.parent / "sustain_base_metrics.jsonl"
    metrics_path.parent.mkdir(parents=True, exist_ok=True)
    records = []
    tokens_per_step = grad_accum * micro_bs * config.seq_len

    with mesh:
        batches = [
            put_batch(b, mesh, accum_axis=True) for b in host_batches
        ]
        t0 = time.perf_counter()
        state, m = step(state, batches[0])  # compile + step 1
        _value_fence(m["loss"])
        compile_s = time.perf_counter() - t0
        _account("compile", compile_s)
        _mark(f"sustain: compile+step1 in {compile_s:.1f}s")

        steps_done = 1
        ckpt_block_s = None
        loss_after_ckpt = None  # original trajectory's step ckpt_at+1
        chunk_rows = []
        while steps_done < target_steps:
            if time.perf_counter() - t_start > 0.6 * deadline:
                _mark(f"sustain: wall budget at {steps_done} steps")
                break
            n = min(chunk, target_steps - steps_done)
            t0 = time.perf_counter()
            for _ in range(n):
                state, m = step(state, batches[steps_done % n_rot])
                steps_done += 1
            _value_fence(m["loss"])
            dt = time.perf_counter() - t0
            _account("step", dt)
            row = {
                "step": steps_done,
                "chunk_steps": n,
                "tokens_per_sec": round(tokens_per_step * n / dt, 1),
                "loss": round(float(m["loss"]), 4),
            }
            chunk_rows.append(row)
            records.append(row)
            if ckpt_block_s is None and steps_done >= ckpt_at:
                t0 = time.perf_counter()
                save_ckpt(Package(
                    next_seq_index=steps_done,
                    state=state,
                    model_config=config.to_dict(),
                    run_id=None,
                ))
                ckpt_block_s = time.perf_counter() - t0
                _account("checkpoint", ckpt_block_s)
                _mark(f"sustain: async ckpt at step {steps_done} "
                      f"(blocked {ckpt_block_s:.2f}s)")
                # the step the restore must reproduce bit-for-bit
                state, m = step(state, batches[steps_done % n_rot])
                steps_done += 1
                _value_fence(m["loss"])
                loss_after_ckpt = float(m["loss"])

        # steady state = median chunk AFTER warmup/ckpt chunks
        tail = [r["tokens_per_sec"] for r in chunk_rows[1:]] or [
            r["tokens_per_sec"] for r in chunk_rows
        ]
        steady = float(np.median(tail)) if tail else 0.0
        final_loss = float(m["loss"])

        save_ckpt.close()  # publish the pending async snapshot
        restore_ok, resume_delta, restore_s = False, None, None
        if ckpt_block_s is not None:
            t0 = time.perf_counter()
            boxed, abstract = abstract_train_state(
                model, optimizer, config.seq_len
            )
            r_shardings = train_state_shardings(boxed, mesh)
            pkg = get_last(sharded_abstract_state(abstract, r_shardings))
            restore_s = time.perf_counter() - t0
            _account("checkpoint", restore_s)
            _mark(f"sustain: restore in {restore_s:.1f}s from step "
                  f"{pkg.next_seq_index}")
            r_state = pkg.state
            r_step = step(r_state, batches[pkg.next_seq_index % n_rot])
            r_state, r_m = r_step
            _value_fence(r_m["loss"])
            resume_delta = abs(float(r_m["loss"]) - loss_after_ckpt)
            restore_ok = resume_delta < 1e-5
            for i in range(resume_steps - 1):
                r_state, r_m = step(
                    r_state, batches[(pkg.next_seq_index + 1 + i) % n_rot]
                )
            _value_fence(r_m["loss"])
            records.append({
                "resumed": True,
                "resume_loss_delta": resume_delta,
                "resume_final_loss": round(float(r_m["loss"]), 4),
            })

    with open(metrics_path, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")
    shutil.rmtree(ckpt_dir, ignore_errors=True)

    peak = profiling.peak_flops(jax.devices()[0])
    per_chip_flops = steady * profiling.flops_per_token(config)
    return {
        "phase": "sustain-base",
        "config": "base" if on_tpu else "smoke",
        "steps": steps_done,
        "steady_tokens_per_sec_per_chip": round(steady, 1),
        "mfu": round(per_chip_flops / peak, 4),
        "compile_s": round(compile_s, 1),
        "final_loss": round(final_loss, 4),
        "ckpt_block_s": (round(ckpt_block_s, 2)
                         if ckpt_block_s is not None else None),
        "restore_s": (round(restore_s, 1) if restore_s is not None
                      else None),
        "resume_loss_delta": resume_delta,
        "resume_exact": restore_ok,
        "metrics_artifact": str(metrics_path),
        **_suspect_fields(per_chip_flops, 1.0, peak),
        **_hbm_stats(),
        "platform": jax.devices()[0].platform,
    }


def _decode_bench() -> dict:
    """Autoregressive decode throughput on the flagship config (BASELINE.md
    config 5): the KV-cache fused decode (sample_fast) vs the
    reference-shaped full-forward-per-token path (sample), same Gumbel
    top-k semantics, annotation-style prime."""
    import jax
    import jax.numpy as jnp
    from flax import linen as nn

    from progen_tpu.data.tokenizer import encode_tokens
    from progen_tpu.models.progen import ProGen
    from progen_tpu.sampling import sample, sample_fast, sample_fast_batched

    on_tpu = _is_tpu_platform(jax.devices()[0].platform)
    # half-context tiny on TPU: three separate decoder jits compile in this
    # phase, and in round 3 the full-length naive decode blew the phase
    # window and wedged the relay on kill. The SGU binds the forward to
    # seq_len, so the model itself is built at the shorter length.
    config = (
        _load_config("tiny", seq_len=512)
        if on_tpu
        else _load_config("smoke")
    )
    model = ProGen(config)
    tokens = jnp.zeros((1, config.seq_len), jnp.int32)
    params = nn.meta.unbox(
        jax.jit(model.init)(jax.random.PRNGKey(0), tokens)["params"]
    )
    prime = jnp.asarray(encode_tokens("[tax=Mammalia] #"), jnp.int32)
    length = config.seq_len
    key = jax.random.PRNGKey(7)

    def run(fn):
        t0 = time.perf_counter()
        out = fn(key, model, params, prime, length, 25, True)
        _value_fence(out)
        compile_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        out = fn(jax.random.PRNGKey(8), model, params, prime, length, 25, True)
        _value_fence(out)
        dt = time.perf_counter() - t0
        gen = length - int(prime.shape[0]) - 1
        return gen / dt, compile_s, out

    fast_tps, fast_compile, out_fast = run(sample_fast)
    naive_tps, naive_compile, out_naive = run(sample)

    # batched KV-cache decode: aggregate tokens/sec over a batch of primes
    # through ONE shared cache loop (the MXU-throughput decode mode)
    bsz = 8
    primes_b = jnp.tile(prime[None], (bsz, 1))
    batched_tps, _, _ = run(
        lambda k, m, p, pr, ln, tk, ab: sample_fast_batched(
            k, m, p, primes_b, ln, tk, ab
        )
    )
    batched_tps *= bsz
    from progen_tpu import profiling as _prof

    peak = _prof.peak_flops(jax.devices()[0])
    # fwd-only flops/token = (6N convention)/3; the naive path pays a full
    # length-n forward per generated token
    fwd_tok = _prof.flops_per_token(config) / 3
    guard = _suspect_fields(
        max(batched_tps * fwd_tok, naive_tps * length * fwd_tok),
        1.0,
        peak,
    )
    return {
        "phase": "decode-tiny",
        "timing_suspect": guard["timing_suspect"],
        "implied_device_tflops": guard["implied_device_tflops"],
        "config": "tiny-seq512" if on_tpu else "smoke",
        "kv_cache_tokens_per_sec": round(fast_tps, 1),
        "kv_batched8_tokens_per_sec": round(batched_tps, 1),
        "naive_tokens_per_sec": round(naive_tps, 1),
        "speedup": round(fast_tps / naive_tps, 2),
        "batch_scaling": round(batched_tps / fast_tps, 2),
        "bit_identical": bool(jnp.array_equal(out_fast, out_naive)),
        "gen_length": int(length - prime.shape[0] - 1),
        "compile_s": {
            "kv_cache": round(fast_compile, 1),
            "naive": round(naive_compile, 1),
        },
        "platform": jax.devices()[0].platform,
    }


def _decode_serve_bench() -> dict:
    """Continuous-batching serving engine (progen_tpu/serving/) under
    staggered arrivals: steady-state decode tokens/s across the slot
    pool and per-request time-to-first-token. One warmup request pays
    both compiles (prefill + decode step) OUTSIDE the measured window;
    the engine's decode_step reads its outputs back to the host every
    iteration, so the timings are honest host-observed wall clock (the
    same property _value_fence enforces elsewhere)."""
    import jax
    import jax.numpy as jnp
    from flax import linen as nn

    from progen_tpu.data.tokenizer import encode_tokens
    from progen_tpu.models.progen import ProGen
    from progen_tpu.serving import (
        Request,
        Scheduler,
        ServeEngine,
        ServingMetrics,
    )

    on_tpu = _is_tpu_platform(jax.devices()[0].platform)
    # same shape policy as decode-tiny: half-context tiny on TPU (three
    # jits already blew a full-length phase window once), smoke on CPU
    config = (
        _load_config("tiny", seq_len=512)
        if on_tpu
        else _load_config("smoke")
    )
    max_slots = 8 if on_tpu else 4
    n_requests = 16 if on_tpu else 8
    model = ProGen(config)
    tokens = jnp.zeros((1, config.seq_len), jnp.int32)
    params = nn.meta.unbox(
        jax.jit(model.init)(jax.random.PRNGKey(0), tokens)["params"]
    )
    prime = jnp.asarray(encode_tokens("[tax=Mammalia] #"), jnp.int32)

    _mark(f"serve init: slots={max_slots} seq_len={config.seq_len}")
    engine = ServeEngine(model, params, max_slots=max_slots,
                         max_len=config.seq_len)
    sched = Scheduler(engine, max_queue=2 * n_requests)

    # warmup: one short request end-to-end = both compiles + cache init
    t0 = time.perf_counter()
    ok, _ = sched.submit(
        Request(id="warm", prime=prime, length=int(prime.shape[0]) + 8,
                add_bos=True, key=jax.random.PRNGKey(0))
    )
    assert ok
    sched.run_to_completion(max_steps=2000)
    compile_s = time.perf_counter() - t0
    _mark(f"serve warm in {compile_s:.1f}s")

    # measured window on fresh metrics: staggered arrivals — half the
    # load up front, the rest dripped in one per 4 decode steps, so the
    # pool sees admissions landing mid-flight (the continuous-batching
    # case, not a static batch)
    sched.metrics = metrics = ServingMetrics()
    gen_len = int(config.seq_len) if on_tpu else 96
    reqs = [
        Request(
            id=f"r{i}", prime=prime,
            # mixed lengths: 50%..100% of the window
            length=int(prime.shape[0]) + 1
            + max(8, (gen_len - int(prime.shape[0]) - 1)
                  * (2 + i % 3) // 4),
            add_bos=True, key=jax.random.PRNGKey(100 + i),
            temperature=(0.8 if i % 3 == 1 else 1.0),
            top_p=(0.95 if i % 3 == 2 else None),
        )
        for i in range(n_requests)
    ]
    pending = list(reqs)
    for req in pending[: n_requests // 2]:
        ok, reason = sched.submit(req)
        assert ok, reason
    pending = pending[n_requests // 2:]
    t0 = time.perf_counter()
    steps = 0
    completions = []
    while sched.has_work or pending:
        if pending and steps % 4 == 0:
            ok, reason = sched.submit(pending.pop(0))
            assert ok, reason
        _, comp = sched.step()
        completions.extend(comp)
        steps += 1
        if steps % 100 == 0:
            _mark(f"serve step {steps}: {len(completions)}/{n_requests}")
        if steps > 100000:
            raise RuntimeError("serving bench failed to drain")
    wall = time.perf_counter() - t0
    m = metrics.snapshot()
    _mark(f"serve drained: {steps} steps in {wall:.1f}s")

    from progen_tpu import profiling as _prof

    peak = _prof.peak_flops(jax.devices()[0])
    fwd_tok = _prof.flops_per_token(config) / 3
    guard = _suspect_fields(
        m.get("decode_tokens_per_s", 0.0) * fwd_tok, 1.0, peak
    )
    return {
        "phase": "decode-serve",
        "timing_suspect": guard["timing_suspect"],
        "implied_device_tflops": guard["implied_device_tflops"],
        "config": "tiny-seq512" if on_tpu else "smoke",
        "max_slots": max_slots,
        "n_requests": n_requests,
        "completed": int(m.get("requests_completed", 0)),
        "steady_state_tokens_per_sec": round(
            m.get("decode_tokens_per_s", 0.0), 1
        ),
        "wall_tokens_per_sec": round(
            m.get("decode_tokens", 0.0) / max(wall, 1e-9), 1
        ),
        "prefill_tokens_per_sec": round(
            m.get("prefill_tokens_per_s", 0.0), 1
        ),
        "ttft_mean_s": round(m.get("ttft_s_mean_s", 0.0), 4),
        "ttft_p50_s": round(m.get("ttft_s_p50_s", 0.0), 4),
        "ttft_p95_s": round(m.get("ttft_s_p95_s", 0.0), 4),
        "ttft_p99_s": round(m.get("ttft_s_p99_s", 0.0), 4),
        "ttft_max_s": round(m.get("ttft_s_max_s", 0.0), 4),
        "request_latency_mean_s": round(
            m.get("latency_s_mean_s", 0.0), 4
        ),
        "request_latency_p99_s": round(
            m.get("latency_s_p99_s", 0.0), 4
        ),
        "decode_steps": int(m.get("decode_steps", 0)),
        "mean_occupancy": round(
            m.get("decode_tokens", 0.0)
            / max(m.get("decode_steps", 1.0), 1.0),
            2,
        ),
        "compile_s": round(compile_s, 1),
        "platform": jax.devices()[0].platform,
        **_hbm_stats(),
    }


def _decode_admit_stall_bench() -> dict:
    """The admission-stall number the chunked-prefill work exists to
    move: decode ITL p99 for live requests WHILE a long prompt admits.

    Two runs of the same scenario — live decoders, then a long-prime
    request submitted mid-flight — one on the monolithic scheduler
    (``prefill_chunk=0``: the whole prefill lands inside one step, and
    every live decoder's next token waits behind it) and one chunked
    (at most ``chunk`` prime tokens between decode steps). Headline
    ``value`` = monolithic ITL p99 / chunked ITL p99 — dimensionless,
    >1 means chunking wins, and the bench gate ratchets it
    (``--metric serve_admit_stall_ratio``).

    Second number: ``prefix_cache_speedup`` = cold TTFT / cache-hit
    TTFT for the same scaffold on a quiet engine (``--metric
    serve_prefix_cache_speedup``). Both are ratios of host-observed
    wall clock on the SAME process/platform, so they are honest on CPU
    smoke shapes too — which is why tier1.yml can enforce them."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from flax import linen as nn

    from progen_tpu.models.progen import ProGen
    from progen_tpu.serving import (
        PrefixCache,
        Request,
        Scheduler,
        ServeEngine,
    )

    on_tpu = _is_tpu_platform(jax.devices()[0].platform)
    # longer window than the other smoke phases: the signal IS the
    # admission stall, and on CPU per-step dispatch overhead (~1-2 ms)
    # would swamp a short prime's prefill. ~270 feed positions makes
    # the monolithic stall step several times a decode step.
    config = (
        _load_config("tiny", seq_len=512)
        if on_tpu
        else _load_config("smoke", seq_len=384)
    )
    chunk = 16 if on_tpu else 8
    n_decoders = 3
    repeats = 3
    model = ProGen(config)
    tokens = jnp.zeros((1, config.seq_len), jnp.int32)
    params = nn.meta.unbox(
        jax.jit(model.init)(jax.random.PRNGKey(0), tokens)["params"]
    )
    rng = np.random.RandomState(7)
    # the admission under test: a prime filling ~70% of the window, so
    # its monolithic prefill dwarfs one decode step
    long_prime = rng.randint(
        1, config.num_tokens, size=int(config.seq_len * 0.7)
    ).astype(np.int32)
    short_prime = rng.randint(1, config.num_tokens, size=6).astype(np.int32)

    # fixed-size measurement window covering the WHOLE admission on both
    # paths (monolithic admits in one step; chunked across ~prime/chunk
    # steps) — identical sample counts keep the two p99s comparable
    window = max(28, len(long_prime) // chunk + 8)

    def _measure(prefill_chunk, prefix_cache):
        """ITL samples (s) for live decoders across the admission
        window of the long request, on a fresh engine+scheduler."""
        engine = ServeEngine(model, params, max_slots=n_decoders + 1,
                             max_len=config.seq_len)
        sched = Scheduler(engine, max_queue=16,
                          prefill_chunk=prefill_chunk,
                          prefix_cache=prefix_cache)
        # warmup pays this path's full compile set (prefill or
        # chunk+finish, plus decode) outside the measured window
        ok, _ = sched.submit(Request(
            id="warm", prime=long_prime[:12], length=20,
            key=jax.random.PRNGKey(0),
        ))
        assert ok
        sched.run_to_completion(max_steps=4000)
        # decoders live through the window plus slack, no longer — the
        # post-measurement drain is dead time
        dec_len = min(int(config.seq_len) - 2,
                      len(short_prime) + 1 + window + 24)
        for i in range(n_decoders):
            ok, reason = sched.submit(Request(
                id=f"dec{i}", prime=short_prime, length=dec_len,
                key=jax.random.PRNGKey(100 + i),
            ))
            assert ok, reason
        for _ in range(6):  # decoders provably in steady state
            sched.step()
        ok, reason = sched.submit(Request(
            id="long", prime=long_prime,
            length=len(long_prime) + 16,
            key=jax.random.PRNGKey(999),
        ))
        assert ok, reason
        itl = []
        admitted = False
        while len(itl) < window:
            t0 = time.perf_counter()
            sched.step()
            itl.append(time.perf_counter() - t0)
            admitted = admitted or not (
                sched._queue or sched._pending is not None
            )
        assert admitted, "window too short: admission never completed"
        sched.run_to_completion(max_steps=20000)
        return itl

    # interleaved repeats, median of per-repeat p99s: one stall sample
    # against a machine-noise p99 would be a coin flip on a busy CPU
    # runner; the median of three interleaved pairs is not
    p99s_mono, p99s_chunk = [], []
    itl_mono, itl_chunk = [], []
    for rep in range(repeats):
        _mark(f"admit-stall: repeat {rep + 1}/{repeats} monolithic")
        itl = _measure(0, None)
        p99s_mono.append(float(np.percentile(itl, 99)))
        itl_mono.extend(itl)
        _mark(f"admit-stall: repeat {rep + 1}/{repeats} chunked")
        itl = _measure(chunk, None)
        p99s_chunk.append(float(np.percentile(itl, 99)))
        itl_chunk.extend(itl)
    p99_mono = float(np.median(p99s_mono))
    p99_chunk = float(np.median(p99s_chunk))
    stall_ratio = p99_mono / max(p99_chunk, 1e-9)
    _mark(f"admit-stall: p99 mono={p99_mono:.4f}s "
          f"chunk={p99_chunk:.4f}s ratio={stall_ratio:.2f}")

    # prefix-cache TTFT: same scaffold cold then hot on a quiet engine.
    # Same max_slots as the measurement engines — the finish program's
    # pool shape stays cached, so cold TTFT is admission cost, not a
    # recompile
    cache = PrefixCache(256 << 20)
    engine = ServeEngine(model, params, max_slots=n_decoders + 1,
                         max_len=config.seq_len)
    sched = Scheduler(engine, max_queue=4, prefill_chunk=chunk,
                      prefix_cache=cache)

    def _ttft(rid):
        ok, reason = sched.submit(Request(
            id=rid, prime=long_prime, length=len(long_prime) + 12,
            key=jax.random.PRNGKey(1234),
        ))
        assert ok, reason
        t0 = time.perf_counter()
        while True:
            ev, _ = sched.step()
            if any(e.request_id == rid for e in ev):
                ttft = time.perf_counter() - t0
                break
        sched.run_to_completion(max_steps=20000)
        return ttft

    # compile warmup for THIS engine already paid: same jits, same
    # shapes as the measurement engines above (process-level jit cache)
    ttft_cold = _ttft("cold")
    ttft_hit = _ttft("hot")
    speedup = ttft_cold / max(ttft_hit, 1e-9)
    st = cache.stats()
    _mark(f"admit-stall: ttft cold={ttft_cold:.3f}s hit={ttft_hit:.3f}s "
          f"speedup={speedup:.2f} (cache hits={st['hits']})")

    return {
        "phase": "decode-admit-stall",
        "metric": "serve_admit_stall_ratio",
        "value": round(stall_ratio, 3),
        "prefix_cache_speedup": round(speedup, 3),
        "config": "tiny-seq512" if on_tpu else "smoke",
        "prefill_chunk": chunk,
        "prime_tokens": int(len(long_prime)),
        "n_decoders": n_decoders,
        "itl_p99_monolithic_s": round(p99_mono, 5),
        "itl_p99_chunked_s": round(p99_chunk, 5),
        "itl_mean_monolithic_s": round(float(np.mean(itl_mono)), 5),
        "itl_mean_chunked_s": round(float(np.mean(itl_chunk)), 5),
        "ttft_cold_s": round(ttft_cold, 4),
        "ttft_hit_s": round(ttft_hit, 4),
        "prefix_cache_hits": int(st["hits"]),
        "prefix_cache_hit_tokens": int(
            sched.metrics.snapshot().get("prefix_cache_hit_tokens", 0)
        ),
        "platform": jax.devices()[0].platform,
        **_hbm_stats(),
    }


def _transport_overhead_bench() -> dict:
    """Framed-TCP loopback vs unix-socket serving: the cost of the
    length-prefixed frame envelope (progen_tpu/fleet/transport.py) on
    the two client-visible numbers, TTFT and streamed tokens/s.

    Two REAL ``cli/serve`` subprocesses (smoke shapes, pinned to CPU so
    the phase never fights the suite's chip claim) serve the identical
    request set — once over ``--socket``, once over ``--tcp`` on
    loopback — with one warmup request paying both compiles outside
    each measured window. Model compute is identical on both sides, so
    the ratios isolate the transport. Headline ``value`` =
    min(tcp/unix tokens-per-sec ratio, unix/tcp TTFT ratio) — the
    conservative parity number, ~1.0 when framing is free, and the
    bench gate ratchets it (``--metric serve_transport_parity``).
    Host-side by construction: honest on any runner, which is why
    tier1.yml can enforce it."""
    import re as _re
    import select
    import signal as _signal
    import socket
    import tempfile

    import jax
    import jax.numpy as jnp
    from flax.core import meta

    from progen_tpu.checkpoint import Package, get_checkpoint_fns
    from progen_tpu.config import ProGenConfig
    from progen_tpu.fleet.transport import (
        FrameDecoder,
        encode_frame,
        fleet_token,
        parse_hostport,
    )
    from progen_tpu.models.progen import ProGen

    n_requests = 8
    gen_length = 20
    config = ProGenConfig(
        num_tokens=256, dim=32, seq_len=32, depth=2, window_size=8,
        global_mlp_depth=1, heads=2, dim_head=16, ff_mult=2,
        dtype="float32",
    )

    def _measure(transport, root, ck):
        """One serve subprocess + one client connection; returns TTFT,
        tokens/s, and the full (id -> [(index, token)]) streams."""
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PROGEN_CHAOS", None)
        env["PYTHONPATH"] = f"{_REPO}{os.pathsep}" + env.get(
            "PYTHONPATH", ""
        )
        spath = str(root / f"{transport}.sock")
        args = [
            sys.executable, "-m", "progen_tpu.cli.serve",
            "--checkpoint_path", str(ck),
            "--max-slots", "4", "--max-queue", "32", "--max-len", "28",
            "--journal_dir", str(root / f"jd_{transport}"),
        ]
        args += (["--socket", spath] if transport == "unix"
                 else ["--tcp", "127.0.0.1:0"])
        err_path = root / f"{transport}.err"
        proc = subprocess.Popen(
            args, stdout=subprocess.DEVNULL,
            stderr=open(err_path, "w"), env=env,
        )
        try:
            # endpoint discovery: serve prints "listening on ..." once
            # the transport is bound (the ephemeral-port handshake)
            endpoint = None
            deadline = time.time() + 180
            while time.time() < deadline and endpoint is None:
                if proc.poll() is not None:
                    raise RuntimeError(
                        f"serve died: {err_path.read_text()[-2000:]}"
                    )
                m = _re.search(
                    r"listening on (?:tcp )?(\S+)",
                    err_path.read_text(),
                )
                if m:
                    endpoint = m.group(1)
                else:
                    time.sleep(0.2)
            if endpoint is None:
                raise RuntimeError(f"{transport} serve never listened")

            auth = fleet_token()
            if transport == "unix":
                sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                sock.connect(spath)
                dec = None
            else:
                host, port = parse_hostport(endpoint)
                sock = socket.create_connection((host, port), timeout=5)
                sock.setsockopt(
                    socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
                )
                dec = FrameDecoder(auth=auth, peer="bench")
            state = {"buf": b""}

            def send_req(obj):
                line = json.dumps(obj)
                if dec is None:
                    sock.sendall(line.encode() + b"\n")
                else:
                    sock.sendall(encode_frame(line, auth=auth))

            def pump_until_done(want, timeout_s):
                """Drain events until every id in ``want`` is done;
                each event is stamped with its host arrival time."""
                events, got = [], set()
                stop = time.time() + timeout_s
                while time.time() < stop and not want <= got:
                    r, _, _ = select.select([sock], [], [], 0.5)
                    if not r:
                        continue
                    data = sock.recv(65536)
                    if not data:
                        break
                    if dec is not None:
                        raws = dec.feed(data)
                    else:
                        state["buf"] += data
                        *full, state["buf"] = state["buf"].split(b"\n")
                        raws = [f.decode() for f in full if f.strip()]
                    now = time.perf_counter()
                    for raw in raws:
                        ev = json.loads(raw)
                        ev["_t"] = now
                        events.append(ev)
                        if ev.get("event") == "done":
                            got.add(ev["id"])
                if not want <= got:
                    raise RuntimeError(
                        f"{transport}: undone after {timeout_s}s: "
                        f"{sorted(want - got)}"
                    )
                return events

            # warmup: both compiles + cache init outside the window
            t0 = time.perf_counter()
            send_req({"id": "warm", "prime": "MKV", "length": 12,
                      "seed": 1})
            pump_until_done({"warm"}, 300)
            compile_s = time.perf_counter() - t0
            _mark(f"transport {transport}: warm in {compile_s:.1f}s")

            submits = {}
            for i in range(n_requests):
                rid = f"r{i}"
                submits[rid] = time.perf_counter()
                send_req({"id": rid, "prime": "MKV",
                          "length": gen_length, "seed": 70 + i})
            events = pump_until_done(set(submits), 300)

            first, streams, n_tokens = {}, {}, 0
            for ev in events:
                if ev.get("event") != "token":
                    continue
                n_tokens += 1
                first.setdefault(ev["id"], ev["_t"])
                streams.setdefault(ev["id"], []).append(
                    (ev["index"], ev["token"])
                )
            wall = max(ev["_t"] for ev in events) - min(submits.values())
            ttfts = [first[r] - submits[r] for r in submits]
            sock.close()
            return {
                "ttft_mean_s": sum(ttfts) / len(ttfts),
                "tokens_per_sec": n_tokens / max(wall, 1e-9),
                "tokens": n_tokens,
                "streams": streams,
                "compile_s": compile_s,
            }
        finally:
            if proc.poll() is None:
                proc.send_signal(_signal.SIGTERM)  # graceful drain
                try:
                    proc.wait(timeout=60)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait(timeout=10)

    with tempfile.TemporaryDirectory() as td:
        root = Path(td)
        model = ProGen(config)
        variables = model.init(
            jax.random.PRNGKey(0),
            jnp.zeros((1, config.seq_len), jnp.int32),
        )
        params = meta.unbox(variables)["params"]
        _, _, save = get_checkpoint_fns(str(root / "ck"))
        save(Package(0, {"params": params}, config.to_dict(),
                     "transport-bench"))
        _mark(f"transport: checkpoint saved, {n_requests} reqs/side")

        unix = _measure("unix", root, root / "ck")
        tcp = _measure("tcp", root, root / "ck")

    tps_ratio = tcp["tokens_per_sec"] / max(unix["tokens_per_sec"], 1e-9)
    ttft_ratio = unix["ttft_mean_s"] / max(tcp["ttft_mean_s"], 1e-9)
    value = min(tps_ratio, ttft_ratio)
    _mark(f"transport: tps_ratio={tps_ratio:.3f} "
          f"ttft_ratio={ttft_ratio:.3f}")
    return {
        "phase": "transport-overhead",
        "metric": "serve_transport_parity",
        "value": round(value, 3),
        "host_side": True,
        "timing_suspect": False,
        "config": "smoke-serve32",
        "n_requests": n_requests,
        "tokens_per_sec_ratio": round(tps_ratio, 3),
        "ttft_ratio": round(ttft_ratio, 3),
        "unix_ttft_mean_s": round(unix["ttft_mean_s"], 4),
        "tcp_ttft_mean_s": round(tcp["ttft_mean_s"], 4),
        "unix_tokens_per_sec": round(unix["tokens_per_sec"], 1),
        "tcp_tokens_per_sec": round(tcp["tokens_per_sec"], 1),
        # transport must not touch the sampled streams: same seeds,
        # same tokens, bit for bit
        "bit_identical": tcp["streams"] == unix["streams"],
        "compile_s": {
            "unix": round(unix["compile_s"], 1),
            "tcp": round(tcp["compile_s"], 1),
        },
        "platform": "host",
    }


def _transport_overhead_safe() -> dict:
    """_transport_overhead_bench that degrades to an error record
    instead of killing the run (it spawns serve subprocesses)."""
    try:
        return _transport_overhead_bench()
    except Exception as e:
        return {"phase": "transport-overhead", "error": repr(e)[:300]}


def _flight_overhead_bench() -> dict:
    """Armed vs disarmed flight recorder on real serving: the cost of
    the always-on black box (progen_tpu/telemetry/flight.py — an
    EMIT_TAPS hook that appends every telemetry record into a bounded
    in-memory ring) on the two client-visible numbers, streamed
    tokens/s and decode ITL p99.

    Two REAL ``cli/serve`` subprocesses (smoke shapes, pinned to CPU so
    the phase never fights the suite's chip claim) serve the identical
    request set over a unix socket — once with ``--flight_dir`` armed,
    once without — with one warmup request paying the compile outside
    each measured window. Model compute and transport are identical on
    both sides, so the ratios isolate the tap. Headline ``value`` =
    min(armed/disarmed tokens-per-sec ratio, disarmed/armed ITL-p99
    ratio) — the conservative parity number, ~1.0 when the recorder is
    free; the forensics contract is that it stays within ~1% of free,
    and the bench gate ratchets it (``--metric flight_overhead_ratio``).
    Host-side by construction: honest on any runner, which is why
    tier1.yml can enforce it."""
    import select
    import signal as _signal
    import socket
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np
    from flax.core import meta

    from progen_tpu.checkpoint import Package, get_checkpoint_fns
    from progen_tpu.config import ProGenConfig
    from progen_tpu.models.progen import ProGen

    n_requests = 8
    gen_length = 24
    config = ProGenConfig(
        num_tokens=256, dim=32, seq_len=32, depth=2, window_size=8,
        global_mlp_depth=1, heads=2, dim_head=16, ff_mult=2,
        dtype="float32",
    )

    def _measure(side, armed, root, ck):
        """One serve subprocess + one unix-socket client; returns
        tokens/s, ITL p99, and the (id -> [(index, token)]) streams."""
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PROGEN_CHAOS", None)
        env["PYTHONPATH"] = f"{_REPO}{os.pathsep}" + env.get(
            "PYTHONPATH", ""
        )
        spath = str(root / f"{side}.sock")
        args = [
            sys.executable, "-m", "progen_tpu.cli.serve",
            "--checkpoint_path", str(ck),
            "--max-slots", "4", "--max-queue", "32", "--max-len", "32",
            "--journal_dir", str(root / f"jd_{side}"),
            "--socket", spath,
        ]
        if armed:
            args += ["--flight_dir", str(root / f"flight_{side}")]
        err_path = root / f"{side}.err"
        proc = subprocess.Popen(
            args, stdout=subprocess.DEVNULL,
            stderr=open(err_path, "w"), env=env,
        )
        try:
            deadline = time.time() + 180
            while time.time() < deadline and not os.path.exists(spath):
                if proc.poll() is not None:
                    raise RuntimeError(
                        f"serve died: {err_path.read_text()[-2000:]}"
                    )
                time.sleep(0.2)
            if not os.path.exists(spath):
                raise RuntimeError(f"{side} serve never listened")

            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            sock.connect(spath)
            state = {"buf": b""}

            def send_req(obj):
                sock.sendall(json.dumps(obj).encode() + b"\n")

            def pump_until_done(want, timeout_s):
                events, got = [], set()
                stop = time.time() + timeout_s
                while time.time() < stop and not want <= got:
                    r, _, _ = select.select([sock], [], [], 0.5)
                    if not r:
                        continue
                    data = sock.recv(65536)
                    if not data:
                        break
                    state["buf"] += data
                    *full, state["buf"] = state["buf"].split(b"\n")
                    now = time.perf_counter()
                    for raw in full:
                        if not raw.strip():
                            continue
                        ev = json.loads(raw)
                        ev["_t"] = now
                        events.append(ev)
                        if ev.get("event") == "done":
                            got.add(ev["id"])
                if not want <= got:
                    raise RuntimeError(
                        f"{side}: undone after {timeout_s}s: "
                        f"{sorted(want - got)}"
                    )
                return events

            t0 = time.perf_counter()
            send_req({"id": "warm", "prime": "MKV", "length": 12,
                      "seed": 1})
            pump_until_done({"warm"}, 300)
            compile_s = time.perf_counter() - t0
            _mark(f"flight {side}: warm in {compile_s:.1f}s")

            submits = {}
            for i in range(n_requests):
                rid = f"r{i}"
                submits[rid] = time.perf_counter()
                send_req({"id": rid, "prime": "MKV",
                          "length": gen_length, "seed": 70 + i})
            events = pump_until_done(set(submits), 300)

            arrivals, streams, n_tokens = {}, {}, 0
            for ev in events:
                if ev.get("event") != "token":
                    continue
                n_tokens += 1
                arrivals.setdefault(ev["id"], []).append(ev["_t"])
                streams.setdefault(ev["id"], []).append(
                    (ev["index"], ev["token"])
                )
            wall = max(ev["_t"] for ev in events) - min(submits.values())
            itl = [
                b - a
                for ts in arrivals.values()
                for a, b in zip(ts, ts[1:])
                if b > a  # same-recv batches carry one stamp
            ]
            sock.close()
            return {
                "tokens_per_sec": n_tokens / max(wall, 1e-9),
                "itl_p99_s": (
                    float(np.percentile(itl, 99)) if itl else 0.0
                ),
                "tokens": n_tokens,
                "streams": streams,
                "compile_s": compile_s,
            }
        finally:
            if proc.poll() is None:
                proc.send_signal(_signal.SIGTERM)  # graceful drain
                try:
                    proc.wait(timeout=60)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait(timeout=10)

    with tempfile.TemporaryDirectory() as td:
        root = Path(td)
        model = ProGen(config)
        variables = model.init(
            jax.random.PRNGKey(0),
            jnp.zeros((1, config.seq_len), jnp.int32),
        )
        params = meta.unbox(variables)["params"]
        _, _, save = get_checkpoint_fns(str(root / "ck"))
        save(Package(0, {"params": params}, config.to_dict(),
                     "flight-bench"))
        _mark(f"flight: checkpoint saved, {n_requests} reqs/side")

        # interleave-free A/B: disarmed first (the baseline), then armed
        off = _measure("disarmed", False, root, root / "ck")
        on = _measure("armed", True, root, root / "ck")

    tps_ratio = on["tokens_per_sec"] / max(off["tokens_per_sec"], 1e-9)
    itl_ratio = off["itl_p99_s"] / max(on["itl_p99_s"], 1e-9)
    value = min(tps_ratio, itl_ratio)
    _mark(f"flight: tps_ratio={tps_ratio:.3f} itl_ratio={itl_ratio:.3f}")
    return {
        "phase": "flight-overhead",
        "metric": "flight_overhead_ratio",
        "value": round(value, 3),
        "host_side": True,
        "timing_suspect": False,
        "config": "smoke-serve32",
        "n_requests": n_requests,
        "tokens_per_sec_ratio": round(tps_ratio, 3),
        "itl_p99_ratio": round(itl_ratio, 3),
        "disarmed_tokens_per_sec": round(off["tokens_per_sec"], 1),
        "armed_tokens_per_sec": round(on["tokens_per_sec"], 1),
        "disarmed_itl_p99_s": round(off["itl_p99_s"], 5),
        "armed_itl_p99_s": round(on["itl_p99_s"], 5),
        # the ring tap must not touch the sampled streams: same seeds,
        # same tokens, bit for bit
        "bit_identical": on["streams"] == off["streams"],
        "compile_s": {
            "disarmed": round(off["compile_s"], 1),
            "armed": round(on["compile_s"], 1),
        },
        "platform": "host",
    }


def _decode_int8_bench() -> dict:
    """Int8 weight-quantized decode (ops/quant.py, --int8 on the serve
    CLI) vs the full-precision engine built from the SAME params: decode
    tokens/s for each, the speedup, greedy-window token agreement, and
    the calibration report the engine computed at load. Decode is
    HBM-bandwidth-bound, so the win only shows on chip; off-TPU smoke
    shapes prove function and agreement, not the bandwidth claim."""
    import jax
    import jax.numpy as jnp
    from flax import linen as nn

    from progen_tpu.data.tokenizer import encode_tokens
    from progen_tpu.models.progen import ProGen
    from progen_tpu.serving import ServeEngine

    on_tpu = _is_tpu_platform(jax.devices()[0].platform)
    config = (
        _load_config("tiny", seq_len=512)
        if on_tpu
        else _load_config("smoke")
    )
    max_slots = 8 if on_tpu else 4
    steps = 64 if on_tpu else 16
    model = ProGen(config)
    tokens = jnp.zeros((1, config.seq_len), jnp.int32)
    params = nn.meta.unbox(
        jax.jit(model.init)(jax.random.PRNGKey(0), tokens)["params"]
    )
    prime = jnp.asarray(encode_tokens("[tax=Mammalia] #"), jnp.int32)
    gen_len = min(int(config.seq_len),
                  int(prime.shape[0]) + 1 + steps + 8)

    streams: dict = {}
    results: dict = {}
    engines: dict = {}
    for label in ("fp", "int8"):
        _mark(f"decode-int8: building {label} engine")
        t0 = time.perf_counter()
        eng = ServeEngine(model, params, max_slots=max_slots,
                          max_len=config.seq_len,
                          quantize_int8=(label == "int8"))
        # same keys per slot in both engines -> streams comparable
        for s in range(max_slots):
            eng.prefill(s, prime, gen_len,
                        key=jax.random.PRNGKey(7 + s))
        eng.decode_step()  # warmup: pays the decode-step compile
        _account("compile", time.perf_counter() - t0)
        seq = []
        live_tokens = 0
        t0 = time.perf_counter()
        for _ in range(steps):
            sampled, was_live, _fin = eng.decode_step()
            seq.append((sampled, was_live))
            live_tokens += int(was_live.sum())
        wall = time.perf_counter() - t0
        _account("step", wall)
        streams[label] = seq
        engines[label] = eng
        results[label] = {
            "tokens_per_sec": round(live_tokens / max(wall, 1e-9), 1),
            "live_tokens": live_tokens,
            "wall_s": wall,
        }
        _mark(f"decode-int8: {label} "
              f"{results[label]['tokens_per_sec']} tok/s")

    agree = total = 0
    for (sa, la), (sb, lb) in zip(streams["fp"], streams["int8"]):
        both = la & lb
        total += int(both.sum())
        agree += int((sa[both] == sb[both]).sum())

    report = dict(engines["int8"].quant_report or {})
    report.pop("leaves", None)  # per-leaf detail stays in the engine log

    from progen_tpu import profiling as _prof

    peak = _prof.peak_flops(jax.devices()[0])
    fwd_tok = _prof.flops_per_token(config) / 3
    guard = _suspect_fields(
        results["fp"]["live_tokens"] * fwd_tok,
        results["fp"]["wall_s"], peak,
    )
    return {
        "phase": "decode-int8",
        "timing_suspect": guard["timing_suspect"],
        "implied_device_tflops": guard["implied_device_tflops"],
        "config": "tiny-seq512" if on_tpu else "smoke",
        "max_slots": max_slots,
        "decode_steps": steps,
        "int8_tokens_per_sec": results["int8"]["tokens_per_sec"],
        "fp_tokens_per_sec": results["fp"]["tokens_per_sec"],
        "speedup": round(
            results["int8"]["tokens_per_sec"]
            / max(results["fp"]["tokens_per_sec"], 1e-9), 2
        ),
        "token_agreement": round(agree / max(total, 1), 4),
        "tokens_compared": total,
        "calibration": report,
        "platform": jax.devices()[0].platform,
        **_hbm_stats(),
    }


def _workload_model():
    """(model, params, config) for the protein-design workload phases —
    the decode-tiny sizing rule: half-context tiny on TPU, smoke on CPU,
    random params (throughput does not care what the weights say)."""
    import jax
    import jax.numpy as jnp
    from flax import linen as nn

    from progen_tpu.models.progen import ProGen

    on_tpu = _is_tpu_platform(jax.devices()[0].platform)
    config = (
        _load_config("tiny", seq_len=512)
        if on_tpu
        else _load_config("smoke")
    )
    model = ProGen(config)
    params = nn.meta.unbox(
        jax.jit(model.init)(
            jax.random.PRNGKey(0), jnp.zeros((1, config.seq_len), jnp.int32)
        )["params"]
    )
    return model, params, config, on_tpu


def _batch_score_bench() -> dict:
    """Bulk perplexity-scoring throughput (workloads/scoring.py): a
    synthetic candidate set through the bucketed compile-once score_step
    into sharded JSONL. The workload's own time ledger separates compile
    from steady-state, so seqs/s and goodput are the steady answer a
    screening run would see."""
    import shutil
    import tempfile

    import jax

    from progen_tpu import profiling
    from progen_tpu.workloads import AA_ALPHABET, run_batch_score

    model, params, config, on_tpu = _workload_model()
    rng = np.random.default_rng(0)
    n_seqs = 256 if on_tpu else 64
    aas = np.array(list(AA_ALPHABET))
    records = []
    for i in range(n_seqs):
        n = int(rng.integers(config.seq_len // 4, config.seq_len - 3))
        seq = "".join(rng.choice(aas, size=n))
        records.append((f"b{i}", ("# " + seq).encode("utf-8")))

    out_dir = tempfile.mkdtemp(prefix="bench-score-")
    try:
        summary = run_batch_score(
            model, params, records, out_dir,
            batch_size=8, logprobs=False, resume=False,
        )
    finally:
        shutil.rmtree(out_dir, ignore_errors=True)

    peak = profiling.peak_flops(jax.devices()[0])
    fwd_tok = profiling.flops_per_token(config) / 3  # fwd-only convention
    step_s = max(summary["times"]["step"], 1e-9)
    guard = _suspect_fields(summary["tokens"] * fwd_tok / step_s, 1.0, peak)
    return {
        "phase": "batch-score",
        "config": "tiny-seq512" if on_tpu else "smoke",
        "n_scored": summary["n_scored"],
        "seqs_per_sec": round(summary["n_scored"] / step_s, 1),
        "tokens_per_sec": round(summary["tokens"] / step_s, 1),
        "goodput_pct": summary["goodput_pct"],
        "batches": summary["batches"],
        "times": summary["times"],
        **guard,
        "platform": jax.devices()[0].platform,
        **_hbm_stats(),
    }


def _mutagenesis_bench() -> dict:
    """Vmapped deep-mutational-scan throughput (workloads/mutagenesis.py):
    every L x 20 point mutant of one synthetic protein in one compiled
    program. First call is billed to compile; the re-scan of a different
    region (same shapes, traced operands) is the steady number."""
    import jax

    from progen_tpu import profiling
    from progen_tpu.workloads import AA_ALPHABET, mutagenesis_scan

    model, params, config, on_tpu = _workload_model()
    rng = np.random.default_rng(0)
    L = min(96 if on_tpu else 48, config.seq_len - 8)
    sequence = "".join(rng.choice(np.array(list(AA_ALPHABET)), size=L))
    half = list(range(L // 2))

    t0 = time.perf_counter()
    mutagenesis_scan(model, params, sequence, positions=half, chunk=32)
    compile_s = time.perf_counter() - t0
    # same shapes, different positions: re-executes without retracing
    other = list(range(L // 2, L - (L % 2)))[: len(half)]
    t0 = time.perf_counter()
    report = mutagenesis_scan(model, params, sequence, positions=other,
                              chunk=32)
    dt = time.perf_counter() - t0

    n_mutants = report["nll"].size
    peak = profiling.peak_flops(jax.devices()[0])
    fwd_tok = profiling.flops_per_token(config) / 3
    # every mutant row is a full seq_len forward (padded training layout)
    guard = _suspect_fields(
        n_mutants * config.seq_len * fwd_tok / max(dt, 1e-9), 1.0, peak
    )
    return {
        "phase": "mutagenesis",
        "config": "tiny-seq512" if on_tpu else "smoke",
        "seq_len_scanned": L,
        "n_mutants": n_mutants,
        "mutants_per_sec": round(n_mutants / max(dt, 1e-9), 1),
        "scan_s": round(dt, 3),
        "compile_s": round(compile_s, 1),
        **guard,
        "platform": jax.devices()[0].platform,
        **_hbm_stats(),
    }


def _data_io_bench() -> dict:
    """Host-side input-pipeline throughput: the from-scratch TFRecord
    codec (write + parse) and the C++ engine vs the pure-Python path, plus
    native batch collation — at Uniref50-like record sizes. No chip
    involved (platform "host", exempt from the TPU gate): this is the
    runtime the reference delegates to tf.data, measured as the framework
    component it is."""
    import gzip
    import tempfile

    rng = np.random.default_rng(0)
    n_rec = 20000
    seqs = [
        bytes(rng.integers(65, 90, size=int(L)).astype(np.uint8))
        for L in rng.integers(200, 1024, size=n_rec)
    ]
    total_mb = sum(len(s) for s in seqs) / 1e6

    from progen_tpu.data import _native
    from progen_tpu.data.dataset import collate as py_collate
    from progen_tpu.data.tfrecord import read_tfrecords, tfrecord_writer

    with tempfile.TemporaryDirectory() as td:
        path = f"{td}/bench.{n_rec}.tfrecord.gz"
        t0 = time.perf_counter()
        with tfrecord_writer(path) as write:
            for s in seqs:
                write(s)
        t_write = time.perf_counter() - t0

        t0 = time.perf_counter()
        out = list(read_tfrecords(path))
        t_py = time.perf_counter() - t0
        assert len(out) == n_rec and out[0] == seqs[0]

        lib = _native.load()
        t_cc = None
        if lib is not None:
            with gzip.open(path, "rb") as f:
                raw = f.read()
            t0 = time.perf_counter()
            out_cc = _native.parse_file(raw)
            t_cc = time.perf_counter() - t0
            assert list(out_cc) == out

        t0 = time.perf_counter()
        py_collate(out[:4096], 1024)
        t_collate = time.perf_counter() - t0

    return {
        "phase": "data-io",
        "host_side": True,
        "records": n_rec,
        "payload_mb": round(total_mb, 1),
        "write_mb_s": round(total_mb / t_write, 1),
        "parse_py_records_s": round(n_rec / t_py, 0),
        "parse_py_mb_s": round(total_mb / t_py, 1),
        **(
            {
                "parse_native_records_s": round(n_rec / t_cc, 0),
                "parse_native_mb_s": round(total_mb / t_cc, 1),
                "native_speedup": round(t_py / t_cc, 2),
            }
            if t_cc is not None
            else {"native_speedup": None}
        ),
        "collate_4096x1024_ms": round(t_collate * 1e3, 1),
        "platform": "host",
    }


def _large_projection() -> dict:
    """ProGen-large (1.2B) sharding study — no chip run: the optimizer
    state alone (f32 params + AdamW m/v = 12 B/param) plus transient f32
    grads exceeds one v5e chip's 16 GB HBM, so the BASELINE.md target for
    this config is the v5e-64 plan, reported from closed-form math."""
    from progen_tpu import profiling
    from progen_tpu.config import ProGenConfig, load_toml_config

    cfg = ProGenConfig.from_dict(
        load_toml_config(str(_REPO / "configs" / "model" / "large.toml"))
    )
    p = cfg.num_params()
    state_bytes = 12 * p      # f32 params + Adam m + v
    grads_bytes = 4 * p       # transient f32 grads (donated step)
    fpt = profiling.flops_per_token(cfg)
    peak = 197e12             # v5e bf16
    # v5e-64 mesh plan: model=8 (qkv/mlp/vocab sharded), data=8
    model_ax, data_ax = 8, 8
    per_chip_state = (state_bytes + grads_bytes) / model_ax
    # --zero1: AdamW m+v (8 B/param) shard over data as well
    per_chip_zero1 = (
        4 * p / model_ax            # f32 params
        + 8 * p / (model_ax * data_ax)  # moments
        + grads_bytes / model_ax
    )
    target_mfu = 0.45
    projected_tps_chip = target_mfu * peak / fpt
    return {
        "phase": "large-projection",
        "config": "large",
        "num_params": p,
        "state_plus_grads_gb": round((state_bytes + grads_bytes) / 2**30, 2),
        "hbm_fit_single_chip": False,
        "mesh_plan": {"data": 8, "model": model_ax, "seq": 1},
        "per_chip_state_gb_at_model8": round(per_chip_state / 2**30, 2),
        "per_chip_state_gb_at_model8_zero1": round(
            per_chip_zero1 / 2**30, 2
        ),
        "flops_per_token": fpt,
        "projected_tokens_per_sec_per_chip_at_45pct_mfu": round(
            projected_tps_chip, 1
        ),
        "note": "single v5e chip cannot hold 1.2B x 16B/param; "
                "remat+scan_layers in large.toml; TP rules shard "
                "qkv/mlp/vocab over `model`, GSPMD inserts one all-reduce "
                "per block (partition.py rule table)",
    }


def _best_archived_tpu_headline() -> dict | None:
    """Newest honest (non-timing_suspect) TPU train-tiny record from
    BENCH_DETAIL.json and the in-repo BENCH_DETAIL_TPU_*.json archives —
    attached to fallback output as provenance (NOT as the fallback's own
    metric: the fallback never claims a number it didn't measure)."""
    best = None
    paths = [_DETAIL_PATH, *sorted(glob.glob(str(_REPO / "BENCH_DETAIL_TPU_*.json")))]
    for path in paths:
        try:
            detail = json.loads(Path(path).read_text())
        except (OSError, json.JSONDecodeError):
            continue
        if detail.get("platform") != "tpu":
            continue
        for p in detail.get("phases", []):
            if (
                p.get("phase") == "train-tiny"
                and "error" not in p
                and not p.get("timing_suspect")
            ):
                best = {
                    "value": p["tokens_per_sec_per_chip"],
                    "unit": "tokens/s/chip",
                    "mfu": p["mfu"],
                    "source": Path(path).name,
                    "run": detail.get("run", ""),
                }
    return best


def _data_io_safe() -> dict:
    """_data_io_bench that degrades to an error record instead of killing
    the run (it builds the C++ engine on first use)."""
    try:
        return _data_io_bench()
    except Exception as e:
        return {"phase": "data-io", "error": repr(e)[:300]}


def _cpu_smoke() -> dict:
    """Off-TPU functional smoke (dead relay / CPU host) — the shared
    _train_bench flow at smoke shapes, re-keyed under a DISTINCT metric
    name so it never poisons the TPU baseline chain. When an honest
    archived TPU headline exists it rides along as ``last_tpu_record``
    so a dead-relay round still surfaces the measured baseline (clearly
    marked as archived, not re-measured)."""
    res = _train_bench("smoke")
    archived = _best_archived_tpu_headline()
    return {
        "metric": "cpu_fallback_smoke_tokens_per_sec",
        "value": res["tokens_per_sec_per_chip"],
        "unit": "tokens/s/chip",
        "vs_baseline": 1.0,
        "mfu": res["mfu"],
        "num_params": res["num_params"],
        "chips": res["chips"],
        "step_ms": res["step_ms"],
        "config": "cpu-fallback smoke (dim=64 depth=2 seq=128 w=32) f32",
        "platform": res["platform"],
        **({"last_tpu_record": archived} if archived else {}),
    }


def run_phase(name: str) -> dict:
    if name.startswith("kernel-fused-w"):
        return _fused_kernel_bench(int(name[len("kernel-fused-w"):]))
    if name.startswith("kernel-w"):
        # "kernel-w<W>" or "kernel-w<W>-n<N>" (long-context shape variant)
        spec = name[len("kernel-w"):].split("-n")
        return _kernel_bench(
            int(spec[0]), int(spec[1]) if len(spec) > 1 else 1024
        )
    if name == "train-tiny-pallas":
        # scan_layers: one scanned body = ~3 embedded Mosaic kernel
        # instances instead of the unrolled stack's 12+ — each is a
        # separate slow remote compile on this relay (the round-3 720s
        # timeout). Compare against train-tiny-scan, its XLA twin with
        # the same layer structure.
        return _train_bench("tiny", use_pallas=True,
                            extra_overrides={"scan_layers": True})
    if name == "train-tiny-scan":
        return _train_bench("tiny", phase_suffix="-scan",
                            extra_overrides={"scan_layers": True})
    if name == "profile-tiny":
        # on-chip trace artifact for offline schedule analysis (where the
        # step's time actually goes — the MFU-gap question cost_analysis
        # can't answer). Loses its timing honesty to profiler overhead,
        # which is fine: this phase's product is the trace, not a number.
        prof = str(_LOG_DIR.parent / "profiles" / "tiny")
        res = _train_bench("tiny", recipe=(4, 4, 3),
                           phase_suffix="-profile", profile_dir=prof)
        res["phase"] = "profile-tiny"  # match the scheduled phase name
        res["trace_dir"] = prof
        res["timing_suspect"] = True  # profiler overhead: not a baseline
        return res
    if name == "train-tiny-bs32":
        # framework-ceiling companion to the recipe-parity headline: same
        # model, micro-batch 32 / no accumulation — MFU at a batch the
        # chip can actually fill (the reference recipe's 4x4 microbatches
        # underfeed a v5e; both numbers are reported side by side)
        return _train_bench("tiny", recipe=(1, 32, 10),
                            phase_suffix="-bs32")
    if name == "train-long8k-xla":
        return _train_bench("long8k", use_pallas=False)
    if name.startswith("train-"):
        return _train_bench(name[len("train-"):])
    if name == "calib-matmul":
        return _calib_bench()
    if name == "decode-tiny":
        return _decode_bench()
    if name == "decode-serve":
        return _decode_serve_bench()
    if name == "decode-admit-stall":
        return _decode_admit_stall_bench()
    if name == "transport-overhead":
        return _transport_overhead_bench()
    if name == "flight-overhead":
        return _flight_overhead_bench()
    if name == "decode-int8":
        return _decode_int8_bench()
    if name == "batch-score":
        return _batch_score_bench()
    if name == "mutagenesis":
        return _mutagenesis_bench()
    if name == "sustain-base":
        return _sustain_bench()
    if name == "sgu-mix":
        return _sgu_mix_bench()
    if name == "large-projection":
        return _large_projection()
    if name == "data-io":
        return _data_io_bench()
    raise ValueError(f"unknown phase {name}")


# --------------------------------------------------------------------------
# orchestrator
# --------------------------------------------------------------------------


def _write_detail(detail: dict, path: Path | None = None) -> None:
    try:
        (path or _DETAIL_PATH).write_text(json.dumps(detail, indent=1))
    except OSError as e:  # never let bookkeeping kill the bench
        print(f"[bench] detail write failed: {e}", file=sys.stderr)


def _has_tpu_evidence(detail: dict) -> bool:
    """True only for ON-CHIP phase results: the closed-form
    large-projection study, host-side phases (data-io), and metric-only
    smoke entries run without a chip, so they never count as evidence."""
    return detail.get("platform") == "tpu" and any(
        "error" not in p
        and not p.get("host_side")
        and p.get("phase") not in (None, "large-projection")
        for p in detail.get("phases", [])
    )


def _write_detail_guarded(detail: dict) -> None:
    """Detail write that can never replace successful TPU evidence with a
    record holding none (CPU fallback, or a run where the relay died
    before any phase landed — round 3 hit both). Evidence-free records
    divert to BENCH_DETAIL_FALLBACK.json."""
    try:
        prior = json.loads(_DETAIL_PATH.read_text())
    except (OSError, json.JSONDecodeError):
        prior = None
    if prior and _has_tpu_evidence(prior) and not _has_tpu_evidence(detail):
        _write_detail(
            detail, path=_DETAIL_PATH.with_name("BENCH_DETAIL_FALLBACK.json")
        )
    else:
        _write_detail(detail)


def _phase_log_tail(name: str, n: int = 1200) -> str:
    # seek-based tail: a wedged phase can spew hundreds of MB of libtpu
    # diagnostics; never load the whole file for 1200 chars
    try:
        with open(_LOG_DIR / f"{name}.log", "rb") as f:
            f.seek(0, os.SEEK_END)
            f.seek(max(f.tell() - n, 0))
            return f.read().decode(errors="replace")
    except OSError:
        return ""


def _run_phase_subprocess(name: str, timeout: float):
    """One phase in its own process (own chip claim, own crash domain).
    SIGTERM then SIGKILL on timeout — kinder to the relay than an instant
    kill mid-claim. The child's stderr streams to runs/bench_logs/<name>.log
    so a killed phase leaves its progress-marker trail ([bench-mark] lines
    from _mark) for post-mortem — round 3's tiny-pallas timeout was
    undiagnosable without this."""
    _LOG_DIR.mkdir(parents=True, exist_ok=True)
    log_path = _LOG_DIR / f"{name}.log"
    with open(log_path, "w") as log:
        proc = subprocess.Popen(
            [sys.executable, str(_REPO / "bench.py"), "_phase", name],
            stdout=subprocess.PIPE,
            stderr=log,
            cwd=str(_REPO),
            text=True,
            env={
                **os.environ,
                "BENCH_REQUIRE_TPU": "1",
                # child self-deadline below the parent kill: a SIGALRM
                # raised at Python level unwinds and releases the chip
                # claim cleanly, where SIGTERM/SIGKILL mid-claim has
                # wedged the relay twice (round 3 runs a and b)
                "BENCH_PHASE_DEADLINE_SEC": str(max(int(timeout) - 30, 60)),
            },
        )
        try:
            out, _ = proc.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            proc.terminate()
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
            return {
                "phase": name,
                "error": f"timeout after {timeout:.0f}s",
                "log_tail": _phase_log_tail(name),
            }
    if proc.returncode != 0:
        return {
            "phase": name,
            "error": f"exit {proc.returncode}",
            "log_tail": _phase_log_tail(name),
        }
    for line in reversed(out.strip().splitlines()):
        try:
            res = json.loads(line)
        except json.JSONDecodeError:
            continue
        if "error" in res and "log_tail" not in res:
            # e.g. the child's self-deadline record: attach the marker
            # trail the same as the kill/exit paths do
            res["log_tail"] = _phase_log_tail(name)
        return res
    return {"phase": name, "error": "no JSON in phase output"}


def _headline_from(res: dict, prior: float | None) -> dict:
    per_chip = res["tokens_per_sec_per_chip"]
    return {
        "metric": "train_tokens_per_sec_per_chip",
        "value": per_chip,
        "unit": "tokens/s/chip",
        "vs_baseline": round(per_chip / prior, 3) if prior else 1.0,
        "mfu": res["mfu"],
        "num_params": res["num_params"],
        "chips": res["chips"],
        "step_ms": res["step_ms"],
        "config": "progen-tiny (dim=512 depth=12 seq=1024 w=256) bf16",
        "implied_device_tflops": res.get("implied_device_tflops"),
        "timing_suspect": res.get("timing_suspect", False),
        "platform": "tpu",
    }


def main() -> None:
    budget = float(os.environ.get("BENCH_BUDGET_SEC", "3000"))
    started = time.perf_counter()
    resume = "--resume" in sys.argv
    # span trail for the whole suite: a B with no E in
    # runs/bench_logs/events.jsonl names the phase the run died in
    from progen_tpu import telemetry

    _LOG_DIR.mkdir(parents=True, exist_ok=True)
    telemetry.configure(path=_LOG_DIR / "events.jsonl")
    # one probe serves liveness + platform (phase children skip re-probing
    # via BENCH_REQUIRE_TPU — a dead relay there surfaces as a timeout)
    on_tpu = _is_tpu_platform(_probe_platform())

    detail: dict = {
        "schema": "bench-suite-v1",
        "platform": "tpu" if on_tpu else "cpu-fallback",
        "phases": [],
    }
    done: set = set()
    if resume and on_tpu:
        # rerun only missing/errored phases, keeping prior clean results
        # (used by the relay-recovery path after a mid-suite wedge)
        try:
            prior_detail = json.loads(_DETAIL_PATH.read_text())
        except (OSError, json.JSONDecodeError):
            prior_detail = None
        if prior_detail and _has_tpu_evidence(prior_detail):
            # a timing_suspect phase (dispatch-rate artifact, round 3a) is
            # NOT a keepable result: rerun it rather than resume a number
            # the honest-timing machinery already rejected
            detail["phases"] = [
                p for p in prior_detail["phases"]
                if p.get("phase")  # drops the phase-less _cpu_smoke record
                and "error" not in p
                and not p.get("timing_suspect")
                and (
                    _is_tpu_platform(p.get("platform", "tpu"))
                    or p.get("host_side")  # chip-free phases keep anywhere
                )
                and p["phase"] != "large-projection"
            ]
            done = {p["phase"] for p in detail["phases"]}

    if not on_tpu:
        _force_cpu()
        result = _cpu_smoke()
        detail["phases"].append(result)
        detail["phases"].append(_data_io_safe())
        detail["phases"].append(_large_projection())
        _write_detail_guarded(detail)
        print(json.dumps(result), flush=True)
        return

    headline = None
    prior = _prior_round_value()
    for p in detail["phases"]:
        if p.get("phase") == "train-tiny":
            headline = _headline_from(p, prior)  # resumed prior headline
            # flush now, same wedge-insurance as the fresh-run path: if
            # the first rerun phase wedges the relay and we get killed,
            # the prior clean headline is already on stdout
            print(json.dumps(headline), flush=True)
    for name, timeout in _PHASES:
        if name in done:
            continue
        remaining = budget - (time.perf_counter() - started)
        if remaining < 90:
            detail["phases"].append(
                {"phase": name, "error": "skipped: budget exhausted"}
            )
            continue
        with telemetry.span(f"bench/{name}", timeout=timeout):
            res = _run_phase_subprocess(name, min(timeout, remaining))
        if "error" not in res and not res.get("host_side") \
                and not _is_tpu_platform(res.get("platform", "tpu")):
            # belt-and-suspenders vs BENCH_REQUIRE_TPU: a fallback result
            # must never be recorded as TPU suite evidence
            res = {
                "phase": name,
                "error": f"phase ran on {res.get('platform')}, not tpu",
            }
        detail["phases"].append(res)
        _write_detail_guarded(detail)
        print(f"[bench] {name}: {json.dumps(res)[:300]}", file=sys.stderr)

        if name == "train-tiny" and "error" not in res:
            headline = _headline_from(res, prior)
            # print + flush NOW: if a later phase wedges the relay and the
            # driver kills us, the headline is already on stdout
            print(json.dumps(headline), flush=True)
        if "error" in res and not _tpu_probe_ok(120):
            # one cooldown+retry before declaring the relay dead: a probe
            # right after a killed phase can fail transiently while the
            # relay tears down that phase's claim
            time.sleep(60)
            if not _tpu_probe_ok(120):
                detail["relay_died_after"] = name
                _write_detail_guarded(detail)
                break
            detail.setdefault("relay_recovered_after", []).append(name)

    if "data-io" not in done:
        detail["phases"].append(_data_io_safe())
    detail["phases"].append(_large_projection())
    _write_detail_guarded(detail)

    if headline is None:
        # tiny phase failed: fall back to an honest CPU smoke so the driver
        # still gets a record (platform key distinguishes it)
        _force_cpu()
        result = _cpu_smoke()
        detail["phases"].append(result)
        _write_detail_guarded(detail)
        print(json.dumps(result), flush=True)
        return

    summary = {}
    for res in detail["phases"]:
        ph = res.get("phase", "?")
        if "error" in res:
            summary[ph] = res["error"][:60]
        elif ph.startswith("kernel") or ph == "sgu-mix":
            summary[ph] = {
                "fwd_speedup": res["fwd_speedup"],
                "bwd_speedup": res["bwd_speedup"],
            }
        elif ph.startswith("train") and ph != "train-tiny":
            summary[ph] = {
                "tps_chip": res["tokens_per_sec_per_chip"],
                "mfu": res["mfu"],
            }
        elif ph == "decode-tiny":
            summary[ph] = {
                "kv_tps": res["kv_cache_tokens_per_sec"],
                "speedup": res["speedup"],
            }
        elif ph == "decode-admit-stall":
            summary[ph] = {
                "stall_ratio": res["value"],
                "prefix_cache_speedup": res["prefix_cache_speedup"],
            }
            # carry both serving ratios on the headline so the gate
            # chains see them even in rounds whose parsed metric is the
            # train number (the last_tpu_record idiom)
            headline["serve_admit_stall_ratio"] = res["value"]
            headline["serve_prefix_cache_speedup"] = res[
                "prefix_cache_speedup"
            ]
        elif ph == "transport-overhead":
            summary[ph] = {
                "parity": res["value"],
                "bit_identical": res["bit_identical"],
            }
            # same carry idiom: keep the transport record on the chain
            # even in rounds whose parsed metric is the train number
            headline["serve_transport_parity"] = res["value"]
        elif ph == "flight-overhead":
            summary[ph] = {
                "parity": res["value"],
                "bit_identical": res["bit_identical"],
            }
            # same carry idiom: keep the forensics record on the chain
            # even in rounds whose parsed metric is the train number
            headline["flight_overhead_ratio"] = res["value"]
        elif ph == "decode-int8":
            summary[ph] = {
                "int8_tps": res["int8_tokens_per_sec"],
                "speedup": res["speedup"],
                "agreement": res["token_agreement"],
            }
        elif ph == "calib-matmul":
            summary[ph] = {
                "achieved_tflops": res["achieved_tflops"],
                "mxu_efficiency": res["mxu_efficiency"],
            }
        elif ph == "data-io":
            summary[ph] = {
                "native_speedup": res.get("native_speedup"),
                "parse_py_mb_s": res.get("parse_py_mb_s"),
            }
    print(json.dumps({**headline, "suite": summary}), flush=True)


def kernel_main() -> None:
    _device_or_cpu_fallback()
    results = [_kernel_bench(256), _kernel_bench(512)]
    print(json.dumps({
        "metric": "pallas_vs_xla_local_attention",
        "results": results,
        "platform": results[0]["platform"],
    }))


def gate_main(argv: list) -> int:
    """``python bench.py gate``: ratchet a headline tokens/s value
    against the best prior round in the BENCH_r0N.json trajectory
    (progen_tpu/utils/bench_gate). Value sources, highest precedence
    first: ``--value N`` (synthetic / pre-measured), ``--from-json FILE``
    (a bench headline or phase JSON carrying ``value``), else a fresh
    CPU-fallback smoke measurement. Exit 0 within tolerance of the best
    prior (or no prior: the value sets the bar), 1 on regression, 2 on
    usage errors — the contract tier1.yml enforces."""
    import argparse

    from progen_tpu.utils.bench_gate import SERVE_CHAINS, run_gate

    ap = argparse.ArgumentParser(prog="bench.py gate")
    ap.add_argument("--value", type=float, default=None)
    ap.add_argument("--from-json", default=None)
    ap.add_argument(
        "--from-json-key", default="value",
        help="key to read from --from-json (default 'value'; e.g. "
             "'prefix_cache_speedup' from the decode-admit-stall phase "
             "JSON, which carries two gated numbers in one record)",
    )
    ap.add_argument("--metric",
                    choices=("cpu", "tpu", "auto") + SERVE_CHAINS,
                    default="cpu")
    ap.add_argument("--tolerance", type=float, default=0.2)
    args = ap.parse_args(argv)

    if args.value is not None:
        value, source = args.value, "--value"
    elif args.from_json:
        try:
            doc = json.loads(Path(args.from_json).read_text())
        except (OSError, ValueError) as e:
            print(f"gate: cannot read {args.from_json}: {e}",
                  file=sys.stderr)
            return 2
        key = args.from_json_key
        raw = doc.get(key) if isinstance(doc, dict) else None
        if raw is None and isinstance(doc, dict) \
                and isinstance(doc.get("parsed"), dict):
            raw = doc["parsed"].get(key)
        if raw is None:
            print(f"gate: no {key!r} in {args.from_json}",
                  file=sys.stderr)
            return 2
        value, source = float(raw), f"{args.from_json}:{key}"
    else:
        _force_cpu()
        value, source = _cpu_smoke()["value"], "fresh cpu smoke"
    try:
        report = run_gate(value, args.metric, args.tolerance, _REPO)
    except ValueError as e:
        print(f"gate: {e}", file=sys.stderr)
        return 2
    print(json.dumps({"source": source, **report}, indent=1))
    return 0 if report["ok"] else 1


def _load_repo_env() -> None:
    """The shipped .env (LIBTPU_INIT_ARGS etc.) must apply to benches the
    same as to the CLIs — otherwise the recorded numbers measure a
    different libtpu/XLA configuration than production training."""
    from progen_tpu.utils.env import load_env_file

    load_env_file(str(_REPO / ".env"))


if __name__ == "__main__":
    _load_repo_env()
    if len(sys.argv) > 2 and sys.argv[1] == "_phase":
        deadline = int(os.environ.get("BENCH_PHASE_DEADLINE_SEC", "0"))
        if deadline > 0:
            import signal

            def _deadline(signum, frame):
                # raising here (vs being SIGTERM'd by the parent) lets the
                # phase unwind Python frames and the PJRT client close its
                # chip claim; only helps when the hang is at Python level,
                # but that costs nothing and the kill path still backstops
                raise TimeoutError(
                    f"phase self-deadline after {deadline}s"
                )

            signal.signal(signal.SIGALRM, _deadline)
            signal.alarm(deadline)
            # stall watchdog below the SIGALRM horizon: when the phase
            # wedges (device hang, dead relay), all-thread stacks + the
            # open spans land in this child's stderr — the phase log the
            # parent tails into log_tail on the timeout kill — BEFORE
            # the alarm/kill destroys the evidence. _mark() beats it, so
            # it only fires when the progress trail actually stops.
            from progen_tpu.telemetry import StallWatchdog

            # escalate_after=2: if the stall survives two reports, the
            # third event snapshots device memory_stats + open spans —
            # the forensic record the SIGALRM kill would otherwise eat
            _WATCHDOG = StallWatchdog(
                max(60.0, deadline * 0.6), file=sys.stderr,
                escalate_after=2,
            ).start()
        # phase-child telemetry: spans + injected faults + the goodput
        # report land in the shared bench event stream (same file the
        # orchestrator writes its bench/<name> spans to — appends from
        # both processes are line-atomic)
        from progen_tpu import telemetry as _tel
        from progen_tpu.telemetry import GoodputLedger as _Ledger

        _LOG_DIR.mkdir(parents=True, exist_ok=True)
        _tel.configure(path=_LOG_DIR / "events.jsonl")
        _PHASE_LEDGER = _Ledger()
        try:
            if os.environ.get("BENCH_REQUIRE_TPU") == "1":
                # orchestrated child: the parent already probed; a dead
                # relay HANGS here and surfaces as the parent's phase
                # timeout, and a CPU fallback must NOT masquerade as a
                # TPU phase result
                import jax

                if not _is_tpu_platform(jax.devices()[0].platform):
                    print("BENCH_REQUIRE_TPU: backend is not TPU",
                          file=sys.stderr)
                    sys.exit(3)
            else:
                _device_or_cpu_fallback()
            result = run_phase(sys.argv[2])
            if deadline > 0:
                # cancel the self-deadline BEFORE teardown: PJRT-client
                # close over the relay can take seconds, and an alarm
                # firing mid-teardown would turn this valid result into
                # an "exit 1" the parent discards
                signal.alarm(0)
            # close the phase's goodput books: the report rides the phase
            # JSON (BENCH_DETAIL) and the event stream (export-trace
            # renders it as a counter track on the bench timeline)
            _gp = _PHASE_LEDGER.report()
            if isinstance(result, dict) and "error" not in result:
                result.setdefault("goodput", _gp)
            _tel.get_telemetry().emit({
                "ev": "goodput", "ts": time.time(),
                "phase": sys.argv[2], **_gp,
            })
            print(json.dumps(result))
        except TimeoutError as e:
            # clean-unwind path for the self-deadline: report as a phase
            # error (exit 0 so the parent parses the JSON, not the rc)
            print(json.dumps({"phase": sys.argv[2], "error": str(e)}))
    elif len(sys.argv) > 1 and sys.argv[1] == "kernel":
        kernel_main()
    elif len(sys.argv) > 1 and sys.argv[1] == "gate":
        sys.exit(gate_main(sys.argv[2:]))
    elif len(sys.argv) > 2 and sys.argv[1] == "--config":
        devs = _device_or_cpu_fallback()
        if not _is_tpu_platform(devs[0].platform) and sys.argv[2] != "smoke":
            # the real configs are minutes/step on a 1-core CPU fallback;
            # refuse rather than look hung (smoke stays runnable anywhere)
            print(
                f"--config {sys.argv[2]} needs a TPU backend "
                "(use --config smoke off-TPU)",
                file=sys.stderr,
            )
            sys.exit(2)
        print(json.dumps(_train_bench(sys.argv[2])))
    else:
        main()
