"""Throughput benchmark — training tokens/sec/chip + MFU on the real chip.

Runs the full donated train step (grad-accum scan + clip + masked AdamW) on
the flagship ProGen-tiny config (README example, BASELINE.md config 1) with
synthetic data, and prints ONE JSON line:
  {"metric": "train_tokens_per_sec_per_chip", "value": ..., "unit":
   "tokens/s/chip", "vs_baseline": ...}

vs_baseline: the reference publishes no numbers (BASELINE.md — README "(wip)",
no benchmarks/ dir), so the denominator is this repo's own recorded round-1
number when present (BENCH_r*.json), else 1.0 (i.e. the value itself is the
baseline being established).

MFU accounting (extra keys, PaLM convention): flops/token =
6*num_params + 12*depth*heads*dim_head*attn_ctx with attn_ctx = 2*window
(each query attends to [prev | current] window). Peak: v5e 197 TFLOP/s bf16,
v4 275, v5p 459; selected by device kind, default 197.
"""

from __future__ import annotations

import glob
import json
import time

import numpy as np


def _tpu_probe_ok(timeout: float = 180.0) -> bool:
    """Probe backend init in a SUBPROCESS: a dead axon relay makes
    jax.devices() hang (not raise), which would swallow the whole bench.
    Probed unconditionally — healthy backends (TPU or CPU-only hosts)
    answer in seconds and the probe process releases any chip claim on
    exit."""
    import subprocess
    import sys

    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout,
            capture_output=True,
        )
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def _device_or_cpu_fallback():
    """jax.devices() with a CPU fallback when the TPU backend is
    unreachable (dead relay: init HANGS, so the probe runs in a timed
    subprocess; plain init errors are caught too) — the 'platform' key in
    the emitted JSON distinguishes the outcomes."""
    import jax

    if not _tpu_probe_ok():
        import jax._src.xla_bridge as xb

        jax.config.update("jax_platforms", "cpu")
        xb._backend_factories.pop("axon", None)
        return jax.devices()
    try:
        return jax.devices()
    except RuntimeError:
        jax.config.update("jax_platforms", "cpu")
        return jax.devices()


def _prior_round_value() -> float | None:
    best = None
    for path in sorted(glob.glob("BENCH_r*.json")):
        try:
            rec = json.loads(open(path).read())
        except (OSError, json.JSONDecodeError):
            continue
        parsed = rec.get("parsed") if isinstance(rec, dict) else None
        if (
            isinstance(parsed, dict)
            and parsed.get("metric", "").startswith("train_tokens")
            and parsed.get("platform", "tpu") == "tpu"
        ):
            best = parsed.get("value", best)
    return best


def main() -> None:
    import jax

    _device_or_cpu_fallback()

    from progen_tpu.config import ProGenConfig
    from progen_tpu.models.progen import ProGen
    from progen_tpu.parallel.partition import make_mesh, put_batch
    from progen_tpu.training.optimizer import make_optimizer
    from progen_tpu.training.step import compile_train_step, init_train_state

    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        config = ProGenConfig(
            num_tokens=256,
            dim=512,
            depth=12,
            heads=8,
            dim_head=64,
            window_size=256,
            seq_len=1024,
            global_mlp_depth=2,
            dtype="bfloat16",
        )
    else:
        # CPU fallback (unreachable TPU): functional smoke at tiny shapes —
        # the full config needs ~minutes/step on a 1-core host. The JSON
        # stays honest via platform/config keys.
        config = ProGenConfig(
            num_tokens=256,
            dim=64,
            depth=2,
            heads=2,
            dim_head=32,
            window_size=32,
            seq_len=128,
            global_mlp_depth=1,
            ff_mult=2,
            dtype="float32",
        )
    n_chips = len(jax.devices())
    mesh = make_mesh()  # all devices on the data axis (1 on the bench chip)
    model = ProGen(config)
    optimizer = make_optimizer()
    state, shardings = init_train_state(
        model, optimizer, jax.random.PRNGKey(0), config.seq_len, mesh=mesh
    )
    step = compile_train_step(model, optimizer, state, shardings, mesh)

    # reference recipe 4 x 4 on TPU; smoke shapes off-TPU
    grad_accum, micro_bs = (4, 4 * n_chips) if on_tpu else (2, 2 * n_chips)
    rng = np.random.default_rng(0)
    batch = rng.integers(
        1, 256, size=(grad_accum, micro_bs, config.seq_len + 1)
    ).astype(np.int32)

    with mesh:
        device_batch = put_batch(batch, mesh, accum_axis=True)
        # warmup/compile
        state, metrics = step(state, device_batch)
        jax.block_until_ready(metrics["loss"])

        n_iters = 10 if on_tpu else 3
        t0 = time.perf_counter()
        for _ in range(n_iters):
            state, metrics = step(state, device_batch)
        jax.block_until_ready(metrics["loss"])
        dt = time.perf_counter() - t0

    tokens_per_step = grad_accum * micro_bs * config.seq_len
    tokens_per_sec = tokens_per_step * n_iters / dt
    per_chip = tokens_per_sec / n_chips

    from progen_tpu import profiling

    num_params = state.num_params()
    mfu = (
        per_chip
        * profiling.flops_per_token(config)
        / profiling.peak_flops(jax.devices()[0])
    )

    prior = _prior_round_value()
    result = {
        # distinct metric off-TPU so a smoke number never poisons the
        # cross-round TPU baseline chain
        "metric": (
            "train_tokens_per_sec_per_chip"
            if on_tpu
            else "cpu_fallback_smoke_tokens_per_sec"
        ),
        "value": round(per_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": (
            round(per_chip / prior, 3) if (prior and on_tpu) else 1.0
        ),
        "mfu": round(mfu, 4),
        "num_params": num_params,
        "chips": n_chips,
        "step_ms": round(1000 * dt / n_iters, 1),
        "config": (
            "progen-tiny (dim=512 depth=12 seq=1024 w=256) bf16"
            if on_tpu
            else "cpu-fallback smoke (dim=64 depth=2 seq=128 w=32) f32"
        ),
        "platform": jax.devices()[0].platform,
    }
    print(json.dumps(result))


def kernel_bench() -> None:
    """`python bench.py kernel` — Pallas windowed-attention kernel vs the
    XLA path, fwd+bwd, tiny-config shapes. Not part of the driver contract
    (which reads main()'s single line); records the kernel delta the
    VERDICT asked for."""
    import jax
    import jax.numpy as jnp

    _device_or_cpu_fallback()

    from progen_tpu.ops.attention import local_attention
    from progen_tpu.ops.pallas_attention import pallas_local_attention

    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        b, h, n, d, w = 16, 8, 1024, 64, 256
    else:
        # interpret-mode Pallas is minutes/call at the TPU shapes — keep the
        # off-TPU path a functional smoke, not a perf claim
        b, h, n, d, w = 2, 2, 128, 32, 32
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (
        jax.random.normal(kk, (b, h, n, d), jnp.bfloat16) for kk in ks
    )

    def time_fn(fn, iters=20):
        out = jax.block_until_ready(fn(q, k, v))  # compile
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(q, k, v)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / iters, out

    xla_fwd = jax.jit(lambda q, k, v: local_attention(q, k, v, window_size=w))
    # interpret mode on CPU (compiled Mosaic is TPU-only)
    pl_fwd = jax.jit(
        lambda q, k, v: pallas_local_attention(q, k, v, w, None, not on_tpu)
    )
    xla_bwd = jax.jit(
        jax.grad(lambda q, k, v: local_attention(q, k, v, window_size=w)
                 .astype(jnp.float32).sum(), argnums=(0, 1, 2))
    )
    pl_bwd = jax.jit(
        jax.grad(lambda q, k, v: pallas_local_attention(q, k, v, w, None,
                                                        not on_tpu)
                 .astype(jnp.float32).sum(), argnums=(0, 1, 2))
    )

    t_xf, o_x = time_fn(xla_fwd)
    t_pf, o_p = time_fn(pl_fwd)
    err = float(
        jnp.abs(o_x.astype(jnp.float32) - o_p.astype(jnp.float32)).max()
    )
    t_xb, _ = time_fn(xla_bwd, iters=10)
    t_pb, _ = time_fn(pl_bwd, iters=10)
    print(json.dumps({
        "metric": "pallas_vs_xla_local_attention",
        "fwd_ms": {"xla": round(t_xf * 1e3, 2), "pallas": round(t_pf * 1e3, 2)},
        "bwd_ms": {"xla": round(t_xb * 1e3, 2), "pallas": round(t_pb * 1e3, 2)},
        "fwd_speedup": round(t_xf / t_pf, 2),
        "bwd_speedup": round(t_xb / t_pb, 2),
        "max_abs_err": err,
        "shape": f"b{b} h{h} n{n} d{d} w{w} bf16",
        "platform": jax.devices()[0].platform,
        "pallas_interpret_mode": not on_tpu,
    }))


if __name__ == "__main__":
    import sys

    if len(sys.argv) > 1 and sys.argv[1] == "kernel":
        kernel_bench()
    else:
        main()
