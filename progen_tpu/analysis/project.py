"""ProjectContext: cross-module indices built once, shared by rules.

The per-module rules (PGL001-008) see one parsed file at a time; the
distributed-systems invariants of PRs 8-19 are cross-module by nature:
a chaos kill-matrix in ``tests/`` (or a ``PROGEN_CHAOS`` example in
tier1.yml or the README) names an injection site that must actually be
installed somewhere in ``progen_tpu/``, and ``resilience/chaos.py``'s
``KNOWN_TARGETS`` registry must stay in lockstep with both. This
module parses every discovered file ONCE, builds the indices, and
hands them to every :class:`~progen_tpu.analysis.core.ProjectRule`.

Indices built here:

  * ``sites`` — every chaos-injectable site actually installed in
    code: string-literal span names (``span("ckpt/save", ...)``),
    retry-site labels (``retry_call(..., label="data/read")`` /
    ``retryable("data/read")``), and direct injection points
    (``maybe_inject("serve/decode")`` / ``on_site`` / ``perturb``).
    These are exactly the names ``resilience/chaos.py`` keys rules on.
  * ``declared`` — the ``KNOWN_TARGETS = frozenset({...})`` literal
    (chaos.py's own registry), wherever one is defined in the linted
    set.
  * ``chaos_refs`` — every ``PROGEN_CHAOS`` target string referenced
    anywhere: chaos-spec literals (``"serve/decode:kill@3"``) and
    f-string prefixes (``f"serve/decode:kill@{n}"``) in Python source
    (string constants AND comments), plus the same spec tokens in
    non-Python text files (tier1.yml, *.md docs) that
    :func:`default_text_files` discovers next to the linted paths.

The spec-token grammar mirrors ``chaos._parse``: ``target:spec`` where
the target contains at least one ``/`` (all real sites are
``area/site`` shaped) and the spec is ``kill[@N]``, ``fail@N``,
``spike@N``, ``nan@N``, or a probability — distinctive enough that
ordinary strings never match.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

from progen_tpu.analysis.core import (
    ModuleContext,
    _comment_map,
    call_name,
    name_suffix_in,
)

# a chaos spec token as it appears in env examples, test parametrize
# lists, CI workflow steps and docs: "ckpt/save:0.3", "data/read:kill",
# "serve/decode:kill@3", "train/loss:nan@2"
_SPEC_TOKEN_RE = re.compile(
    r"\b([a-z0-9_]+(?:/[a-z0-9_]+)+)"
    r":(?:kill(?:@\d+)?|fail@\d+|spike@\d+|nan@\d+|"
    r"(?:0?\.\d+|[01](?:\.0+)?))(?![\w@/])"
)
# an f-string's literal prefix, cut at the formatted hit index:
# f"serve/decode:kill@{n}" leaves "serve/decode:kill@"
_SPEC_PREFIX_RE = re.compile(
    r"([a-z0-9_]+(?:/[a-z0-9_]+)+):(?:kill|fail|spike|nan)@$"
)

_SITE_CALL_TAILS = ("maybe_inject", "on_site", "perturb")
_RETRY_CALLS = ("retry_call", "retryable")


@dataclass
class ChaosRef:
    """One referenced PROGEN_CHAOS target, with enough location to
    report on: ``ctx``/``node`` for Python sources (suppressible),
    bare path/line for text files."""

    target: str
    path: str
    line: int
    ctx: Optional[ModuleContext] = None
    node: Optional[ast.AST] = None


@dataclass
class ProjectContext:
    """Everything project rules share about the linted file set."""

    contexts: List[ModuleContext] = field(default_factory=list)
    text_files: List[Path] = field(default_factory=list)
    # site name -> [(path, line), ...] where it is installed
    sites: Dict[str, List[Tuple[str, int]]] = field(default_factory=dict)
    # KNOWN_TARGETS entries: target -> (ctx, node of the declaring str)
    declared: Dict[str, Tuple[ModuleContext, ast.AST]] = field(
        default_factory=dict
    )
    declaration: Optional[Tuple[ModuleContext, ast.AST]] = None
    chaos_refs: List[ChaosRef] = field(default_factory=list)

    @classmethod
    def build(cls, contexts: Sequence[ModuleContext],
              text_files: Sequence = ()) -> "ProjectContext":
        proj = cls(contexts=list(contexts),
                   text_files=[Path(p) for p in text_files])
        for ctx in proj.contexts:
            proj._index_module(ctx)
        for path in proj.text_files:
            proj._index_text_file(path)
        return proj

    # ----- per-module indexing --------------------------------------------

    def _add_site(self, name: str, ctx: ModuleContext, node) -> None:
        self.sites.setdefault(name, []).append(
            (ctx.path, getattr(node, "lineno", 0))
        )

    def _index_module(self, ctx: ModuleContext) -> None:
        # f-string literal parts are handled by _index_fstring (which
        # also applies the prefix grammar); don't double-index them as
        # standalone constants
        fstring_parts = {
            id(part)
            for node in ast.walk(ctx.tree)
            if isinstance(node, ast.JoinedStr)
            for part in node.values
        }
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                self._index_call(ctx, node)
            elif isinstance(node, ast.Assign):
                self._index_known_targets(ctx, node)
            elif isinstance(node, ast.Constant) and isinstance(
                node.value, str
            ) and id(node) not in fstring_parts:
                self._index_ref_string(ctx, node, node.value)
            elif isinstance(node, ast.JoinedStr):
                self._index_fstring(ctx, node)
        for line_no, comment in _comment_map(ctx.source).items():
            for m in _SPEC_TOKEN_RE.finditer(comment):
                self.chaos_refs.append(
                    ChaosRef(m.group(1), ctx.path, line_no, ctx=ctx)
                )

    def _index_call(self, ctx: ModuleContext, node: ast.Call) -> None:
        cname = call_name(node)
        # modules alias the helpers on import ("from spans import span
        # as _span") — strip the private prefix before matching
        tail = (cname.rsplit(".", 1)[-1] if cname else "").lstrip("_")
        if tail == "span" and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(
                arg.value, str
            ):
                self._add_site(arg.value, ctx, arg)
        elif tail in _SITE_CALL_TAILS and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(
                arg.value, str
            ):
                self._add_site(arg.value, ctx, arg)
        if name_suffix_in(cname, _RETRY_CALLS):
            for kw in node.keywords:
                if kw.arg == "label" and isinstance(
                    kw.value, ast.Constant
                ) and isinstance(kw.value.value, str):
                    self._add_site(kw.value.value, ctx, kw.value)
            if tail == "retryable" and node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and isinstance(
                    arg.value, str
                ):
                    self._add_site(arg.value, ctx, arg)

    def _index_known_targets(self, ctx: ModuleContext,
                             node: ast.Assign) -> None:
        if not any(
            isinstance(t, ast.Name) and t.id == "KNOWN_TARGETS"
            for t in node.targets
        ):
            return
        value = node.value
        if isinstance(value, ast.Call) and call_name(value) in (
            "frozenset", "set"
        ) and value.args:
            value = value.args[0]
        if not isinstance(value, (ast.Set, ast.Tuple, ast.List)):
            return
        self.declaration = (ctx, node)
        for elt in value.elts:
            if isinstance(elt, ast.Constant) and isinstance(
                elt.value, str
            ):
                self.declared.setdefault(elt.value, (ctx, elt))

    def _index_ref_string(self, ctx: ModuleContext, node,
                          text: str) -> None:
        for m in _SPEC_TOKEN_RE.finditer(text):
            self.chaos_refs.append(ChaosRef(
                m.group(1), ctx.path, getattr(node, "lineno", 0),
                ctx=ctx, node=node,
            ))
        m = _SPEC_PREFIX_RE.search(text)
        if m:
            self.chaos_refs.append(ChaosRef(
                m.group(1), ctx.path, getattr(node, "lineno", 0),
                ctx=ctx, node=node,
            ))

    def _index_fstring(self, ctx: ModuleContext,
                       node: ast.JoinedStr) -> None:
        for part in node.values:
            if isinstance(part, ast.Constant) and isinstance(
                part.value, str
            ):
                self._index_ref_string(ctx, node, part.value)

    # ----- text files (tier1.yml, docs) -----------------------------------

    def _index_text_file(self, path: Path) -> None:
        try:
            text = path.read_text()
        except OSError:
            return
        try:
            rel = str(path.relative_to(Path.cwd()))
        except ValueError:
            rel = str(path)
        for i, line in enumerate(text.splitlines(), start=1):
            for m in _SPEC_TOKEN_RE.finditer(line):
                self.chaos_refs.append(ChaosRef(m.group(1), rel, i))


def default_text_files(paths: Sequence) -> List[Path]:
    """The non-Python files whose PROGEN_CHAOS references PGL009
    checks: CI workflows and markdown docs of the repo the linted
    paths belong to (found by walking up to a ``pyproject.toml``)."""
    roots = set()
    for p in paths:
        cur = Path(p).resolve()
        if cur.is_file():
            cur = cur.parent
        while True:
            if (cur / "pyproject.toml").is_file():
                roots.add(cur)
                break
            if cur.parent == cur:
                break
            cur = cur.parent
    out: List[Path] = []
    for root in sorted(roots):
        workflows = root / ".github" / "workflows"
        if workflows.is_dir():
            out.extend(sorted(workflows.glob("*.yml")))
            out.extend(sorted(workflows.glob("*.yaml")))
        out.extend(sorted(root.glob("*.md")))
        docs = root / "docs"
        if docs.is_dir():
            out.extend(sorted(docs.rglob("*.md")))
    return out
