"""PGL006 — telemetry hygiene, driven by the event-grammar registry.

Span hygiene only pays off when it is enforced (Dapper's lesson): a
span name that varies per call explodes the name cardinality the
summarize/trace tooling groups on; a hand-rolled ``{"ev": "B"}`` record
that never gets its ``E`` (an exception, an early return) corrupts the
open-span accounting the stall watchdog reports from. And a metric name
that fails the Prometheus grammar gets silently mangled by
``telemetry/prometheus.py``'s ``_name()`` at render time — the
dashboard query then matches nothing.

The per-``ev`` record grammars (which module may build each record
family, which fields are required, which values each enum field
allows) live in one declarative table: ``analysis/event_grammar.py``.
This rule is the PRODUCER side of that registry — it checks every
record-building site against the declaration. PGL010
(rules_grammar_consumers.py) is the consumer side: readers dispatching
on the same enum fields must handle every declared value. Extending a
grammar (a new op, a new record family) means editing the registry
once; both rules and the generated README reference section follow.

Beyond the registry, three bespoke checks survive here because they
are not per-``ev`` grammars:

  * ``span(...)`` / ``.span(...)`` names must be string literals (a
    bare name is allowed only when the enclosing function forwards its
    own parameter — the wrapper pattern ``spans.span`` itself uses);
  * string-literal metric names fed to the registry (``.inc``,
    ``.set_gauge``, ``.observe``, ``.set_gauges`` keys) must satisfy
    the Prometheus name rules the renderer enforces
    (``[a-zA-Z_:][a-zA-Z0-9_:]*``);
  * an ``ev`` tag with no registered grammar must still be a clean
    greppable identifier, and must be a string literal when emitted.
"""

from __future__ import annotations

import ast
import re

from progen_tpu.analysis.core import Rule, call_name
from progen_tpu.analysis.event_grammar import (
    BY_EV,
    GRAMMARS,
    TRACE_KEY_MISSPELLINGS,
    EventGrammar,
)

_PROM_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_REGISTRY_METHODS = ("inc", "set_gauge", "observe")

_DICT_SCOPE_GRAMMARS = tuple(g for g in GRAMMARS if g.scope == "dict")


def _str_const(node) -> bool:
    return isinstance(node, ast.Constant) and isinstance(node.value, str)


class TelemetryHygieneRule(Rule):
    id = "PGL006"
    severity = "error"
    doc = ("event-grammar producer hygiene: literal span names, every "
           "ev record family built only by its registered owner with "
           "declared required fields and enum alphabets "
           "(analysis/event_grammar.py), Prometheus-legal metric names")

    def _enclosing_params(self, node) -> set:
        fn = self.ctx.enclosing_function(node)
        if fn is None:
            return set()
        a = fn.args
        return {p.arg for p in a.posonlyargs + a.args + a.kwonlyargs}

    def visit_Call(self, node: ast.Call) -> None:
        self.generic_visit(node)
        cname = call_name(node)
        tail = cname.rsplit(".", 1)[-1] if cname else ""
        if tail == "span" and node.args:
            self._check_span_name(node)
        if tail in ("emit", "log_event"):
            for arg in node.args:
                if isinstance(arg, ast.Dict):
                    self._check_event_dict(arg)
        if tail in _REGISTRY_METHODS and node.args:
            if _str_const(node.args[0]):
                self._check_prom_name(node.args[0], node.args[0].value)
        if tail == "set_gauges" and node.args:
            if isinstance(node.args[0], ast.Dict):
                for k in node.args[0].keys:
                    if _str_const(k):
                        self._check_prom_name(k, k.value)

    def visit_Dict(self, node: ast.Dict) -> None:
        # dict-scope grammars run on EVERY dict literal: samples/alerts/
        # scale/... records reach disk through the TSDB or an alert
        # file, not through emit() — an emit-only check would never see
        # them
        self.generic_visit(node)
        for k, v in zip(node.keys, node.values):
            if not (_str_const(k) and k.value == "ev" and _str_const(v)):
                continue
            grammar = BY_EV.get(v.value)
            if grammar is not None and grammar.scope == "dict":
                self._check_grammar(node, v, grammar)

    # ----- registry-driven record checks ----------------------------------

    def _check_grammar(self, d: ast.Dict, ev_node,
                       grammar: EventGrammar) -> None:
        if not grammar.owns(self.ctx.path):
            self.report(ev_node, grammar.owner_message)
        if grammar.required:
            present = {kk.value for kk in d.keys if _str_const(kk)}
            missing = [f for f in grammar.required if f not in present]
            if missing:
                self.report(
                    ev_node,
                    f"{grammar.ev} record missing field(s) "
                    f"{'/'.join(missing)} — {grammar.required_message}",
                )
        for enum in grammar.enums:
            for k, v in zip(d.keys, d.values):
                if not (_str_const(k) and k.value == enum.field):
                    continue
                if _str_const(v) and v.value not in enum.values:
                    self.report(
                        v,
                        f"{enum.what} is '{v.value}' — must be one of "
                        f"{'/'.join(enum.values)}: {enum.why}",
                    )
        if grammar.check_trace_key:
            for k in d.keys:
                if _str_const(k) and k.value in TRACE_KEY_MISSPELLINGS:
                    self.report(
                        k,
                        f"trace-context key '{k.value}' — the blessed "
                        f"spelling is 'trace_id' (stitch journey "
                        f"grouping and the kill-matrix contiguity "
                        f"assert grep exactly that key); a misspelled "
                        f"hop silently falls out of its journey",
                    )

    def _check_event_dict(self, d: ast.Dict) -> None:
        for k, v in zip(d.keys, d.values):
            if not (_str_const(k) and k.value == "ev"):
                continue
            if not _str_const(v):
                self.report(
                    v,
                    "event 'ev' tag must be a string literal so event "
                    "streams stay greppable",
                )
                continue
            grammar = BY_EV.get(v.value)
            if grammar is None:
                if not _PROM_NAME_RE.match(v.value):
                    self.report(
                        v,
                        f"event tag '{v.value}' is not a clean "
                        f"identifier ([a-zA-Z_][a-zA-Z0-9_]*) — "
                        f"downstream tooling keys on it",
                    )
            elif grammar.scope == "emit":
                # dict-scope grammars are handled by visit_Dict (which
                # also sees this literal) — checking both would double-
                # report
                self._check_grammar(d, v, grammar)

    # ----- bespoke checks (not per-ev grammars) ---------------------------

    def _check_span_name(self, node: ast.Call) -> None:
        name_arg = node.args[0]
        if _str_const(name_arg):
            return
        if isinstance(name_arg, ast.Name) and \
                name_arg.id in self._enclosing_params(node):
            return  # forwarding wrapper: span(name) inside def f(name)
        kind = (
            "an f-string" if isinstance(name_arg, ast.JoinedStr)
            else "a non-literal expression"
        )
        self.report(
            name_arg,
            f"span name is {kind} — span names must be string literals "
            f"so the trace/summarize tooling groups on a bounded, "
            f"greppable set; put varying data in span attrs instead",
        )

    def _check_prom_name(self, node, name: str) -> None:
        if not _PROM_NAME_RE.match(name):
            self.report(
                node,
                f"metric name '{name}' fails the Prometheus name rules "
                f"(telemetry/prometheus.py would mangle it at render "
                f"time and dashboard queries would miss): use "
                f"[a-zA-Z_:][a-zA-Z0-9_:]*",
            )
