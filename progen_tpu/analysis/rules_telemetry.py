"""PGL006 — telemetry hygiene.

Span hygiene only pays off when it is enforced (Dapper's lesson): a
span name that varies per call explodes the name cardinality the
summarize/trace tooling groups on; a hand-rolled ``{"ev": "B"}`` record
that never gets its ``E`` (an exception, an early return) corrupts the
open-span accounting the stall watchdog reports from. And a metric name
that fails the Prometheus grammar gets silently mangled by
``telemetry/prometheus.py``'s ``_name()`` at render time — the
dashboard query then matches nothing. Three checks:

  * ``span(...)`` / ``.span(...)`` names must be string literals
    (a bare name is allowed only when the enclosing function forwards
    its own parameter — the wrapper pattern ``spans.span`` itself uses);
  * raw ``"ev": "B"``/``"ev": "E"`` records must not be emitted outside
    ``telemetry/spans.py`` — B/E pairing goes through the ``span()``
    context manager, whose ``finally`` guarantees the E;
  * string-literal metric names fed to the registry (``.inc``,
    ``.set_gauge``, ``.observe``, ``.set_gauges`` keys) and literal
    ``"ev"`` values must already satisfy the Prometheus name rules the
    renderer enforces (``[a-zA-Z_:][a-zA-Z0-9_:]*``) — this covers the
    PR-7 names (``clock_beacon``, ``itl_s``, ``slots`` /
    ``slot_occupancy``) like any other;
  * raw ``"ev": "req"`` async-lifecycle records must not be emitted
    outside ``serving/scheduler.py`` or ``serving/router.py`` — those
    two own the queued/prefill/decode (and routed/dispatched) phase
    grammar and the every-``b``-gets-its-``e`` exception-safety burden
    (same reasoning as B/E ↔ spans.py), and a literal ``"ph"`` in a
    req record must be one of ``"b"``/``"n"``/``"e"`` (the async
    trace-event alphabet);
  * raw ``"ev": "route"`` records must not be emitted outside
    ``serving/router.py``, and a literal ``"status"`` must be one of
    ``dispatched``/``handoff``/``shed``/``replica_down`` — the router
    section of ``summarize`` (and the failover smoke in CI) keys on
    exactly this alphabet;
  * raw ``"ev": "journal"`` records must not be emitted outside
    ``serving/journal.py`` — the replay journal's ``op`` grammar
    (``accept``/``token``/``done``) IS the crash-recovery contract
    (a free-hand record replay can't parse is silently lost work), and
    a literal ``"op"`` must come from that alphabet;
  * raw ``"ev": "reload"`` records must not be emitted outside
    ``serving/reload.py``, and a literal ``"status"`` must be one of
    ``staged``/``committed``/``rejected`` — the zero-downtime smoke in
    CI greps these to assert a reload fully applied or fully didn't.
  * raw ``"ev": "score"`` records must not be emitted outside
    ``progen_tpu/workloads/``, and a literal ``"op"`` must be one of
    ``start``/``resume``/``batch``/``skip``/``done`` — the batch-score
    journal's grammar is the resume/progress contract the CI workloads
    smoke (and ``summarize``) read.
  * raw ``"ev": "prefix_cache"`` records must not be emitted outside
    ``serving/prefix_cache.py``, and a literal ``"op"`` must be one of
    ``hit``/``miss``/``evict`` — cache-reuse accounting (and the CI
    serving smoke's hit assertion) key on exactly this alphabet.
  * raw ``"ev": "slo"`` records must not be emitted outside
    ``telemetry/slo.py`` — the watchtower's transition grammar is what
    the SLO gate and summarize key on — and a literal ``"state"`` must
    be one of ``ok``/``warn``/``burning``/``resolved``.
  * the trace-context field on ``req``/``route`` records is spelled
    exactly ``trace_id`` — the stitcher's journey grouping and the
    kill-matrix contiguity assert grep that one key; a literal
    ``"trace"``/``"traceid"``-style key is a silently-dropped hop.
  * ``"ev": "sample"`` dict literals (the fleet collector's scrape
    records) may only be built in ``telemetry/collector.py`` — every
    sample goes through ``make_sample`` so the TSDB, the fleet
    aggregator, and the console all agree on one schema; a literal
    ``"role"`` must be ``replica``/``router``/``run``. Checked on ALL
    dict literals (not just ``emit(...)`` args): samples are written
    through the TSDB, not the telemetry sink.
  * ``"ev": "alert"`` dict literals may only be built in
    ``telemetry/alerts.py`` (the ``AlertSink`` constructors), must
    carry the ``kind``/``state``/``source``/``objective`` fields the
    alert relay and the CI fleet-metrics smoke key on, and literal
    ``kind``/``state`` values must come from the
    ``staleness``/``slo_burn``/``deploy_rollback`` and
    ``stale``/``fresh``/``warn``/``burning``/``resolved``/
    ``rolled_back`` alphabets.
  * ``"ev": "scale"`` dict literals (autoscaler decisions) may only be
    built in ``fleet/autoscaler.py``, must carry ``action`` and
    ``reason`` (the CI autoscale smoke asserts an up AND a down were
    observed, by exactly those fields), and a literal ``action`` must
    be ``up``/``down``/``hold``.
  * ``"ev": "frame_drop"`` dict literals (rejected transport frames)
    may only be built in ``fleet/transport.py`` — a drop record is the
    transport's proof a frame was condemned, and a hand-rolled one
    would claim enforcement that never ran; a literal ``reason`` must
    come from the ``bad_magic``/``bad_version``/``bad_auth``/
    ``oversized``/``chaos``/``idle_timeout`` alphabet.
  * ``"ev": "notify"`` dict literals (alert delivery decisions) may
    only be built in ``telemetry/alert_router.py`` — a notify record
    claims the dedup/silence/rate pipeline ran; a hand-rolled one
    forges a delivery the on-call never received. A literal ``status``
    must come from the ``sent``/``failed``/``silenced``/``deduped``/
    ``escalated`` delivery alphabet (the console counts and the CI
    egress smoke key on exactly these).
  * ``"ev": "ship"`` dict literals (TSDB retention-tier decisions) may
    only be built in ``telemetry/tsdb.py`` — a ship record is the
    shipper's proof a block's digest was verified into the archive
    manifest; a literal ``op`` must come from the ``shipped``/
    ``skipped``/``verify_failed`` alphabet.
  * raw ``"ev": "flight"`` records must not be emitted outside
    ``telemetry/flight.py`` — a ``dumped`` record is the flight
    recorder's receipt that a sealed, digest-valid black box reached
    disk (the forensics smoke and ``query --trace`` key on it); a
    literal ``op`` must come from the ``armed``/``dumped``/
    ``truncated`` alphabet.
  * raw ``"ev": "profile"`` records must not be emitted outside
    ``telemetry/flight.py`` — the profile pin ledger pairs
    ``requested`` with ``started``/``stopped`` (or ``rejected``) so
    an on-demand ``jax.profiler`` window is provably bounded and
    rate-limited; a literal ``op`` must come from that alphabet.
  * ``"ev": "deploy"`` dict literals (deployment decisions) may only
    be built in ``progen_tpu/deploy/`` — the deploy ledger is the
    controller's resume authority, and a hand-rolled record forges a
    canary/promote/rollback decision the controller never made; a
    literal ``op`` must come from the ``observed``/``canary``/
    ``probe``/``promote``/``rollback``/``converged`` alphabet (the CI
    deployment smoke and the kill-matrix convergence asserts key on
    exactly these).
"""

from __future__ import annotations

import ast
import re

from progen_tpu.analysis.core import Rule, call_name

_PROM_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_REGISTRY_METHODS = ("inc", "set_gauge", "observe")


def _str_const(node) -> bool:
    return isinstance(node, ast.Constant) and isinstance(node.value, str)


class TelemetryHygieneRule(Rule):
    id = "PGL006"
    severity = "error"
    doc = ("span/metric naming hygiene: literal span names, B/E only "
           "via the span() context manager, Prometheus-legal metric "
           "names")

    def _in_spans_module(self) -> bool:
        return self.ctx.path.replace("\\", "/").endswith(
            "telemetry/spans.py"
        )

    def _in_scheduler_module(self) -> bool:
        return self.ctx.path.replace("\\", "/").endswith(
            "serving/scheduler.py"
        )

    def _in_module(self, tail: str) -> bool:
        return self.ctx.path.replace("\\", "/").endswith(tail)

    def _enclosing_params(self, node) -> set:
        fn = self.ctx.enclosing_function(node)
        if fn is None:
            return set()
        a = fn.args
        return {p.arg for p in a.posonlyargs + a.args + a.kwonlyargs}

    def visit_Call(self, node: ast.Call) -> None:
        self.generic_visit(node)
        cname = call_name(node)
        tail = cname.rsplit(".", 1)[-1] if cname else ""
        if tail == "span" and node.args:
            self._check_span_name(node)
        if tail in ("emit", "log_event"):
            for arg in node.args:
                if isinstance(arg, ast.Dict):
                    self._check_event_dict(arg)
        if tail in _REGISTRY_METHODS and node.args:
            if _str_const(node.args[0]):
                self._check_prom_name(node.args[0], node.args[0].value)
        if tail == "set_gauges" and node.args:
            if isinstance(node.args[0], ast.Dict):
                for k in node.args[0].keys:
                    if _str_const(k):
                        self._check_prom_name(k, k.value)

    # collector-record grammar: checked on every dict literal, because
    # samples/alerts reach disk through the TSDB / AlertSink file, not
    # through emit() — an emit-only check would never see them
    _ALERT_FIELDS = ("kind", "state", "source", "objective")
    _ALERT_KINDS = ("staleness", "slo_burn", "deploy_rollback")
    _ALERT_STATES = ("stale", "fresh", "warn", "burning", "resolved",
                     "rolled_back")
    _SAMPLE_ROLES = ("replica", "router", "run")
    _SCALE_FIELDS = ("action", "reason")
    _SCALE_ACTIONS = ("up", "down", "hold")
    _DROP_REASONS = ("bad_magic", "bad_version", "bad_auth",
                     "oversized", "chaos", "idle_timeout")
    _NOTIFY_STATUSES = ("sent", "failed", "silenced", "deduped",
                        "escalated")
    _SHIP_OPS = ("shipped", "skipped", "verify_failed")
    _DEPLOY_OPS = ("observed", "canary", "probe", "promote",
                   "rollback", "converged")

    def visit_Dict(self, node: ast.Dict) -> None:
        self.generic_visit(node)
        for k, v in zip(node.keys, node.values):
            if not (_str_const(k) and k.value == "ev" and _str_const(v)):
                continue
            if v.value == "sample":
                if not self._in_module("telemetry/collector.py"):
                    self.report(
                        v,
                        "raw collector sample record built outside "
                        "telemetry/collector.py — the TSDB, the fleet "
                        "aggregator and the ops console all parse one "
                        "schema; build samples with make_sample()",
                    )
                self._check_literal_member(
                    node, "role", self._SAMPLE_ROLES,
                    "sample record 'role'",
                    "fleet aggregation buckets liveness by exactly "
                    "these roles",
                )
            elif v.value == "alert":
                if not self._in_module("telemetry/alerts.py"):
                    self.report(
                        v,
                        "raw alert record built outside "
                        "telemetry/alerts.py — alerts are edge-triggered "
                        "state machines; a hand-rolled record bypasses "
                        "the transition dedup and the field grammar the "
                        "relay/CI smoke key on; go through AlertSink",
                    )
                present = {
                    kk.value for kk in node.keys if _str_const(kk)
                }
                missing = [
                    f for f in self._ALERT_FIELDS if f not in present
                ]
                if missing:
                    self.report(
                        v,
                        f"alert record missing field(s) "
                        f"{'/'.join(missing)} — the alert relay and the "
                        f"fleet-metrics smoke key on "
                        f"kind/state/source/objective being present on "
                        f"every alert",
                    )
                self._check_literal_member(
                    node, "kind", self._ALERT_KINDS,
                    "alert record 'kind'",
                    "only staleness, slo_burn and deploy_rollback "
                    "alerts exist; a new kind needs the grammar (and "
                    "this rule) extended",
                )
                self._check_literal_member(
                    node, "state", self._ALERT_STATES,
                    "alert record 'state'",
                    "the console colors and the smoke's quiet/burn "
                    "asserts only know these states",
                )
            elif v.value == "scale":
                if not self._in_module("fleet/autoscaler.py"):
                    self.report(
                        v,
                        "raw scale record built outside "
                        "fleet/autoscaler.py — scaling decisions are the "
                        "policy engine's judgment (hysteresis, cooldowns, "
                        "edge-triggering), and the CI autoscale smoke "
                        "keys on its records alone; go through "
                        "Autoscaler.decide, not hand-rolled records",
                    )
                present = {
                    kk.value for kk in node.keys if _str_const(kk)
                }
                missing = [
                    f for f in self._SCALE_FIELDS if f not in present
                ]
                if missing:
                    self.report(
                        v,
                        f"scale record missing field(s) "
                        f"{'/'.join(missing)} — the autoscale smoke "
                        f"asserts an up AND a down were observed by "
                        f"exactly the action/reason fields",
                    )
                self._check_literal_member(
                    node, "action", self._SCALE_ACTIONS,
                    "scale record 'action'",
                    "the smoke's up/down asserts and summarize only "
                    "know these actions",
                )
            elif v.value == "frame_drop":
                if not self._in_module("fleet/transport.py"):
                    self.report(
                        v,
                        "raw frame_drop record built outside "
                        "fleet/transport.py — a drop record is the "
                        "transport's proof a frame was validated and "
                        "condemned; a hand-rolled one claims enforcement "
                        "that never ran",
                    )
                self._check_literal_member(
                    node, "reason", self._DROP_REASONS,
                    "frame_drop record 'reason'",
                    "drop triage greps exactly this reason set; an "
                    "unknown reason is an invisible wire failure",
                )
            elif v.value == "notify":
                if not self._in_module("telemetry/alert_router.py"):
                    self.report(
                        v,
                        "raw notify record built outside "
                        "telemetry/alert_router.py — a notify record "
                        "claims the dedup/silence/rate pipeline ran; a "
                        "hand-rolled one forges a delivery the on-call "
                        "never received; go through AlertRouter",
                    )
                self._check_literal_member(
                    node, "status", self._NOTIFY_STATUSES,
                    "notify record 'status'",
                    "the console's delivery counts and the CI egress "
                    "smoke classify by exactly the "
                    "sent/failed/silenced/deduped/escalated alphabet",
                )
            elif v.value == "ship":
                if not self._in_module("telemetry/tsdb.py"):
                    self.report(
                        v,
                        "raw ship record built outside "
                        "telemetry/tsdb.py — a ship record is the "
                        "shipper's proof a block's digest was verified "
                        "into the archive manifest; a hand-rolled one "
                        "claims history that was never tiered out",
                    )
                self._check_literal_member(
                    node, "op", self._SHIP_OPS,
                    "ship record 'op'",
                    "retention triage greps exactly the "
                    "shipped/skipped/verify_failed op set",
                )
            elif v.value == "deploy":
                if "/deploy/" not in self.ctx.path.replace("\\", "/"):
                    self.report(
                        v,
                        "raw deploy record built outside "
                        "progen_tpu/deploy/ — the deploy ledger is the "
                        "controller's resume authority; a hand-rolled "
                        "record forges a canary/promote/rollback "
                        "decision the controller never made; go "
                        "through DeployLedger",
                    )
                self._check_literal_member(
                    node, "op", self._DEPLOY_OPS,
                    "deploy record 'op'",
                    "the deployment smoke and the kill-matrix "
                    "convergence asserts grep exactly the observed/"
                    "canary/probe/promote/rollback/converged op set",
                )

    def _check_span_name(self, node: ast.Call) -> None:
        name_arg = node.args[0]
        if _str_const(name_arg):
            return
        if isinstance(name_arg, ast.Name) and \
                name_arg.id in self._enclosing_params(node):
            return  # forwarding wrapper: span(name) inside def f(name)
        kind = (
            "an f-string" if isinstance(name_arg, ast.JoinedStr)
            else "a non-literal expression"
        )
        self.report(
            name_arg,
            f"span name is {kind} — span names must be string literals "
            f"so the trace/summarize tooling groups on a bounded, "
            f"greppable set; put varying data in span attrs instead",
        )

    def _check_event_dict(self, d: ast.Dict) -> None:
        for k, v in zip(d.keys, d.values):
            if not (_str_const(k) and k.value == "ev"):
                continue
            if not _str_const(v):
                self.report(
                    v,
                    "event 'ev' tag must be a string literal so event "
                    "streams stay greppable",
                )
                continue
            if v.value in ("B", "E") and not self._in_spans_module():
                self.report(
                    v,
                    "raw B/E span record emitted directly — use the "
                    "span() context manager, whose finally-block "
                    "guarantees the matching E even on exceptions",
                )
            elif v.value == "req":
                if not (
                    self._in_scheduler_module()
                    or self._in_module("serving/router.py")
                ):
                    self.report(
                        v,
                        "raw async req record emitted outside "
                        "serving/scheduler.py or serving/router.py — "
                        "they own the request lifecycle grammar (every "
                        "'b' must get its 'e' on all exit paths); go "
                        "through Scheduler/Router, not hand-rolled "
                        "records",
                    )
                self._check_req_ph(d)
                self._check_trace_key(d)
            elif v.value == "route":
                if not self._in_module("serving/router.py"):
                    self.report(
                        v,
                        "raw route record emitted outside "
                        "serving/router.py — the routing-decision "
                        "grammar is what summarize's router section and "
                        "the CI failover smoke key on; go through "
                        "Router, not hand-rolled records",
                    )
                self._check_literal_member(
                    d, "status",
                    ("dispatched", "handoff", "shed", "replica_down"),
                    "route record 'status'",
                    "an unknown status is invisible to the router "
                    "table in summarize and to the failover smoke",
                )
                self._check_trace_key(d)
            elif v.value == "journal":
                if not self._in_module("serving/journal.py"):
                    self.report(
                        v,
                        "raw journal record emitted outside "
                        "serving/journal.py — the replay journal's op "
                        "grammar is the crash-recovery contract; go "
                        "through RequestJournal, not hand-rolled "
                        "records",
                    )
                self._check_literal_member(
                    d, "op", ("accept", "token", "done"),
                    "journal record 'op'",
                    "replay_requests drops records it can't parse — "
                    "an unknown op is silently lost work",
                )
            elif v.value == "reload":
                if not self._in_module("serving/reload.py"):
                    self.report(
                        v,
                        "raw reload record emitted outside "
                        "serving/reload.py — reload status records are "
                        "what the zero-downtime smoke asserts on; go "
                        "through WeightReloader, not hand-rolled "
                        "records",
                    )
                self._check_literal_member(
                    d, "status", ("staged", "committed", "rejected"),
                    "reload record 'status'",
                    "anything else reads as a torn reload to the "
                    "zero-downtime tooling",
                )
            elif v.value == "score":
                if "/workloads/" not in self.ctx.path.replace("\\", "/"):
                    self.report(
                        v,
                        "raw score record emitted outside "
                        "progen_tpu/workloads/ — the batch-score "
                        "journal's op grammar is the resume/progress "
                        "contract the CI workloads smoke greps; go "
                        "through ScoreJournal, not hand-rolled records",
                    )
                self._check_literal_member(
                    d, "op", ("start", "resume", "batch", "skip", "done"),
                    "score record 'op'",
                    "an unknown op is invisible to the scoring progress "
                    "tooling and the resume smoke",
                )
            elif v.value == "prefix_cache":
                if not self._in_module("serving/prefix_cache.py"):
                    self.report(
                        v,
                        "raw prefix_cache record emitted outside "
                        "serving/prefix_cache.py — cache reuse events "
                        "are what the serving smoke's hit assertion and "
                        "summarize key on; go through PrefixCache, not "
                        "hand-rolled records",
                    )
                self._check_literal_member(
                    d, "op", ("hit", "miss", "evict"),
                    "prefix_cache record 'op'",
                    "an unknown op is invisible to the cache-reuse "
                    "accounting and the serving smoke",
                )
            elif v.value == "slo":
                if not self._in_module("telemetry/slo.py"):
                    self.report(
                        v,
                        "raw slo record emitted outside "
                        "telemetry/slo.py — objective-state transitions "
                        "are the watchtower's judgment, keyed on by the "
                        "SLO gate and summarize; go through SloWatch, "
                        "not hand-rolled records",
                    )
                self._check_literal_member(
                    d, "state", ("ok", "warn", "burning", "resolved"),
                    "slo record 'state'",
                    "the gate's exit-code contract and the transition "
                    "grammar only know these states",
                )
            elif v.value == "flight":
                if not self._in_module("telemetry/flight.py"):
                    self.report(
                        v,
                        "raw flight record emitted outside "
                        "telemetry/flight.py — a 'dumped' record is the "
                        "recorder's receipt that a sealed, digest-valid "
                        "black box reached disk; a hand-rolled one "
                        "claims forensic evidence that was never "
                        "written; go through FlightRecorder",
                    )
                self._check_literal_member(
                    d, "op", ("armed", "dumped", "truncated"),
                    "flight record 'op'",
                    "the forensics smoke and query --trace grep "
                    "exactly the armed/dumped/truncated op set",
                )
            elif v.value == "profile":
                if not self._in_module("telemetry/flight.py"):
                    self.report(
                        v,
                        "raw profile record emitted outside "
                        "telemetry/flight.py — the pin watcher's "
                        "request/ack ledger is the proof a jax.profiler "
                        "window actually ran (and was rate-limited); go "
                        "through request_profile/ProfilePinWatcher",
                    )
                self._check_literal_member(
                    d, "op",
                    ("requested", "started", "stopped", "rejected"),
                    "profile record 'op'",
                    "the on-demand profiling smoke pairs requested/"
                    "started/stopped and triages rejected — an unknown "
                    "op is an invisible window",
                )
            elif not _PROM_NAME_RE.match(v.value):
                self.report(
                    v,
                    f"event tag '{v.value}' is not a clean identifier "
                    f"([a-zA-Z_][a-zA-Z0-9_]*) — downstream tooling "
                    f"keys on it",
                )

    def _check_req_ph(self, d: ast.Dict) -> None:
        for k, v in zip(d.keys, d.values):
            if not (_str_const(k) and k.value == "ph"):
                continue
            if _str_const(v) and v.value not in ("b", "n", "e"):
                self.report(
                    v,
                    f"req record 'ph' is '{v.value}' — async trace "
                    f"events only use 'b' (begin), 'n' (instant), "
                    f"'e' (end); anything else is dropped by the "
                    f"trace builder",
                )

    # misspellings of the one blessed trace-context key: the stitcher's
    # journey grouping greps records for exactly "trace_id", so a hop
    # written under any of these never joins its journey
    _TRACE_MISSPELLINGS = (
        "trace", "traceid", "traceId", "trace_ctx", "trace_context",
        "span_id", "spanid",
    )

    def _check_trace_key(self, d: ast.Dict) -> None:
        for k in d.keys:
            if _str_const(k) and k.value in self._TRACE_MISSPELLINGS:
                self.report(
                    k,
                    f"trace-context key '{k.value}' — the blessed "
                    f"spelling is 'trace_id' (stitch journey grouping "
                    f"and the kill-matrix contiguity assert grep "
                    f"exactly that key); a misspelled hop silently "
                    f"falls out of its journey",
                )

    def _check_literal_member(self, d: ast.Dict, field: str,
                              allowed: tuple, what: str,
                              why: str) -> None:
        """A literal ``field`` value in the record must come from the
        ``allowed`` alphabet (non-literals are the emitter's problem)."""
        for k, v in zip(d.keys, d.values):
            if not (_str_const(k) and k.value == field):
                continue
            if _str_const(v) and v.value not in allowed:
                self.report(
                    v,
                    f"{what} is '{v.value}' — must be one of "
                    f"{'/'.join(allowed)}: {why}",
                )

    def _check_prom_name(self, node, name: str) -> None:
        if not _PROM_NAME_RE.match(name):
            self.report(
                node,
                f"metric name '{name}' fails the Prometheus name rules "
                f"(telemetry/prometheus.py would mangle it at render "
                f"time and dashboard queries would miss): use "
                f"[a-zA-Z_:][a-zA-Z0-9_:]*",
            )
