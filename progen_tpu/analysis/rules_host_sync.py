"""PGL001 — host-device sync inside a traced region.

``float(x)``, ``x.item()``, ``bool(x)``, ``np.asarray(x)``,
``jax.device_get(x)`` on a traced value force the device to finish
everything in flight and ship the result to the host. In host code that
is the intended fence (the train loop's deferred-metrics flush does it
on purpose); inside a jitted/scanned/vmapped body it either raises a
``TracerConversionError`` at trace time or — worse, via ``np.asarray``
on a committed array in a region that jit later swallows — silently
serializes the hot loop. pytest on CPU never notices; the goodput
ledger does.

The rule fires only inside traced regions (see analysis/traced.py).
Conversions of trace-time-constant expressions (literals, ``.shape``
/``.ndim``/``len()`` arithmetic) are exempt — those are Python ints at
trace time, not tracer reads.
"""

from __future__ import annotations

import ast

from progen_tpu.analysis.core import Rule, call_name, name_suffix_in

# callables that read a device value back to the host
_SYNC_BUILTINS = ("float", "int", "bool")
_SYNC_CALLS = (
    "np.asarray", "np.array", "numpy.asarray", "numpy.array",
    "onp.asarray", "onp.array",
    "jax.device_get", "device_get",
)
_SYNC_METHODS = ("item", "tolist", "__array__")

# attribute tails whose read is trace-time Python, not a device sync
_STATIC_ATTRS = ("shape", "ndim", "size", "dtype")


def _is_static_expr(node: ast.AST) -> bool:
    """Trace-time-constant expressions: converting these costs nothing."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
        return True
    if isinstance(node, ast.Subscript):
        return _is_static_expr(node.value)
    if isinstance(node, ast.BinOp):
        return _is_static_expr(node.left) and _is_static_expr(node.right)
    if isinstance(node, ast.UnaryOp):
        return _is_static_expr(node.operand)
    if isinstance(node, ast.Call):
        return name_suffix_in(call_name(node), ("len",))
    return False


class HostSyncRule(Rule):
    id = "PGL001"
    severity = "error"
    doc = ("host-device sync (float()/.item()/np.asarray/device_get/"
           "bool()) inside a jitted, scanned, or vmapped region")

    def visit_Call(self, node: ast.Call) -> None:
        self.generic_visit(node)
        if not self.ctx.in_traced_region(node):
            return
        cname = call_name(node)
        if cname in _SYNC_BUILTINS:
            if node.args and not _is_static_expr(node.args[0]):
                self.report(
                    node,
                    f"{cname}() on a traced value forces a host sync "
                    f"inside a traced region; keep it a jnp scalar or "
                    f"move the read outside the trace",
                )
            return
        if name_suffix_in(cname, _SYNC_CALLS):
            self.report(
                node,
                f"{cname}(...) pulls a device array to the host inside a "
                f"traced region; use jnp.asarray / restructure so the "
                f"transfer happens outside the trace",
            )
            return
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _SYNC_METHODS
            and not node.args
        ):
            self.report(
                node,
                f".{node.func.attr}() reads a traced value back to the "
                f"host inside a traced region",
            )
