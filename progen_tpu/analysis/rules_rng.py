"""PGL002 — RNG key used twice without an interposing split/fold_in.

jax PRNG keys are VALUES, not stateful generators: passing the same key
to two samplers draws the same bits twice. In a sampler that means
correlated noise (every slot of a batch decoding the same Gumbel
stream); in an init it means tied weights. Nothing errors — outputs are
just silently wrong, and only statistically so.

The rule runs a small per-function dataflow over assignments:

  * a name becomes a FRESH key when assigned from
    ``jax.random.PRNGKey/key/split/fold_in`` (or when it is a function
    parameter named like a key: ``key``, ``rng``, ``*_key`` ...);
  * passing a key to any call CONSUMES it (``split(key)`` included —
    splitting the same key twice yields identical children), EXCEPT
    ``fold_in``, which derives data-dependent children and is the
    sanctioned way to reuse one parent key;
  * consuming an already-consumed key reports.

``if``/``else`` branches analyze independently and merge
conservatively (a name consumed on only one path is not reported
later). Loop bodies run TWICE, so a key consumed inside a loop without
an in-loop re-derivation reports on the simulated second iteration —
the classic ``for i: noise = normal(key, ...)`` bug.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Optional, Set, Tuple

from progen_tpu.analysis.core import Rule, call_name, name_suffix_in

FRESH = "fresh"
CONSUMED = "consumed"
MAYBE = "maybe"  # divergent merge: not reported on later use

_KEY_PRODUCERS = (
    "random.PRNGKey", "PRNGKey", "random.key",
    "random.split", "random.fold_in", "fold_in",
    "random.wrap_key_data",
)
_NON_CONSUMING = (
    "random.fold_in", "fold_in", "random.key_data",
    # abstract evaluation: traces shapes/dtypes only, draws no bits
    "eval_shape", "jax.eval_shape",
)
_KEY_PARAM_RE = re.compile(r"(^|_)(key|keys|rng|rngs|prng)$")
# a key-named param annotated (or defaulted) as a plain host type is not a
# PRNG key — e.g. the TFRecord feature name `key: bytes = b"seq"`
_NON_KEY_ANNOTATIONS = ("str", "bytes", "int", "float", "bool")

_FUNCTION_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _is_key_producer(node: ast.AST) -> bool:
    return isinstance(node, ast.Call) and name_suffix_in(
        call_name(node), _KEY_PRODUCERS
    )


def _key_params(args: ast.arguments) -> Set[str]:
    """Param names that look like PRNG keys, minus any whose annotation
    or default pins them to a plain host type."""
    params = list(args.posonlyargs) + list(args.args)
    defaults: Dict[str, ast.expr] = {}
    for p, d in zip(reversed(params), reversed(args.defaults)):
        defaults[p.arg] = d
    for p, d in zip(args.kwonlyargs, args.kw_defaults):
        if d is not None:
            defaults[p.arg] = d
    out: Set[str] = set()
    for p in params + list(args.kwonlyargs):
        if not _KEY_PARAM_RE.search(p.arg):
            continue
        ann = p.annotation
        if isinstance(ann, ast.Name) and ann.id in _NON_KEY_ANNOTATIONS:
            continue
        d = defaults.get(p.arg)
        if isinstance(d, ast.Constant) and isinstance(
            d.value, (str, bytes, int, float, bool)
        ):
            continue
        out.add(p.arg)
    return out


def _terminates(stmts) -> bool:
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break)
    )


class RngReuseRule(Rule):
    id = "PGL002"
    severity = "error"
    doc = ("RNG key consumed twice without an interposing "
           "jax.random.split/fold_in — identical random bits drawn")

    def run(self):
        for node in ast.walk(self.ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._analyze_function(node)
        return self.findings

    # ----- function-level dataflow ---------------------------------------

    def _analyze_function(self, fn) -> None:
        state: Dict[str, str] = {}
        for name in _key_params(fn.args):
            state[name] = FRESH
        reported: Set[Tuple[int, str]] = set()
        self._exec_block(fn.body, state, reported)

    def _exec_block(self, stmts, state: Dict[str, str],
                    reported: Set[Tuple[int, str]]) -> None:
        for stmt in stmts:
            self._exec_stmt(stmt, state, reported)

    def _exec_stmt(self, stmt, state, reported) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def: closure reads see the current key states, but
            # its params shadow and its consumptions stay local
            inner = dict(state)
            a = stmt.args
            keyish = _key_params(a)
            for p in a.posonlyargs + a.args + a.kwonlyargs:
                if p.arg in keyish:
                    inner[p.arg] = FRESH
                else:
                    inner.pop(p.arg, None)
            self._exec_block(stmt.body, inner, reported)
            state.pop(stmt.name, None)
            return
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = stmt.value
            if value is not None:
                self._eval_expr(value, state, reported)
            targets = (
                stmt.targets if isinstance(stmt, ast.Assign)
                else [stmt.target]
            )
            produced = _is_key_producer(value) if value is not None else False
            alias_state: Optional[str] = None
            if isinstance(value, ast.Name) and value.id in state:
                alias_state = state[value.id]
            for t in targets:
                self._bind_target(t, produced, alias_state, state)
            return
        if isinstance(stmt, ast.Expr):
            self._eval_expr(stmt.value, state, reported)
            return
        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self._eval_expr(stmt.value, state, reported)
            return
        if isinstance(stmt, ast.If):
            self._eval_expr(stmt.test, state, reported)
            s_body, s_else = dict(state), dict(state)
            self._exec_block(stmt.body, s_body, reported)
            self._exec_block(stmt.orelse, s_else, reported)
            # a branch ending in return/raise doesn't fall through: only
            # the surviving branch's state reaches the code after the if
            body_exits = _terminates(stmt.body)
            else_exits = _terminates(stmt.orelse)
            if body_exits and not else_exits:
                state.clear()
                state.update(s_else)
            elif else_exits and not body_exits:
                state.clear()
                state.update(s_body)
            else:
                self._merge(state, s_body, s_else)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._eval_expr(stmt.iter, state, reported)
            self._bind_target(stmt.target, False, None, state)
            for _ in range(2):  # second pass = simulated next iteration
                self._exec_block(stmt.body, state, reported)
            self._exec_block(stmt.orelse, state, reported)
            return
        if isinstance(stmt, ast.While):
            for _ in range(2):
                self._eval_expr(stmt.test, state, reported)
                self._exec_block(stmt.body, state, reported)
            self._exec_block(stmt.orelse, state, reported)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._eval_expr(item.context_expr, state, reported)
                if item.optional_vars is not None:
                    self._bind_target(item.optional_vars, False, None, state)
            self._exec_block(stmt.body, state, reported)
            return
        if isinstance(stmt, ast.Try):
            self._exec_block(stmt.body, state, reported)
            for h in stmt.handlers:
                self._exec_block(h.body, dict(state), reported)
            self._exec_block(stmt.orelse, state, reported)
            self._exec_block(stmt.finalbody, state, reported)
            return
        # anything else: scan contained expressions conservatively
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._eval_expr(child, state, reported)

    def _bind_target(self, target, produced: bool,
                     alias_state: Optional[str], state) -> None:
        if isinstance(target, ast.Name):
            if produced:
                state[target.id] = FRESH
            elif alias_state is not None:
                state[target.id] = alias_state
            else:
                state.pop(target.id, None)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._bind_target(elt, produced, alias_state, state)

    def _merge(self, state, s1, s2) -> None:
        for name in set(s1) | set(s2):
            a, b = s1.get(name), s2.get(name)
            if a == b and a is not None:
                state[name] = a
            elif a is None and b is None:
                state.pop(name, None)
            else:
                state[name] = MAYBE

    # ----- expression consumption ----------------------------------------

    def _eval_expr(self, expr, state, reported) -> None:
        if isinstance(expr, ast.Lambda):
            inner = dict(state)
            a = expr.args
            for p in a.posonlyargs + a.args + a.kwonlyargs:
                inner.pop(p.arg, None)
            self._eval_expr(expr.body, inner, reported)
            return
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            consuming = not name_suffix_in(call_name(node), _NON_CONSUMING)
            for arg in list(node.args) + [
                kw.value for kw in node.keywords
            ]:
                if not isinstance(arg, ast.Name):
                    continue
                st = state.get(arg.id)
                if st is None or not consuming:
                    continue
                if st == CONSUMED:
                    key = (arg.lineno, arg.id)
                    if key not in reported:
                        reported.add(key)
                        self.report(
                            arg,
                            f"RNG key '{arg.id}' is consumed again "
                            f"without an interposing jax.random.split/"
                            f"fold_in — the same random bits are drawn "
                            f"twice",
                        )
                else:
                    state[arg.id] = CONSUMED
