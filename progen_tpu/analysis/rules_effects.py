"""PGL005 — side effects inside traced code.

``print``, tracker/telemetry emission, and file IO inside a
jit/scan/shard_map body run ONCE, at trace time — then never again, no
matter how many steps execute. The symptom is a metric that freezes at
its step-0 value or a log line that vanishes after the first call;
nothing crashes, so only a rule catches it. ``jax.debug.print`` /
``jax.debug.callback`` / ``io_callback`` / ``pl.debug_print`` are the
sanctioned effectful escape hatches and are exempt.

Trace-time-only effects that are INTENTIONAL (e.g. reading a kernel
policy table while tracing a shard_map body) get an inline
``# progen: ignore[PGL005]`` with the justification right there.
"""

from __future__ import annotations

import ast

from progen_tpu.analysis.core import Rule, call_name, name_suffix_in

_EFFECT_CALLS = ("print", "open", "input", "step_print")
# attribute-call tails that emit/persist: tracker + telemetry + file IO
_EFFECT_METHODS = (
    "log", "log_event", "log_html", "emit",
    "write", "writelines", "write_text", "write_bytes",
    "info", "warning", "error", "debug", "exception",
)
_ALLOWED = (
    "jax.debug.print", "debug.print", "pl.debug_print",
    "jax.debug.callback", "debug.callback",
    "jax.experimental.io_callback", "io_callback",
    "host_callback.call",
)


class TracedEffectsRule(Rule):
    id = "PGL005"
    severity = "error"
    doc = ("side effect (print/tracker.log/telemetry emit/file IO) "
           "inside traced code runs once at trace time, then never again")

    def visit_Call(self, node: ast.Call) -> None:
        self.generic_visit(node)
        if not self.ctx.in_traced_region(node):
            return
        cname = call_name(node)
        if name_suffix_in(cname, _ALLOWED):
            return
        if cname in _EFFECT_CALLS:
            self.report(
                node,
                f"{cname}(...) inside a traced region executes once at "
                f"trace time only; use jax.debug.print/io_callback or "
                f"move it outside the trace",
            )
            return
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _EFFECT_METHODS
        ):
            self.report(
                node,
                f".{node.func.attr}(...) inside a traced region executes "
                f"once at trace time only — telemetry/log records from "
                f"here will silently stop after the first step",
            )
