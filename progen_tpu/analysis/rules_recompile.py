"""PGL004 — recompilation hazards.

XLA compiles once per (shapes, dtypes, static argument VALUES, function
identity). Three syntactic patterns defeat that cache and each has
burned a real JAX codebase:

  * an f-string / str.format / list / dict / set flowing into a static
    argument: every distinct value (or every call, for unhashables —
    those raise) is a fresh compile of the whole step;
  * ``jax.jit(lambda ...: ...)`` inside a function or loop: the lambda
    is a NEW function object per execution, so the jit cache never
    hits;
  * ``jax.jit(f)(x)`` immediately invoked inside a loop: same cache
    miss, one compile per iteration.
  * Python ``if``/``while`` directly on a traced parameter: under jit
    this raises TracerBoolConversionError; "fixed" by making the value
    static, it becomes one compile per distinct value — flag the branch
    itself so neither outcome ships.

Static-argument call-site checking resolves through the module's jit
wrapper registry (analysis/traced.py), so positional arguments map to
``static_argnames`` through the wrapped def's real signature.
"""

from __future__ import annotations

import ast

from progen_tpu.analysis.core import Rule, call_name, name_suffix_in
from progen_tpu.analysis.traced import static_call_args

_UNHASHABLE_NODES = (
    ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp, ast.SetComp,
    ast.GeneratorExp,
)
_FUNCTION_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
_JIT_NAMES = ("jax.jit", "jit", "pjit")


def _is_varying_str(node: ast.AST) -> bool:
    if isinstance(node, ast.JoinedStr):
        return True
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == "format"
    )


class RecompileRule(Rule):
    id = "PGL004"
    severity = "error"
    doc = ("recompilation hazard: unhashable/varying static args, "
           "jit-of-fresh-lambda, jit-in-loop, branch on traced value")

    def _in_loop(self, node: ast.AST) -> bool:
        return any(
            isinstance(a, (ast.For, ast.While, ast.AsyncFor))
            for a in self.ctx.ancestors(node)
        )

    def _in_function(self, node: ast.AST) -> bool:
        return self.ctx.enclosing_function(node) is not None

    def visit_Call(self, node: ast.Call) -> None:
        self.generic_visit(node)
        cname = call_name(node)
        # (a) static args at call sites of registered jit wrappers
        registry = getattr(self.ctx.traced_index, "jit_registry", {})
        info = registry.get(cname) if cname else None
        if info is not None and info.static_names:
            for pname, arg in static_call_args(info, node):
                if _is_varying_str(arg):
                    self.report(
                        arg,
                        f"f-string/format() value flowing into static "
                        f"argument '{pname}' of '{info.name}' — every "
                        f"distinct string is a full recompile",
                    )
                elif isinstance(arg, _UNHASHABLE_NODES):
                    self.report(
                        arg,
                        f"non-hashable {type(arg).__name__} passed as "
                        f"static argument '{pname}' of '{info.name}' — "
                        f"jit static args must be hashable (use a tuple)",
                    )
        # (b)/(c) jit of a fresh lambda / jit-in-loop immediate invocation
        if name_suffix_in(cname, _JIT_NAMES) and node.args:
            if isinstance(node.args[0], ast.Lambda) and (
                self._in_function(node) or self._in_loop(node)
            ):
                self.report(
                    node,
                    "jax.jit(<lambda>) inside a function/loop creates a "
                    "new cache entry per execution — hoist the jitted "
                    "callable to module scope or cache the wrapper",
                )
            parent = self.ctx.parent(node)
            if (
                isinstance(parent, ast.Call)
                and parent.func is node
                and self._in_loop(node)
            ):
                self.report(
                    node,
                    "jax.jit(f)(...) immediately invoked inside a loop "
                    "recompiles every iteration — build the jitted "
                    "function once outside the loop",
                )

    # (d) Python branch on a traced parameter
    def _check_branch(self, node, test: ast.AST) -> None:
        idx = self.ctx.traced_index
        if idx is None:
            return
        traced_def = idx.enclosing_traced_def(node)
        if traced_def is None:
            return
        a = traced_def.args
        params = {
            p.arg for p in a.posonlyargs + a.args + a.kwonlyargs
        } - {"self", "cls"}
        if isinstance(traced_def, (ast.FunctionDef, ast.AsyncFunctionDef)):
            info = idx.jit_registry.get(traced_def.name)
            if info is not None:
                params -= info.static_names
        name = self._bare_traced_name(test, params)
        if name:
            self.report(
                test,
                f"Python branch on traced value '{name}' — under jit "
                f"this raises at trace time, and making it static means "
                f"one recompile per distinct value; use jnp.where/"
                f"lax.cond or hoist the decision out of the trace",
            )

    def _bare_traced_name(self, test: ast.AST, params) -> str:
        """A param name used as a bare truth value in ``test`` ('' if
        none): Name, ``not Name``, comparisons of Names, bool ops of
        those. Names under attributes/subscripts/calls (``x.shape[0]``)
        do not count — those are trace-time Python."""
        if isinstance(test, ast.Name):
            return test.id if test.id in params else ""
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            return self._bare_traced_name(test.operand, params)
        if isinstance(test, ast.Compare):
            # `x is None` / `x is not None` are trace-time identity
            # checks on default sentinels, not value reads
            if all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops
            ):
                return ""
            for side in [test.left] + list(test.comparators):
                if isinstance(side, ast.Name) and side.id in params:
                    return side.id
            return ""
        if isinstance(test, ast.BoolOp):
            for v in test.values:
                name = self._bare_traced_name(v, params)
                if name:
                    return name
        return ""

    def visit_If(self, node: ast.If) -> None:
        self.generic_visit(node)
        self._check_branch(node, node.test)

    def visit_While(self, node: ast.While) -> None:
        self.generic_visit(node)
        self._check_branch(node, node.test)

    def visit_IfExp(self, node: ast.IfExp) -> None:
        self.generic_visit(node)
        self._check_branch(node, node.test)
