"""Traced-region and jit-wrapper indexing.

Almost every rule here needs the answer to one question before it can
say anything useful: *does this code run under a jax trace?* A
``float()`` in host code is a log line; the same call inside a jitted
step is a device sync in the hot loop. This module computes that answer
syntactically, once per module:

  * functions whose DECORATORS trace them (``@jax.jit``,
    ``@functools.partial(jax.jit, ...)``, ``@jax.vmap``, grad, remat...);
  * functions/lambdas PASSED to tracing callables (``lax.scan`` bodies,
    ``lax.fori_loop`` bodies, ``shard_map`` shard functions, ``vmap``ed
    callables) — resolved by name against defs in enclosing scopes;
  * everything lexically inside either of the above (a nested ``def``
    in a jitted function executes at trace time).

It also builds the module's JIT WRAPPER REGISTRY: for every function
jitted with ``static_argnums``/``static_argnames`` or
``donate_argnums``/``donate_argnames`` — whether via decorator or via
``name = jax.jit(fn, ...)`` — the registry records which parameters are
static and which are donated, mapped through the wrapped def's
signature so positional call sites resolve to names. PGL003 (donated
use-after-call) and PGL004 (recompilation hazards) are lookups against
it. Resolution is module-local by design: a linter that guessed across
imports would guess wrong quietly.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from progen_tpu.analysis.core import (
    ModuleContext,
    call_name,
    dotted_name,
    name_suffix_in,
)

# decorators that make the decorated function's body traced
TRACING_DECORATORS = (
    "jax.jit", "jit", "pjit", "jax.pjit",
    "jax.vmap", "vmap", "jax.pmap", "pmap",
    "jax.grad", "grad", "jax.value_and_grad", "value_and_grad",
    "jax.checkpoint", "jax.remat", "nn.remat", "nn.jit",
    "jax.named_call",
)

# callables whose function-valued arguments are traced bodies
TRACING_CALLERS = (
    "lax.fori_loop", "fori_loop",
    "lax.scan",
    "lax.while_loop", "while_loop",
    "lax.cond", "lax.switch", "lax.map", "lax.associative_scan",
    "jax.jit", "jit", "jax.vmap", "vmap", "jax.pmap", "pmap",
    "jax.grad", "grad", "jax.value_and_grad", "value_and_grad",
    "jax.checkpoint", "jax.remat",
    "shard_map",
)

_FUNCTION_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _is_partial_of(call: ast.Call, suffixes) -> bool:
    """``functools.partial(jax.jit, ...)`` / ``partial(jit, ...)``."""
    if not name_suffix_in(call_name(call), ("partial",)):
        return False
    return bool(call.args) and name_suffix_in(
        dotted_name(call.args[0]), suffixes
    )


def _decorator_traces(dec: ast.AST) -> bool:
    if isinstance(dec, ast.Call):
        if name_suffix_in(call_name(dec), TRACING_DECORATORS):
            return True
        return _is_partial_of(dec, TRACING_DECORATORS)
    return name_suffix_in(dotted_name(dec), TRACING_DECORATORS)


def _int_literals(node: ast.AST) -> Optional[List[int]]:
    """Ints from a literal int / tuple / list, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if not (
                isinstance(elt, ast.Constant) and isinstance(elt.value, int)
            ):
                return None
            out.append(elt.value)
        return out
    return None


def _str_literals(node: ast.AST) -> Optional[List[str]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if not (
                isinstance(elt, ast.Constant) and isinstance(elt.value, str)
            ):
                return None
            out.append(elt.value)
        return out
    return None


@dataclass
class JitInfo:
    """One jit-wrapped callable the module can call by name."""

    name: str  # the callable name call sites use
    params: List[str] = field(default_factory=list)  # wrapped signature
    static_names: Set[str] = field(default_factory=set)
    donated_names: Set[str] = field(default_factory=set)
    def_node: Optional[ast.AST] = None

    def param_for_pos(self, pos: int) -> Optional[str]:
        return self.params[pos] if 0 <= pos < len(self.params) else None


def _params_of(fn: ast.AST) -> List[str]:
    if not isinstance(fn, _FUNCTION_NODES):
        return []
    a = fn.args
    return [p.arg for p in (a.posonlyargs + a.args)]


def _jit_kw_sets(call: ast.Call, params: List[str]):
    """(static_names, donated_names) from a jit(...) call's keywords,
    positions resolved through ``params``."""
    static: Set[str] = set()
    donated: Set[str] = set()
    for kw in call.keywords:
        if kw.arg in ("static_argnums", "donate_argnums"):
            nums = _int_literals(kw.value) or []
            target = static if kw.arg == "static_argnums" else donated
            for i in nums:
                name = params[i] if 0 <= i < len(params) else None
                if name:
                    target.add(name)
        elif kw.arg in ("static_argnames", "donate_argnames"):
            names = _str_literals(kw.value) or []
            target = static if kw.arg == "static_argnames" else donated
            target.update(names)
    return static, donated


class TracedIndex:
    """Per-module map of traced function nodes + jit wrapper registry.

    Construction attaches the index to the context
    (``ctx.traced_index``) so ``ctx.in_traced_region`` works.
    """

    def __init__(self, ctx: ModuleContext):
        self.ctx = ctx
        self.traced: Set[ast.AST] = set()
        self.jit_registry: Dict[str, JitInfo] = {}
        # (scope node or None for module) -> {name: def node}
        self._defs: Dict[Optional[ast.AST], Dict[str, ast.AST]] = {}
        self._collect_defs()
        self._mark_decorated()
        self._mark_bodies_and_registry()
        ctx.traced_index = self

    # ----- def collection -------------------------------------------------

    def _collect_defs(self) -> None:
        for node in ast.walk(self.ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scope = self.ctx.enclosing_function(node)
                self._defs.setdefault(scope, {})[node.name] = node

    def resolve_def(self, name: str,
                    from_node: ast.AST) -> Optional[ast.AST]:
        """Innermost def named ``name`` visible from ``from_node``."""
        scope = self.ctx.enclosing_function(from_node)
        while True:
            found = self._defs.get(scope, {}).get(name)
            if found is not None:
                return found
            if scope is None:
                return None
            scope = self.ctx.enclosing_function(scope)

    # ----- traced marking -------------------------------------------------

    def _mark_decorated(self) -> None:
        for node in ast.walk(self.ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if any(_decorator_traces(d) for d in node.decorator_list):
                    self.traced.add(node)

    def _mark_arg(self, arg: ast.AST, call: ast.Call) -> None:
        if isinstance(arg, ast.Lambda):
            self.traced.add(arg)
        elif isinstance(arg, ast.Name):
            fn = self.resolve_def(arg.id, call)
            if fn is not None:
                self.traced.add(fn)

    def _mark_bodies_and_registry(self) -> None:
        for node in ast.walk(self.ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            cname = call_name(node)
            if name_suffix_in(cname, TRACING_CALLERS) or _is_partial_of(
                node, TRACING_DECORATORS
            ):
                args = node.args[1:] if _is_partial_of(
                    node, TRACING_DECORATORS
                ) else node.args
                for arg in args:
                    self._mark_arg(arg, node)
            self._maybe_register_jit(node)
        # decorator-carried static/donate info for decorated defs
        for node in ast.walk(self.ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for dec in node.decorator_list:
                if not isinstance(dec, ast.Call):
                    continue
                is_jit = name_suffix_in(
                    call_name(dec), ("jax.jit", "jit", "pjit")
                ) or _is_partial_of(dec, ("jax.jit", "jit", "pjit"))
                if not is_jit:
                    continue
                params = _params_of(node)
                static, donated = _jit_kw_sets(dec, params)
                if static or donated:
                    self.jit_registry[node.name] = JitInfo(
                        name=node.name,
                        params=params,
                        static_names=static,
                        donated_names=donated,
                        def_node=node,
                    )

    def _maybe_register_jit(self, call: ast.Call) -> None:
        """``name = jax.jit(fn, static_argnums=..., donate_argnums=...)``
        -> registry entry under ``name`` (the assignment target)."""
        if not name_suffix_in(call_name(call), ("jax.jit", "jit", "pjit")):
            return
        if not (call.args and call.keywords):
            return
        fn_arg = call.args[0]
        params: List[str] = []
        fn_node = None
        if isinstance(fn_arg, ast.Name):
            fn_node = self.resolve_def(fn_arg.id, call)
            params = _params_of(fn_node) if fn_node is not None else []
        elif isinstance(fn_arg, ast.Lambda):
            fn_node = fn_arg
            params = _params_of(fn_arg)
        static, donated = _jit_kw_sets(call, params)
        if not (static or donated):
            return
        parent = self.ctx.parent(call)
        if isinstance(parent, ast.Assign) and len(parent.targets) == 1 and \
                isinstance(parent.targets[0], ast.Name):
            self.jit_registry[parent.targets[0].id] = JitInfo(
                name=parent.targets[0].id,
                params=params,
                static_names=static,
                donated_names=donated,
                def_node=fn_node,
            )

    # ----- queries --------------------------------------------------------

    def in_traced_region(self, node: ast.AST) -> bool:
        if node in self.traced:
            return True
        return any(
            anc in self.traced
            for anc in self.ctx.ancestors(node)
            if isinstance(anc, _FUNCTION_NODES)
        )

    def enclosing_traced_def(self, node: ast.AST) -> Optional[ast.AST]:
        if node in self.traced:
            return node
        for anc in self.ctx.ancestors(node):
            if anc in self.traced and isinstance(anc, _FUNCTION_NODES):
                return anc
        return None


def static_call_args(
    info: JitInfo, call: ast.Call
) -> List[Tuple[str, ast.AST]]:
    """(static param name, argument expression) pairs for one call of a
    registered jit wrapper."""
    out: List[Tuple[str, ast.AST]] = []
    for i, arg in enumerate(call.args):
        name = info.param_for_pos(i)
        if name and name in info.static_names:
            out.append((name, arg))
    for kw in call.keywords:
        if kw.arg and kw.arg in info.static_names:
            out.append((kw.arg, kw.value))
    return out


def donated_call_args(
    info: JitInfo, call: ast.Call
) -> List[Tuple[str, ast.AST]]:
    out: List[Tuple[str, ast.AST]] = []
    for i, arg in enumerate(call.args):
        name = info.param_for_pos(i)
        if name and name in info.donated_names:
            out.append((name, arg))
    for kw in call.keywords:
        if kw.arg and kw.arg in info.donated_names:
            out.append((kw.arg, kw.value))
    return out
