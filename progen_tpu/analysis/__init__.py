"""progen-tpu-lint: JAX/TPU-aware static analysis for this stack.

The defect classes that hurt a TPU training/serving stack most —
silent recompilation, host-device syncs in hot loops, RNG key reuse,
donated-buffer use-after-free, trace-time-only side effects, unpaired
telemetry spans — are invisible to pytest on CPU and only surface as
goodput loss or wrong samples on a real pod. This package moves their
detection left of runtime: an AST linter with one rule per defect
class, run over the whole package in CI (``progen-tpu-lint
progen_tpu/``), failing the build on any non-baselined finding.

Rules (see each module's docstring for the full rationale):

  PGL001  host-device sync inside a jitted/scanned region
  PGL002  RNG key reuse without split/fold_in
  PGL003  donated argument referenced after the donating call
  PGL004  recompilation hazards (varying/unhashable static args,
          jit-of-fresh-lambda, branch on traced values)
  PGL005  side effects inside traced code (run once, at trace time)
  PGL006  telemetry hygiene (literal span names, event-grammar
          producer checks via analysis/event_grammar.py,
          Prometheus-legal metric names)
  PGL007  durable-path write discipline (atomic tmp+fsync+replace
          publishes, fsync'd ledger appends)
  PGL008  lock discipline (guarded-attr consistency; no blocking
          locks or lock-holding I/O in tap/excepthook/signal handlers)
  PGL009  chaos-site drift (every PROGEN_CHAOS target referenced in
          tests/CI/docs names an installed site; KNOWN_TARGETS
          matches the installed surface) — whole-project pass
  PGL010  event-grammar exhaustiveness, consumer side (dispatches on
          op/status/state handle every declared value or carry a
          default branch)

Suppress a single accepted finding inline with
``# progen: ignore[PGL005]``; grandfathered findings live in
``lint_baseline.json`` with a reason string each (analysis/runner.py).
"""

from progen_tpu.analysis.core import (
    Finding,
    ModuleContext,
    ProjectRule,
    Rule,
)
from progen_tpu.analysis.event_grammar import (
    BY_EV,
    GRAMMARS,
    EventGrammar,
    render_grammar_markdown,
)
from progen_tpu.analysis.project import ProjectContext, default_text_files
from progen_tpu.analysis.runner import (
    PROJECT_RULES,
    RULE_DOCS,
    RULES,
    BaselineError,
    discover_files,
    lint_file,
    lint_paths,
    load_baseline,
    report_json,
)
from progen_tpu.analysis.traced import TracedIndex

__all__ = [
    "Finding",
    "ModuleContext",
    "ProjectContext",
    "ProjectRule",
    "Rule",
    "RULES",
    "PROJECT_RULES",
    "RULE_DOCS",
    "BY_EV",
    "GRAMMARS",
    "EventGrammar",
    "BaselineError",
    "TracedIndex",
    "default_text_files",
    "discover_files",
    "lint_file",
    "lint_paths",
    "load_baseline",
    "render_grammar_markdown",
    "report_json",
]
