"""PGL010 — event-grammar exhaustiveness, consumer side.

PGL006 polices producers: a record family's enum fields only carry
declared values. This rule polices the other half of the contract: a
READER that dispatches on one of those enum fields — an
``if op == "accept": ... elif op == "token": ...`` chain, a membership
test, a ``match`` statement — must either handle every value the
grammar declares or carry an explicit default branch. Without this,
extending a grammar is a trap: add ``"evict"`` to the prefix-cache ops
and every fold/replay/summarize consumer that was written against the
two-value alphabet silently drops the new records — no crash, just
wrong totals (the deploy-ledger fold and the journal replay are
exactly such consumers; both are exhaustive today and are this rule's
true negatives).

Detection is deliberately conservative — silence over false alarms:

  * a *dispatch* is an if/elif chain (or ``match``) whose tests all
    compare the same subject against string literals, where the
    subject is ``rec.get(F)``/``rec[F]`` (or a variable assigned from
    one) for a field ``F`` in ``event_grammar.DISPATCH_FIELDS``;
  * chains handling fewer than two distinct values are filters, not
    dispatches, and are skipped;
  * the handled-value set binds to a grammar only when it is
    unambiguous: the enclosing function pins the ``ev`` (an
    ``x.get("ev") == "journal"`` comparison), or exactly one declared
    enum for ``F`` overlaps the handled values;
  * a chain with an ``else`` (or ``case _:``) is exhaustive by
    construction — the default branch is the extension point.

Once bound: handled ⊊ declared with no default → report the missing
values; a handled literal outside the declared alphabet → report it
(the consumer branches on a value no producer may emit — dead code or
a misspelling).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from progen_tpu.analysis.core import Rule
from progen_tpu.analysis.event_grammar import DISPATCH_FIELDS, enum_index

_ENUM_INDEX = enum_index()


def _subject_field(expr: ast.AST) -> Optional[str]:
    """The dispatch field when ``expr`` is ``X.get(F)``/``X[F]`` for
    F in DISPATCH_FIELDS, else None."""
    if isinstance(expr, ast.Call) and isinstance(
        expr.func, ast.Attribute
    ) and expr.func.attr == "get" and expr.args:
        key = expr.args[0]
        if isinstance(key, ast.Constant) and key.value in \
                DISPATCH_FIELDS:
            return key.value
    if isinstance(expr, ast.Subscript):
        sl = expr.slice
        if isinstance(sl, ast.Constant) and sl.value in DISPATCH_FIELDS:
            return sl.value
    return None


def _str_consts(node: ast.AST) -> Optional[Set[str]]:
    """The literal string set of a Constant / tuple-set-list of
    Constants, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.Set, ast.List)):
        out: Set[str] = set()
        for elt in node.elts:
            if not (isinstance(elt, ast.Constant)
                    and isinstance(elt.value, str)):
                return None
            out.add(elt.value)
        return out
    return None


class GrammarConsumerRule(Rule):
    id = "PGL010"
    severity = "error"
    doc = ("event-grammar exhaustiveness, consumer side: readers "
           "dispatching on rec['op']/['status']/['state'] must handle "
           "every value the grammar declares or carry an explicit "
           "default branch — otherwise extending a grammar silently "
           "drops records in every stale consumer")

    def run(self):
        for node in ast.walk(self.ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._check_function(node)
        return self.findings

    # ----- per-function ---------------------------------------------------

    def _check_function(self, fn) -> None:
        field_vars = self._field_vars(fn)
        pinned_evs = self._pinned_evs(fn)
        for node in ast.walk(fn):
            if isinstance(node, ast.If) and not self._is_elif(node):
                self._check_chain(node, field_vars, pinned_evs)
            elif isinstance(node, ast.Match):
                self._check_match(node, field_vars, pinned_evs)

    def _is_elif(self, node: ast.If) -> bool:
        parent = self.ctx.parent(node)
        return isinstance(parent, ast.If) and parent.orelse == [node]

    def _field_vars(self, fn) -> Dict[str, str]:
        """var name -> dispatch field, for ``op = rec.get("op")``
        style local bindings."""
        out: Dict[str, str] = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                f = _subject_field(node.value)
                if f is not None:
                    out[node.targets[0].id] = f
        return out

    def _pinned_evs(self, fn) -> Set[str]:
        """ev literals this function compares ``rec.get("ev")`` (or
        ``rec["ev"]``) against — used to disambiguate grammar binding."""
        out: Set[str] = set()
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Compare)
                    and len(node.ops) == 1):
                continue
            sides = (node.left, node.comparators[0])
            for a, b in (sides, sides[::-1]):
                if self._is_ev_access(a):
                    vals = _str_consts(b)
                    if vals:
                        out.update(vals)
        return out

    @staticmethod
    def _is_ev_access(expr: ast.AST) -> bool:
        if isinstance(expr, ast.Call) and isinstance(
            expr.func, ast.Attribute
        ) and expr.func.attr == "get" and expr.args:
            k = expr.args[0]
            return isinstance(k, ast.Constant) and k.value == "ev"
        if isinstance(expr, ast.Subscript):
            sl = expr.slice
            return isinstance(sl, ast.Constant) and sl.value == "ev"
        return False

    # ----- chain extraction -----------------------------------------------

    def _test_facts(
        self, test: ast.AST, field_vars: Dict[str, str]
    ) -> Optional[Tuple[str, Set[str]]]:
        """(field, values) when ``test`` compares a dispatch subject
        against string literals, else None."""
        if isinstance(test, ast.BoolOp) and isinstance(
            test.op, ast.Or
        ):
            field: Optional[str] = None
            values: Set[str] = set()
            for sub in test.values:
                facts = self._test_facts(sub, field_vars)
                if facts is None:
                    return None
                f, v = facts
                if field is None:
                    field = f
                elif field != f:
                    return None
                values.update(v)
            return (field, values) if field else None
        if not (isinstance(test, ast.Compare) and len(test.ops) == 1):
            return None
        op = test.ops[0]
        sides = (test.left, test.comparators[0])
        for subj, lit in (sides, sides[::-1]):
            field = self._resolve_field(subj, field_vars)
            if field is None:
                continue
            vals = _str_consts(lit)
            if vals is None:
                return None
            if isinstance(op, ast.Eq) or (
                isinstance(op, ast.In) and subj is sides[0]
            ):
                return (field, vals)
            return None
        return None

    @staticmethod
    def _resolve_field(expr: ast.AST,
                       field_vars: Dict[str, str]) -> Optional[str]:
        f = _subject_field(expr)
        if f is not None:
            return f
        if isinstance(expr, ast.Name):
            return field_vars.get(expr.id)
        return None

    def _check_chain(self, head: ast.If, field_vars, pinned_evs) -> None:
        field: Optional[str] = None
        handled: Set[str] = set()
        cur: ast.stmt = head
        has_default = False
        while True:
            facts = self._test_facts(cur.test, field_vars)
            if facts is None:
                return  # mixed chain: not a pure enum dispatch
            f, vals = facts
            if field is None:
                field = f
            elif field != f:
                return
            handled.update(vals)
            if not cur.orelse:
                break
            if len(cur.orelse) == 1 and isinstance(
                cur.orelse[0], ast.If
            ):
                cur = cur.orelse[0]
                continue
            has_default = True
            break
        self._judge(head, field, handled, has_default, pinned_evs)

    def _check_match(self, node: ast.Match, field_vars,
                     pinned_evs) -> None:
        field = self._resolve_field(node.subject, field_vars)
        if field is None:
            return
        handled: Set[str] = set()
        has_default = False
        for case in node.cases:
            pat = case.pattern
            if isinstance(pat, ast.MatchValue) and isinstance(
                pat.value, ast.Constant
            ) and isinstance(pat.value.value, str):
                handled.add(pat.value.value)
            elif isinstance(pat, ast.MatchOr):
                for sub in pat.patterns:
                    if isinstance(sub, ast.MatchValue) and isinstance(
                        sub.value, ast.Constant
                    ) and isinstance(sub.value.value, str):
                        handled.add(sub.value.value)
                    else:
                        return
            elif isinstance(pat, ast.MatchAs) and pat.pattern is None:
                has_default = True
            else:
                return
        self._judge(node, field, handled, has_default, pinned_evs)

    # ----- binding + verdict ----------------------------------------------

    def _judge(self, node, field: Optional[str], handled: Set[str],
               has_default: bool, pinned_evs: Set[str]) -> None:
        if field is None or len(handled) < 2:
            return  # a one-value test is a filter, not a dispatch
        entry = self._bind(field, handled, pinned_evs)
        if entry is None:
            return
        unknown = handled - entry.values
        if unknown:
            self.report(
                node,
                f"dispatch on '{field}' handles "
                f"{'/'.join(sorted(unknown))} — not in the declared "
                f"'{entry.ev}' alphabet "
                f"({'/'.join(sorted(entry.values))}); no producer may "
                f"emit it, so this branch is dead code or a "
                f"misspelling (see analysis/event_grammar.py)",
            )
            return
        if has_default:
            return
        missing = entry.values - handled
        if missing:
            self.report(
                node,
                f"dispatch on '{field}' of the '{entry.ev}' grammar "
                f"handles {'/'.join(sorted(handled))} but not "
                f"{'/'.join(sorted(missing))}, and has no default "
                f"branch — records with the unhandled value(s) are "
                f"silently dropped; handle them or add an explicit "
                f"else (see analysis/event_grammar.py)",
            )

    def _bind(self, field: str, handled: Set[str], pinned_evs: Set[str]):
        entries = _ENUM_INDEX.get(field, [])
        if pinned_evs:
            pinned = [
                e for e in entries
                if e.ev in pinned_evs and (handled & e.values)
            ]
            if len(pinned) == 1:
                return pinned[0]
        overlapping = [e for e in entries if handled & e.values]
        if len(overlapping) == 1:
            return overlapping[0]
        supersets = [e for e in overlapping if handled <= e.values]
        if len(supersets) == 1:
            return supersets[0]
        return None
