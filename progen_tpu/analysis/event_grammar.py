"""The event-grammar registry: one declarative table for every ``ev``.

PRs 2-19 grew a fleet of JSONL event streams — spans, request
lifecycles, replay journals, routing decisions, deploy ledgers, alert
deliveries — each with a producer module that owns the record shape and
a set of consumers (summarize, stitch, the kill matrices, CI smokes)
that grep exactly that shape. The grammar used to live as ~600 lines of
hand-coded per-ev branches inside rules_telemetry.py, which meant the
producer rule (PGL006) was the ONLY thing that knew the alphabets: a
consumer could silently dispatch on half an enum and nothing noticed.

This module is now the single source of truth. Each :class:`EventGrammar`
declares, for one ``ev`` value:

  * ``owners`` — the module(s) allowed to build the record (path
    suffixes, or package dirs written ``"/pkg/"``);
  * ``scope`` — ``"emit"`` (checked on dicts passed to
    ``emit()``/``log_event()``) or ``"dict"`` (checked on EVERY dict
    literal, for records that reach disk through a writer other than
    the telemetry sink — TSDB samples, alert files);
  * ``required`` — fields that must be present on every record;
  * ``enums`` — fields whose literal values must come from a declared
    alphabet;
  * ``check_trace_key`` — whether misspellings of the one blessed
    trace-context key (``trace_id``) are policed on this record.

PGL006 (rules_telemetry.py) validates producers against this table;
PGL010 (rules_grammar_consumers.py) validates consumers — a reader
dispatching on ``rec["op"]``/``rec["status"]``/``rec["state"]`` must
handle every declared value or carry an explicit default branch.
``progen-tpu-lint --registry-dump`` renders the table into the README's
generated "Event grammars" section, and CI asserts the committed docs
match the dump.

Pure data + stdlib: importable from the jax-free lint CLI.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Tuple

# the record fields consumers dispatch on — PGL010 recognizes a
# dispatch by these subscript/.get() keys, and binds the handled value
# set back to a grammar through the enum declarations below
DISPATCH_FIELDS = (
    "op", "status", "state", "ph", "kind", "action", "reason", "role",
)


@dataclass(frozen=True)
class EnumField:
    """One enum-constrained field: literal values must come from
    ``values``. ``what``/``why`` feed the finding message."""

    field: str
    values: Tuple[str, ...]
    what: str
    why: str


@dataclass(frozen=True)
class EventGrammar:
    """The declared shape of one ``ev`` record family."""

    ev: str
    owners: Tuple[str, ...]
    owner_message: str
    scope: str = "emit"  # "emit" | "dict"
    required: Tuple[str, ...] = ()
    required_message: str = ""
    enums: Tuple[EnumField, ...] = ()
    check_trace_key: bool = False

    def owns(self, path: str) -> bool:
        p = path.replace("\\", "/")
        for owner in self.owners:
            if owner.endswith("/"):
                if owner in p:
                    return True
            elif p.endswith(owner):
                return True
        return False

    def enum_for(self, field_name: str) -> "EnumField | None":
        for e in self.enums:
            if e.field == field_name:
                return e
        return None


def _g(*args, **kwargs) -> EventGrammar:
    return EventGrammar(*args, **kwargs)


_SPAN_BE_MESSAGE = (
    "raw B/E span record emitted directly — use the span() context "
    "manager, whose finally-block guarantees the matching E even on "
    "exceptions"
)

GRAMMARS: Tuple[EventGrammar, ...] = (
    _g(
        ev="B",
        owners=("telemetry/spans.py",),
        owner_message=_SPAN_BE_MESSAGE,
    ),
    _g(
        ev="E",
        owners=("telemetry/spans.py",),
        owner_message=_SPAN_BE_MESSAGE,
    ),
    _g(
        ev="req",
        owners=("serving/scheduler.py", "serving/router.py"),
        owner_message=(
            "raw async req record emitted outside serving/scheduler.py "
            "or serving/router.py — they own the request lifecycle "
            "grammar (every 'b' must get its 'e' on all exit paths); go "
            "through Scheduler/Router, not hand-rolled records"
        ),
        enums=(
            EnumField(
                "ph", ("b", "n", "e"), "req record 'ph'",
                "async trace events only use 'b' (begin), 'n' "
                "(instant), 'e' (end); anything else is dropped by the "
                "trace builder",
            ),
        ),
        check_trace_key=True,
    ),
    _g(
        ev="route",
        owners=("serving/router.py",),
        owner_message=(
            "raw route record emitted outside serving/router.py — the "
            "routing-decision grammar is what summarize's router "
            "section and the CI failover smoke key on; go through "
            "Router, not hand-rolled records"
        ),
        enums=(
            EnumField(
                "status",
                ("dispatched", "handoff", "shed", "replica_down"),
                "route record 'status'",
                "an unknown status is invisible to the router table in "
                "summarize and to the failover smoke",
            ),
        ),
        check_trace_key=True,
    ),
    _g(
        ev="journal",
        owners=("serving/journal.py",),
        owner_message=(
            "raw journal record emitted outside serving/journal.py — "
            "the replay journal's op grammar is the crash-recovery "
            "contract; go through RequestJournal, not hand-rolled "
            "records"
        ),
        enums=(
            EnumField(
                "op", ("accept", "token", "done"),
                "journal record 'op'",
                "replay_requests drops records it can't parse — an "
                "unknown op is silently lost work",
            ),
        ),
    ),
    _g(
        ev="reload",
        owners=("serving/reload.py",),
        owner_message=(
            "raw reload record emitted outside serving/reload.py — "
            "reload status records are what the zero-downtime smoke "
            "asserts on; go through WeightReloader, not hand-rolled "
            "records"
        ),
        enums=(
            EnumField(
                "status", ("staged", "committed", "rejected"),
                "reload record 'status'",
                "anything else reads as a torn reload to the "
                "zero-downtime tooling",
            ),
        ),
    ),
    _g(
        ev="score",
        owners=("/workloads/",),
        owner_message=(
            "raw score record emitted outside progen_tpu/workloads/ — "
            "the batch-score journal's op grammar is the "
            "resume/progress contract the CI workloads smoke greps; go "
            "through ScoreJournal, not hand-rolled records"
        ),
        enums=(
            EnumField(
                "op", ("start", "resume", "batch", "skip", "done"),
                "score record 'op'",
                "an unknown op is invisible to the scoring progress "
                "tooling and the resume smoke",
            ),
        ),
    ),
    _g(
        ev="prefix_cache",
        owners=("serving/prefix_cache.py",),
        owner_message=(
            "raw prefix_cache record emitted outside "
            "serving/prefix_cache.py — cache reuse events are what the "
            "serving smoke's hit assertion and summarize key on; go "
            "through PrefixCache, not hand-rolled records"
        ),
        enums=(
            EnumField(
                "op", ("hit", "miss", "evict"),
                "prefix_cache record 'op'",
                "an unknown op is invisible to the cache-reuse "
                "accounting and the serving smoke",
            ),
        ),
    ),
    _g(
        ev="slo",
        owners=("telemetry/slo.py",),
        owner_message=(
            "raw slo record emitted outside telemetry/slo.py — "
            "objective-state transitions are the watchtower's "
            "judgment, keyed on by the SLO gate and summarize; go "
            "through SloWatch, not hand-rolled records"
        ),
        enums=(
            EnumField(
                "state", ("ok", "warn", "burning", "resolved"),
                "slo record 'state'",
                "the gate's exit-code contract and the transition "
                "grammar only know these states",
            ),
        ),
    ),
    _g(
        ev="flight",
        owners=("telemetry/flight.py",),
        owner_message=(
            "raw flight record emitted outside telemetry/flight.py — a "
            "'dumped' record is the recorder's receipt that a sealed, "
            "digest-valid black box reached disk; a hand-rolled one "
            "claims forensic evidence that was never written; go "
            "through FlightRecorder"
        ),
        enums=(
            EnumField(
                "op", ("armed", "dumped", "truncated"),
                "flight record 'op'",
                "the forensics smoke and query --trace grep exactly "
                "the armed/dumped/truncated op set",
            ),
        ),
    ),
    _g(
        ev="profile",
        owners=("telemetry/flight.py",),
        owner_message=(
            "raw profile record emitted outside telemetry/flight.py — "
            "the pin watcher's request/ack ledger is the proof a "
            "jax.profiler window actually ran (and was rate-limited); "
            "go through request_profile/ProfilePinWatcher"
        ),
        enums=(
            EnumField(
                "op", ("requested", "started", "stopped", "rejected"),
                "profile record 'op'",
                "the on-demand profiling smoke pairs "
                "requested/started/stopped and triages rejected — an "
                "unknown op is an invisible window",
            ),
        ),
    ),
    # ----- dict-scope grammars: records that reach disk through a
    # writer other than the telemetry sink (TSDB, alert files), so the
    # check runs on every dict literal, not just emit() args
    _g(
        ev="sample",
        owners=("telemetry/collector.py",),
        owner_message=(
            "raw collector sample record built outside "
            "telemetry/collector.py — the TSDB, the fleet aggregator "
            "and the ops console all parse one schema; build samples "
            "with make_sample()"
        ),
        scope="dict",
        enums=(
            EnumField(
                "role", ("replica", "router", "run"),
                "sample record 'role'",
                "fleet aggregation buckets liveness by exactly these "
                "roles",
            ),
        ),
    ),
    _g(
        ev="alert",
        owners=("telemetry/alerts.py",),
        owner_message=(
            "raw alert record built outside telemetry/alerts.py — "
            "alerts are edge-triggered state machines; a hand-rolled "
            "record bypasses the transition dedup and the field "
            "grammar the relay/CI smoke key on; go through AlertSink"
        ),
        scope="dict",
        required=("kind", "state", "source", "objective"),
        required_message=(
            "the alert relay and the fleet-metrics smoke key on "
            "kind/state/source/objective being present on every alert"
        ),
        enums=(
            EnumField(
                "kind", ("staleness", "slo_burn", "deploy_rollback"),
                "alert record 'kind'",
                "only staleness, slo_burn and deploy_rollback alerts "
                "exist; a new kind needs the grammar (and this rule) "
                "extended",
            ),
            EnumField(
                "state",
                ("stale", "fresh", "warn", "burning", "resolved",
                 "rolled_back"),
                "alert record 'state'",
                "the console colors and the smoke's quiet/burn asserts "
                "only know these states",
            ),
        ),
    ),
    _g(
        ev="scale",
        owners=("fleet/autoscaler.py",),
        owner_message=(
            "raw scale record built outside fleet/autoscaler.py — "
            "scaling decisions are the policy engine's judgment "
            "(hysteresis, cooldowns, edge-triggering), and the CI "
            "autoscale smoke keys on its records alone; go through "
            "Autoscaler.decide, not hand-rolled records"
        ),
        scope="dict",
        required=("action", "reason"),
        required_message=(
            "the autoscale smoke asserts an up AND a down were "
            "observed by exactly the action/reason fields"
        ),
        enums=(
            EnumField(
                "action", ("up", "down", "hold"),
                "scale record 'action'",
                "the smoke's up/down asserts and summarize only know "
                "these actions",
            ),
        ),
    ),
    _g(
        ev="frame_drop",
        owners=("fleet/transport.py",),
        owner_message=(
            "raw frame_drop record built outside fleet/transport.py — "
            "a drop record is the transport's proof a frame was "
            "validated and condemned; a hand-rolled one claims "
            "enforcement that never ran"
        ),
        scope="dict",
        enums=(
            EnumField(
                "reason",
                ("bad_magic", "bad_version", "bad_auth", "oversized",
                 "chaos", "idle_timeout"),
                "frame_drop record 'reason'",
                "drop triage greps exactly this reason set; an unknown "
                "reason is an invisible wire failure",
            ),
        ),
    ),
    _g(
        ev="notify",
        owners=("telemetry/alert_router.py",),
        owner_message=(
            "raw notify record built outside telemetry/alert_router.py "
            "— a notify record claims the dedup/silence/rate pipeline "
            "ran; a hand-rolled one forges a delivery the on-call "
            "never received; go through AlertRouter"
        ),
        scope="dict",
        enums=(
            EnumField(
                "status",
                ("sent", "failed", "silenced", "deduped", "escalated"),
                "notify record 'status'",
                "the console's delivery counts and the CI egress smoke "
                "classify by exactly the "
                "sent/failed/silenced/deduped/escalated alphabet",
            ),
        ),
    ),
    _g(
        ev="ship",
        owners=("telemetry/tsdb.py",),
        owner_message=(
            "raw ship record built outside telemetry/tsdb.py — a ship "
            "record is the shipper's proof a block's digest was "
            "verified into the archive manifest; a hand-rolled one "
            "claims history that was never tiered out"
        ),
        scope="dict",
        enums=(
            EnumField(
                "op", ("shipped", "skipped", "verify_failed"),
                "ship record 'op'",
                "retention triage greps exactly the "
                "shipped/skipped/verify_failed op set",
            ),
        ),
    ),
    _g(
        ev="deploy",
        owners=("/deploy/",),
        owner_message=(
            "raw deploy record built outside progen_tpu/deploy/ — the "
            "deploy ledger is the controller's resume authority; a "
            "hand-rolled record forges a canary/promote/rollback "
            "decision the controller never made; go through "
            "DeployLedger"
        ),
        scope="dict",
        enums=(
            EnumField(
                "op",
                ("observed", "canary", "probe", "promote", "rollback",
                 "converged"),
                "deploy record 'op'",
                "the deployment smoke and the kill-matrix convergence "
                "asserts grep exactly the "
                "observed/canary/probe/promote/rollback/converged op "
                "set",
            ),
        ),
    ),
)

BY_EV: Dict[str, EventGrammar] = {g.ev: g for g in GRAMMARS}

# misspellings of the one blessed trace-context key: the stitcher's
# journey grouping greps records for exactly "trace_id", so a hop
# written under any of these never joins its journey
TRACE_KEY_MISSPELLINGS = (
    "trace", "traceid", "traceId", "trace_ctx", "trace_context",
    "span_id", "spanid",
)


@dataclass
class _EnumEntry:
    ev: str
    grammar: EventGrammar
    enum: EnumField
    values: frozenset = field(default_factory=frozenset)


def enum_index() -> Dict[str, List[_EnumEntry]]:
    """field name -> every (ev, enum) declaring it — PGL010's lookup
    table for binding a consumer's handled-value set to a grammar."""
    out: Dict[str, List[_EnumEntry]] = {}
    for g in GRAMMARS:
        for e in g.enums:
            out.setdefault(e.field, []).append(
                _EnumEntry(g.ev, g, e, frozenset(e.values))
            )
    return out


def render_grammar_markdown() -> str:
    """The generated "Event grammars" reference table — rendered into
    README.md by ``progen-tpu-lint --registry-dump`` and checked
    against the committed docs in CI."""
    lines = [
        "| `ev` | producer | scope | required fields | enum fields |",
        "|---|---|---|---|---|",
    ]
    for g in GRAMMARS:
        owners = ", ".join(f"`{o}`" for o in g.owners)
        required = ", ".join(f"`{f}`" for f in g.required) or "—"
        enums = "; ".join(
            f"`{e.field}` ∈ {'/'.join(e.values)}" for e in g.enums
        ) or "—"
        scope = "all dicts" if g.scope == "dict" else "emit"
        lines.append(
            f"| `{g.ev}` | {owners} | {scope} | {required} | {enums} |"
        )
    return "\n".join(lines) + "\n"
