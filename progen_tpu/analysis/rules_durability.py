"""PGL007 — durable-path write discipline (atomic publish / fsync).

The exactly-once guarantees of PRs 8-19 all bottom out in two file
idioms. State that must survive a kill (`meta.json`, `manifest.json`,
`*.pin`, `*.ack`) is published atomically: write a sibling ``.tmp``,
``os.fsync`` it, then ``os.replace`` onto the final name — a reader
sees the old complete file or the new complete file, never a torn one.
State that must survive a kill *per record* (``*.jsonl`` ledgers and
journals) is appended then ``flush`` + ``os.fsync``'d — the replay
contract ("a token the client saw is in the journal") is only as
strong as the weakest emit. Both idioms are hand-enforced conventions,
and the failure mode of forgetting one is silent: everything works
until the first power cut, and then a ledger admits a decision it
never durably made.

This rule finds the three ways the conventions decay, with
handle-level dataflow in the style of PGL002's key tracking:

  * a direct overwrite — ``open(durable, "w")`` / ``.write_text`` on a
    durable final path (not a ``.tmp`` sibling): a crash mid-write
    leaves a torn file where a complete one used to be;
  * a rename publish without fsync — the tmp file is written and
    ``os.replace``'d but never fsynced, so the rename can land in the
    directory before the data lands in the file (publishing garbage);
  * an fsync-less append — a handle opened ``"a"`` on a durable path
    whose writing method never calls ``os.fsync(handle.fileno())``
    (``flush`` alone moves bytes to the OS, not to disk).

What counts as *durable* is evidence-based, not blanket: a path
expression is durable when a string literal in it (including f-string
segments, ``Path /`` joins, ``with_name``/``with_suffix`` args and
resolved module-level constants) ends in ``.jsonl``/``.ack``/``.pin``
or names ``meta.json``/``manifest.json``, or when the variable/attr
naming it matches the pin/ack/journal/ledger/manifest vocabulary. A
``.tmp``/``.part`` marker anywhere in the expression wins and marks
the path as a scratch sibling (where direct writes are the POINT).
Telemetry streams that tolerate a torn tail by design (metrics,
spans) are baselined with reasons, not exempted here.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from progen_tpu.analysis.core import Rule, call_name, dotted_name

_DURABLE_SUFFIXES = (".jsonl", ".ack", ".pin")
_DURABLE_BASENAMES = ("meta.json", "manifest.json")
_DURABLE_NAME_RE = re.compile(
    r"(^|_)(pin|ack|journal|ledger|manifest|meta)(_|$)|"
    r"(^|_)(pin|ack|journal|ledger|manifest)s?_(path|file|f)$"
)
_TMP_NAME_RE = re.compile(r"(^|_)(tmp|temp|scratch)(_|$)|tmp$")

_WRITE_MODES = ("w", "wb", "w+", "wb+", "x", "xb")
_APPEND_MODES = ("a", "ab", "a+", "ab+")


def _durable_text(s: str) -> bool:
    return s.endswith(_DURABLE_SUFFIXES) or any(
        s == b or s.endswith("/" + b) for b in _DURABLE_BASENAMES
    )


def _tmp_text(s: str) -> bool:
    return ".tmp" in s or s.endswith(".part")


def _walk_shallow(node: ast.AST) -> Iterator[ast.AST]:
    """Descendants of ``node``, not crossing into nested functions —
    the dataflow facts below are per-function."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if not isinstance(
            n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            stack.extend(ast.iter_child_nodes(n))


def _base_name(node: ast.AST) -> Optional[str]:
    """A stable identifier for a handle/path expression: ``f`` for
    Name, ``self._f`` for a self attribute."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        d = dotted_name(node)
        if d and d.startswith("self."):
            return d
    return None


class DurabilityRule(Rule):
    id = "PGL007"
    severity = "error"
    doc = ("durable-path write discipline: ledger/journal/ack/manifest "
           "paths must be published atomically (tmp + os.fsync + "
           "os.replace) or appended with flush + os.fsync — direct "
           "overwrites, fsync-less renames and fsync-less appends all "
           "lose acknowledged state on a crash")

    def run(self):
        self._module_consts = self._collect_module_consts()
        for node in self.ctx.tree.body:
            if isinstance(node, ast.ClassDef):
                self._check_class(node)
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                self._check_function(node, {}, set())
        return self.findings

    # ----- classification -------------------------------------------------

    def _collect_module_consts(self) -> Dict[str, str]:
        consts: Dict[str, str] = {}
        for node in self.ctx.tree.body:
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Constant
            ) and isinstance(node.value.value, str):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        consts[t.id] = node.value.value
        return consts

    def _classify(self, expr: ast.AST,
                  cls_attrs: Dict[str, Optional[str]]) -> Optional[str]:
        """"tmp" | "durable" | None for a path expression. tmp wins:
        ``path.with_suffix(".jsonl.tmp")`` is the scratch sibling."""
        kinds = set()
        self._classify_into(expr, cls_attrs, kinds)
        if "tmp" in kinds:
            return "tmp"
        if "durable" in kinds:
            return "durable"
        return None

    def _classify_into(self, expr, cls_attrs, kinds: Set[str]) -> None:
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            if _tmp_text(expr.value):
                kinds.add("tmp")
            if _durable_text(expr.value):
                kinds.add("durable")
        elif isinstance(expr, ast.Name):
            self._classify_ident(expr.id, kinds)
            const = self._module_consts.get(expr.id)
            if const is not None:
                if _tmp_text(const):
                    kinds.add("tmp")
                if _durable_text(const):
                    kinds.add("durable")
        elif isinstance(expr, ast.Attribute):
            base = _base_name(expr)
            if base and base.startswith("self."):
                attr = expr.attr
                known = cls_attrs.get(attr)
                if known is not None:
                    kinds.add(known)
                else:
                    self._classify_ident(attr, kinds)
            elif isinstance(expr, ast.Attribute):
                self._classify_ident(expr.attr, kinds)
        elif isinstance(expr, ast.BinOp):
            # Path "/" joins and string "+" concatenation both carry
            # the durable/tmp evidence of either side
            self._classify_into(expr.left, cls_attrs, kinds)
            self._classify_into(expr.right, cls_attrs, kinds)
        elif isinstance(expr, ast.JoinedStr):
            for part in expr.values:
                if isinstance(part, ast.Constant) and isinstance(
                    part.value, str
                ):
                    if _tmp_text(part.value):
                        kinds.add("tmp")
                    if _durable_text(part.value):
                        kinds.add("durable")
        elif isinstance(expr, ast.Call):
            cname = call_name(expr) or ""
            tail = cname.rsplit(".", 1)[-1]
            if tail in ("with_name", "with_suffix") and expr.args:
                self._classify_into(expr.args[0], cls_attrs, kinds)
                if isinstance(expr.func, ast.Attribute):
                    self._classify_into(
                        expr.func.value, cls_attrs, kinds
                    )
            elif tail in ("Path", "joinpath", "resolve", "absolute"):
                for a in expr.args:
                    self._classify_into(a, cls_attrs, kinds)
                if isinstance(expr.func, ast.Attribute):
                    self._classify_into(
                        expr.func.value, cls_attrs, kinds
                    )

    def _classify_ident(self, ident: str, kinds: Set[str]) -> None:
        low = ident.lower()
        if _TMP_NAME_RE.search(low):
            kinds.add("tmp")
        elif _DURABLE_NAME_RE.search(low):
            kinds.add("durable")

    # ----- per-class / per-function analysis ------------------------------

    def _check_class(self, cls: ast.ClassDef) -> None:
        cls_attrs: Dict[str, Optional[str]] = {}
        # a class that CALLS itself a journal/ledger has declared its
        # file durable, however generically the path attr is named
        if re.search(r"journal|ledger", cls.name, re.IGNORECASE):
            cls_attrs["path"] = "durable"
        durable_handles: Set[str] = set()
        init = next(
            (
                n for n in cls.body
                if isinstance(n, ast.FunctionDef) and n.name == "__init__"
            ),
            None,
        )
        if init is not None:
            for node in _walk_shallow(init):
                if not isinstance(node, ast.Assign):
                    continue
                for t in node.targets:
                    if not (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        continue
                    open_info = self._open_call(node.value, cls_attrs)
                    if open_info is not None:
                        path_kind, mode = open_info
                        if (
                            path_kind == "durable"
                            and mode in _APPEND_MODES
                        ):
                            durable_handles.add(t.attr)
                        continue
                    kind = self._classify(node.value, cls_attrs)
                    if kind is None:
                        kinds: Set[str] = set()
                        self._classify_ident(t.attr, kinds)
                        kind = next(iter(kinds), None)
                    if kind is not None:
                        cls_attrs[t.attr] = kind
        for node in cls.body:
            if isinstance(node, ast.FunctionDef) and node.name != \
                    "__init__":
                self._check_function(node, cls_attrs, durable_handles)
            elif isinstance(node, ast.ClassDef):
                self._check_class(node)
        if init is not None:
            self._check_function(init, cls_attrs, set())

    def _open_call(self, expr, cls_attrs) -> Optional[Tuple[str, str]]:
        """(path_kind, mode) when ``expr`` opens a file, else None."""
        if not isinstance(expr, ast.Call):
            return None
        cname = call_name(expr) or ""
        tail = cname.rsplit(".", 1)[-1]
        if tail != "open":
            return None
        mode = "r"
        if cname == "open":
            if not expr.args:
                return None
            path_expr = expr.args[0]
            if len(expr.args) > 1 and isinstance(
                expr.args[1], ast.Constant
            ):
                mode = str(expr.args[1].value)
        else:
            path_expr = expr.func.value
            if expr.args and isinstance(expr.args[0], ast.Constant):
                mode = str(expr.args[0].value)
        for kw in expr.keywords:
            if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                mode = str(kw.value.value)
        kind = self._classify(path_expr, cls_attrs)
        return (kind or "", mode.replace("t", "").replace("+", "") +
                ("+" if "+" in mode else ""))

    def _check_function(self, fn, cls_attrs,
                        durable_handles: Set[str]) -> None:
        fsync_bases: Set[str] = set()
        any_fsync = False
        # identifiers written via write_text/write_bytes/open-"w" here
        written_bases: Set[str] = set()
        local_append: Dict[str, ast.AST] = {}  # handle -> open node
        handle_writes: Dict[str, ast.AST] = {}  # handle -> first write
        replaces: List[Tuple[ast.AST, ast.AST, ast.AST]] = []

        for node in _walk_shallow(fn):
            if isinstance(node, ast.Call):
                cname = call_name(node) or ""
                tail = cname.rsplit(".", 1)[-1]
                if tail == "fsync" and node.args:
                    any_fsync = True
                    arg = node.args[0]
                    if isinstance(arg, ast.Call) and isinstance(
                        arg.func, ast.Attribute
                    ) and arg.func.attr == "fileno":
                        base = _base_name(arg.func.value)
                    else:
                        base = _base_name(arg)
                    if base:
                        fsync_bases.add(base)
                elif tail in ("write_text", "write_bytes") and \
                        isinstance(node.func, ast.Attribute):
                    target = node.func.value
                    base = _base_name(target)
                    if base:
                        written_bases.add(base)
                    kind = self._classify(target, cls_attrs)
                    if kind == "durable":
                        self.report(
                            node,
                            f"direct .{tail} overwrite of a durable "
                            f"path — a crash mid-write leaves a torn "
                            f"file; write a .tmp sibling, os.fsync it, "
                            f"then os.replace onto the final name",
                        )
                elif tail == "replace" and cname.startswith(("os.",)) \
                        and len(node.args) >= 2:
                    replaces.append((node, node.args[0], node.args[1]))
                elif tail == "replace" and isinstance(
                    node.func, ast.Attribute
                ) and len(node.args) == 1 and not node.keywords:
                    # Path.replace(dst) — one arg; two args is
                    # str.replace(old, new), which is not a rename
                    replaces.append(
                        (node, node.func.value, node.args[0])
                    )
                elif tail == "rename" and cname.startswith("os.") and \
                        len(node.args) >= 2:
                    replaces.append((node, node.args[0], node.args[1]))
                elif tail in ("write", "writelines") and isinstance(
                    node.func, ast.Attribute
                ):
                    base = _base_name(node.func.value)
                    if base:
                        handle_writes.setdefault(base, node)
                elif tail == "dump" and cname.endswith("json.dump") \
                        and len(node.args) >= 2:
                    base = _base_name(node.args[1])
                    if base:
                        handle_writes.setdefault(base, node)
            if isinstance(node, (ast.Assign, ast.withitem)):
                value = (
                    node.value if isinstance(node, ast.Assign)
                    else node.context_expr
                )
                open_info = self._open_call(value, cls_attrs)
                if open_info is None:
                    continue
                kind, mode = open_info
                targets = (
                    node.targets if isinstance(node, ast.Assign)
                    else ([node.optional_vars] if node.optional_vars
                          else [])
                )
                bases = [
                    b for b in (_base_name(t) for t in targets) if b
                ]
                if kind == "durable" and mode in _WRITE_MODES:
                    self.report(
                        value,
                        "open(durable_path, \"w\") overwrites the "
                        "published file in place — a crash mid-write "
                        "leaves a torn file where a complete one was; "
                        "write a .tmp sibling, os.fsync it, then "
                        "os.replace onto the final name",
                    )
                elif kind == "durable" and mode in _APPEND_MODES:
                    for b in bases:
                        local_append[b] = value
                if mode in _WRITE_MODES or mode in _APPEND_MODES:
                    for b in bases:
                        written_bases.add(b)
                    path_base = _base_name(
                        value.args[0] if call_name(value) == "open"
                        and value.args else value.func.value
                    )
                    if path_base:
                        written_bases.add(path_base)

        for handle, open_node in local_append.items():
            if handle in handle_writes and handle not in fsync_bases:
                self.report(
                    handle_writes[handle],
                    f"append to durable path via '{handle}' without "
                    f"os.fsync({handle}.fileno()) — flush() moves "
                    f"bytes to the OS, not to disk; an acknowledged "
                    f"record can vanish on power loss",
                )
        for attr_handle in durable_handles:
            base = "self." + attr_handle
            if base in handle_writes and base not in fsync_bases:
                self.report(
                    handle_writes[base],
                    f"append to durable handle '{base}' without "
                    f"os.fsync({base}.fileno()) in this method — "
                    f"flush() alone does not survive power loss, and "
                    f"the replay contract is only as strong as the "
                    f"weakest emit",
                )
        for rep_node, src, dst in replaces:
            if self._classify(dst, cls_attrs) != "durable":
                continue
            src_base = _base_name(src)
            if src_base and src_base in written_bases and not any_fsync:
                self.report(
                    rep_node,
                    "os.replace publishes a tmp file this function "
                    "wrote but never fsynced — the rename can reach "
                    "the directory before the data reaches the file, "
                    "publishing garbage after a crash; fsync the tmp "
                    "handle before replacing",
                )
