"""PGL008 — lock discipline: guarded-attr consistency + handler safety.

Two defect classes, both of which this repo has shipped and debugged:

**Inconsistent guarding.** A class that mutates ``self._cursor`` under
``with self._lock:`` in one method and bare in another has decided the
attribute needs the lock — and then not taken it. The bare write is a
torn-update race that no CPU pytest run will ever catch (the GIL makes
single-opcode writes atomic, but compound updates and invariant pairs
are not). The rule is per-class: collect every instance attribute
written under a ``with self.<something-lock>:`` block in at least one
method, then flag writes of the same attribute outside any lock in
other methods (``__init__`` is exempt — no concurrent aliases exist
yet).

**Blocking work in handler contexts.** Emit taps, span-entry hooks,
``sys.excepthook`` and ``signal.signal`` handlers run re-entrantly
inside arbitrary code — including code that already holds the very
locks the handler wants. The PR 19 flight-recorder deadlock was
exactly this: the tap fired mid-emit, the dump path did a blocking
``self._lock.acquire()``, and the thread waited on itself. (The fix —
``acquire(blocking=False)`` and shedding the dump — is the
true-negative fixture.) This half of the rule builds the set of
functions reachable from any handler registration in the module
(``EMIT_TAPS.append(...)``, ``*_HOOKS.append(...)``,
``sys.excepthook = ...``, ``signal.signal(sig, ...)``, following
``self.method()`` and bare same-module calls) and flags, inside that
set: blocking ``.acquire()`` on lock-ish receivers, and I/O performed
while lexically holding a lock (``time.sleep``, file writes, HTTP) —
the handler may already be inside the emit path it is about to wait
on.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from progen_tpu.analysis.core import Rule, call_name, dotted_name

_HANDLER_LIST_SUFFIXES = ("_TAPS", "_HOOKS")
_HTTP_TAILS = ("urlopen", "get", "post", "put", "request", "connect",
               "sendall", "send")
_HTTP_PREFIXES = ("requests.", "urllib.", "http.", "socket.")


def _is_lockish(name: Optional[str]) -> bool:
    return bool(name) and "lock" in name.lower()


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and isinstance(
        node.value, ast.Name
    ) and node.value.id == "self":
        return node.attr
    return None


class LockDisciplineRule(Rule):
    id = "PGL008"
    severity = "error"
    doc = ("lock discipline: attributes guarded by 'with self._lock' "
           "in one method must not be written bare in another, and "
           "emit-tap/excepthook/signal-handler code must never take a "
           "blocking lock or do I/O while holding one (the flight-dump "
           "deadlock class)")

    def run(self):
        for node in ast.walk(self.ctx.tree):
            if isinstance(node, ast.ClassDef):
                self._check_guarded_attrs(node)
        self._check_handlers()
        return self.findings

    # ----- part 1: guarded-attribute consistency --------------------------

    def _lock_with_ancestor(self, node: ast.AST,
                            within: ast.AST) -> Optional[str]:
        """Name of the lock-ish ``with`` context ``node`` sits in
        (lexically, inside ``within``), else None."""
        for anc in self.ctx.ancestors(node):
            if anc is within:
                return None
            if isinstance(anc, ast.With):
                for item in anc.items:
                    d = dotted_name(item.context_expr)
                    if d is None and isinstance(
                        item.context_expr, ast.Call
                    ):
                        d = call_name(item.context_expr)
                    if _is_lockish(d):
                        return d
        return None

    def _check_guarded_attrs(self, cls: ast.ClassDef) -> None:
        methods = [
            n for n in cls.body if isinstance(n, ast.FunctionDef)
        ]
        guarded: Dict[str, Tuple[str, str]] = {}  # attr -> (lock, meth)
        bare: List[Tuple[str, ast.AST, str]] = []
        for meth in methods:
            for node in ast.walk(meth):
                if isinstance(node, (ast.FunctionDef, ast.Lambda)) and \
                        node is not meth:
                    continue
                targets: List[ast.AST] = []
                if isinstance(node, ast.Assign):
                    targets = list(node.targets)
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                    targets = [node.target]
                for t in targets:
                    if isinstance(t, (ast.Tuple, ast.List)):
                        elts = t.elts
                    else:
                        elts = [t]
                    for elt in elts:
                        attr = _self_attr(elt)
                        if attr is None:
                            continue
                        lock = self._lock_with_ancestor(node, meth)
                        if lock is not None:
                            guarded.setdefault(
                                attr, (lock, meth.name)
                            )
                        elif meth.name not in (
                            "__init__", "__post_init__"
                        ):
                            bare.append((attr, node, meth.name))
        for attr, node, meth_name in bare:
            if attr not in guarded:
                continue
            lock, guard_meth = guarded[attr]
            self.report(
                node,
                f"self.{attr} is written under 'with {lock}:' in "
                f"{guard_meth}() but written bare here — the class "
                f"decided this attribute needs the lock; an unguarded "
                f"write is a torn-update race the GIL will not save "
                f"you from",
            )

    # ----- part 2: handler-context safety ---------------------------------

    def _handler_entry_names(self) -> Set[str]:
        entries: Set[str] = set()

        def add_target(fn_expr: ast.AST) -> None:
            if isinstance(fn_expr, ast.Name):
                entries.add(fn_expr.id)
            else:
                attr = _self_attr(fn_expr)
                if attr:
                    entries.add(attr)

        for node in ast.walk(self.ctx.tree):
            if isinstance(node, ast.Call):
                cname = call_name(node) or ""
                tail = cname.rsplit(".", 1)[-1]
                if tail == "append" and isinstance(
                    node.func, ast.Attribute
                ) and node.args:
                    recv = dotted_name(node.func.value) or ""
                    leaf = recv.rsplit(".", 1)[-1]
                    if leaf.endswith(_HANDLER_LIST_SUFFIXES):
                        add_target(node.args[0])
                elif tail == "signal" and cname.endswith(
                    "signal.signal"
                ) and len(node.args) >= 2:
                    add_target(node.args[1])
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if dotted_name(t) == "sys.excepthook":
                        add_target(node.value)
        return entries

    def _function_table(self) -> Dict[str, List[ast.FunctionDef]]:
        table: Dict[str, List[ast.FunctionDef]] = {}
        for node in ast.walk(self.ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                table.setdefault(node.name, []).append(node)
        return table

    def _called_names(self, fn: ast.FunctionDef) -> Set[str]:
        out: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                if isinstance(node.func, ast.Name):
                    out.add(node.func.id)
                else:
                    attr = _self_attr(node.func)
                    if attr:
                        out.add(attr)
        return out

    def _check_handlers(self) -> None:
        entries = self._handler_entry_names()
        if not entries:
            return
        table = self._function_table()
        reachable: Set[str] = set()
        frontier = [n for n in entries if n in table]
        while frontier:
            name = frontier.pop()
            if name in reachable:
                continue
            reachable.add(name)
            for fn in table[name]:
                for callee in self._called_names(fn):
                    if callee in table and callee not in reachable:
                        frontier.append(callee)
        for name in sorted(reachable):
            for fn in table[name]:
                self._check_handler_body(fn, entries)

    def _check_handler_body(self, fn: ast.FunctionDef,
                            entries: Set[str]) -> None:
        origin = (
            "is registered as an emit-tap/hook/excepthook/signal "
            "handler" if fn.name in entries
            else "is reachable from a registered "
            "tap/hook/excepthook/signal handler"
        )
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            cname = call_name(node) or ""
            tail = cname.rsplit(".", 1)[-1]
            if tail == "acquire" and isinstance(
                node.func, ast.Attribute
            ):
                recv = dotted_name(node.func.value)
                if _is_lockish(recv) and self._acquire_blocks(node):
                    self.report(
                        node,
                        f"blocking {recv}.acquire() — {fn.name}() "
                        f"{origin}, so it can fire re-entrantly inside "
                        f"code already holding this lock and wait on "
                        f"itself (the flight-dump deadlock class); use "
                        f"acquire(blocking=False) and shed on "
                        f"contention",
                    )
            elif self._is_io_call(cname, tail, node):
                lock = self._lock_with_ancestor(node, fn)
                if lock is not None:
                    self.report(
                        node,
                        f"I/O ({cname or tail}) while holding "
                        f"'{lock}' — {fn.name}() {origin}; holding a "
                        f"lock across I/O in a re-entrant context "
                        f"stalls every thread that touches the lock "
                        f"for the duration of the I/O",
                    )

    @staticmethod
    def _acquire_blocks(node: ast.Call) -> bool:
        for kw in node.keywords:
            if kw.arg == "blocking" and isinstance(
                kw.value, ast.Constant
            ) and kw.value.value is False:
                return False
            if kw.arg == "timeout" and isinstance(
                kw.value, ast.Constant
            ) and kw.value.value == 0:
                return False
        if node.args and isinstance(node.args[0], ast.Constant) and \
                node.args[0].value is False:
            return False
        return True

    def _is_io_call(self, cname: str, tail: str,
                    node: ast.Call) -> bool:
        if cname.endswith("time.sleep") or cname == "sleep":
            return True
        if tail == "open" or tail in ("write_text", "write_bytes"):
            return True
        if any(cname.startswith(p) for p in _HTTP_PREFIXES) and \
                tail in _HTTP_TAILS:
            return True
        return False
