"""PGL009 — chaos-site drift: every referenced target must exist.

The chaos harness (``resilience/chaos.py``) keys injection rules on
site names — span names, retry labels, ``maybe_inject``/``perturb``
call sites. A ``PROGEN_CHAOS="ckpt/save:kill@2"`` kill-matrix entry in
a test, in tier1.yml, or in the README only tests something if a site
named ``ckpt/save`` is actually installed in the code. When the code
is refactored and a span renamed, the kill-matrix keeps passing — it
now injects into nothing, and the crash-safety property it used to
prove is unguarded. That is the worst kind of CI rot: green and
meaningless. Chaos.py's runtime warn-once on unknown targets catches
the env-var case *if someone reads the logs*; this rule fails the
build instead, and from the whole-project index, so a reference in a
yml workflow or a doc is held to the same standard as one in a test.

Three drift directions, all errors:

  * **ghost reference** — a ``target:spec`` string (test, CI workflow,
    doc) names a site no span/retry-label/inject call installs;
  * **stale registry** — a referenced site exists in code but is
    missing from ``KNOWN_TARGETS``, so the runtime's
    unknown-target warning fires spuriously and the declared registry
    no longer documents the real surface;
  * **dead declaration** — ``KNOWN_TARGETS`` declares a site nothing
    installs: the registry promises an injection point that is not
    there.

Site and reference indices come from
:class:`~progen_tpu.analysis.project.ProjectContext`, built once over
the whole linted set (plus tier1.yml and the markdown docs). The rule
only judges when a ``KNOWN_TARGETS`` declaration is in the linted set:
the declaration is the marker that the injection surface is in scope.
Linting a single test file proves nothing about which sites exist, so
no findings are produced — lint the package and the declaration comes
with it.
"""

from __future__ import annotations

from typing import Set, Tuple

from progen_tpu.analysis.core import ProjectRule


class ChaosDriftRule(ProjectRule):
    id = "PGL009"
    severity = "error"
    doc = ("chaos-site drift: every PROGEN_CHAOS target referenced in "
           "tests/tier1.yml/docs must name an installed span/retry/"
           "inject site, and resilience/chaos.py's KNOWN_TARGETS must "
           "match the installed surface in both directions — a ghost "
           "reference is a kill-matrix that silently tests nothing")

    def run(self):
        proj = self.project
        if proj.declaration is None:
            # without KNOWN_TARGETS in the linted set the installed
            # surface is not in scope — a partial lint (one test file)
            # proves nothing about which sites exist
            return self.findings
        seen: Set[Tuple[str, str, int, str]] = set()

        def once(kind: str, target: str, path: str, line: int) -> bool:
            key = (kind, target, path, line)
            if key in seen:
                return False
            seen.add(key)
            return True

        for ref in proj.chaos_refs:
            if ref.target in proj.sites:
                if (
                    proj.declaration is not None
                    and ref.target not in proj.declared
                    and once("undecl", ref.target, ref.path, ref.line)
                ):
                    self._emit(
                        ref,
                        f"chaos target '{ref.target}' is installed in "
                        f"code but missing from KNOWN_TARGETS — the "
                        f"runtime will warn-once 'unknown chaos "
                        f"target' on every install and the declared "
                        f"registry no longer documents the real "
                        f"injection surface; add it to KNOWN_TARGETS",
                    )
                continue
            if once("ghost", ref.target, ref.path, ref.line):
                self._emit(
                    ref,
                    f"chaos target '{ref.target}' is referenced here "
                    f"but no span/retry-label/inject site installs it "
                    f"— this kill-matrix entry injects into nothing "
                    f"and the crash-safety property it claims to test "
                    f"is unguarded (site renamed or removed?)",
                )
        if proj.declaration is not None:
            for target, (ctx, node) in sorted(proj.declared.items()):
                if target in proj.sites:
                    continue
                self.report_at(
                    ctx, node,
                    f"KNOWN_TARGETS declares chaos site '{target}' "
                    f"but no span/retry-label/inject call installs it "
                    f"— the registry promises an injection point that "
                    f"does not exist",
                )
        self.findings.sort(key=lambda f: (f.path, f.line, f.message))
        return self.findings

    def _emit(self, ref, message: str) -> None:
        if ref.ctx is not None and ref.node is not None:
            self.report_at(ref.ctx, ref.node, message)
        elif ref.ctx is not None:
            # comment-only reference: suppression still honored via
            # the line check, no AST node to hang qualname on
            if not ref.ctx.is_suppressed(self.id, ref.line):
                self.report_text(ref.path, ref.line, message)
        else:
            self.report_text(ref.path, ref.line, message)
