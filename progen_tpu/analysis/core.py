"""Shared linter core: findings, per-module context, suppressions.

The rules in this package are AST visitors over one parsed module at a
time. Everything they need beyond the raw tree lives on
``ModuleContext``: parent links (ast has none), enclosing-function
qualnames for stable baseline keys, the comment map that powers inline
``# progen: ignore[RULE]`` suppressions, and the traced-region index
(analysis/traced.py) that tells a rule whether a node's code runs under
a jax trace (jit/vmap/grad decorator, lax.scan body, shard_map body...)
— the question almost every TPU-stack rule starts with.

Suppression syntax (two placements, same grammar):

    x = float(y)  # progen: ignore[PGL001] -- trace-time constant
    # progen: ignore[PGL002, PGL005]
    noisy_statement()

A bare ``# progen: ignore`` (no bracket) suppresses every rule on that
line; a comment that is the whole line applies to the line below it.
Suppressions are for one-off trace-time-only idioms; recurring accepted
findings belong in ``lint_baseline.json`` where they carry a reason.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Set

SEVERITIES = ("error", "warning")

_IGNORE_RE = re.compile(
    r"#\s*progen:\s*ignore(?:\[(?P<rules>[A-Za-z0-9_,\s]*)\])?"
)


@dataclass
class Finding:
    """One lint finding, locatable and baseline-keyable.

    ``func`` is the dotted enclosing-function qualname (``""`` at module
    level) — baseline entries match on (rule, path, func) so they
    survive unrelated line drift in the file.
    """

    rule: str
    severity: str
    path: str
    line: int
    col: int
    message: str
    func: str = ""

    def location(self) -> str:
        return f"{self.path}:{self.line}:{self.col}"

    def render(self) -> str:
        where = f" [{self.func}]" if self.func else ""
        return (
            f"{self.location()} {self.rule} {self.severity}: "
            f"{self.message}{where}"
        )

    def to_json(self) -> dict:
        return {
            "rule": self.rule,
            "severity": self.severity,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "func": self.func,
            "message": self.message,
        }


def dotted_name(node: Optional[ast.AST]) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None. The syntactic
    spine every rule matches callables on — no imports are resolved, so
    rules match on suffixes (``lax.scan``) rather than absolute paths."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def name_suffix_in(name: Optional[str], suffixes) -> bool:
    """True when ``name`` equals a suffix or ends with ``.<suffix>`` —
    matches both ``jax.lax.scan`` and ``lax.scan`` against ``lax.scan``."""
    if not name:
        return False
    for suf in suffixes:
        if name == suf or name.endswith("." + suf):
            return True
    return False


def call_name(node: ast.AST) -> Optional[str]:
    return dotted_name(node.func) if isinstance(node, ast.Call) else None


_FUNCTION_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _comment_map(source: str) -> Dict[int, str]:
    """line -> comment text. tokenize sees what ast discards."""
    comments: Dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                comments[tok.start[0]] = tok.string
    except (tokenize.TokenError, IndentationError):
        pass
    return comments


class ModuleContext:
    """Everything rules share about one parsed module."""

    def __init__(self, path, source: str, rel_to: Optional[Path] = None):
        self.abs_path = Path(path)
        try:
            self.path = str(self.abs_path.relative_to(rel_to or Path.cwd()))
        except ValueError:
            self.path = str(self.abs_path)
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=str(path))
        self.parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent
        self._qualnames: Dict[ast.AST, str] = {}
        self._suppressions = self._build_suppressions(source)
        # built lazily by traced.TracedIndex via attach_traced_index()
        self.traced_index = None

    # ----- suppressions ---------------------------------------------------

    def _build_suppressions(self, source: str) -> Dict[int, Set[str]]:
        supp: Dict[int, Set[str]] = {}
        for line_no, comment in _comment_map(source).items():
            m = _IGNORE_RE.search(comment)
            if not m:
                continue
            rules = m.group("rules")
            codes = (
                {r.strip().upper() for r in rules.split(",") if r.strip()}
                if rules is not None
                else {"*"}
            )
            src_line = (
                self.lines[line_no - 1] if line_no <= len(self.lines) else ""
            )
            target = line_no
            if src_line.lstrip().startswith("#"):
                # standalone comment guards the next CODE line (a
                # multi-line justification comment may sit in between)
                target = line_no + 1
                while target <= len(self.lines) and self.lines[
                    target - 1
                ].lstrip().startswith("#"):
                    target += 1
            supp.setdefault(target, set()).update(codes)
        return supp

    def is_suppressed(self, rule: str, line: int) -> bool:
        codes = self._suppressions.get(line, set())
        return "*" in codes or rule in codes

    # ----- structure helpers ----------------------------------------------

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self.parents.get(node)

    def ancestors(self, node: ast.AST):
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        for anc in self.ancestors(node):
            if isinstance(anc, _FUNCTION_NODES):
                return anc
        return None

    def qualname(self, node: ast.AST) -> str:
        """Dotted name of the function enclosing ``node`` ('' at module
        scope); lambdas render as ``<lambda>``."""
        if node in self._qualnames:
            return self._qualnames[node]
        parts: List[str] = []
        fn = (
            node
            if isinstance(node, _FUNCTION_NODES)
            else self.enclosing_function(node)
        )
        cur = fn
        while cur is not None:
            if isinstance(cur, ast.Lambda):
                parts.append("<lambda>")
            elif isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                parts.append(cur.name)
            elif isinstance(cur, ast.ClassDef):
                parts.append(cur.name)
            cur = self.parents.get(cur)
        qn = ".".join(reversed(parts))
        self._qualnames[node] = qn
        return qn

    def in_traced_region(self, node: ast.AST) -> bool:
        """True when ``node`` sits (lexically) inside a function whose
        body jax traces — see traced.TracedIndex for what qualifies."""
        if self.traced_index is None:
            return False
        return self.traced_index.in_traced_region(node)


class ProjectRule:
    """Base class for whole-project rules: one instance lints one
    :class:`~progen_tpu.analysis.project.ProjectContext` (cross-module
    indices built once by the runner, shared by every project rule).

    Module-scoped findings go through :meth:`report_at`, which honors
    inline ``# progen: ignore[...]`` suppressions exactly like
    :class:`Rule.report`; findings anchored in non-Python files (a CI
    workflow, a README) go through :meth:`report_text` — no inline
    suppression there, the baseline is the only grandfathering
    mechanism.
    """

    id = "PGL000"
    severity = "error"
    doc = ""

    def __init__(self, project):
        self.project = project
        self.findings: List[Finding] = []

    def run(self) -> List[Finding]:
        raise NotImplementedError

    def report_at(self, ctx: "ModuleContext", node: ast.AST,
                  message: str) -> None:
        line = getattr(node, "lineno", 0)
        if ctx.is_suppressed(self.id, line):
            return
        self.findings.append(
            Finding(
                rule=self.id,
                severity=self.severity,
                path=ctx.path,
                line=line,
                col=getattr(node, "col_offset", 0),
                message=message,
                func=ctx.qualname(node),
            )
        )

    def report_text(self, path: str, line: int, message: str) -> None:
        self.findings.append(
            Finding(
                rule=self.id,
                severity=self.severity,
                path=str(path),
                line=int(line),
                col=0,
                message=message,
            )
        )


@dataclass
class Rule(ast.NodeVisitor):
    """Base class: one rule instance lints one module. Subclasses set
    ``id``/``severity``/``doc`` and visit; ``report`` funnels findings
    through suppression checking."""

    ctx: ModuleContext
    findings: List[Finding] = field(default_factory=list)

    id = "PGL000"
    severity = "error"
    doc = ""

    def run(self) -> List[Finding]:
        self.visit(self.ctx.tree)
        return self.findings

    def report(self, node: ast.AST, message: str,
               severity: Optional[str] = None) -> None:
        line = getattr(node, "lineno", 0)
        if self.ctx.is_suppressed(self.id, line):
            return
        self.findings.append(
            Finding(
                rule=self.id,
                severity=severity or self.severity,
                path=self.ctx.path,
                line=line,
                col=getattr(node, "col_offset", 0),
                message=message,
                func=self.ctx.qualname(node),
            )
        )
