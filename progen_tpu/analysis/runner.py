"""Lint runner: file discovery, baseline filtering, reports.

``lint_paths`` is the whole pipeline: parse each module once, build its
traced-region index, run every rule, then split findings into NEW vs
BASELINED. The baseline (``lint_baseline.json``) grandfathers accepted
findings so the gate can be strict from day one without a big-bang
cleanup; entries match on ``(rule, path, func)`` — NOT line numbers —
so unrelated edits to a file don't resurrect them, and every entry
must carry a human ``reason`` (entries without one are rejected at
load, which is what keeps the baseline from becoming a dumping
ground).

Baseline entry shape::

    {"rule": "PGL005", "path": "progen_tpu/x.py",
     "func": "outer.inner", "reason": "trace-time only: ..."}

``path`` matches by suffix, so the baseline works from any invocation
directory.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

from progen_tpu.analysis.core import Finding, ModuleContext
from progen_tpu.analysis.project import ProjectContext, default_text_files
from progen_tpu.analysis.rules_chaos import ChaosDriftRule
from progen_tpu.analysis.rules_donation import DonationRule
from progen_tpu.analysis.rules_durability import DurabilityRule
from progen_tpu.analysis.rules_effects import TracedEffectsRule
from progen_tpu.analysis.rules_grammar_consumers import GrammarConsumerRule
from progen_tpu.analysis.rules_host_sync import HostSyncRule
from progen_tpu.analysis.rules_locks import LockDisciplineRule
from progen_tpu.analysis.rules_recompile import RecompileRule
from progen_tpu.analysis.rules_rng import RngReuseRule
from progen_tpu.analysis.rules_telemetry import TelemetryHygieneRule
from progen_tpu.analysis.traced import TracedIndex

RULES = (
    HostSyncRule,
    RngReuseRule,
    DonationRule,
    RecompileRule,
    TracedEffectsRule,
    TelemetryHygieneRule,
    DurabilityRule,
    LockDisciplineRule,
    GrammarConsumerRule,
)

# whole-project rules: one instance lints the ProjectContext built
# over every discovered module (plus tier1.yml and the docs), after
# the per-module rules have run
PROJECT_RULES = (ChaosDriftRule,)

RULE_DOCS: Dict[str, str] = {
    r.id: r.doc for r in RULES + PROJECT_RULES
}

_SKIP_DIR_NAMES = {
    "__pycache__", ".git", ".ruff_cache", "node_modules", "build",
    "dist", ".eggs",
    # intentionally-defective corpus for tests/test_analysis.py — linted
    # explicitly by those tests, never by the package gate
    "lint_fixtures",
}


class BaselineError(ValueError):
    """Malformed baseline file — reported loudly, never skipped."""


def load_baseline(path) -> List[dict]:
    raw = json.loads(Path(path).read_text())
    entries = raw["findings"] if isinstance(raw, dict) else raw
    if not isinstance(entries, list):
        raise BaselineError(
            f"{path}: baseline must be a list of entries (or "
            f"{{'findings': [...]}})"
        )
    for i, e in enumerate(entries):
        if not isinstance(e, dict):
            raise BaselineError(f"{path}: entry {i} is not an object")
        for field in ("rule", "path", "reason"):
            if not isinstance(e.get(field), str) or not e[field].strip():
                raise BaselineError(
                    f"{path}: entry {i} missing non-empty '{field}' — "
                    f"every baselined finding needs a justification"
                )
    return entries


def _baseline_matches(entry: dict, finding: Finding) -> bool:
    if entry["rule"] != finding.rule:
        return False
    fpath = finding.path.replace("\\", "/")
    epath = entry["path"].replace("\\", "/")
    if not (fpath == epath or fpath.endswith("/" + epath)):
        return False
    if "func" in entry and entry["func"] != finding.func:
        return False
    return True


def discover_files(paths: Sequence) -> List[Path]:
    files: List[Path] = []
    for p in paths:
        p = Path(p)
        if p.is_dir():
            for f in sorted(p.rglob("*.py")):
                if not any(part in _SKIP_DIR_NAMES for part in f.parts):
                    files.append(f)
        elif p.suffix == ".py":
            files.append(p)
    return files


def _parse_module(path, rel_to: Optional[Path] = None):
    """(ctx, None) or (None, PGL000 finding) for a syntax error."""
    source = Path(path).read_text()
    try:
        ctx = ModuleContext(path, source, rel_to=rel_to)
    except SyntaxError as e:
        return None, Finding(
            rule="PGL000",
            severity="error",
            path=str(path),
            line=e.lineno or 0,
            col=e.offset or 0,
            message=f"syntax error: {e.msg}",
        )
    TracedIndex(ctx)
    return ctx, None


def _run_module_rules(ctx: ModuleContext, rules) -> List[Finding]:
    findings: List[Finding] = []
    for rule_cls in rules:
        findings.extend(rule_cls(ctx).run())
    findings.sort(key=lambda f: (f.line, f.col, f.rule))
    return findings


def _run_project_rules(contexts, text_files,
                       project_rules) -> List[Finding]:
    if not project_rules or not contexts:
        return []
    project = ProjectContext.build(contexts, text_files=text_files)
    findings: List[Finding] = []
    for rule_cls in project_rules:
        findings.extend(rule_cls(project).run())
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def lint_file(path, rel_to: Optional[Path] = None, rules=RULES,
              project_rules=PROJECT_RULES) -> List[Finding]:
    """All findings for one file — including project rules run over a
    single-file ProjectContext, so the fixture corpora exercise them
    standalone. Syntax errors surface as a single PGL000 error finding
    rather than crashing the run."""
    ctx, err = _parse_module(path, rel_to=rel_to)
    if err is not None:
        return [err]
    findings = _run_module_rules(ctx, rules)
    findings.extend(_run_project_rules([ctx], (), project_rules))
    return findings


def lint_paths(
    paths: Sequence,
    baseline: Optional[Sequence[dict]] = None,
    rel_to: Optional[Path] = None,
    rules=RULES,
    project_rules=PROJECT_RULES,
) -> Tuple[List[Finding], List[Finding]]:
    """(new_findings, baselined_findings) over every file under
    ``paths``. Modules are parsed ONCE; the per-module rules run on
    each, then the whole-project rules run on a ProjectContext built
    over all of them plus the repo's CI workflows and markdown docs.
    The exit-code contract is ``fail iff new_findings``."""
    all_findings: List[Finding] = []
    contexts: List[ModuleContext] = []
    for f in discover_files(paths):
        ctx, err = _parse_module(f, rel_to=rel_to)
        if err is not None:
            all_findings.append(err)
            continue
        contexts.append(ctx)
        all_findings.extend(_run_module_rules(ctx, rules))
    all_findings.extend(
        _run_project_rules(
            contexts, default_text_files(paths), project_rules
        )
    )
    if not baseline:
        return all_findings, []
    new, matched = [], []
    for finding in all_findings:
        if any(_baseline_matches(e, finding) for e in baseline):
            matched.append(finding)
        else:
            new.append(finding)
    return new, matched


def report_json(new: List[Finding], baselined: List[Finding]) -> dict:
    """The machine-readable report CI uploads as an artifact."""
    return {
        "tool": "progen-tpu-lint",
        "rules": RULE_DOCS,
        "summary": {
            "new": len(new),
            "baselined": len(baselined),
            "by_rule": _by_rule(new),
        },
        "findings": [f.to_json() for f in new],
        "baselined": [f.to_json() for f in baselined],
    }


def _by_rule(findings: List[Finding]) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for f in findings:
        out[f.rule] = out.get(f.rule, 0) + 1
    return out
