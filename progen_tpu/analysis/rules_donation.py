"""PGL003 — donated buffer referenced after the donating call.

``donate_argnums``/``donate_argnames`` hands the argument's device
buffer to XLA for reuse as output storage: after the call the old array
object is DELETED. Reading it again raises
``RuntimeError: Array has been deleted`` on a real backend — but only
where donation actually engages (CPU jit often keeps the buffer alive),
so CPU pytest passes while the pod run dies at step 2. The train step
donates its TrainState for exactly this in-place-update reason
(training/step.py), which is what makes the pattern worth a rule.

Module-local by design: the rule knows a callable donates when the
module itself created it — ``@partial(jax.jit, donate_argnums=...)`` on
a def, or ``name = jax.jit(fn, donate_argnums=...)`` — and then flags
any read of a donated bare-name argument after the call, until the name
is rebound. Loop bodies run twice, so a donating call in a loop whose
argument is not rebound each iteration reports too.
"""

from __future__ import annotations

import ast
from typing import Dict, Set, Tuple

from progen_tpu.analysis.core import Rule, call_name
from progen_tpu.analysis.traced import donated_call_args

_FUNCTION_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


class DonationRule(Rule):
    id = "PGL003"
    severity = "error"
    doc = ("argument donated via donate_argnums/donate_argnames is "
           "referenced after the call — its buffer may be deleted")

    def run(self):
        if self.ctx.traced_index is None or \
                not self.ctx.traced_index.jit_registry:
            return self.findings
        for node in ast.walk(self.ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._analyze_function(node)
        return self.findings

    def _analyze_function(self, fn) -> None:
        # name -> line of the donating call that consumed it
        donated: Dict[str, int] = {}
        reported: Set[Tuple[int, str]] = set()
        self._exec_block(fn.body, donated, reported)

    def _exec_block(self, stmts, donated, reported) -> None:
        for stmt in stmts:
            self._exec_stmt(stmt, donated, reported)

    def _exec_stmt(self, stmt, donated, reported) -> None:
        if isinstance(stmt, _FUNCTION_NODES[:2]):
            self._exec_block(stmt.body, dict(donated), reported)
            return
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            if stmt.value is not None:
                self._eval_expr(stmt.value, donated, reported)
            targets = (
                stmt.targets if isinstance(stmt, ast.Assign)
                else [stmt.target]
            )
            for t in targets:
                self._clear_target(t, donated)
            return
        if isinstance(stmt, ast.Expr):
            self._eval_expr(stmt.value, donated, reported)
            return
        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self._eval_expr(stmt.value, donated, reported)
            return
        if isinstance(stmt, ast.If):
            self._eval_expr(stmt.test, donated, reported)
            d1, d2 = dict(donated), dict(donated)
            self._exec_block(stmt.body, d1, reported)
            self._exec_block(stmt.orelse, d2, reported)
            # donated after the if only when donated on BOTH paths
            donated.clear()
            donated.update({
                k: d1[k] for k in set(d1) & set(d2)
            })
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._eval_expr(stmt.iter, donated, reported)
            self._clear_target(stmt.target, donated)
            for _ in range(2):  # donation from iteration N read at N+1
                self._exec_block(stmt.body, donated, reported)
            self._exec_block(stmt.orelse, donated, reported)
            return
        if isinstance(stmt, ast.While):
            for _ in range(2):
                self._eval_expr(stmt.test, donated, reported)
                self._exec_block(stmt.body, donated, reported)
            self._exec_block(stmt.orelse, donated, reported)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._eval_expr(item.context_expr, donated, reported)
            self._exec_block(stmt.body, donated, reported)
            return
        if isinstance(stmt, ast.Try):
            self._exec_block(stmt.body, donated, reported)
            for h in stmt.handlers:
                self._exec_block(h.body, dict(donated), reported)
            self._exec_block(stmt.orelse, donated, reported)
            self._exec_block(stmt.finalbody, donated, reported)
            return
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._eval_expr(child, donated, reported)

    def _clear_target(self, target, donated) -> None:
        if isinstance(target, ast.Name):
            donated.pop(target.id, None)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._clear_target(elt, donated)

    def _eval_expr(self, expr, donated, reported) -> None:
        registry = self.ctx.traced_index.jit_registry
        for node in ast.walk(expr):
            if isinstance(node, ast.Name) and isinstance(
                node.ctx, ast.Load
            ) and node.id in donated:
                key = (node.lineno, node.id)
                if key not in reported:
                    reported.add(key)
                    self.report(
                        node,
                        f"'{node.id}' was donated at line "
                        f"{donated[node.id]} and is referenced afterwards "
                        f"— the donated buffer may already be deleted on "
                        f"device",
                    )
        # mark donations AFTER scanning reads: the donating call's own
        # argument read is legal
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            cname = call_name(node)
            info = registry.get(cname) if cname else None
            if info is None:
                continue
            for _pname, arg in donated_call_args(info, node):
                if isinstance(arg, ast.Name):
                    donated[arg.id] = node.lineno
