"""Batch scoring CLI — bulk perplexity ranking of candidate sequences.

The protein-design screening workload (progen_tpu/workloads/scoring.py):
stream a FASTA file or a TFRecord split through the training data path,
score every sequence with the shared ``sequence_scores`` reduction, and
write sharded JSONL (per-sequence NLL/perplexity, optional per-token
logprobs) plus a progress journal. Killed mid-run, a re-run with
``--resume`` (the default) skips every durably written id and completes
the remainder — zero duplicates, zero lost work.

Run: python -m progen_tpu.cli.batch_score --checkpoint_path ./ckpts \
         --input candidates.fasta --out_dir ./scores
"""

from __future__ import annotations

from progen_tpu.utils.env import load_env_file

load_env_file()  # XLA/env flags before jax import (ref train.py:1-2)

import json
import os
import sys

import click


@click.command()
@click.option("--checkpoint_path", default="./ckpts")
@click.option("--input", "input_path", required=True,
              help="a FASTA file, or a TFRecord folder (see --split)")
@click.option("--split", default="valid",
              type=click.Choice(["train", "valid"]),
              help="which TFRecord split to score when --input is a folder")
@click.option("--context", default="",
              help="conditioning tag prepended to every FASTA sequence "
                   "(scored as 'context # SEQ', the annotation grammar)")
@click.option("--out_dir", default="./scores",
              help="output dir: scores-*.jsonl shards + score journal")
@click.option("--batch_size", default=8)
@click.option("--shard_size", default=512,
              help="output lines per shard before rotating")
@click.option("--logprobs/--no-logprobs", default=True,
              help="include per-token logprobs in each output record")
@click.option("--resume/--no-resume", default=True,
              help="skip ids already in the output shards (torn tails "
                   "from a kill are truncated first)")
@click.option("--max_batches", default=None, type=int,
              help="stop after N scored batches (deterministic partial "
                   "run for resume tests)")
@click.option("--prom_file", default=None, type=str,
              help="write Prometheus text exposition here "
                   "(progen_score_* families)")
@click.option("--metrics-every", default=0,
              help="rewrite --prom_file every N batches (0 = at end only)")
def main(checkpoint_path, input_path, split, context, out_dir, batch_size,
         shard_size, logprobs, resume, max_batches, prom_file,
         metrics_every):
    from progen_tpu import telemetry
    from progen_tpu.checkpoint import get_checkpoint_fns
    from progen_tpu.config import ProGenConfig
    from progen_tpu.models.progen import ProGen
    from progen_tpu.resilience.chaos import install_from_env
    from progen_tpu.telemetry import MetricsRegistry
    from progen_tpu.tracking import make_tracker
    from progen_tpu.workloads import (
        fasta_records,
        run_batch_score,
        tfrecord_records,
    )

    # the CI resume test drives this process with PROGEN_CHAOS alone
    # (score/batch:kill@N — SIGKILL after the Nth durable batch)
    install_from_env()

    _, get_last, _ = get_checkpoint_fns(checkpoint_path)
    pkg = get_last.restore_params()  # params only: no optimizer moments
    if pkg is None:
        sys.exit(f"no checkpoints found at {checkpoint_path}")
    config = ProGenConfig.from_dict(pkg.model_config)
    model = ProGen(config)

    if os.path.isdir(input_path):
        records = tfrecord_records(input_path, split)
    else:
        records = fasta_records(input_path, context)

    tracker = make_tracker("progen-batch-score")
    # journal records double as telemetry events (ev:"score" grammar,
    # analysis/rules_telemetry.py PGL006) — mirror them to the tracker
    telemetry.configure(sink=tracker.log_event)
    metrics = MetricsRegistry()
    try:
        summary = run_batch_score(
            model, pkg.state, records, out_dir,
            batch_size=batch_size, logprobs=logprobs,
            shard_size=shard_size, resume=resume,
            metrics=metrics, prom_file=prom_file,
            metrics_every=metrics_every, max_batches=max_batches,
        )
    finally:
        telemetry.configure()  # detach before the sink closes
        tracker.finish()
    print(json.dumps(summary))


if __name__ == "__main__":
    main()
