"""``progen-tpu-top`` — live fleet ops console over a collector TSDB.

Opens the store READ-ONLY (never races the collector) and renders the
``console.build_snapshot`` view: per-source up/age/slots/queue/latency
rows, the fleet rollup, SLO burn states, recent alerts (annotated with
routed/silenced delivery state when an alert-router ledger exists), a
notifications tail with delivery counts, and the TSDB's own health
line. ``--alerts-only`` drops the source/fleet tables for an on-call
terminal.

Keys (watch mode): ``q`` quits; any other key refreshes immediately.
``--once`` renders a single frame; ``--once --json`` dumps the exact
snapshot dict as JSON — the scripting/CI surface, asserted by the
tier-1 fleet-metrics smoke.
"""

from __future__ import annotations

import sys

import click

from progen_tpu.telemetry import console as console_mod
from progen_tpu.telemetry.slo import load_objectives
from progen_tpu.telemetry.tsdb import TsdbReader


@click.command()
@click.option(
    "--tsdb", "tsdb_dir", required=True,
    type=click.Path(exists=True, file_okay=False),
    help="collector store directory to watch",
)
@click.option(
    "--slo", "slo_path",
    type=click.Path(exists=True, dir_okay=False), default=None,
    help="objectives TOML: show fleet SLO states in the dashboard",
)
@click.option(
    "--alerts", "alerts_path", type=click.Path(dir_okay=False),
    default=None,
    help="alerts JSONL [default: <tsdb>/alerts.jsonl when present]",
)
@click.option(
    "--refresh", type=float, default=2.0, show_default=True,
    help="seconds between frames in watch mode",
)
@click.option(
    "--frames", type=int, default=0, show_default=True,
    help="stop watch mode after N frames (0 = until q/killed)",
)
@click.option("--once", is_flag=True, help="render one frame and exit")
@click.option(
    "--json", "json_out", is_flag=True,
    help="with --once: print the snapshot as JSON instead of ANSI",
)
@click.option(
    "--notifications", "notifications_path",
    type=click.Path(dir_okay=False), default=None,
    help="alert-router ledger [default: <tsdb>/notifications.jsonl "
         "when present]",
)
@click.option(
    "--alerts-only", is_flag=True,
    help="render only the SLO/alert/notification panes (on-call view)",
)
@click.option(
    "--color/--no-color", default=None,
    help="force ANSI color on/off [default: on for TTYs]",
)
def main(tsdb_dir, slo_path, alerts_path, refresh, frames, once,
         json_out, notifications_path, alerts_only, color):
    """Live ANSI dashboard (or one-shot JSON) for the metrics fleet."""
    tsdb = TsdbReader(tsdb_dir)
    cfg = load_objectives(slo_path) if slo_path else None
    if alerts_path is None:
        default_alerts = tsdb.root / "alerts.jsonl"
        alerts_path = default_alerts if default_alerts.exists() else None
    if notifications_path is None:
        default_notes = tsdb.root / "notifications.jsonl"
        notifications_path = (
            default_notes if default_notes.exists() else None
        )
    if color is None:
        color = sys.stdout.isatty()
    if json_out and not once:
        raise click.UsageError("--json requires --once")
    if once:
        snap = console_mod.build_snapshot(
            tsdb, slo_cfg=cfg, alerts_path=alerts_path,
            notifications_path=notifications_path,
        )
        if json_out:
            click.echo(console_mod.snapshot_json(snap))
        else:
            click.echo(console_mod.render(
                snap, color=color, alerts_only=alerts_only
            ))
        return
    console_mod.watch(
        tsdb, slo_cfg=cfg, alerts_path=alerts_path,
        refresh_s=refresh, color=color,
        max_frames=frames if frames > 0 else None,
        notifications_path=notifications_path,
        alerts_only=alerts_only,
    )


if __name__ == "__main__":
    main()
