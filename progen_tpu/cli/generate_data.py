"""Data-generation CLI: FASTA -> TFRecord shards.

Parity with /root/reference/generate_data.py:160-172 (same flags, same TOML
schema) without the Prefect DAG wrapper — the two ETL stages are plain
functions in progen_tpu/data/fasta.py.

Run: python -m progen_tpu.cli.generate_data --data_dir ./configs/data
"""

from __future__ import annotations

from progen_tpu.utils.env import load_env_file

load_env_file()  # XLA/env flags before jax import (ref train.py:1-2)

from pathlib import Path

import click


@click.command()
@click.option("--data_dir", default="./configs/data")
@click.option("--name", default="default")
@click.option("--seed", default=None, type=int, help="seedable ETL (additive)")
def main(data_dir, name, seed):
    from progen_tpu.config import load_toml_config
    from progen_tpu.data.fasta import generate_data

    config_path = Path(data_dir) / f"{name}.toml"
    assert config_path.exists(), f"config does not exist at {config_path}"
    config = load_toml_config(str(config_path))
    written = generate_data(config, seed=seed)
    total = len(written)
    print(f"wrote {total} tfrecord shard(s):")
    for path in written:
        print(f"  {path}")


if __name__ == "__main__":
    main()
