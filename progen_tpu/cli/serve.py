"""Serving CLI — continuous-batching engine loop against a checkpoint.

Runs the slot-pool engine (progen_tpu/serving/) as a single-threaded
event loop. Requests arrive as JSON lines, one object per request:

    {"id": "r1", "prime": "[tax=Mammalia] #", "length": 256,
     "temperature": 0.8, "top_p": 0.95, "top_k": 25, "seed": 7}

(``id`` and ``prime`` required; everything else optional — ``length``
defaults to --max-len.) The router's resume wire (serving/router.py)
uses three extra optional fields: ``prime_tokens`` (raw token ids,
bypassing the tokenizer), ``key`` (explicit uint32 PRNG key pair) and
``add_bos`` (default true) — together they let a handed-off request
continue bit-identically on another replica.

Two protein-design request shapes ride the same wire
(progen_tpu/workloads/):

    {"id": "f1", "template": "MK?LV??G", "free_char": "?", ...}
    {"id": "e1", "prime": "[tax=Mammalia] # MKLV", "embed": true}

``template`` is fixed-position infilling: frozen characters are kept
verbatim, ``free_char`` slots (default "?") are sampled; the leading
frozen run becomes the prime and ``length`` is the template's, so both
are derived, not read. (The resume wire may instead carry buffer-
aligned ``template_tokens`` + ``frozen`` lists.) ``embed: true`` asks
for a mean-pooled final-norm embedding of the prime instead of
generation — the reply is a single terminal ``embedding`` event.
Responses stream back as JSON lines, one per
event, interleaved across requests as the engine produces them:

    {"event": "token", "id": "r1", "token": 77, "text": "L", "index": 18}
    {"event": "done", "id": "r1", "text": "...", "n_generated": 238,
     "ttft_s": 0.01, "latency_s": 0.9}
    {"event": "embedding", "id": "e1", "dim": 1024, "values": [...],
     "latency_s": 0.02}
    {"event": "rejected", "id": "r9", "reason": "queue_full"}

Three transports, same protocol:
  * default: requests on stdin, events on stdout (pipe-friendly;
    EOF drains the queue and exits);
  * --socket PATH: a unix domain socket server; each connection
    submits requests and receives exactly its own events;
  * --tcp HOST:PORT: the same server over framed TCP
    (fleet/transport.py — every frame's payload is exactly one of
    these JSONL lines, so streams are bit-identical to the unix
    transport and journal/replay/handoff work unchanged).

Connection-oriented transports also answer a control line,
``{"ctl": "release", "id": ...}`` — the router's rebalance/scale-down
path asking this replica to surrender one still-queued request
(``{"event": "released", "released": true|false}``; a granted release
is journaled ``done(handed_off)`` so --replay skips it).

Zero-downtime ops (see README "Zero-downtime ops"):
  * SIGHUP hot-reloads the newest verified checkpoint in a background
    thread and swaps it in between decode steps — zero recompiles,
    zero dropped requests; ``--reload_watch N`` polls the checkpoint
    dir every N seconds and reloads automatically;
  * ``--journal_dir DIR`` journals accepted requests + emitted tokens
    to DIR/journal.jsonl; after a crash, ``--replay DIR`` resumes every
    unfinished accepted request bit-identically (dedup on request id —
    completed work is never re-emitted).

Run: python -m progen_tpu.cli.serve --max-slots 8 --max-queue 64
"""

from __future__ import annotations

from progen_tpu.utils.env import load_env_file

load_env_file()  # XLA/env flags before jax import (ref train.py:1-2)

import json
import os
import select
import socket
import sys

import click
import numpy as np


def _parse_request(line, defaults):
    """JSONL line -> (Request, error_string). Tokenizes the prime and
    applies server defaults; malformed input becomes a rejection event
    rather than a crash (a server must outlive its worst client)."""
    from progen_tpu.data.tokenizer import encode_tokens
    from progen_tpu.serving import Request

    try:
        obj = json.loads(line)
        if not isinstance(obj, dict):
            raise ValueError("request must be a JSON object")
        rid = str(obj["id"])
    except (ValueError, KeyError) as e:
        return None, f"bad request line: {e}"
    try:
        if obj.get("prime_tokens") is not None:
            # raw token ids: the router's resume wire (already-tokenized
            # prefix of a handed-off request) — bypasses the tokenizer
            prime = np.asarray(
                [int(t) for t in obj["prime_tokens"]], dtype=np.int32
            )
        else:
            prime = np.asarray(
                encode_tokens(str(obj.get("prime", ""))), dtype=np.int32
            )
        key = None
        if obj.get("key") is not None:
            # explicit PRNG key (raw uint32 pair): resumed requests must
            # continue the EXACT stream, not restart a seed
            import jax.numpy as jnp

            key = jnp.asarray(
                [int(k) for k in obj["key"]], dtype=jnp.uint32
            )
        add_bos = bool(obj.get("add_bos", True))
        length = int(obj.get("length", defaults["length"]))
        template = frozen = None
        if obj.get("template") is not None:
            # infilling: the template fixes prime AND length — frozen
            # prefix is the prime, template width is the length
            from progen_tpu.workloads.infill import (
                infill_request_arrays,
                parse_template,
            )

            toks, frz = parse_template(
                str(obj["template"]), str(obj.get("free_char", "?"))
            )
            prime, length, template, frozen = infill_request_arrays(
                toks, frz, add_bos=add_bos
            )
        elif obj.get("template_tokens") is not None:
            # resume wire: buffer-aligned constraint arrays as journaled
            # (prime/length/add_bos already carried by their own fields)
            template = np.asarray(
                [int(t) for t in obj["template_tokens"]], dtype=np.int32
            )
            frozen = np.asarray(
                [bool(f) for f in obj.get("frozen", [])], dtype=bool
            )
        req = Request(
            id=rid,
            prime=prime,
            length=length,
            kind="embed" if obj.get("embed") else "generate",
            template=template,
            frozen=frozen,
            top_k=(None if obj.get("top_k", defaults["top_k"]) is None
                   else int(obj.get("top_k", defaults["top_k"]))),
            # default True: server parity with cli/sample.py; resumed
            # requests carry their journaled add_bos explicitly
            add_bos=add_bos,
            temperature=float(
                obj.get("temperature", defaults["temperature"])
            ),
            top_p=(None if obj.get("top_p", defaults["top_p"]) is None
                   else float(obj.get("top_p", defaults["top_p"]))),
            seed=int(obj.get("seed", defaults["seed"])),
            key=key,
            deadline_s=(None if obj.get("deadline_s") is None
                        else float(obj["deadline_s"])),
            # cross-process trace context minted by the router (or an
            # upstream client): stamped on this replica's req records
            # and journaled, so the fleet trace stays one journey
            trace_id=(None if obj.get("trace_id") is None
                      else str(obj["trace_id"])),
        )
        return req, None
    except (ValueError, TypeError) as e:
        # keep the id so the rejection can still be routed to its request
        return (
            Request(id=rid, prime=np.zeros(0, np.int32), length=-1),
            f"bad request fields: {e}",
        )


def _events_to_lines(events, completions, starts):
    """Engine step output -> protocol JSONL strings. ``starts`` maps
    request id -> primed positions, so done-events can report only the
    generated suffix as text (parity with sample.py's print)."""
    from progen_tpu.data.tokenizer import decode_tokens

    lines = []
    for ev in events:
        lines.append(json.dumps({
            "event": "token",
            "id": ev.request_id,
            "token": int(ev.token),
            "text": decode_tokens([ev.token]),
            "index": int(ev.index),
        }))
    for c in completions:
        start = starts.pop(c.request_id, 0)
        if getattr(c, "embedding", None) is not None:
            # embed requests terminate with the vector, not a done line
            vec = c.embedding
            lines.append(json.dumps({
                "event": "embedding",
                "id": c.request_id,
                "dim": int(vec.shape[0]),
                "values": [round(float(x), 6) for x in vec],
                "latency_s": round(c.latency_s, 6),
            }))
            continue
        lines.append(json.dumps({
            "event": "done",
            "id": c.request_id,
            "text": decode_tokens(c.tokens[start:]),
            "n_generated": int(c.n_generated),
            "ttft_s": round(c.ttft_s, 6),
            "latency_s": round(c.latency_s, 6),
        }))
    return lines


def _build(checkpoint_path, max_slots, max_len, max_queue,
           quantize_int8=False, journal=None, prefill_chunk=0,
           prefix_cache_mb=0, pin=None):
    import os.path

    from progen_tpu.checkpoint import get_checkpoint_fns
    from progen_tpu.config import ProGenConfig
    from progen_tpu.models.progen import ProGen
    from progen_tpu.serving import PrefixCache, Scheduler, ServeEngine

    _, get_last, _ = get_checkpoint_fns(checkpoint_path)
    pkg = None
    if pin is not None:
        # a pre-existing pin file names the checkpoint this replica must
        # serve (a controller-managed fleet member rebooting mid-deploy);
        # an unloadable pin falls back to newest — the replica must come
        # up serving SOMETHING, and the ack tells the controller the pin
        # was not honored
        pkg = get_last.restore_params(at=pin)
        if pkg is None:
            print(
                f"reload pin {pin}: not restorable, falling back to "
                f"newest checkpoint", file=sys.stderr,
            )
    if pkg is None:
        pkg = get_last.restore_params()
    if pkg is None:
        sys.exit(f"no checkpoints found at {checkpoint_path}")
    config = ProGenConfig.from_dict(pkg.model_config)
    model = ProGen(config)
    engine = ServeEngine(
        model, pkg.state, max_slots=max_slots,
        max_len=min(max_len or config.seq_len, config.seq_len),
        quantize_int8=quantize_int8,
    )
    if engine.quant_report is not None:
        r = engine.quant_report
        print(
            f"int8 weights: {r['quantized_leaves']} kernels, "
            f"{r['bytes_fp']} -> {r['bytes_int8']} bytes, "
            f"calib logits max-abs-err {r['logits_max_abs_err']:.3g}",
            file=sys.stderr,
        )
    ckpt_name = os.path.basename(pkg.path) if pkg.path else None
    prefix_cache = None
    if prefix_cache_mb:
        prefix_cache = PrefixCache(int(prefix_cache_mb) * (1 << 20))
    sched = Scheduler(engine, max_queue=max_queue, journal=journal,
                      prefill_chunk=prefill_chunk,
                      prefix_cache=prefix_cache)
    return sched, engine, ckpt_name


@click.command()
@click.option("--checkpoint_path", default="./ckpts")
@click.option("--max-slots", default=8,
              help="device decode lanes: concurrent requests advanced "
                   "per step (fixes the compiled shapes)")
@click.option("--max-queue", default=64,
              help="bounded admission queue; submits beyond this are "
                   "rejected with reason 'queue_full'")
@click.option("--max-len", default=None, type=int,
              help="longest servable sequence (default: the model's "
                   "seq_len); also the per-request 'length' default")
@click.option("--int8/--no-int8", "quantize_int8", default=False,
              help="serve int8 weight-quantized matmuls (per-channel "
                   "symmetric, dequant fused on-device); logs a "
                   "max-abs-error calibration report at load")
@click.option("--prefill_chunk", default=0,
              help="admit long prompts N prime tokens per decode step "
                   "(chunked prefill) instead of stalling every live "
                   "decode for the whole prompt; 0 = monolithic "
                   "admission. Streams are bit-identical either way")
@click.option("--prefix_cache_mb", default=0,
              help="LRU cache of prefill-state snapshots keyed on the "
                   "token-prefix hash, in MiB of device cache bytes "
                   "(0 = off): repeated scaffolds skip their shared "
                   "prefix at admission. Invalidated on hot reload")
@click.option("--top_k", default=25, help="default per-request top_k")
@click.option("--temperature", default=1.0,
              help="default per-request temperature")
@click.option("--top_p", default=None, type=float,
              help="default per-request nucleus mass")
@click.option("--seed", default=42, help="default per-request PRNG seed")
@click.option("--socket", "socket_path", default=None, type=str,
              help="serve a unix domain socket at PATH instead of "
                   "stdin/stdout")
@click.option("--tcp", "tcp_hostport", default=None, type=str,
              help="serve framed TCP at HOST:PORT (fleet transport: "
                   "length-prefixed frames whose payloads are exactly "
                   "the JSONL protocol lines; PORT 0 = ephemeral, the "
                   "bound port is printed on stderr)")
@click.option("--idle_timeout", default=0.0, type=float,
              help="drop a --tcp peer silent for more than N seconds "
                   "(0 = never; unix sockets never need this, half-open "
                   "TCP peers hold sockets forever)")
@click.option("--metrics-every", default=0,
              help="log a serve/ metrics snapshot to the tracker (and "
                   "rewrite --prom_file) every N decode steps "
                   "(0 = only at exit)")
@click.option("--prom_file", default=None, type=str,
              help="write Prometheus text exposition here (atomic "
                   "rewrite on the --metrics-every cadence and at exit; "
                   "node-exporter textfile-collector compatible)")
@click.option("--prom_port", default=0,
              help="serve Prometheus text exposition over HTTP on this "
                   "localhost port (0 = off)")
@click.option("--heartbeat", default=0.0,
              help="rewrite --prom_file at least every N seconds even "
                   "when idle (0 = only on the --metrics-every cadence). "
                   "The fleet collector reads exposition mtime as the "
                   "liveness signal; without a heartbeat an idle but "
                   "healthy replica looks dead")
@click.option("--journal_dir", default=None, type=str,
              help="journal accepted requests + emitted tokens to "
                   "DIR/journal.jsonl (crash-safe, append-only) so a "
                   "later --replay loses zero accepted work")
@click.option("--replay", "replay_dir", default=None, type=str,
              help="on startup, replay DIR/journal.jsonl: resume every "
                   "accepted-but-unfinished request bit-identically "
                   "(dedup on request id; finished work is settled, "
                   "never re-decoded)")
@click.option("--reload_watch", default=0.0, type=float,
              help="poll the checkpoint dir every N seconds and "
                   "hot-reload when a new complete checkpoint appears "
                   "(0 = off; SIGHUP always triggers a reload)")
@click.option("--reload_pin", "reload_pin_path", default=None, type=str,
              help="per-replica pin control file (reload.pin): when it "
                   "names a checkpoint, the --reload_watch poll loads "
                   "exactly that one (newest-wins suspended) and "
                   "answers through FILE.ack; at startup a pinned "
                   "checkpoint is restored directly. The deploy "
                   "controller's canary/promote seam. Implies "
                   "--reload_watch 2 when unset")
@click.option("--flight_dir", default=None, type=str,
              help="arm the flight recorder: keep the last "
                   "events/spans/requests in a bounded in-memory ring "
                   "and dump an atomic flight-<host>-<ts>.json here on "
                   "crash paths (chaos kill, stall escalation, "
                   "unhandled exception, second kill signal)")
@click.option("--profile_pin", "profile_pin_path", default=None, type=str,
              help="profile.pin control file: when it carries a token "
                   "(optionally '<token> <seconds>'), start a bounded "
                   "jax.profiler trace window on the live process and "
                   "answer through FILE.ack — no restart. Polled every "
                   "2s between decode steps")
@click.option("--profile_out", default=None, type=str,
              help="directory for on-demand profiler trace windows "
                   "(default: <profile_pin dir>/profiles)")
def main(checkpoint_path, max_slots, max_queue, max_len, quantize_int8,
         prefill_chunk, prefix_cache_mb, top_k, temperature, top_p, seed,
         socket_path, tcp_hostport, idle_timeout, metrics_every,
         prom_file, prom_port, heartbeat, journal_dir, replay_dir,
         reload_watch, reload_pin_path, flight_dir, profile_pin_path,
         profile_out):
    from progen_tpu import telemetry
    from progen_tpu.resilience.chaos import install_from_env
    from progen_tpu.telemetry import (
        prometheus_text,
        start_prometheus_server,
        write_prometheus,
    )
    from progen_tpu.tracking import make_tracker

    # serving chaos sites (serve/prefill, serve/decode, serve/reload*)
    # arm from the environment, same as cli/train.py — the serve
    # kill-matrix drives this process via PROGEN_CHAOS alone
    install_from_env()

    journal = None
    if journal_dir:
        from progen_tpu.serving import RequestJournal

        journal = RequestJournal(os.path.join(journal_dir, "journal.jsonl"))
    startup_pin = None
    if reload_pin_path:
        if not reload_watch:
            reload_watch = 2.0  # a pin nobody polls is a dead letter
        try:
            with open(reload_pin_path) as f:
                startup_pin = f.read().strip() or None
        except OSError:
            startup_pin = None
    sched, engine, ckpt_name = _build(
        checkpoint_path, max_slots, max_len, max_queue,
        quantize_int8=quantize_int8, journal=journal,
        prefill_chunk=prefill_chunk, prefix_cache_mb=prefix_cache_mb,
        pin=startup_pin,
    )
    defaults = {
        "length": engine.max_len, "top_k": top_k,
        "temperature": temperature, "top_p": top_p, "seed": seed,
    }
    tracker = make_tracker("progen-serve")
    # per-request async tracing: the scheduler's req/slots records and
    # the engine's serve/prefill spans land in the tracker's
    # events.jsonl — `progen-tpu-telemetry export-trace` renders each
    # accepted request as one async track (queued → prefill → decode)
    telemetry.configure(sink=tracker.log_event)
    run_dir = getattr(tracker, "path", None)
    if run_dir is not None:
        print(
            f"request traces: {run_dir}/events.jsonl "
            "(render with progen-tpu-telemetry export-trace)",
            file=sys.stderr,
        )

    # forensics: black-box ring + on-demand profiler window, both armed
    # only when asked — the flight-overhead bench pins the armed cost
    from progen_tpu.telemetry import flight as flight_mod

    if flight_dir:
        flight_mod.arm(flight_dir, metrics_fn=sched.metrics.snapshot)
        print(f"flight recorder armed: dumps to {flight_dir}",
              file=sys.stderr)
    prof_watcher = None
    if profile_pin_path:
        prof_out = profile_out or os.path.join(
            os.path.dirname(profile_pin_path) or ".", "profiles"
        )
        prof_watcher = flight_mod.ProfilePinWatcher(
            profile_pin_path, prof_out
        )
        print(f"profile pin watched: {profile_pin_path} "
              f"(windows to {prof_out})", file=sys.stderr)

    import time as _time

    hb = {"last": _time.monotonic()}

    from progen_tpu.checkpoint import checkpoint_digest, digest_gauge

    ckd = {"name": ckpt_name}

    def _digest_of(name):
        if not name:
            return -1.0
        return digest_gauge(checkpoint_digest(
            os.path.join(checkpoint_path, name)
        ))

    ckd["gauge"] = _digest_of(ckpt_name)

    def publish(step=None):
        # compile counts ride the metrics: the router's kill-matrix
        # reads the survivor's prom file to prove handoff didn't trigger
        # a recompile (resume state is shape-identical to fresh intake)
        sched.metrics.set_gauge(
            "prefill_compile_count", engine.prefill_compile_count()
        )
        sched.metrics.set_gauge(
            "decode_compile_count", engine.decode_compile_count()
        )
        # live checkpoint identity (first 48 digest bits as a float):
        # the deploy controller and the router read fleet skew from this
        sched.metrics.set_gauge("checkpoint_digest", ckd["gauge"])
        sched.metrics.log_to(tracker, step=step)
        if prom_file:
            write_prometheus(prom_file, prometheus_text(sched.metrics))
            hb["last"] = _time.monotonic()

    prom_srv = None
    if prom_port:
        prom_srv = start_prometheus_server(
            lambda: prometheus_text(sched.metrics), port=prom_port
        )
        print(
            f"prometheus on http://127.0.0.1:"
            f"{prom_srv.server_address[1]}/metrics",
            file=sys.stderr,
        )
    print(
        f"serving: max_slots={engine.max_slots} max_len={engine.max_len} "
        f"max_queue={sched.max_queue}"
        + (f" checkpoint={ckpt_name}" if ckpt_name else ""),
        file=sys.stderr,
    )

    # hot weight reload: SIGHUP (or the --reload_watch poller) stages
    # the newest verified checkpoint on a background thread; tick()
    # commits it between decode steps — zero recompiles, zero drops
    from progen_tpu.serving import WeightReloader

    reloader = WeightReloader(
        engine, checkpoint_path, metrics=sched.metrics,
        current=ckpt_name, pin_path=reload_pin_path,
    )
    # answer a pre-existing pin file now: committed when _build restored
    # it, rejected when it fell back — the controller must not wait on a
    # pin this process already settled
    reloader.note_startup_pin()
    reload_req = {"flag": False}

    def tick():
        """Once per serve-loop iteration, between decode steps."""
        # prom rewrite only (no tracker row): mtime freshness for the
        # fleet collector's staleness check, without metrics.jsonl spam
        if heartbeat and prom_file \
                and _time.monotonic() - hb["last"] >= heartbeat:
            write_prometheus(prom_file, prometheus_text(sched.metrics))
            hb["last"] = _time.monotonic()
        if reload_req["flag"]:
            reload_req["flag"] = False
            if reloader.request_reload():
                print("reload: loading newest checkpoint in background",
                      file=sys.stderr)
        if reload_watch:
            reloader.poll_watch(reload_watch)
        if prof_watcher is not None:
            prof_watcher.poll_watch()
        name = reloader.maybe_commit()
        if name is not None:
            ckd["name"], ckd["gauge"] = name, _digest_of(name)
            print(f"reload: now serving {name}", file=sys.stderr)
            publish()  # the digest gauge must not wait a metrics cadence
        elif reloader.last_error is not None:
            print(f"reload: rejected ({reloader.last_error}) — still "
                  f"serving {reloader.current}", file=sys.stderr)
            reloader.last_error = None

    # crash recovery: resume the previous process's unfinished accepted
    # requests before opening intake. Requests whose journaled stream
    # already hit its stop rule are settled here (done event, no decode)
    replayed_lines = []
    starts0 = {}
    if replay_dir:
        from progen_tpu.data.tokenizer import decode_tokens
        from progen_tpu.serving import replay_into

        jpath = os.path.join(replay_dir, "journal.jsonl")
        if os.path.exists(jpath):
            summary = replay_into(sched, jpath)
            for req in summary["resumed"]:
                starts0[req.id] = len(req.prime) + (1 if req.add_bos else 0)
            for f in summary["finished"]:
                replayed_lines.append(json.dumps({
                    "event": "done", "id": f["id"],
                    "text": decode_tokens(f["emitted"]),
                    "n_generated": 0, "ttft_s": 0.0, "latency_s": 0.0,
                    "replayed": True,
                }))
            print(
                f"replay: resumed {len(summary['resumed'])} request(s), "
                f"settled {len(summary['finished'])} already-finished, "
                f"skipped {summary['skipped_done']} done "
                f"({summary['dropped_lines']} torn journal line(s))",
                file=sys.stderr,
            )
        else:
            print(f"replay: no journal at {jpath}", file=sys.stderr)

    # graceful drain: the FIRST SIGTERM/SIGINT closes intake — queued
    # requests are shed as 'rejected: draining', in-flight slots decode
    # to completion, metrics flush, exit 0 (what a rolling restart
    # wants). A SECOND signal means "now": close the open per-request
    # trace tracks (reason 'killed' — the post-mortem trace must be
    # honest about what was in flight) and exit immediately.
    import signal

    shutdown = {"flag": False}

    def _request_drain(signum, frame):
        if shutdown["flag"]:
            print(f"signal {signum} again: exiting now", file=sys.stderr)
            try:
                sched.close_tracks("killed")
            except Exception:
                pass  # a torn trace line beats a hung exit
            # last act: the black box (atomic — a kill mid-dump leaves
            # no torn file, and dump_now never raises)
            flight_mod.dump_now("killed", note=f"signal {signum}")
            sys.stderr.flush()
            os._exit(1)
        shutdown["flag"] = True
        print(
            f"signal {signum}: draining — intake closed, finishing "
            "in-flight requests; signal again to kill",
            file=sys.stderr,
        )

    def _request_reload(signum, frame):
        reload_req["flag"] = True  # handler-minimal; tick() does the work

    old_term = signal.signal(signal.SIGTERM, _request_drain)
    old_int = signal.signal(signal.SIGINT, _request_drain)
    old_hup = signal.signal(signal.SIGHUP, _request_reload)
    try:
        if tcp_hostport:
            _serve_tcp(sched, defaults, tcp_hostport, publish,
                       metrics_every, shutdown, tick=tick,
                       idle_timeout=idle_timeout)
        elif socket_path:
            _serve_socket(sched, defaults, socket_path, publish,
                          metrics_every, shutdown, tick=tick)
        else:
            _serve_stdio(sched, defaults, publish, metrics_every,
                         shutdown, tick=tick, starts0=starts0,
                         preamble=replayed_lines)
    finally:
        signal.signal(signal.SIGTERM, old_term)
        signal.signal(signal.SIGINT, old_int)
        signal.signal(signal.SIGHUP, old_hup)
        publish()
        print(
            f"compile counts: prefill={engine.prefill_compile_count()} "
            f"decode={engine.decode_compile_count()}",
            file=sys.stderr,
        )
        if prom_srv is not None:
            prom_srv.shutdown()
        if prof_watcher is not None:
            prof_watcher.close()  # flush an in-flight profiler window
        flight_mod.disarm()
        telemetry.configure()  # detach before the sink closes
        tracker.finish()
        if journal is not None:
            journal.close()


def _submit_line(sched, line, defaults):
    """Parse + submit one request line; returns (rejection_line | None,
    request | None)."""
    req, err = _parse_request(line, defaults)
    if err is not None:
        rid = req.id if req is not None else None
        return json.dumps(
            {"event": "rejected", "id": rid, "reason": err}
        ), None
    ok, reason = sched.submit(req)
    if not ok:
        return json.dumps(
            {"event": "rejected", "id": req.id, "reason": reason}
        ), None
    return None, req


def _shed_lines(sched, starts, owners=None):
    """Requests the scheduler shed (deadline expiry, drain) become
    rejection events for their owners; returns (fd_or_None, line)
    pairs — fd is None on the stdio transport."""
    out = []
    for req, reason in sched.pop_expired():
        starts.pop(req.id, None)
        if owners is None:
            out.append((None, json.dumps({
                "event": "rejected", "id": req.id, "reason": reason,
            })))
        else:
            fd, public = owners.pop(req.id, (None, None))
            if fd is not None:
                out.append((fd, json.dumps({
                    "event": "rejected", "id": public, "reason": reason,
                })))
    return out


def _serve_stdio(sched, defaults, publish, metrics_every, shutdown,
                 tick=None, starts0=None, preamble=None):
    """stdin-JSONL transport: poll stdin between decode steps so new
    requests join mid-flight (continuous batching, not read-all-then-
    drain); EOF stops intake and the loop drains what remains. A drain
    signal (see main) also stops intake, but sheds the QUEUE — only
    in-flight slots run to completion. ``tick`` runs once per loop
    iteration (reload staging/commit); ``starts0``/``preamble`` carry
    replayed-request state from --replay."""
    starts = dict(starts0 or {})
    out = sys.stdout
    eof = False
    drained = False
    steps = 0
    buf = ""  # bytes off the pipe that don't yet end in a newline

    def emit(lines):
        for ln in lines:
            out.write(ln + "\n")
        out.flush()

    emit(list(preamble or []))
    while (not eof and not shutdown["flag"]) or sched.has_work:
        if tick is not None:
            tick()
        if shutdown["flag"] and not drained:
            drained = True
            sched.drain_queue()
        # take every line already waiting; bounded idle wait (not a full
        # block) so a drain signal interrupts within one tick. Reads the
        # raw fd into an explicit line buffer: select()+readline() loses
        # lines — readline pulls everything waiting on the pipe into the
        # TextIOWrapper buffer, returns ONE line, and select never
        # reports the rest (they're no longer on the fd), so a client
        # that writes a batch of requests and keeps the pipe open would
        # see all but the first stall until its next write or EOF.
        while not eof and not shutdown["flag"]:
            nl = buf.find("\n")
            if nl < 0:
                timeout = 0.2 if not sched.has_work else 0.0
                try:
                    ready, _, _ = select.select([sys.stdin], [], [], timeout)
                except OSError:
                    break
                if not ready:
                    break
                data = os.read(sys.stdin.fileno(), 65536)
                if not data:
                    eof = True
                    # a final unterminated line still gets an answer (a
                    # torn write parses as a rejection, not silence)
                    line, buf = buf, ""
                else:
                    buf += data.decode("utf-8", errors="replace")
                    continue
            else:
                line, buf = buf[:nl], buf[nl + 1:]
            if not line.strip():
                continue
            rej, req = _submit_line(sched, line, defaults)
            if rej is not None:
                emit([rej])
            else:
                starts[req.id] = len(req.prime) + (1 if req.add_bos else 0)
        if sched.has_work:
            events, comps = sched.step()
            emit(_events_to_lines(events, comps, starts))
            steps += 1
            if metrics_every and steps % metrics_every == 0:
                publish(steps)
        # requests shed this tick (deadline expiry inside step(), or the
        # drain above) surface as rejection events
        emit([ln for _, ln in _shed_lines(sched, starts)])


def _handle_client_line(sched, line, defaults, fd, owners, starts, send):
    """One client line on a connection-oriented transport: a release
    ctl (the router's rebalance/scale-down path asking this replica to
    surrender a queued request) or a request submission. Request ids
    are namespaced per connection so two clients may both call their
    request "1"."""
    try:
        ctl = json.loads(line)
    except ValueError:
        ctl = None
    if isinstance(ctl, dict) and ctl.get("ctl") == "release":
        public = str(ctl.get("id"))
        internal = f"{fd}:{public}"
        released = sched.release(internal)
        if released:
            owners.pop(internal, None)
            starts.pop(internal, None)
        send(fd, [json.dumps({
            "event": "released", "id": public, "released": released,
        })])
        return
    req, err = _parse_request(line, defaults)
    if req is not None and err is None:
        public = req.id
        req.id = f"{fd}:{public}"
        ok, reason = sched.submit(req)
        if ok:
            owners[req.id] = (fd, public)
            starts[req.id] = len(req.prime) + (1 if req.add_bos else 0)
            return
        err = reason
        public_id = public
    else:
        public_id = req.id if req is not None else None
    send(fd, [json.dumps({
        "event": "rejected", "id": public_id, "reason": err,
    })])


def _serve_socket(sched, defaults, socket_path, publish, metrics_every,
                  shutdown, tick=None):
    """Unix-socket transport: one select loop over {listener, clients,
    engine}; request ids are namespaced per connection internally so two
    clients may both call their request "1". On drain the listener
    closes (new connections refused), the queue is shed, in-flight
    slots finish streaming to their clients, then the loop exits."""
    if os.path.exists(socket_path):
        os.unlink(socket_path)
    srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    srv.bind(socket_path)
    srv.listen(16)
    srv.setblocking(False)
    clients = {}  # fd -> (sock, recv_buffer)
    owners = {}  # internal request id -> fd
    starts = {}
    steps = 0
    print(f"listening on {socket_path}", file=sys.stderr)

    def send(fd, internal_lines):
        sock, _ = clients.get(fd, (None, None))
        if sock is None:
            return
        try:
            for ln in internal_lines:
                sock.sendall(ln.encode() + b"\n")
        except OSError:
            _drop(fd)

    def _drop(fd):
        sock, _ = clients.pop(fd, (None, None))
        if sock is not None:
            sock.close()

    drained = False
    try:
        while True:
            if tick is not None:
                tick()
            if shutdown["flag"]:
                if not drained:
                    drained = True
                    srv.close()  # refuse new connections during drain
                    sched.drain_queue()
                    for fd, ln in _shed_lines(sched, starts, owners):
                        send(fd, [ln])
                if not sched.has_work:
                    break
            rlist = ([] if drained else [srv]) + [
                s for s, _ in clients.values()
            ]
            timeout = 0.0 if sched.has_work else 0.2
            try:
                ready, _, _ = (
                    select.select(rlist, [], [], timeout)
                    if rlist else ([], [], [])
                )
            except OSError:
                continue  # a peer vanished between list and select
            for sock in ready:
                if sock is srv:
                    conn, _ = srv.accept()
                    conn.setblocking(False)
                    clients[conn.fileno()] = (conn, b"")
                    continue
                fd = sock.fileno()
                try:
                    data = sock.recv(65536)
                except OSError:
                    data = b""
                if not data:
                    _drop(fd)
                    continue
                _, buf = clients[fd]
                buf += data
                *lines, buf = buf.split(b"\n")
                clients[fd] = (sock, buf)
                for raw in lines:
                    if not raw.strip():
                        continue
                    _handle_client_line(
                        sched, raw.decode("utf-8", "replace"), defaults,
                        fd, owners, starts, send,
                    )
            if sched.has_work:
                events, comps = sched.step()
                for fd, ln in _shed_lines(sched, starts, owners):
                    send(fd, [ln])
                for ev in events:
                    fd, public = owners.get(ev.request_id, (None, None))
                    if fd is None:
                        continue
                    ev.request_id = public
                    send(fd, _events_to_lines([ev], [], starts))
                for c in comps:
                    fd, public = owners.pop(c.request_id, (None, None))
                    if fd is None:
                        continue
                    start = starts.pop(c.request_id, 0)
                    c.request_id = public
                    send(fd, _events_to_lines([], [c], {public: start}))
                steps += 1
                if metrics_every and steps % metrics_every == 0:
                    publish(steps)
    finally:
        for fd in list(clients):
            _drop(fd)
        srv.close()
        if os.path.exists(socket_path):
            os.unlink(socket_path)


def _serve_tcp(sched, defaults, hostport, publish, metrics_every,
               shutdown, tick=None, idle_timeout=0.0):
    """Framed-TCP transport: the unix-socket loop with frames instead
    of newlines (fleet/transport.py owns validation, drop records and
    condemnation — a framing violation reads as EOF here). Same id
    namespacing, same drain contract; additionally reaps peers silent
    past ``idle_timeout``."""
    from progen_tpu.fleet.transport import FramedListener, parse_hostport

    host, port = parse_hostport(hostport)
    listener = FramedListener(host, port, idle_timeout=idle_timeout)
    clients = {}  # fd -> FramedConnection
    owners = {}  # internal request id -> (fd, public id)
    starts = {}
    steps = 0
    # the bound port line is the startup handshake: with PORT 0 it is
    # the only place the ephemeral port exists
    print(f"listening on tcp {listener.host}:{listener.port}",
          file=sys.stderr)
    sys.stderr.flush()

    def send(fd, internal_lines):
        conn = clients.get(fd)
        if conn is None:
            return
        try:
            for ln in internal_lines:
                conn.send_line(ln)
        except OSError:
            _drop(fd)

    def _drop(fd):
        conn = clients.pop(fd, None)
        if conn is not None:
            conn.close()

    drained = False
    try:
        while True:
            if tick is not None:
                tick()
            for fd, conn in list(clients.items()):
                if conn.idle_expired():
                    _drop(fd)
            if shutdown["flag"]:
                if not drained:
                    drained = True
                    listener.close()  # refuse new dials during drain
                    sched.drain_queue()
                    for fd, ln in _shed_lines(sched, starts, owners):
                        send(fd, [ln])
                if not sched.has_work:
                    break
            rlist = ([] if drained else [listener]) + list(clients.values())
            timeout = 0.0 if sched.has_work else 0.2
            try:
                ready, _, _ = (
                    select.select(rlist, [], [], timeout)
                    if rlist else ([], [], [])
                )
            except OSError:
                continue  # a peer vanished between list and select
            for obj in ready:
                if obj is listener:
                    conn = listener.accept()
                    if conn is not None:
                        clients[conn.fileno()] = conn
                    continue
                if obj.sock is None:
                    continue  # dropped earlier this iteration
                fd = obj.fileno()
                lines, eof = obj.recv_lines()
                for line in lines:
                    if not line.strip():
                        continue
                    _handle_client_line(sched, line, defaults, fd,
                                        owners, starts, send)
                if eof:
                    _drop(fd)
            if sched.has_work:
                events, comps = sched.step()
                for fd, ln in _shed_lines(sched, starts, owners):
                    send(fd, [ln])
                for ev in events:
                    fd, public = owners.get(ev.request_id, (None, None))
                    if fd is None:
                        continue
                    ev.request_id = public
                    send(fd, _events_to_lines([ev], [], starts))
                for c in comps:
                    fd, public = owners.pop(c.request_id, (None, None))
                    if fd is None:
                        continue
                    start = starts.pop(c.request_id, 0)
                    c.request_id = public
                    send(fd, _events_to_lines([], [c], {public: start}))
                steps += 1
                if metrics_every and steps % metrics_every == 0:
                    publish(steps)
    finally:
        for fd in list(clients):
            _drop(fd)
        listener.close()


if __name__ == "__main__":
    main()
