"""Evaluation CLI — mean EOS-masked loss + perplexity over a data split.

The reference has no offline eval entry point (its only validation is the
in-loop cadence, /root/reference/train.py:207-211); this evaluates the
latest checkpoint over a whole ``train``/``valid`` split in one pass with
the exact training loss semantics (per-sequence masked mean,
progen_tpu/training/loss.py) and reports the mean and ``exp(mean)``
perplexity.

Run: python -m progen_tpu.cli.eval --checkpoint_path ./ckpts \
         --data_path ./train_data --split valid
"""

from __future__ import annotations

from progen_tpu.utils.env import load_env_file

load_env_file()  # XLA/env flags before jax import (ref train.py:1-2)

import sys

import click
import numpy as np

import jax


@click.command()
@click.option("--checkpoint_path", default="./ckpts")
@click.option("--data_path", default="./train_data")
@click.option("--split", default="valid",
              type=click.Choice(["train", "valid"]))
@click.option("--batch_size", default=8)
def main(checkpoint_path, data_path, split, batch_size):
    from progen_tpu.checkpoint import get_checkpoint_fns
    from progen_tpu.config import ProGenConfig
    from progen_tpu.data.dataset import iterator_from_tfrecords_folder
    from progen_tpu.models.progen import ProGen
    from progen_tpu.training.loss import sequence_scores

    _, get_last, _ = get_checkpoint_fns(checkpoint_path)
    pkg = get_last.restore_params()  # params only: no optimizer moments
    if pkg is None:
        sys.exit(f"no checkpoints found at {checkpoint_path}")
    config = ProGenConfig.from_dict(pkg.model_config)
    model = ProGen(config)
    params = pkg.state

    num_seqs, iter_fn = iterator_from_tfrecords_folder(data_path, split)
    if num_seqs == 0:
        sys.exit(f"no {split} records under {data_path}")

    @jax.jit
    def per_seq_loss(params, data):
        ids, labels = data[..., :-1], data[..., 1:]
        # the shared scorer (training/loss.py): eval and the batch-score
        # workload reduce the same per-token logprobs, bit-for-bit
        logits = model.apply({"params": params}, ids)
        return sequence_scores(logits, labels)[0]  # (batch,)

    losses = []
    # loop=False walks the split exactly once; the final ragged batch is
    # padded to the static batch shape (one recompile avoided) and the pad
    # rows sliced off the result
    for batch in iter_fn(config.seq_len, batch_size):
        n = batch.shape[0]
        if n < batch_size:
            batch = np.pad(batch, ((0, batch_size - n), (0, 0)))
        losses.append(np.asarray(per_seq_loss(params, batch))[:n])
    per_seq = np.concatenate(losses)
    assert per_seq.shape[0] == num_seqs
    mean = float(per_seq.mean())
    print(f"{split} sequences: {num_seqs:,}")
    print(f"loss: {mean:.4f}")
    print(f"perplexity: {float(np.exp(mean)):.4f}")


if __name__ == "__main__":
    main()
