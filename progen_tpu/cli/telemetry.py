"""Run-analysis CLI over the telemetry event stream.

Pure-host tooling — no jax, no device, no config: it reads the
``events.jsonl`` (and optionally ``metrics.jsonl``) any train/bench/
serve run leaves behind and turns them into the two artifacts a
post-mortem actually wants:

  * ``export-trace`` — Chrome Trace Event / Perfetto JSON. Load the
    output at https://ui.perfetto.dev (or ``chrome://tracing``): span
    slices per host/thread, instant markers for retries/anomalies/
    stalls/chaos, counter tracks for step_ms, MFU, goodput buckets,
    and HBM.
  * ``summarize`` — terminal report: per-host goodput table with the
    cross-host skew/straggler breakdown, per-span-name p50/p95/p99
    latency (reservoir quantiles over every completed span), serving
    request-phase + TTFT/ITL latency quantiles when the stream came
    from a serve run, and resilience event counts.
  * ``stitch`` — N hosts' events.jsonl → ONE fleet trace on a common
    corrected clock (clock_beacon-anchored skew correction, cross-host
    step flow arrows, fleet-wide goodput skew). ``--force-hosts`` gives
    each input file its own process track (serving fleets share one
    host) and unlocks the per-request journey flows: router dispatch →
    replica track, with ``handoff`` arrows into the survivor when a
    replica died midstream.
  * ``slo-report`` — the fleet SLO gate (telemetry/slo.py): objectives
    from TOML, burn rates over metrics.jsonl / Prometheus textfiles,
    exit 0 (ok) / 1 (warn) / 2 (burning) for CI, ``--watch`` for a live
    loop emitting ``ev: "slo"`` transition records.

Every reader reports how many torn/garbage input lines it had to skip —
a trace that silently lost records is an observability bug.

Run: python -m progen_tpu.cli.telemetry export-trace logs/events.jsonl
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

import click

from progen_tpu.telemetry import slo as slo_mod
from progen_tpu.telemetry.goodput import goodput_skew
from progen_tpu.telemetry.registry import _Timing
from progen_tpu.telemetry.stitch import stitch_trace
from progen_tpu.telemetry.trace import (
    INSTANT_EVENTS,
    LineDrops,
    export_trace,
    iter_jsonl,
)


def _echo_drops(n: int) -> None:
    if n:
        click.echo(
            f"WARNING: skipped {n} torn/garbage line"
            f"{'s' if n != 1 else ''} in the input stream(s)"
        )


@click.group()
def main():
    """Analyze telemetry event streams (events.jsonl)."""


@main.command("export-trace")
@click.argument(
    "events", type=click.Path(exists=True, dir_okay=False)
)
@click.option(
    "--metrics",
    type=click.Path(dir_okay=False),
    default=None,
    help="metrics.jsonl for perf counter tracks "
    "(default: sibling of EVENTS when present)",
)
@click.option(
    "--out",
    type=click.Path(dir_okay=False),
    default=None,
    help="output trace path (default: trace.json beside EVENTS)",
)
def export_trace_cmd(events, metrics, out):
    """Convert EVENTS (events.jsonl) to Perfetto trace-event JSON."""
    events = Path(events)
    if metrics is None:
        sibling = events.with_name("metrics.jsonl")
        metrics = str(sibling) if sibling.exists() else None
    if out is None:
        out = str(events.with_name("trace.json"))
    trace = export_trace(events, out, metrics_path=metrics)
    n = len(trace["traceEvents"])
    click.echo(f"wrote {out} ({n} trace events)")
    _echo_drops(trace.get("progenDroppedLines", 0))
    click.echo("open at https://ui.perfetto.dev or chrome://tracing")


@main.command("stitch")
@click.argument(
    "events", nargs=-1, required=True,
    type=click.Path(exists=True, dir_okay=False),
)
@click.option(
    "--metrics", "metrics_paths", multiple=True,
    type=click.Path(exists=True, dir_okay=False),
    help="per-host metrics.jsonl, repeatable; zipped positionally "
         "with the EVENTS arguments",
)
@click.option(
    "--out", type=click.Path(dir_okay=False), default=None,
    help="output trace path (default: stitched_trace.json beside the "
         "first EVENTS file)",
)
@click.option(
    "--reference", default=0, show_default=True,
    help="host whose clock the fleet is corrected onto",
)
@click.option(
    "--force-hosts", is_flag=True, default=False,
    help="assign each EVENTS file its argument position as its process "
         "track (serving fleets all stamp host 0; distinct tracks are "
         "required for per-request journey flows)",
)
def stitch_cmd(events, metrics_paths, out, reference, force_hosts):
    """Merge N hosts' EVENTS files into ONE clock-aligned fleet trace.

    Per-host clock skew is corrected from the clock_beacon records the
    train loop emits at step boundaries (median beacon delta vs the
    reference host); cross-host step_sync flow arrows link each step's
    beacons so a straggler renders as an arrow fan. Request records
    carrying a trace_id are additionally linked into per-request
    journeys (dispatch/handoff flow arrows router → replica) and
    tabulated under the trace's progenTraces key."""
    if out is None:
        out = str(Path(events[0]).with_name("stitched_trace.json"))
    trace = stitch_trace(
        list(events), out_path=out,
        metrics_paths=list(metrics_paths), reference=reference,
        force_hosts=force_hosts,
    )
    info = trace.get("progenStitch", {})
    offsets = trace.get("progenClockOffsets", {})
    click.echo(
        f"wrote {out} ({len(trace['traceEvents'])} trace events from "
        f"{info.get('hosts', len(events))} host streams)"
    )
    if offsets:
        for h in sorted(offsets, key=int):
            click.echo(
                f"  host {h}: clock offset "
                f"{float(offsets[h]) * 1e3:+.3f} ms vs host {reference}"
            )
        click.echo(
            f"  {info.get('beacon_steps', 0)} beacon steps, "
            f"{info.get('flow_arrows', 0)} cross-host step arrows"
        )
    else:
        click.echo(
            "  no clock_beacon records found — streams merged on raw "
            "(uncorrected) host clocks"
        )
    journeys = trace.get("progenTraces", {})
    if journeys:
        handoffs = sum(j.get("handoffs", 0) for j in journeys.values())
        click.echo(
            f"  {len(journeys)} request journeys, "
            f"{info.get('request_flows', 0)} dispatch/handoff arrows"
            + (f" ({handoffs} handoffs)" if handoffs else "")
        )
    _echo_drops(trace.get("progenDroppedLines", 0))
    click.echo("open at https://ui.perfetto.dev or chrome://tracing")


def _host_reports(events_path, metrics_path, drops=None) -> list:
    """Latest per-host goodput reports. Primary source: the
    ``goodput_host`` records every host emits at end of run. Fallback
    for runs predating per-host emission: the last metrics.jsonl row
    carrying ``goodput_pct`` becomes host 0's report."""
    by_host: dict = {}
    for rec in iter_jsonl(events_path, drops):
        if rec.get("ev") == "goodput_host" and "host" in rec:
            by_host[int(rec["host"])] = {
                k: v for k, v in rec.items()
                if k not in ("ev", "ts", "host", "pid")
            }
    if by_host:
        return [by_host[h] for h in sorted(by_host)]
    if metrics_path is not None and Path(metrics_path).exists():
        last = None
        for rec in iter_jsonl(metrics_path, drops):
            if "goodput_pct" in rec:
                last = rec
        if last is not None:
            return [{
                k: v for k, v in last.items()
                if k == "goodput_pct" or k.startswith("bucket_s/")
                or k == "wall_s"
            }]
    return []


@main.command("summarize")
@click.argument(
    "events", type=click.Path(exists=True, dir_okay=False)
)
@click.option(
    "--metrics",
    type=click.Path(dir_okay=False),
    default=None,
    help="metrics.jsonl (default: sibling of EVENTS when present)",
)
@click.option(
    "--spans",
    "top_spans",
    type=int,
    default=20,
    show_default=True,
    help="max span families in the latency table",
)
@click.option(
    "--traces",
    "top_traces",
    type=int,
    default=10,
    show_default=True,
    help="max rows in the per-trace request journey table",
)
@click.option(
    "--slo",
    "slo_path",
    type=click.Path(exists=True, dir_okay=False),
    default=None,
    help="SLO objectives TOML — adds a burn-rate section judged over "
         "the metrics stream (report only, no exit-code gate)",
)
def summarize_cmd(events, metrics, top_spans, top_traces, slo_path):
    """Per-host goodput + skew, span latency quantiles, request
    journeys, SLO burn rates, event counts."""
    events = Path(events)
    if metrics is None:
        sibling = events.with_name("metrics.jsonl")
        metrics = str(sibling) if sibling.exists() else None

    # each input file is drop-counted exactly once (the goodput-report
    # pass below re-reads the same files, so it is left uncounted)
    drops = LineDrops()
    reports = _host_reports(events, metrics)
    if reports:
        click.echo("== goodput (per host) ==")
        buckets = sorted(
            {k for rep in reports for k in rep if k.startswith("bucket_s/")}
        )
        header = f"{'host':>4} {'wall_s':>9} {'goodput%':>9}"
        for b in buckets:
            header += f" {b.split('/', 1)[1]:>11}"
        click.echo(header)
        for i, rep in enumerate(reports):
            line = (
                f"{i:>4} {rep.get('wall_s', 0.0):>9.2f} "
                f"{rep.get('goodput_pct', 0.0):>9.2f}"
            )
            for b in buckets:
                line += f" {float(rep.get(b, 0.0)):>11.3f}"
            click.echo(line)
        if len(reports) > 1:
            click.echo("")
            click.echo("== cross-host skew (straggler table) ==")
            skew = goodput_skew(reports)
            click.echo(
                f"{'bucket':<14} {'min':>10} {'max':>10} {'skew':>10}"
            )
            for name, row in skew.items():
                if not isinstance(row, dict):
                    continue
                click.echo(
                    f"{name:<14} {row['min']:>10.3f} {row['max']:>10.3f} "
                    f"{row['skew']:>10.3f}  straggler host "
                    f"{row['straggler']}"
                )
        click.echo("")

    timings: dict = {}
    counts: dict = {}
    open_req: dict = {}
    routes: list = []
    journeys: dict = {}
    for rec in iter_jsonl(events, drops):
        ev = rec.get("ev")
        if ev == "E" and "dur_s" in rec:
            timings.setdefault(
                str(rec.get("span", "?")), _Timing()
            ).observe(float(rec["dur_s"]))
        elif ev == "req":
            # request lifecycle phases: pair b/e per (request, phase)
            # into req/<phase> timing families in the span table
            ph, rid, name = rec.get("ph"), rec.get("req"), rec.get("name")
            if ph == "b":
                open_req[(rid, name)] = rec.get("ts")
            elif ph == "e":
                t0 = open_req.pop((rid, name), None)
                if t0 is not None and rec.get("ts") is not None:
                    timings.setdefault(
                        f"req/{name}", _Timing()
                    ).observe(float(rec["ts"]) - float(t0))
            # trace_id-carrying records fold into per-request journeys
            tr, ts = rec.get("trace_id"), rec.get("ts")
            if tr is not None and ts is not None:
                j = journeys.setdefault(str(tr), {
                    "t0": float(ts), "t1": float(ts), "hops": 0,
                    "handoffs": 0, "shed": False, "reqs": set(),
                })
                j["t0"] = min(j["t0"], float(ts))
                j["t1"] = max(j["t1"], float(ts))
                if rid is not None:
                    j["reqs"].add(str(rid))
                if ph == "b" and name == "dispatched":
                    j["hops"] += 1
                    if rec.get("resumed"):
                        j["handoffs"] += 1
                elif ph == "n" and name == "shed":
                    j["shed"] = True
        elif ev not in ("B", "E", None):
            counts[str(ev)] = counts.get(str(ev), 0) + 1
            if ev == "route":
                routes.append(rec)

    if timings:
        click.echo("== span latency (s) ==")
        click.echo(
            f"{'span':<28} {'count':>6} {'p50':>9} {'p95':>9} "
            f"{'p99':>9} {'total':>9}"
        )
        families = sorted(
            timings.items(), key=lambda kv: kv[1].sum, reverse=True
        )
        for name, t in families[:top_spans]:
            click.echo(
                f"{name:<28} {t.count:>6} {t.quantile(0.5):>9.4f} "
                f"{t.quantile(0.95):>9.4f} {t.quantile(0.99):>9.4f} "
                f"{t.sum:>9.3f}"
            )
        if len(families) > top_spans:
            click.echo(f"... {len(families) - top_spans} more (--spans)")
        click.echo("")

    if routes:
        # the router's routing-decision records (serving/router.py):
        # one per dispatch/handoff/shed/replica-death, replica-attributed
        per: dict = {}

        def _row(i):
            return per.setdefault(int(i), {
                "routed": 0, "retried": 0, "handoff_in": 0,
                "handoff_out": 0, "shed": 0, "down": 0,
            })

        shed_router = 0
        for r in routes:
            st = r.get("status")
            if st == "dispatched" and r.get("replica") is not None:
                _row(r["replica"])["routed"] += 1
                if r.get("retry"):
                    _row(r["replica"])["retried"] += 1
            elif st == "handoff":
                if r.get("from") is not None:
                    _row(r["from"])["handoff_out"] += 1
                if r.get("to") is not None:
                    _row(r["to"])["handoff_in"] += 1
            elif st == "shed":
                if r.get("replica") is not None:
                    _row(r["replica"])["shed"] += 1
                else:
                    shed_router += 1  # shed before any replica owned it
            elif st == "replica_down" and r.get("replica") is not None:
                _row(r["replica"])["down"] += 1
        click.echo("== router (per replica) ==")
        click.echo(
            f"{'replica':>7} {'routed':>7} {'retried':>8} "
            f"{'handoff_in':>11} {'handoff_out':>12} {'shed':>5} "
            f"{'down':>5}"
        )
        for i in sorted(per):
            p = per[i]
            click.echo(
                f"{i:>7} {p['routed']:>7} {p['retried']:>8} "
                f"{p['handoff_in']:>11} {p['handoff_out']:>12} "
                f"{p['shed']:>5} {p['down']:>5}"
            )
        if shed_router:
            click.echo(f"shed at the router (no replica): {shed_router}")
        click.echo("")

    if journeys:
        # per-request journeys: every req record carrying the router's
        # trace_id, longest (slowest end-to-end) first
        click.echo("== request journeys (by trace_id) ==")
        click.echo(
            f"{'trace':<18} {'span_s':>8} {'hops':>5} {'handoffs':>9} "
            f"{'shed':>5}"
        )
        rows = sorted(
            journeys.items(), key=lambda kv: kv[1]["t1"] - kv[1]["t0"],
            reverse=True,
        )
        for tr, j in rows[:top_traces]:
            click.echo(
                f"{tr:<18} {j['t1'] - j['t0']:>8.3f} {j['hops']:>5} "
                f"{j['handoffs']:>9} {'yes' if j['shed'] else '-':>5}"
            )
        if len(rows) > top_traces:
            click.echo(f"... {len(rows) - top_traces} more (--traces)")
        click.echo("")

    serve_row = None
    router_row = None
    if metrics is not None and Path(metrics).exists():
        for rec in iter_jsonl(metrics, drops):
            if any(k.startswith("serve/") for k in rec):
                serve_row = rec  # last snapshot wins (cumulative)
            if any(k.startswith("router/") for k in rec):
                router_row = rec
    if router_row is not None:
        click.echo("== fleet request latency (s) ==")
        click.echo(
            f"{'metric':<12} {'count':>6} {'p50':>9} {'p95':>9} {'p99':>9}"
        )
        for fam in ("ttft_s", "latency_s"):
            if f"router/{fam}_count" not in router_row:
                continue
            click.echo(
                f"{fam:<12} "
                f"{int(router_row[f'router/{fam}_count']):>6} "
                f"{router_row.get(f'router/{fam}_p50_s', 0.0):>9.4f} "
                f"{router_row.get(f'router/{fam}_p95_s', 0.0):>9.4f} "
                f"{router_row.get(f'router/{fam}_p99_s', 0.0):>9.4f}"
            )
        click.echo("")
    if serve_row is not None:
        click.echo("== serving latency (s) ==")
        click.echo(
            f"{'metric':<12} {'count':>6} {'p50':>9} {'p95':>9} {'p99':>9}"
        )
        for fam in ("ttft_s", "itl_s", "latency_s"):
            if f"serve/{fam}_count" not in serve_row:
                continue
            click.echo(
                f"{fam:<12} "
                f"{int(serve_row[f'serve/{fam}_count']):>6} "
                f"{serve_row.get(f'serve/{fam}_p50_s', 0.0):>9.4f} "
                f"{serve_row.get(f'serve/{fam}_p95_s', 0.0):>9.4f} "
                f"{serve_row.get(f'serve/{fam}_p99_s', 0.0):>9.4f}"
            )
        click.echo("")

    if slo_path is not None:
        cfg = slo_mod.load_objectives(slo_path)
        series = []
        if metrics is not None and Path(metrics).exists():
            series.append(slo_mod.samples_from_metrics(
                iter_jsonl(metrics, drops)
            ))
        click.echo("== SLOs ==")
        click.echo(
            slo_mod.render_report(cfg, slo_mod.evaluate(cfg, series))
        )
        click.echo("")

    if counts:
        click.echo("== events ==")
        order = [e for e in INSTANT_EVENTS if e in counts]
        order += sorted(set(counts) - set(order))
        for ev in order:
            click.echo(f"{ev:<24} {counts[ev]:>6}")
    _echo_drops(drops.count)


@main.command("query")
@click.option(
    "--trace", "trace_id", required=True,
    help="the trace_id to reconstruct (router intake mints these)",
)
@click.option(
    "--events", "events_paths", multiple=True,
    type=click.Path(exists=True, dir_okay=False),
    help="events.jsonl OR flight-*.json dump, repeatable (a killed "
         "host's black box joins like a survivor's stream)",
)
@click.option(
    "--journal", "journal_paths", multiple=True,
    type=click.Path(exists=True, dir_okay=False),
    help="serving journal.jsonl, repeatable (accept/token/done "
         "records; the token stream is summarized first/last)",
)
@click.option(
    "--tsdb", "tsdb_dir", type=click.Path(file_okay=False), default=None,
    help="collector TSDB: samples whose exemplars name the trace",
)
@click.option(
    "--notifications", "notify_paths", multiple=True,
    type=click.Path(exists=True, dir_okay=False),
    help="alerts.jsonl / notifications.jsonl, repeatable: any record "
         "mentioning the trace joins the timeline",
)
@click.option(
    "--logs", "log_dirs", multiple=True,
    type=click.Path(exists=True, file_okay=False),
    help="directory to auto-discover evidence under (recursive): "
         "events.jsonl, journal.jsonl, flight-*.json, alerts.jsonl, "
         "notifications.jsonl",
)
@click.option(
    "--json", "json_out", type=click.Path(dir_okay=False), default=None,
    help="also write the timeline as JSON",
)
def query_cmd(trace_id, events_paths, journal_paths, tsdb_dir,
              notify_paths, log_dirs, json_out):
    """Reconstruct one request's journey across every evidence stream.

    Joins events.jsonl streams, flight-recorder dumps, serving
    journals, collector TSDB exemplars and alert/notification ledgers
    on a single trace_id and prints the merged chronological timeline —
    a request that died with its replica still reads contiguously:
    router intake -> dispatch -> the dead replica's journaled tokens
    (from its flight dump) -> handoff -> the survivor's completion.
    Exits 1 when the trace appears nowhere."""
    from progen_tpu.telemetry import flight

    events = [Path(p) for p in events_paths]
    journals = [Path(p) for p in journal_paths]
    notifies = [Path(p) for p in notify_paths]
    for d in log_dirs:
        root = Path(d)
        events += sorted(root.rglob("events.jsonl"))
        events += flight.find_dumps(root)
        journals += sorted(root.rglob("journal*.jsonl"))
        for name in ("alerts.jsonl", "notifications.jsonl"):
            notifies += sorted(root.rglob(name))
    # a file named both explicitly and via --logs must join only once
    events = list(dict.fromkeys(p.resolve() for p in events))
    journals = list(dict.fromkeys(p.resolve() for p in journals))
    notifies = list(dict.fromkeys(p.resolve() for p in notifies))
    drops = LineDrops()
    timeline = flight.trace_timeline(
        trace_id,
        events=events,
        journals=journals,
        tsdb_dir=tsdb_dir,
        extra_jsonl=notifies,
        drops=drops,
    )
    if json_out is not None:
        Path(json_out).parent.mkdir(parents=True, exist_ok=True)
        Path(json_out).write_text(json.dumps(
            {"trace_id": str(trace_id), "timeline": timeline},
            indent=2, default=str,
        ))
    if not timeline:
        click.echo(f"trace {trace_id}: no records found")
        _echo_drops(drops.count)
        sys.exit(1)
    t0 = timeline[0]["ts"]
    click.echo(
        f"trace {trace_id}: {len(timeline)} records across "
        f"{len({e['src'] for e in timeline})} streams, "
        f"{timeline[-1]['ts'] - t0:.3f}s end to end"
    )
    for e in timeline:
        stamp = time.strftime("%H:%M:%S", time.localtime(e["ts"]))
        click.echo(
            f"  {stamp} +{e['ts'] - t0:>8.3f}s "
            f"{e['src']:<24} {e['what']}"
        )
    _echo_drops(drops.count)


_DEFAULT_OBJECTIVES = (
    Path(__file__).resolve().parents[2] / "configs" / "serving"
    / "slo.toml"
)


@main.command("slo-report")
@click.option(
    "--objectives", type=click.Path(exists=True, dir_okay=False),
    default=None,
    help="SLO TOML (default: the repo's configs/serving/slo.toml)",
)
@click.option(
    "--metrics", "metrics_paths", multiple=True,
    type=click.Path(exists=True, dir_okay=False),
    help="metrics.jsonl time series, repeatable (router + replicas)",
)
@click.option(
    "--prom", "prom_paths", multiple=True,
    type=click.Path(dir_okay=False),
    help="Prometheus exposition textfile, repeatable; mtime age past "
         "burn.stale_after_s marks the source stale",
)
@click.option(
    "--tsdb", "tsdb_dir", type=click.Path(file_okay=False), default=None,
    help="collector TSDB directory: evaluate the fleet-AGGREGATED "
         "series (reset-safe summed counters, merged quantiles) "
         "instead of per-file evidence",
)
@click.option(
    "--events-out", type=click.Path(dir_okay=False), default=None,
    help="append ev:slo state-transition records to this events.jsonl",
)
@click.option(
    "--json", "json_out", type=click.Path(dir_okay=False), default=None,
    help="also write the full results as JSON (CI artifact)",
)
@click.option(
    "--watch", "watch_s", type=float, default=None,
    help="live mode: re-evaluate every N seconds on the wall clock "
         "(default: judge the archived artifacts once and exit)",
)
@click.option(
    "--max-ticks", type=int, default=0, show_default=True,
    help="stop --watch after N evaluations (0 = run until killed)",
)
def slo_report_cmd(
    objectives, metrics_paths, prom_paths, tsdb_dir, events_out,
    json_out, watch_s, max_ticks,
):
    """Judge the fleet's SLOs and exit 0 (ok) / 1 (warn) / 2 (burning).

    Report mode (no --watch) is deterministic over archived artifacts:
    "now" is the newest metrics sample, so re-running the gate on the
    same files always yields the same verdict. --watch re-reads the
    sources every tick on the wall clock and emits ev:"slo" transition
    records (to --events-out, or the process telemetry sink)."""
    cfg = slo_mod.load_objectives(
        objectives if objectives is not None else _DEFAULT_OBJECTIVES
    )
    drops = LineDrops()

    def _gather():
        series = [
            slo_mod.samples_from_metrics(iter_jsonl(mp, drops))
            for mp in metrics_paths
        ]
        if tsdb_dir is not None:
            from progen_tpu.telemetry.collector import fleet_series
            from progen_tpu.telemetry.tsdb import TsdbReader

            fleet = fleet_series(TsdbReader(tsdb_dir).read(drops))
            series.append(fleet)
            if fleet:
                click.echo(
                    f"fleet series: {len(fleet)} ticks from {tsdb_dir}",
                    err=True,
                )
            else:
                click.echo(
                    f"WARNING: no samples in tsdb {tsdb_dir}", err=True
                )
        proms = []
        for pp in prom_paths:
            got = slo_mod.read_prom_file(pp)
            if got is None:
                click.echo(f"WARNING: prom file missing: {pp}", err=True)
            else:
                proms.append(got)
        return series, proms

    sink = None
    watch = None
    if events_out is not None:
        from progen_tpu.telemetry.spans import EventLog

        sink = EventLog(events_out)
        watch = slo_mod.SloWatch(cfg, emit=sink.emit)

    if watch_s is None:
        series, proms = _gather()
        results = slo_mod.evaluate(cfg, series, proms)
        if watch is not None:
            watch.observe(results)
    else:
        ticks = 0
        results = []
        if watch is None:
            watch = slo_mod.SloWatch(cfg)  # process telemetry sink
        while True:
            series, proms = _gather()
            results = slo_mod.evaluate(
                cfg, series, proms, now=time.time()
            )
            for rec in watch.observe(results):
                click.echo(
                    f"slo transition: {rec['objective']} "
                    f"{rec['prev']} -> {rec['state']}"
                )
            ticks += 1
            if max_ticks and ticks >= max_ticks:
                break
            time.sleep(max(0.0, watch_s))

    click.echo(slo_mod.render_report(cfg, results))
    _echo_drops(drops.count)
    if json_out is not None:
        payload = slo_mod.results_payload(results)
        Path(json_out).parent.mkdir(parents=True, exist_ok=True)
        Path(json_out).write_text(json.dumps(payload, indent=2))
    if sink is not None:
        sink.close()
    sys.exit(slo_mod.exit_code(results))


if __name__ == "__main__":
    main()
