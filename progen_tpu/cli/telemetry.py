"""Run-analysis CLI over the telemetry event stream.

Pure-host tooling — no jax, no device, no config: it reads the
``events.jsonl`` (and optionally ``metrics.jsonl``) any train/bench/
serve run leaves behind and turns them into the two artifacts a
post-mortem actually wants:

  * ``export-trace`` — Chrome Trace Event / Perfetto JSON. Load the
    output at https://ui.perfetto.dev (or ``chrome://tracing``): span
    slices per host/thread, instant markers for retries/anomalies/
    stalls/chaos, counter tracks for step_ms, MFU, goodput buckets,
    and HBM.
  * ``summarize`` — terminal report: per-host goodput table with the
    cross-host skew/straggler breakdown, per-span-name p50/p95/p99
    latency (reservoir quantiles over every completed span), and
    resilience event counts.

Run: python -m progen_tpu.cli.telemetry export-trace logs/events.jsonl
"""

from __future__ import annotations

from pathlib import Path

import click

from progen_tpu.telemetry.goodput import goodput_skew
from progen_tpu.telemetry.registry import _Timing
from progen_tpu.telemetry.trace import (
    INSTANT_EVENTS,
    export_trace,
    iter_jsonl,
)


@click.group()
def main():
    """Analyze telemetry event streams (events.jsonl)."""


@main.command("export-trace")
@click.argument(
    "events", type=click.Path(exists=True, dir_okay=False)
)
@click.option(
    "--metrics",
    type=click.Path(dir_okay=False),
    default=None,
    help="metrics.jsonl for perf counter tracks "
    "(default: sibling of EVENTS when present)",
)
@click.option(
    "--out",
    type=click.Path(dir_okay=False),
    default=None,
    help="output trace path (default: trace.json beside EVENTS)",
)
def export_trace_cmd(events, metrics, out):
    """Convert EVENTS (events.jsonl) to Perfetto trace-event JSON."""
    events = Path(events)
    if metrics is None:
        sibling = events.with_name("metrics.jsonl")
        metrics = str(sibling) if sibling.exists() else None
    if out is None:
        out = str(events.with_name("trace.json"))
    trace = export_trace(events, out, metrics_path=metrics)
    n = len(trace["traceEvents"])
    click.echo(f"wrote {out} ({n} trace events)")
    click.echo("open at https://ui.perfetto.dev or chrome://tracing")


def _host_reports(events_path, metrics_path) -> list:
    """Latest per-host goodput reports. Primary source: the
    ``goodput_host`` records every host emits at end of run. Fallback
    for runs predating per-host emission: the last metrics.jsonl row
    carrying ``goodput_pct`` becomes host 0's report."""
    by_host: dict = {}
    for rec in iter_jsonl(events_path):
        if rec.get("ev") == "goodput_host" and "host" in rec:
            by_host[int(rec["host"])] = {
                k: v for k, v in rec.items()
                if k not in ("ev", "ts", "host", "pid")
            }
    if by_host:
        return [by_host[h] for h in sorted(by_host)]
    if metrics_path is not None and Path(metrics_path).exists():
        last = None
        for rec in iter_jsonl(metrics_path):
            if "goodput_pct" in rec:
                last = rec
        if last is not None:
            return [{
                k: v for k, v in last.items()
                if k == "goodput_pct" or k.startswith("bucket_s/")
                or k == "wall_s"
            }]
    return []


@main.command("summarize")
@click.argument(
    "events", type=click.Path(exists=True, dir_okay=False)
)
@click.option(
    "--metrics",
    type=click.Path(dir_okay=False),
    default=None,
    help="metrics.jsonl (default: sibling of EVENTS when present)",
)
@click.option(
    "--spans",
    "top_spans",
    type=int,
    default=20,
    show_default=True,
    help="max span families in the latency table",
)
def summarize_cmd(events, metrics, top_spans):
    """Per-host goodput + skew, span latency quantiles, event counts."""
    events = Path(events)
    if metrics is None:
        sibling = events.with_name("metrics.jsonl")
        metrics = str(sibling) if sibling.exists() else None

    reports = _host_reports(events, metrics)
    if reports:
        click.echo("== goodput (per host) ==")
        buckets = sorted(
            {k for rep in reports for k in rep if k.startswith("bucket_s/")}
        )
        header = f"{'host':>4} {'wall_s':>9} {'goodput%':>9}"
        for b in buckets:
            header += f" {b.split('/', 1)[1]:>11}"
        click.echo(header)
        for i, rep in enumerate(reports):
            line = (
                f"{i:>4} {rep.get('wall_s', 0.0):>9.2f} "
                f"{rep.get('goodput_pct', 0.0):>9.2f}"
            )
            for b in buckets:
                line += f" {float(rep.get(b, 0.0)):>11.3f}"
            click.echo(line)
        if len(reports) > 1:
            click.echo("")
            click.echo("== cross-host skew (straggler table) ==")
            skew = goodput_skew(reports)
            click.echo(
                f"{'bucket':<14} {'min':>10} {'max':>10} {'skew':>10}"
            )
            for name, row in skew.items():
                if not isinstance(row, dict):
                    continue
                click.echo(
                    f"{name:<14} {row['min']:>10.3f} {row['max']:>10.3f} "
                    f"{row['skew']:>10.3f}  straggler host "
                    f"{row['straggler']}"
                )
        click.echo("")

    timings: dict = {}
    counts: dict = {}
    for rec in iter_jsonl(events):
        ev = rec.get("ev")
        if ev == "E" and "dur_s" in rec:
            timings.setdefault(
                str(rec.get("span", "?")), _Timing()
            ).observe(float(rec["dur_s"]))
        elif ev not in ("B", "E", None):
            counts[str(ev)] = counts.get(str(ev), 0) + 1

    if timings:
        click.echo("== span latency (s) ==")
        click.echo(
            f"{'span':<28} {'count':>6} {'p50':>9} {'p95':>9} "
            f"{'p99':>9} {'total':>9}"
        )
        families = sorted(
            timings.items(), key=lambda kv: kv[1].sum, reverse=True
        )
        for name, t in families[:top_spans]:
            click.echo(
                f"{name:<28} {t.count:>6} {t.quantile(0.5):>9.4f} "
                f"{t.quantile(0.95):>9.4f} {t.quantile(0.99):>9.4f} "
                f"{t.sum:>9.3f}"
            )
        if len(families) > top_spans:
            click.echo(f"... {len(families) - top_spans} more (--spans)")
        click.echo("")

    if counts:
        click.echo("== events ==")
        order = [e for e in INSTANT_EVENTS if e in counts]
        order += sorted(set(counts) - set(order))
        for ev in order:
            click.echo(f"{ev:<24} {counts[ev]:>6}")


if __name__ == "__main__":
    main()
