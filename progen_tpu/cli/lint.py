"""progen-tpu-lint CLI: the commit-time gate over the PGL rules.

Pure-host tooling — no jax import, so it runs in any CI step (and in a
pre-commit hook) in milliseconds. Exit code contract:

  0  no findings beyond the baseline
  1  at least one NEW finding (printed, and written to --json if given)
  2  usage/baseline errors (malformed baseline entries fail loudly —
     a silent baseline is how gates rot)

Run: progen-tpu-lint progen_tpu/ [--baseline lint_baseline.json]
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

import click

from progen_tpu.analysis import (
    RULE_DOCS,
    BaselineError,
    lint_paths,
    load_baseline,
    report_json,
)


@click.command()
@click.argument("paths", nargs=-1, type=click.Path(exists=True))
@click.option(
    "--baseline",
    "baseline_path",
    type=click.Path(dir_okay=False),
    default=None,
    help="baseline JSON of grandfathered findings (default: "
    "lint_baseline.json next to the first PATH or in the cwd, when "
    "present)",
)
@click.option(
    "--no-baseline",
    is_flag=True,
    default=False,
    help="ignore any baseline file: report every finding as new",
)
@click.option(
    "--json",
    "json_out",
    type=click.Path(dir_okay=False),
    default=None,
    help="write the machine-readable findings report here (CI uploads "
    "this as an artifact on failure)",
)
@click.option(
    "--list-rules", is_flag=True, default=False,
    help="print the rule table and exit",
)
@click.option(
    "--registry-dump", is_flag=True, default=False,
    help="print the generated chaos-site + event-grammar registry "
    "block (paste between the registry markers in README.md) and exit",
)
@click.option(
    "--registry-check",
    "registry_check_path",
    type=click.Path(exists=True, dir_okay=False),
    default=None,
    help="verify the registry block committed in the given markdown "
    "file matches the code; exit 1 on drift",
)
def main(paths, baseline_path, no_baseline, json_out, list_rules,
         registry_dump, registry_check_path):
    """Lint PATHS (files or directories) with the PGL rule set."""
    if list_rules:
        for rule_id in sorted(RULE_DOCS):
            click.echo(f"{rule_id}  {RULE_DOCS[rule_id]}")
        return
    if registry_dump:
        from progen_tpu.analysis.registry import render_registry_markdown

        click.echo(render_registry_markdown())
        return
    if registry_check_path:
        from progen_tpu.analysis.registry import registry_check

        problem = registry_check(registry_check_path)
        if problem is not None:
            click.echo(f"error: {problem}", err=True)
            sys.exit(1)
        click.echo(f"{registry_check_path}: registry block up to date")
        return
    if not paths:
        raise click.UsageError("no paths given (try: progen-tpu-lint .)")

    baseline = []
    if not no_baseline:
        candidates = (
            [Path(baseline_path)]
            if baseline_path
            else [
                Path(paths[0]).resolve().parent / "lint_baseline.json",
                Path.cwd() / "lint_baseline.json",
            ]
        )
        for cand in candidates:
            if cand.is_file():
                try:
                    baseline = load_baseline(cand)
                except (BaselineError, json.JSONDecodeError) as e:
                    click.echo(f"error: bad baseline: {e}", err=True)
                    sys.exit(2)
                break
        else:
            if baseline_path:
                click.echo(
                    f"error: baseline not found: {baseline_path}", err=True
                )
                sys.exit(2)

    new, baselined = lint_paths(paths, baseline=baseline)

    if json_out:
        Path(json_out).parent.mkdir(parents=True, exist_ok=True)
        Path(json_out).write_text(
            json.dumps(report_json(new, baselined), indent=2) + "\n"
        )

    for f in new:
        click.echo(f.render())
    if new:
        by_rule = {}
        for f in new:
            by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
        breakdown = ", ".join(
            f"{k}: {v}" for k, v in sorted(by_rule.items())
        )
        click.echo(
            f"\n{len(new)} finding(s) ({breakdown})"
            + (f"; {len(baselined)} baselined" if baselined else ""),
            err=True,
        )
        sys.exit(1)
    click.echo(
        f"clean ({len(baselined)} baselined finding(s))"
        if baselined
        else "clean"
    )


if __name__ == "__main__":
    main()
