"""Checkpoint migration CLI — reference pickle -> native sharded format.

Run: python -m progen_tpu.cli.convert --src ./old/ckpt_1690000000.pkl \
         --dest ./ckpts

The written checkpoint resumes directly in `cli.train` (config + progress
carried over; Adam moments re-warm — see progen_tpu/convert.py) and
samples directly in `cli.sample`.
"""

from __future__ import annotations

from progen_tpu.utils.env import load_env_file

load_env_file()  # XLA/env flags before jax import (ref train.py:1-2)

import click


@click.command()
@click.option("--src", required=True,
              help="reference ckpt_*.pkl (cloudpickle package)")
@click.option("--dest", default="./ckpts",
              help="native checkpoint directory to write into")
def main(src, dest):
    from progen_tpu.convert import convert_checkpoint

    written = convert_checkpoint(src, dest)
    print(f"converted {src} -> {written}")


if __name__ == "__main__":
    main()
