"""Training CLI.

Flag-set parity with /root/reference/train.py:36-57 (same names, same
defaults), plus TPU-native mesh knobs (--mesh_data/--mesh_seq/--mesh_model)
the reference's single-host pmap had no equivalent for
(--data_parallel maps to "shard the data axis over every device").

Loop semantics (/root/reference/train.py:179-222): iterate sequence indices
in effective-batch strides; checkpoint / validate / sample on their
cadences; resume from the latest checkpoint (config-in-checkpoint overrides
the TOML, train.py:94-100); --new wipes after interactive confirmation.

Run: python -m progen_tpu.cli.train [flags]
"""

from __future__ import annotations

from progen_tpu.utils.env import load_env_file

load_env_file()  # XLA/env flags before jax import (ref train.py:1-2)

import sys
from pathlib import Path

import click
import numpy as np

import jax


def confirm(question: str) -> bool:
    """Interactive y/n guard for --new (train.py:85-88 semantics)."""
    return input(f"{question} (y/n) ").strip().lower() == "y"


class AnomalyRollback(Exception):
    """Raised by the metrics flush when the loss sentinel escalates
    (``patience`` consecutive anomalies); caught by the train loop, which
    restores the last good checkpoint and skips ahead in the data stream
    past the offending window."""


@click.command()
@click.option("--seed", default=42)
@click.option("--batch_size", default=4)
@click.option("--grad_accum_every", default=4)
@click.option("--learning_rate", default=2e-4)
@click.option("--weight_decay", default=1e-3)
@click.option("--data_parallel", default=False, is_flag=True)
@click.option("--max_grad_norm", default=0.5)
@click.option("--validate_every", default=100)
@click.option("--sample_every", default=500)
@click.option("--checkpoint_every", default=1000)
@click.option("--checkpoint_path", default="./ckpts")
@click.option("--checkpoint_keep_n", default=500)
@click.option("--config_path", default="./configs/model")
@click.option("--model_name", default="default")
@click.option("--prime_length", default=25)
@click.option("--seq_len", default=1024)
@click.option("--mixed_precision", default=False, is_flag=True)
@click.option("--data_path", default="./train_data")
@click.option("--wandb_off", default=False, is_flag=True)
@click.option("--wandb_project_name", default="progen-training")
@click.option("--new", default=False, is_flag=True)
@click.option("--mesh_data", default=0, help="data-parallel mesh axis size (0 = auto)")
@click.option("--mesh_seq", default=1, help="sequence-parallel mesh axis size")
@click.option("--mesh_model", default=1, help="tensor-parallel mesh axis size")
@click.option("--num_steps", default=0, help="stop after N optimizer steps (0 = full data)")
@click.option("--epochs", default=1,
              help="passes over the training data (reference semantics: 1)")
@click.option("--lr_schedule", default="constant",
              type=click.Choice(["constant", "cosine"]),
              help="constant (reference parity) or warmup+cosine decay "
                   "over the whole run")
@click.option("--warmup_steps", default=0,
              help="linear warmup steps for --lr_schedule cosine")
@click.option("--shuffle_seed", default=None, type=int,
              help="deterministic per-epoch training-data reshuffle "
                   "(resume-exact; unset = ETL order, reference parity)")
@click.option("--profile_dir", default="", help="jax.profiler trace dir for steps 2-4")
@click.option("--hardware_rng", default=False, is_flag=True,
              help="TPU-fast partitionable rbg PRNG (ref: set_hardware_rng_)")
@click.option("--naive_sample", default=False, is_flag=True,
              help="cadenced samples via the full-forward-per-token decoder "
                   "(reference parity path) instead of the KV-cache decode")
@click.option("--ring_attn", default=False, is_flag=True,
              help="explicit ring halo-exchange attention over the seq mesh "
                   "axis (requires --mesh_seq > 1) instead of GSPMD-inferred "
                   "collectives")
@click.option("--async_checkpoint", default=False, is_flag=True,
              help="overlap checkpoint writes with training (device arrays "
                   "are snapshotted to host synchronously; the storage "
                   "commit runs in the background and finalizes at the next "
                   "save)")
@click.option("--zero1", default=False, is_flag=True,
              help="ZeRO-1: shard the AdamW moments over the data mesh axis "
                   "(1/data-size the optimizer memory; forward/backward "
                   "layout unchanged)")
@click.option("--mesh_pipe", default=0,
              help="GPipe pipeline stages over the model mesh axis (the "
                   "depth-sharded path when the layer stack outgrows one "
                   "chip even after TP; repurposes the model axis, so "
                   "mutually exclusive with --mesh_model > 1). Requires "
                   "scan_layers=true in the model TOML. Composes with "
                   "--mesh_data: microbatch rows shard over the data axis "
                   "inside the pipeline. NOTE: backward is the GPipe "
                   "autodiff transpose — O(microbatches) activation "
                   "memory; pair with remat=true")
@click.option("--pipe_microbatches", default=0,
              help="GPipe microbatches per micro-step (0 = same as "
                   "--mesh_pipe); bubble fraction = (P-1)/(M+P-1), so "
                   "larger M amortizes the bubble at the cost of "
                   "activation memory")
@click.option("--pipe_schedule", default="gpipe",
              type=click.Choice(["gpipe", "1f1b"]),
              help="pipeline schedule: gpipe (autodiff transpose, "
                   "O(microbatches) boundary activations) or 1f1b "
                   "(interleaved fwd/bwd, O(stages) in-flight activations "
                   "— the large-microbatch-count deployment)")
@click.option("--stall_timeout", default=900.0,
              help="stall-watchdog deadline (seconds): when no optimizer "
                   "step completes within it, dump all-thread stacks and "
                   "the open/recent telemetry spans to stderr, then keep "
                   "running (0 = off)")
@click.option("--stall_escalate_after", default=3,
              help="after N consecutive stall reports for ONE stall, "
                   "snapshot per-device memory_stats + the open-span list "
                   "into the event stream before the surrounding timeout "
                   "kills the run (0 = legacy single report per stall)")
@click.option("--anomaly_factor", default=6.0,
              help="loss-spike threshold: anomalous when loss exceeds the "
                   "EMA baseline by this many deviations (0 = non-finite "
                   "detection only)")
@click.option("--anomaly_patience", default=3,
              help="consecutive anomalous steps before rolling back to the "
                   "last good checkpoint and skipping ahead in the data "
                   "stream; isolated spikes are skipped (the train step's "
                   "finite gate already refused any non-finite update)")
@click.option("--prom_file", default=None, type=str,
              help="write train-loop Prometheus text exposition here "
                   "(goodput %, step_ms quantiles, tokens/s/chip, MFU, HBM "
                   "gauges, resilience counters; atomic rewrite on the "
                   "--validate_every cadence and at exit; node-exporter "
                   "textfile-collector compatible)")
@click.option("--prom_port", default=0,
              help="serve the same train-loop exposition over HTTP on "
                   "this localhost port (0 = off)")
@click.option("--flight_dir", default=None, type=str,
              help="arm the flight recorder: bounded in-memory ring of "
                   "recent telemetry, dumped atomically here on stall "
                   "escalation, anomaly rollback, chaos kill, or an "
                   "unhandled exception")
@click.option("--profile_pin", "profile_pin_path", default=None, type=str,
              help="profile.pin control file: a token written here "
                   "starts a bounded jax.profiler window on the LIVE "
                   "loop (acked through FILE.ack) — unlike "
                   "--profile_dir's fixed steps 2-4, this profiles the "
                   "moment something looks wrong")
def main(
    seed,
    batch_size,
    grad_accum_every,
    learning_rate,
    weight_decay,
    data_parallel,
    max_grad_norm,
    validate_every,
    sample_every,
    checkpoint_every,
    checkpoint_path,
    checkpoint_keep_n,
    config_path,
    model_name,
    prime_length,
    seq_len,
    mixed_precision,
    data_path,
    wandb_off,
    wandb_project_name,
    new,
    mesh_data,
    mesh_seq,
    mesh_model,
    num_steps,
    epochs,
    lr_schedule,
    warmup_steps,
    shuffle_seed,
    profile_dir,
    hardware_rng,
    naive_sample,
    ring_attn,
    async_checkpoint,
    zero1,
    mesh_pipe,
    pipe_microbatches,
    pipe_schedule,
    stall_timeout,
    stall_escalate_after,
    anomaly_factor,
    anomaly_patience,
    prom_file,
    prom_port,
    flight_dir,
    profile_pin_path,
):
    from progen_tpu.checkpoint import Package, get_checkpoint_fns
    from progen_tpu.config import ProGenConfig, load_toml_config
    from progen_tpu.data.dataset import iterator_from_tfrecords_folder
    from progen_tpu.data.tokenizer import decode_tokens
    from progen_tpu.models.progen import ProGen
    from progen_tpu.parallel.partition import (
        initialize_distributed,
        is_coordinator,
        make_mesh,
        put_batch,
    )
    # KV-cache decode by default: O(2w*d) attention per emitted token, so a
    # cadenced sample costs seconds, not (at long context) thousands of full
    # forwards blocking the train loop. Bit-identical to the naive path
    # (tests/test_sampling.py); --naive_sample keeps the parity decoder.
    from progen_tpu.sampling import sample, sample_fast

    sample_tokens = sample if naive_sample else sample_fast
    from progen_tpu.tracking import make_tracker, render_sample_html
    from progen_tpu.training import emit_clock_beacon
    from progen_tpu.training.optimizer import make_optimizer
    from progen_tpu.training.step import (
        abstract_train_state,
        compile_train_step,
        init_train_state,
        compile_eval_step,
        train_state_shardings,
    )

    from progen_tpu.resilience import chaos
    from progen_tpu.resilience.anomaly import (
        ROLLBACK,
        SPIKE,
        LossSentinel,
        PoisonBisector,
        consistent_flag,
    )

    if hardware_rng:
        from progen_tpu.utils.rng import use_hardware_rng

        use_hardware_rng()
    initialize_distributed()
    # fault injection (PROGEN_CHAOS="ckpt/save:0.3,data/read:kill"): no-op
    # unless the env asks for it; uninstalled in the finally below so an
    # in-process caller (tests) never leaks rules into the next run
    chaos.install_from_env()

    # shared metrics registry: resilience wiring (retry/chaos/watchdog/
    # checkpoint/anomaly) increments counters here as a side effect of the
    # run; reset keeps in-process reruns (tests) from bleeding counts, and
    # pre-seeding declares every resilience family at 0 so the Prometheus
    # exposition always carries them (an absent counter and a zero counter
    # are different dashboards)
    from progen_tpu.telemetry import get_registry

    reg = get_registry()
    reg.reset()
    for _c in (
        "retries", "anomalies", "anomaly_rollbacks", "chaos_injections",
        "stalls", "stall_escalations", "ckpt_quarantines",
        "ckpt_commit_failures",
    ):
        reg.inc(_c, 0)

    reset_ckpt, get_last, save_ckpt = get_checkpoint_fns(
        checkpoint_path, keep_last_n=checkpoint_keep_n,
        async_save=async_checkpoint,
    )
    if new:
        if not confirm(
            "are you sure you want to clear all your checkpoints and "
            "restart training?"
        ):
            sys.exit(0)
        reset_ckpt()

    # --- model config: checkpoint overrides TOML on resume (train.py:94-100)
    last_meta = get_last.peek()  # metadata only; arrays restored sharded below
    if last_meta is None:
        toml_path = Path(config_path) / f"{model_name}.toml"
        assert toml_path.exists(), f"model config not found: {toml_path}"
        model_kwargs = load_toml_config(str(toml_path))
    else:
        model_kwargs = last_meta.model_config
    model_kwargs.setdefault("seq_len", seq_len)
    # reference semantics (train.py:53,106): full f32 unless --mixed_precision
    # opts into the fast dtype; an explicit TOML dtype wins when flag absent
    config = ProGenConfig.from_dict(
        {**model_kwargs, "dtype": "bfloat16" if mixed_precision
         else model_kwargs.get("dtype", "float32")}
    )

    # --- optimizer structure follows the checkpoint on resume: a schedule
    # mismatch would change the optax state pytree and break the sharded
    # restore, so train_config overrides the flags like model_config does
    saved_tc = getattr(last_meta, "train_config", None) if last_meta else None
    total_steps = 0
    if saved_tc:
        lr_schedule = saved_tc.get("lr_schedule", lr_schedule)
        warmup_steps = saved_tc.get("warmup_steps", warmup_steps)
        total_steps = saved_tc.get("total_steps", 0)
        # data order must also survive a flagless resume: the resume skip
        # indexes the SHUFFLED stream, so the seed rides the checkpoint
        shuffle_seed = saved_tc.get("shuffle_seed", shuffle_seed)
    if lr_schedule == "cosine" and not total_steps:
        # the cosine horizon needs the run length; the counts come from the
        # filename contract, so this early peek costs one glob
        n_total, _ = iterator_from_tfrecords_folder(data_path)
        total_steps = max(
            (n_total * max(epochs, 1)) // (batch_size * grad_accum_every), 1
        )
        if num_steps:
            # a capped run decays over the steps that will actually happen
            total_steps = min(total_steps, num_steps)
    optimizer = make_optimizer(
        learning_rate, weight_decay, max_grad_norm,
        schedule=lr_schedule, warmup_steps=warmup_steps,
        total_steps=total_steps,
    )
    train_config = {
        "lr_schedule": lr_schedule,
        "warmup_steps": warmup_steps,
        "total_steps": total_steps,
        "shuffle_seed": shuffle_seed,
    }

    # --- pipeline stages ride the model mesh axis (parallel/pipeline.py)
    pipe_m = 0
    if mesh_pipe > 1:
        if mesh_model > 1:
            raise click.UsageError(
                "--mesh_pipe repurposes the model mesh axis as the stage "
                "axis; it is mutually exclusive with --mesh_model > 1"
            )
        if ring_attn:
            raise click.UsageError(
                "--mesh_pipe and --ring_attn are separate deployment "
                "paths (stages run inside shard_map; the ring rides the "
                "seq axis of the GSPMD step)"
            )
        if not config.scan_layers:
            raise click.UsageError(
                "--mesh_pipe needs scan_layers=true in the model TOML: "
                "the stacked 'layers' param axis IS the stage axis "
                "(models/progen.stack_params converts old checkpoints)"
            )
        n_uniform = config.depth - config.global_mlp_depth
        if n_uniform % mesh_pipe:
            raise click.UsageError(
                f"{n_uniform} uniform layers not divisible by "
                f"{mesh_pipe} pipeline stages"
            )
        pipe_m = pipe_microbatches or mesh_pipe
        if batch_size % pipe_m:
            raise click.UsageError(
                f"--batch_size {batch_size} not divisible by "
                f"{pipe_m} pipeline microbatches"
            )
        mesh_model = mesh_pipe

    # --- mesh: data_parallel -> absorb all devices on the data axis
    if mesh_data == 0:
        mesh_data = -1 if (data_parallel or mesh_seq * mesh_model > 1) else 1
    mesh = make_mesh(data=mesh_data, seq=mesh_seq, model=mesh_model)

    if mesh_pipe > 1 and (batch_size // pipe_m) % mesh.shape["data"]:
        raise click.UsageError(
            f"PPxDP composition shards each {batch_size // pipe_m}-row "
            f"microbatch over the data axis; not divisible by "
            f"data={mesh.shape['data']}"
        )
    if ring_attn and mesh.shape["seq"] < 2:
        raise click.UsageError(
            "--ring_attn needs a sequence-parallel mesh (--mesh_seq > 1)"
        )
    if ring_attn or config.use_ring_attn:
        # config.use_ring_attn may also arrive via a resumed checkpoint's
        # config; on a topology without a seq axis the model falls back to
        # the local path by itself (mesh guard in LocalAttentionBlock)
        import dataclasses

        config = dataclasses.replace(config, use_ring_attn=True)
        model = ProGen(config, mesh=mesh)
    else:
        model = ProGen(config)

    # --- state: cold init or sharded restore (never both). Pipeline mode
    # lays the state out by PIPELINE_RULES (stacked layer axis = stages;
    # TP rules off) — same checkpoint format either way, only placement.
    from progen_tpu.parallel.partition import DEFAULT_RULES, PIPELINE_RULES

    rules = PIPELINE_RULES if mesh_pipe > 1 else DEFAULT_RULES
    start_seq_index, run_id = 0, None
    if last_meta is None:
        state, shardings = init_train_state(
            model, optimizer, jax.random.PRNGKey(seed), config.seq_len,
            mesh=mesh, rules=rules, zero1=zero1,
        )
    else:
        from progen_tpu.checkpoint import sharded_abstract_state

        boxed, abstract = abstract_train_state(
            model, optimizer, config.seq_len
        )
        shardings = train_state_shardings(boxed, mesh, rules, zero1=zero1)
        pkg = get_last(sharded_abstract_state(abstract, shardings))
        state = pkg.state
        start_seq_index = pkg.next_seq_index
        run_id = pkg.run_id

    tracker = make_tracker(
        wandb_project_name, run_id, disabled=wandb_off
    )
    run_id = tracker.run_id or run_id
    num_params = state.num_params()
    tracker.set_config({**config.to_dict(), "num_params": num_params})

    # --- telemetry: spans ride the tracker's event stream (events.jsonl
    # next to metrics.jsonl; Noop on non-coordinators / --wandb_off), the
    # ledger classifies the loop's wall clock from here on
    from progen_tpu import telemetry
    from progen_tpu.telemetry import (
        GoodputLedger,
        StallWatchdog,
        emit_per_host_goodput,
        hbm_gauges,
        prometheus_text,
        start_prometheus_server,
        step_print,
        write_prometheus,
    )

    telemetry.configure(sink=tracker.log_event)
    ledger = GoodputLedger()

    # forensics: the black box rides the telemetry tap; the profile pin
    # is polled once per optimizer step alongside the watchdog beat
    from progen_tpu.telemetry import flight as flight_mod

    if flight_dir:
        flight_mod.arm(flight_dir, metrics_fn=reg.snapshot)
    prof_watcher = None
    if profile_pin_path:
        import os as _os

        prof_watcher = flight_mod.ProfilePinWatcher(
            profile_pin_path,
            _os.path.join(
                _os.path.dirname(profile_pin_path) or ".", "profiles"
            ),
        )

    # --- train-loop Prometheus: the registry already carries the
    # resilience counters and step_s reservoir; goodput + HBM ride in as
    # gauges at render time so file and HTTP expositions agree
    def _render_prom() -> str:
        reg.set_gauges({
            k.replace("/", "_"): v
            for k, v in ledger.report().items()
            if isinstance(v, (int, float))
        })
        reg.set_gauges(hbm_gauges())
        return prometheus_text(reg, prefix="progen_train_")

    def publish_prom() -> None:
        if prom_file and is_coordinator():
            write_prometheus(prom_file, _render_prom())

    prom_srv = None
    if prom_port and is_coordinator():
        prom_srv = start_prometheus_server(_render_prom, port=prom_port)
        print(
            f"prometheus on http://127.0.0.1:"
            f"{prom_srv.server_address[1]}/metrics",
            file=sys.stderr,
        )

    # --- data
    num_train, train_iter_fn = iterator_from_tfrecords_folder(data_path)
    num_valid, valid_iter_fn = iterator_from_tfrecords_folder(
        data_path, "valid"
    )
    assert num_train > 0 and num_valid > 0, "no training/validation data"
    proc_kwargs = dict(
        process_index=jax.process_index(), process_count=jax.process_count()
    )
    train_ds = train_iter_fn(
        config.seq_len,
        batch_size,
        skip=start_seq_index,
        loop=True,
        shuffle_seed=shuffle_seed,
        **proc_kwargs,
    )
    valid_ds = valid_iter_fn(
        config.seq_len, batch_size, loop=True, **proc_kwargs
    )
    if is_coordinator():
        print(f"params: {num_params:,}")
        print(f"train sequences: {num_train:,}  valid: {num_valid:,}")

    effective_batch = batch_size * grad_accum_every
    sample_rng = jax.random.PRNGKey(seed + 1)

    local_bs = batch_size // jax.process_count()

    def pad_rows(m):
        # ragged tails (end of data) are padded up to the local batch size
        # with 0-rows so every process contributes identical shapes to the
        # global array; a 0-row adds one EOS position to the loss mask
        return np.pad(m, ((0, local_bs - m.shape[0]), (0, 0)))

    def next_super_batch():
        with ledger.track("data"):
            micro = [
                pad_rows(next(train_ds)) for _ in range(grad_accum_every)
            ]
            return put_batch(np.stack(micro), mesh, accum_axis=True)

    import tqdm

    # preemption-safe shutdown: first SIGTERM/SIGINT finishes the current
    # step, saves a final checkpoint, and exits cleanly (preemptible TPU
    # VMs send SIGTERM before eviction); a second signal kills immediately
    import signal

    stop_requested = {"flag": False}

    def _request_stop(signum, frame):
        if stop_requested["flag"]:
            raise KeyboardInterrupt
        stop_requested["flag"] = True
        if is_coordinator():
            print(f"signal {signum}: finishing step, then checkpoint+exit")

    signal.signal(signal.SIGTERM, _request_stop)
    signal.signal(signal.SIGINT, _request_stop)

    from progen_tpu import profiling

    timer = profiling.StepTimer(
        n_chips=len(jax.devices()),
        flops_per_tok=profiling.flops_per_token(config),
        peak=profiling.peak_flops(jax.devices()[0]),
    )
    import time

    # reference parity is ONE pass over the data (train.py:179); --epochs
    # extends the same record-index bookkeeping across passes (the data
    # iterator's skip/loop indices are global across epochs)
    num_total = num_train * max(epochs, 1)
    # the data cursor is a VARIABLE, not a range: an anomaly rollback skips
    # it ahead past the offending window, so the loop is a while over it
    seq_cursor = start_seq_index
    steps_done = 0
    rollbacks_done = 0  # distinct poison WINDOWS rolled back
    max_rollbacks = 3  # a third relapse means skipping isn't fixing it
    # bisection probes inside one window each cost a restore but don't
    # count as a new window; the backstop bounds total restores anyway
    total_rollbacks = 0
    max_total_rollbacks = max_rollbacks * 4
    bisector = None  # PoisonBisector over the current poison window
    bisect_start = 0  # seq_cursor where that window begins
    sentinel = LossSentinel(factor=anomaly_factor, patience=anomaly_patience)
    profiler_active = False
    # metric step continues across resumes (state.step is checkpointed);
    # a restarted loop must not rewind the tracker's step axis
    start_step = int(jax.device_get(state.step))
    # stall watchdog: beaten once per loop iteration below; a wedged
    # collective / device hang then leaves stacks + open spans in stderr
    # instead of a silent timeout kill (BASELINE.md's "dead all window")
    watchdog = (
        StallWatchdog(
            stall_timeout, escalate_after=stall_escalate_after
        ).start()
        if stall_timeout > 0
        else None
    )
    try:
      with mesh:
        # compiled steps live INSIDE the try: a jit failure here must
        # still run the finally that stops the loop=True prefetch workers
        with telemetry.span("train/compile"), ledger.track("compile") as tr:
            if mesh_pipe > 1:
                if pipe_schedule == "1f1b":
                    from progen_tpu.parallel.pipeline_1f1b import (
                        compile_1f1b_train_step,
                    )

                    train_step = compile_1f1b_train_step(
                        model, optimizer, shardings, mesh,
                        n_microbatches=pipe_m,
                    )
                else:
                    from progen_tpu.parallel.pipeline import (
                        compile_pipeline_train_step,
                    )

                    train_step = compile_pipeline_train_step(
                        model, optimizer, shardings, mesh,
                        n_microbatches=pipe_m,
                    )
                # rules=(): GSPMD activation constraints are meaningless
                # when the model axis holds stages, and the step runs
                # without them
                eval_step = compile_eval_step(
                    model, shardings, mesh, rules=()
                )
            else:
                train_step = compile_train_step(
                    model, optimizer, state, shardings, mesh
                )
                eval_step = compile_eval_step(model, shardings, mesh)
        # post-compile HBM is the first OOM-relevant reading: weights +
        # optimizer state + compiled-program reservations are all resident
        tracker.log(
            {"compile_s": round(tr.seconds, 3), **hbm_gauges()},
            step=start_step,
        )
        if watchdog is not None:
            watchdog.beat()  # compile done; the step clock starts now
        # pre-fetch only when the loop will actually run: resuming a
        # completed run (cursor already past the data) must fall through, not block
        # on a skip-exhausted iterator
        if seq_cursor < num_total and not (num_steps and num_steps <= 0):
            batch = next_super_batch()

        # deferred metrics: the host logs step N-1's loss AFTER step N is
        # dispatched, so the device always has a step in flight instead of
        # idling while the host prints/tracks (the reference fetches every
        # step, train.py:192). Cadence steps flush synchronously so the
        # non-finite gate always precedes a checkpoint write.
        pending = None

        def flush_metrics():
            nonlocal pending
            if pending is None:
                return
            p_step, p_metrics, p_bucket = pending
            pending = None
            with ledger.track(p_bucket):
                # host sync fence: the wait here IS the device step time
                # (or, for the first step under lazy jit, the compile)
                loss = float(p_metrics["last_micro_loss"])
            # the fetch above is the post-collective barrier every host
            # just crossed together: beacon it so `telemetry stitch`
            # can align the fleet's clocks on this step boundary
            emit_clock_beacon(p_step)
            grad_norm = float(p_metrics["grad_norm"])
            skipped = int(p_metrics.get("skipped", 0))
            # chaos perturbation point: PROGEN_CHAOS="train/loss:spike@2"
            # feeds the sentinel a poisoned value without touching the
            # device state — the rollback path rehearsed in-process
            loss = chaos.perturb("train/loss", loss)
            # failure TOLERANCE, not just detection (SURVEY §5): the
            # step's finite gate already refused a non-finite update, so
            # an isolated anomaly is skipped; ``patience`` consecutive
            # ones escalate to checkpoint rollback + data skip-ahead.
            # The verdict must bind every host (one host rolling back
            # alone deadlocks the next collective) — allgather-max, the
            # same pattern as the stop flag below.
            verdict = sentinel.observe(loss, grad_norm)
            if consistent_flag(verdict == ROLLBACK):
                raise AnomalyRollback(p_step, loss)
            if verdict == SPIKE or skipped:
                if is_coordinator():
                    step_print(
                        p_step,
                        f"anomaly: loss {loss:.4g} grad_norm "
                        f"{grad_norm:.4g}"
                        + (" (update refused on-device)" if skipped else "")
                        + f"; {sentinel.consecutive}/{sentinel.patience} "
                        "consecutive before rollback",
                    )
                reg.inc("anomalies")
                telemetry.get_telemetry().emit({
                    "ev": "anomaly", "ts": time.time(), "step": p_step,
                    "loss": loss, "grad_norm": grad_norm,
                    "skipped": skipped,
                    "consecutive": sentinel.consecutive,
                })
            perf = timer.tick(effective_batch * config.seq_len)
            if perf is not None:
                # the step_s reservoir is what the Prometheus summary
                # quantiles render from; throughput/MFU ride as gauges
                reg.observe("step_s", perf["step_ms"] / 1000.0)
                reg.set_gauges({
                    "tokens_per_sec_per_chip":
                        perf["tokens_per_sec_per_chip"],
                    "mfu": perf["mfu"],
                })
            with ledger.track("log"):
                if is_coordinator():
                    step_print(p_step, f"loss: {loss:.4f}")
                tracker.log(
                    {"loss": loss, "grad_norm": grad_norm,
                     **({"anomaly": 1} if verdict != "ok" else {}),
                     **(perf or {}), **hbm_gauges()},
                    step=p_step,
                )
        pbar = tqdm.tqdm(
            total=num_total, initial=min(seq_cursor, num_total),
            mininterval=10, unit="seq",
        )
        i = 0
        while seq_cursor < num_total:
          try:
            seq_index = seq_cursor
            stop = stop_requested["flag"]
            if jax.process_count() > 1:
                # every host must agree before leaving the collective loop
                # (a lone host breaking into the collective save deadlocks);
                # reduce-max: ANY host's signal stops all hosts
                from jax.experimental import multihost_utils

                stop = bool(
                    multihost_utils.process_allgather(np.int32(stop)).max()
                )
            if stop:
                break
            if num_steps and steps_done >= num_steps:
                break
            if profile_dir and i == 2:
                from jax import profiler as jax_profiler

                jax_profiler.start_trace(profile_dir)
                profiler_active = True
            # the first call of a lazily-jitted step traces and compiles
            # synchronously — that's compile time, not step time
            step_bucket = "compile" if steps_done == 0 else "step"
            with ledger.track(step_bucket):
                # async dispatch: cheap when the device is pipelined, the
                # full wait shows up at flush_metrics' host sync instead
                state, metrics = train_step(state, batch)
            steps_done += 1
            # prepare the NEXT batch while the device is busy (async
            # dispatch): host input pipeline overlaps device compute —
            # skipped when this was the last step
            is_last = (num_steps and steps_done >= num_steps) or (
                seq_index + effective_batch >= num_total
            )
            if not is_last:
                batch = next_super_batch()
            global_step = start_step + steps_done
            # log the PREVIOUS step (already complete — no device stall),
            # then queue this one
            flush_metrics()
            pending = (global_step, metrics, step_bucket)
            if watchdog is not None:
                watchdog.beat()
            if prof_watcher is not None:
                prof_watcher.poll_watch()
            if async_checkpoint:
                # per-step poll of the background commit thread: a fatal
                # commit error aborts at the NEXT step (with a
                # ckpt_commit_failed event), not minutes later at flush
                save_ckpt.check_error()
            # single source of truth for the cadence triggers: sync_now
            # MUST cover every condition that writes a checkpoint below,
            # or a NaN state could enter the rotation unchecked
            do_ckpt = i % checkpoint_every == 0
            do_valid = i % validate_every == 0
            do_sample = i % sample_every == 0
            if is_last or profiler_active or do_ckpt or do_valid or do_sample:
                flush_metrics()
            if profiler_active and i >= 4:
                from jax import profiler as jax_profiler

                jax_profiler.stop_trace()
                profiler_active = False

            next_seq_index = seq_index + effective_batch
            # cadence work below runs between step timings; each block
            # credits its goodput bucket AND excludes itself from the
            # StepTimer window, so step_ms/MFU stay pure step numbers
            # instead of silently absorbing checkpoint/eval/sample time
            if do_ckpt:
                with telemetry.span("train/ckpt", step=global_step), \
                        ledger.track("checkpoint") as tr:
                    save_ckpt(
                        Package(
                            next_seq_index=next_seq_index,
                            state=state,
                            model_config=config.to_dict(),
                            run_id=run_id,
                            train_config=train_config,
                        )
                    )
                timer.exclude(tr.seconds)
            if do_valid:
                with telemetry.span("train/eval", step=global_step), \
                        ledger.track("eval") as tr:
                    vloss = float(
                        eval_step(
                            state, put_batch(pad_rows(next(valid_ds)), mesh)
                        )
                    )
                timer.exclude(tr.seconds)
                if is_coordinator():
                    step_print(global_step, f"valid_loss: {vloss:.4f}")
                tracker.log(
                    {"valid_loss": vloss, **ledger.report()},
                    step=global_step,
                )
                publish_prom()  # same cadence as the goodput log line
            if do_sample:
                with telemetry.span("train/sample", step=global_step), \
                        ledger.track("sample") as tr:
                    valid_batch = np.asarray(next(valid_ds))
                    prime = valid_batch[0, 1 : prime_length + 1]  # skip BOS
                    if jax.process_count() > 1:
                        # every process must feed the IDENTICAL prime into
                        # the jitted decode over globally-sharded params
                        from jax.experimental import multihost_utils

                        prime = multihost_utils.broadcast_one_to_all(prime)
                    sampled = sample_tokens(
                        jax.random.fold_in(sample_rng, i),
                        model,
                        state.params,
                        prime,
                        config.seq_len,
                        top_k=25,
                        add_bos=True,
                    )
                    prime_str = decode_tokens(prime)
                    sampled_str = decode_tokens(
                        np.asarray(sampled)[prime_length + 1 :]
                    )
                timer.exclude(tr.seconds)
                if is_coordinator():
                    step_print(global_step, f"sample: {sampled_str[:120]}")
                tracker.log_html(
                    "samples",
                    render_sample_html(prime_str, sampled_str),
                    step=global_step,
                )
            seq_cursor = next_seq_index
            i += 1
            pbar.update(effective_batch)
          except AnomalyRollback as exc:
            total_rollbacks += 1
            pending = None  # the queued step's metrics are the anomaly
            step_at, bad_loss = exc.args
            # same poison window re-spiking (the resume landed before
            # the poison), or a NEW window? Re-spikes near the current
            # window feed the bisector; anything else opens a fresh one
            same_window = (
                bisector is not None
                and not bisector.exhausted
                and seq_cursor < bisect_start + 3 * effective_batch
            )
            if same_window:
                bisector.observe_respike()
            else:
                rollbacks_done += 1
                bisect_start = seq_cursor
                bisector = PoisonBisector(
                    effective_batch, min_step=batch_size
                )
            if (
                rollbacks_done > max_rollbacks
                or total_rollbacks > max_total_rollbacks
            ):
                raise RuntimeError(
                    f"{total_rollbacks} anomaly rollbacks without recovery "
                    f"(last loss {bad_loss} at step {step_at}); skipping "
                    "data is not fixing this — inspect the stream/hparams"
                ) from exc
            with telemetry.span("train/rollback", step=step_at), \
                    ledger.track("checkpoint") as tr:
                from progen_tpu.checkpoint import sharded_abstract_state

                # publish any in-flight async save so the restore walk
                # sees the newest COMPLETE checkpoint
                save_ckpt.flush()
                _, abstract = abstract_train_state(
                    model, optimizer, config.seq_len
                )
                pkg = get_last(sharded_abstract_state(abstract, shardings))
                if pkg is None:
                    raise RuntimeError(
                        f"anomaly rollback requested at step {step_at} "
                        "but no checkpoint exists to roll back to"
                    ) from exc
                state = pkg.state
                # skip ahead INTO the offending window, not past it:
                # the bisector proposes the smallest prefix-skip worth
                # trying (half the remaining window, aligned to one
                # per-device batch); if the poison is past the resume
                # point the window re-spikes and the next probe skips
                # more — exhaustion degrades to the legacy whole-window
                # discard, so clean tail data is salvaged, never lost
                skip = bisector.propose()
                seq_cursor = bisect_start + skip
                train_ds.close()
                train_ds = train_iter_fn(
                    config.seq_len,
                    batch_size,
                    skip=seq_cursor,
                    loop=True,
                    shuffle_seed=shuffle_seed,
                    **proc_kwargs,
                )
                sentinel.reset()
                if seq_cursor < num_total:
                    batch = next_super_batch()
            timer.exclude(tr.seconds)
            restored_step = int(jax.device_get(state.step))
            if is_coordinator():
                step_print(
                    step_at,
                    f"anomaly rollback {rollbacks_done}/{max_rollbacks}: "
                    f"restored checkpoint (state step {restored_step}), "
                    f"data skipped ahead to sequence {seq_cursor} "
                    f"(bisect: {skip}/{bisector.window} of the window "
                    f"discarded, {bisector.salvaged} salvaged)",
                )
            reg.inc("anomaly_rollbacks")
            telemetry.get_telemetry().emit({
                "ev": "anomaly_rollback", "ts": time.time(),
                "step": step_at, "loss": bad_loss,
                "restored_step": restored_step,
                "next_seq_index": seq_cursor,
                "rollbacks_done": rollbacks_done,
                "total_rollbacks": total_rollbacks,
                "bisect_skip": skip,
                "bisect_window": bisector.window,
                "bisect_salvaged": bisector.salvaged,
            })
            pbar.update(effective_batch)
            if watchdog is not None:
                watchdog.beat()
        pbar.close()
        # stop-flag / exhausted-iterator exits leave the last step queued:
        # its loss (and the sentinel verdict) must land before the final
        # save; a rollback verdict HERE just ends the run — the state
        # below passed the finite gate, so saving it is safe
        try:
            flush_metrics()
        except AnomalyRollback:
            pass
        # goodput closes the books on the loop: MFU said how fast the
        # steps were, this says how often the loop was actually stepping
        report = ledger.report()
        tracker.log(report, step=start_step + steps_done)
        if is_coordinator():
            step_print(
                start_step + steps_done,
                f"goodput: {report['goodput_pct']:.1f}% of "
                f"{report['wall_s']:.1f}s wall "
                f"(attributed {report['coverage_pct']:.1f}%)",
            )
        # per-host goodput (COLLECTIVE — every host reaches this line on
        # every exit path of the while loop above): each host's ledger
        # vector is allgathered and the full table lands in every host's
        # event stream, so one events.jsonl reconstructs the straggler
        # skew (`telemetry summarize`)
        host_reports = emit_per_host_goodput(ledger)
        if is_coordinator() and len(host_reports) > 1:
            from progen_tpu.telemetry import goodput_skew

            skew = goodput_skew(host_reports)
            worst = max(
                (
                    (row["skew"], name, row["straggler"])
                    for name, row in skew.items()
                    if isinstance(row, dict) and name != "goodput_pct"
                ),
                default=None,
            )
            if worst is not None:
                step_print(
                    start_step + steps_done,
                    f"goodput skew across {skew['hosts']} hosts: worst "
                    f"bucket '{worst[1]}' +{worst[0]:.2f}s on host "
                    f"{worst[2]}",
                )
        publish_prom()  # final exposition includes the end-of-run books

    finally:
        # nested so each cleanup runs even if an earlier one raises
        try:
            chaos.uninstall()  # rules must not leak into a later in-process run
            if prom_srv is not None:
                prom_srv.shutdown()
            if watchdog is not None:
                watchdog.stop()
            if prof_watcher is not None:
                prof_watcher.close()  # flush an in-flight window
            flight_mod.disarm()
            # detach the span sink BEFORE the tracker closes its files:
            # a later span in this process must not write to a dead fd
            telemetry.configure()
            if profiler_active:
                from jax import profiler as jax_profiler

                jax_profiler.stop_trace()
        finally:
            try:
                # async mode: publish any committed-but-unfinalized
                # checkpoint and stop the background thread even on aborts
                # (e.g. the non-finite-loss raise) — every periodic save's
                # state was verified finite before it was saved, so the
                # pending snapshot is always good
                save_ckpt.close()
            finally:
                # stop the prefetch workers (loop=True streams never
                # exhaust); nested again so one close failing cannot
                # leak the other worker
                try:
                    train_ds.close()
                finally:
                    valid_ds.close()

    # final checkpoint so short runs (e.g. --num_steps) always persist;
    # the cursor counts exactly the records consumed by executed steps
    # PLUS any rollback skip-ahead — resume must not re-read either
    save_ckpt(
        Package(
            next_seq_index=seq_cursor,
            state=state,
            model_config=config.to_dict(),
            run_id=run_id,
            train_config=train_config,
        )
    )
    save_ckpt.close()  # async mode: publish the final save before exit
    tracker.finish()


if __name__ == "__main__":
    main()
