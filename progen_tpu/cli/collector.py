"""``progen-tpu-collector`` — the fleet metrics scrape loop.

Point it at the exposition files the fleet already writes (replica and
router ``--prom_file`` textfiles, tracker ``metrics.jsonl`` streams)
and it ticks forever: scrape → stamp → append to the ring-buffer TSDB,
with staleness and fleet-SLO burn transitions fanned into an alerts
JSONL. Deliberately jax-free — it is a sidecar, not a replica — so it
starts in milliseconds and can run on any host that sees the files.

Sources come from repeatable ``--source name=...,role=...,prom=...``
specs (the router's ``--replica`` syntax) or a flat TOML
(``configs/serving/collector.toml`` is the shipped example); flags
override config values.

Egress (all optional, all non-blocking for the scrape loop):
``--remote-write URL`` pushes the merged fleet series to a Prometheus
remote-write receiver (bounded spool, drops counted); ``--alert-config
TOML`` routes alert transitions through dedup/severity/silences to
webhook/file/stderr sinks with a ``notifications.jsonl`` ledger;
``--archive DIR`` ships sealed TSDB blocks verbatim (digest manifest)
before the ring degrades them.
"""

from __future__ import annotations

import signal
import sys
import time

import click

from progen_tpu.telemetry.alert_router import (
    AlertRouter,
    load_router_config,
)
from progen_tpu.telemetry.alerts import AlertSink
from progen_tpu.telemetry.collector import (
    Collector,
    load_collector_config,
    parse_source_spec,
)
from progen_tpu.telemetry.remote_write import RemoteWriteBridge
from progen_tpu.telemetry.slo import load_objectives
from progen_tpu.telemetry.tsdb import BlockShipper, RingTSDB


@click.command()
@click.option(
    "--tsdb", "tsdb_dir", required=True,
    type=click.Path(file_okay=False),
    help="ring-buffer store directory (created if missing; one "
         "collector per directory)",
)
@click.option(
    "--source", "source_specs", multiple=True,
    help="scrape target: name=r0,role=replica,prom=/path/metrics.prom"
         "[,metrics=/path/metrics.jsonl] — repeatable",
)
@click.option(
    "--config", "config_path",
    type=click.Path(exists=True, dir_okay=False), default=None,
    help="flat TOML with [collector] settings and [source_<name>] "
         "tables (flags override)",
)
@click.option(
    "--interval", type=float, default=None,
    help="seconds between scrapes [default: 2]",
)
@click.option(
    "--stale-after", type=float, default=None,
    help="exposition age (s) past which a source counts as down "
         "[default: 10]",
)
@click.option(
    "--budget-bytes", type=int, default=None,
    help="TSDB ring byte budget; over it, old blocks downsample then "
         "drop [default: 8 MiB]",
)
@click.option(
    "--block-bytes", type=int, default=None,
    help="TSDB block size before seal-and-rotate [default: 256 KiB]",
)
@click.option(
    "--slo", "slo_path",
    type=click.Path(exists=True, dir_okay=False), default=None,
    help="objectives TOML: evaluate fleet SLOs each tick and alert on "
         "burn transitions",
)
@click.option(
    "--alerts-out", type=click.Path(dir_okay=False), default=None,
    help="alerts JSONL path [default: <tsdb>/alerts.jsonl]",
)
@click.option(
    "--remote-write", "remote_write_url", default=None,
    help="push the merged fleet series to this HTTP endpoint "
         "(Prometheus remote-write, JSON body; bounded spool, "
         "never blocks the scrape loop)",
)
@click.option(
    "--alert-config", "alert_config_path",
    type=click.Path(exists=True, dir_okay=False), default=None,
    help="alert router TOML ([alert_router] + [route_<name>] tables); "
         "notifications ledger lands beside the alerts JSONL",
)
@click.option(
    "--archive", "archive_dir",
    type=click.Path(file_okay=False), default=None,
    help="ship sealed TSDB blocks verbatim to this directory (digest "
         "manifest) before the ring downsamples or drops them",
)
@click.option(
    "--flight-dir", "flight_dir",
    type=click.Path(file_okay=False), default=None,
    help="arm the collector's flight recorder: bounded ring of recent "
         "scrape/SLO telemetry, dumped atomically here on crash paths "
         "and on fleet SLO 'burning' edges",
)
@click.option(
    "--profile-pin", "profile_pins", multiple=True,
    help="on the first fleet SLO 'burning' edge, request an on-demand "
         "jax.profiler window by writing this control file (a serve/"
         "train --profile_pin path) — repeatable, rate-limited",
)
@click.option(
    "--profile-min-interval", type=float, default=300.0,
    show_default=True,
    help="seconds between auto-requested profile windows (per "
         "collector, across all pins)",
)
@click.option(
    "--max-ticks", type=int, default=0, show_default=True,
    help="stop after N scrapes (0 = run until SIGTERM/SIGINT)",
)
@click.option(
    "--once", is_flag=True, help="single scrape, then exit (CI probes)"
)
def main(
    tsdb_dir, source_specs, config_path, interval, stale_after,
    budget_bytes, block_bytes, slo_path, alerts_out,
    remote_write_url, alert_config_path, archive_dir,
    flight_dir, profile_pins, profile_min_interval,
    max_ticks, once,
):
    """Scrape fleet metrics sources into a bounded TSDB + alert sink."""
    settings = {}
    sources = []
    if config_path is not None:
        settings, sources = load_collector_config(config_path)
    try:
        sources += [parse_source_spec(s) for s in source_specs]
    except ValueError as e:
        raise click.UsageError(str(e))
    if not sources:
        raise click.UsageError(
            "no sources: pass --source and/or --config"
        )
    interval = float(
        interval if interval is not None
        else settings.get("interval_s", 2.0)
    )
    stale_after = float(
        stale_after if stale_after is not None
        else settings.get("stale_after_s", 10.0)
    )
    budget_bytes = int(
        budget_bytes if budget_bytes is not None
        else settings.get("budget_bytes", 8 << 20)
    )
    block_bytes = int(
        block_bytes if block_bytes is not None
        else settings.get("block_bytes", 256 << 10)
    )
    if slo_path is None:
        slo_path = settings.get("slo") or None
    cfg = load_objectives(slo_path) if slo_path else None

    shipper = (
        BlockShipper(archive_dir) if archive_dir is not None else None
    )
    tsdb = RingTSDB(
        tsdb_dir, budget_bytes=budget_bytes, block_bytes=block_bytes,
        shipper=shipper,
    )
    alerts_path = (
        alerts_out if alerts_out is not None
        else tsdb.root / "alerts.jsonl"
    )
    router = None
    if alert_config_path is not None:
        severity, routes = load_router_config(alert_config_path)
        router = AlertRouter(
            tsdb.root / "notifications.jsonl", routes,
            severity=severity,
        )
    alerts = AlertSink(
        alerts_path,
        relay=router.handle if router is not None else None,
    )
    bridge = (
        RemoteWriteBridge(remote_write_url)
        if remote_write_url else None
    )
    coll = Collector(
        tsdb, sources, stale_after_s=stale_after,
        slo_cfg=cfg, alerts=alerts, remote_write=bridge,
        profile_pins=profile_pins,
        profile_min_interval_s=profile_min_interval,
    )
    from progen_tpu.telemetry import flight as flight_mod
    if flight_dir:
        flight_mod.arm(flight_dir)
    click.echo(
        f"collector: {len(sources)} sources -> {tsdb.root} "
        f"(every {interval:g}s, stale after {stale_after:g}s, "
        f"budget {budget_bytes} B"
        + (", fleet SLOs on" if cfg else "")
        + (f", remote-write {remote_write_url}" if bridge else "")
        + (f", {len(router.routes)} alert routes" if router else "")
        + (f", archive {archive_dir}" if shipper else "")
        + (f", flight {flight_dir}" if flight_dir else "")
        + (f", auto-profile x{len(profile_pins)}"
           if profile_pins else "")
        + ")",
        err=True,
    )

    stop = {"flag": False}

    def _stop(signum, frame):
        stop["flag"] = True

    signal.signal(signal.SIGTERM, _stop)
    signal.signal(signal.SIGINT, _stop)

    ticks = 0
    try:
        while not stop["flag"]:
            coll.scrape_once()
            if router is not None:
                router.tick()  # fire any due escalation chains
            ticks += 1
            if once or (max_ticks and ticks >= max_ticks):
                break
            deadline = time.time() + interval
            while not stop["flag"] and time.time() < deadline:
                time.sleep(min(0.2, interval))
    finally:
        flight_mod.disarm()
        tsdb.close()
        alerts.close()
        if router is not None:
            router.close()
    tail = ""
    if bridge is not None:
        s = bridge.stats()
        tail += (
            f", remote-write {s['sent_points']} pts sent "
            f"({s['dropped_points']} dropped, "
            f"{s['push_failures']} push failures)"
        )
    if router is not None:
        tail += (
            f", notify {router.counts['sent']} sent / "
            f"{router.counts['silenced']} silenced / "
            f"{router.counts['deduped']} deduped / "
            f"{router.counts['escalated']} escalated"
        )
    if shipper is not None:
        tail += (
            f", archive {shipper.shipped} shipped / "
            f"{shipper.skipped} skipped / "
            f"{shipper.verify_failed} verify-failed"
        )
    click.echo(
        f"collector: {ticks} ticks, {len(tsdb.blocks())} blocks, "
        f"{tsdb.total_bytes()} bytes, "
        f"{tsdb.dropped_lines} torn lines dropped" + tail,
        err=True,
    )
    sys.exit(0)


if __name__ == "__main__":
    main()
