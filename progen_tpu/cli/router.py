"""Router CLI — elastic multi-replica serving front-end.

Fans the same JSONL request protocol cli/serve.py speaks across N
serve replicas (each `cli/serve --socket ... --journal_dir ...` with
its OWN journal), and survives replica death by journal-ownership
handoff (serving/router.py). Two ways to get a fleet:

  * point at running replicas:

        progen-tpu-router \
          --replica sock=/run/r0.sock,journal=/var/r0,prom=/var/r0/m.prom \
          --replica sock=/run/r1.sock,journal=/var/r1,prom=/var/r1/m.prom

  * or spawn one (dev/smoke): ``--spawn 2 --checkpoint_path ./ckpts
    --fleet_dir ./fleet`` starts two serve subprocesses with per-replica
    socket/journal/prom files under ``fleet_dir/replica{i}/``;
    ``--respawn`` restarts a dead replica with ``--replay`` of its own
    journal — safe against double-serving because the handoff writes
    ``handed_off`` ownership marks BEFORE any restart can replay.

Requests arrive on stdin (default) or a unix socket (--socket PATH),
exactly as cli/serve.py: one JSON object per line, ``id`` required,
optional ``tenant`` for per-tenant quotas. Token/done/rejected events
stream back interleaved. Shedding reasons the router adds on top of
the replica's: ``router_queue_full``, ``tenant_quota``, ``draining``,
``no_replicas``, ``replica_lost``.

SIGTERM/SIGINT drains: intake closes, queued requests are shed with
reason ``draining``, in-flight streams (and any handoffs their
replicas' deaths force) run to completion, then exit 0. A second
signal kills immediately (open request tracks are closed with reason
``killed`` first, so the post-mortem trace is honest).

Router metrics render under the ``progen_router_`` Prometheus prefix
(--prom_file / --prom_port) and land in the tracker under ``router/``.

Run: python -m progen_tpu.cli.router --spawn 2 --checkpoint_path ./ckpts
"""

from __future__ import annotations

from progen_tpu.utils.env import load_env_file

load_env_file()  # env flags before any heavy import (ref serve.py)

import json
import os
import select
import signal
import socket as socketlib
import subprocess
import sys

import click


@click.command()
@click.option("--replica", "replica_specs", multiple=True,
              help="replica endpoint, repeatable: "
                   "'sock=PATH[,journal=DIR][,prom=FILE][,name=N]' or a "
                   "bare socket path (no journal = no handoff, only "
                   "re-dispatch of never-accepted requests)")
@click.option("--spawn", default=0,
              help="spawn N serve replicas under --fleet_dir instead of "
                   "connecting to --replica endpoints")
@click.option("--checkpoint_path", default="./ckpts",
              help="checkpoint for spawned replicas")
@click.option("--fleet_dir", default="./fleet", type=str,
              help="per-replica socket/journal/prom/log files land in "
                   "FLEET_DIR/replica{i}/")
@click.option("--respawn/--no-respawn", default=False,
              help="restart a dead spawned replica with --replay of its "
                   "own journal (handed-off work is skipped via its "
                   "ownership marks)")
@click.option("--replica-max-slots", default=8,
              help="--max-slots for spawned replicas")
@click.option("--replica-max-queue", default=64,
              help="--max-queue for spawned replicas")
@click.option("--max-len", default=None, type=int,
              help="--max-len for spawned replicas")
@click.option("--max-queue", default=256,
              help="router admission queue bound (shed reason "
                   "'router_queue_full' beyond it)")
@click.option("--tenant_quota", default=0,
              help="max outstanding requests per 'tenant' field "
                   "(0 = unlimited; shed reason 'tenant_quota')")
@click.option("--heartbeat_timeout", default=30.0, type=float,
              help="deprioritize a replica whose prom-file heartbeat is "
                   "older than this many seconds")
@click.option("--socket", "socket_path", default=None, type=str,
              help="serve a unix domain socket at PATH instead of "
                   "stdin/stdout")
@click.option("--metrics-every", default=0,
              help="log a router/ metrics snapshot (and rewrite "
                   "--prom_file) every N loop ticks (0 = only at exit)")
@click.option("--prom_file", default=None, type=str,
              help="write progen_router_* Prometheus text here")
@click.option("--prom_port", default=0,
              help="serve progen_router_* metrics over HTTP on this "
                   "localhost port (0 = off)")
def main(replica_specs, spawn, checkpoint_path, fleet_dir, respawn,
         replica_max_slots, replica_max_queue, max_len, max_queue,
         tenant_quota, heartbeat_timeout, socket_path, metrics_every,
         prom_file, prom_port):
    from progen_tpu import telemetry
    from progen_tpu.resilience.chaos import install_from_env
    from progen_tpu.serving.router import Router, parse_replica_spec
    from progen_tpu.telemetry import (
        prometheus_text,
        start_prometheus_server,
        write_prometheus,
    )
    from progen_tpu.tracking import make_tracker

    # router chaos sites (router/connect, router/dispatch,
    # router/handoff) arm from the environment, same as cli/serve.py
    install_from_env()

    if spawn and replica_specs:
        sys.exit("use --spawn or --replica, not both")
    if not spawn and not replica_specs:
        sys.exit("no fleet: pass --replica specs or --spawn N")

    procs = {}  # replica index -> (Popen, replica_dir, log file)

    def _spawn_replica(i, replay=False):
        rdir = os.path.join(fleet_dir, f"replica{i}")
        os.makedirs(rdir, exist_ok=True)
        args = [
            sys.executable, "-m", "progen_tpu.cli.serve",
            "--checkpoint_path", checkpoint_path,
            "--socket", os.path.join(rdir, "serve.sock"),
            "--journal_dir", rdir,
            "--prom_file", os.path.join(rdir, "metrics.prom"),
            "--metrics-every", "4",
            "--max-slots", str(replica_max_slots),
            "--max-queue", str(replica_max_queue),
        ]
        if max_len is not None:
            args += ["--max-len", str(max_len)]
        if replay:
            args += ["--replay", rdir]
        log = open(os.path.join(rdir, "replica.log"), "ab")
        proc = subprocess.Popen(
            args, stdin=subprocess.DEVNULL, stdout=log, stderr=log
        )
        procs[i] = (proc, rdir, log)
        print(
            f"replica{i}: pid {proc.pid}"
            + (" (replaying its journal)" if replay else ""),
            file=sys.stderr,
        )

    if spawn:
        specs = []
        for i in range(spawn):
            rdir = os.path.join(fleet_dir, f"replica{i}")
            specs.append(parse_replica_spec(
                f"sock={os.path.join(rdir, 'serve.sock')},"
                f"journal={rdir},"
                f"prom={os.path.join(rdir, 'metrics.prom')}"
            ))
            _spawn_replica(i)
    else:
        specs = [parse_replica_spec(s) for s in replica_specs]

    router = Router(
        specs, max_queue=max_queue, tenant_quota=tenant_quota,
        heartbeat_timeout=heartbeat_timeout,
    )
    tracker = make_tracker("progen-router")
    telemetry.configure(sink=tracker.log_event)
    run_dir = getattr(tracker, "path", None)
    if run_dir is not None:
        print(
            f"router traces: {run_dir}/events.jsonl "
            "(render with progen-tpu-telemetry export-trace)",
            file=sys.stderr,
        )

    def publish(step=None):
        router.metrics.log_to(tracker, step=step, prefix="router/")
        if prom_file:
            write_prometheus(
                prom_file,
                prometheus_text(router.metrics, prefix="progen_router_"),
            )

    prom_srv = None
    if prom_port:
        prom_srv = start_prometheus_server(
            lambda: prometheus_text(
                router.metrics, prefix="progen_router_"
            ),
            port=prom_port,
        )
        print(
            f"prometheus on http://127.0.0.1:"
            f"{prom_srv.server_address[1]}/metrics",
            file=sys.stderr,
        )
    print(
        f"routing across {len(specs)} replica(s): "
        + ", ".join(s.socket_path for s in specs),
        file=sys.stderr,
    )

    shutdown = {"flag": False}

    def _request_drain(signum, frame):
        if shutdown["flag"]:
            print(f"signal {signum} again: exiting now", file=sys.stderr)
            try:
                router.close_tracks("killed")
            except Exception:
                pass  # a torn trace line beats a hung exit
            sys.stderr.flush()
            os._exit(1)
        shutdown["flag"] = True
        print(
            f"signal {signum}: draining — intake closed, queued requests "
            "shed, in-flight streams finishing; signal again to kill",
            file=sys.stderr,
        )

    def tick():
        """Once per front-loop iteration, AFTER router.poll() — so a
        dead spawned replica's handoff (triggered by the socket EOF
        inside poll) has already written its ownership marks before any
        --respawn replay can read the journal."""
        if shutdown["flag"]:
            return
        for i, (proc, rdir, log) in list(procs.items()):
            if proc.poll() is None:
                continue
            del procs[i]
            log.close()
            print(
                f"replica{i}: exited rc={proc.returncode}",
                file=sys.stderr,
            )
            if respawn and not router.links[i].up:
                _spawn_replica(i, replay=True)

    old_term = signal.signal(signal.SIGTERM, _request_drain)
    old_int = signal.signal(signal.SIGINT, _request_drain)
    try:
        if socket_path:
            _front_socket(router, socket_path, publish, metrics_every,
                          shutdown, tick=tick)
        else:
            _front_stdio(router, publish, metrics_every, shutdown,
                         tick=tick)
    finally:
        signal.signal(signal.SIGTERM, old_term)
        signal.signal(signal.SIGINT, old_int)
        publish()
        if prom_srv is not None:
            prom_srv.shutdown()
        for i, (proc, rdir, log) in procs.items():
            proc.terminate()
        for i, (proc, rdir, log) in procs.items():
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
            log.close()
        telemetry.configure()  # detach before the sink closes
        tracker.finish()


def _submit_obj(router, line, client=None):
    """Parse + submit one request line; returns a rejection event dict
    to answer immediately, or None."""
    try:
        obj = json.loads(line)
        if not isinstance(obj, dict):
            raise ValueError("request must be a JSON object")
    except ValueError as e:
        return {"event": "rejected", "id": None,
                "reason": f"bad request line: {e}"}
    return router.submit(obj, client=client)


def _front_stdio(router, publish, metrics_every, shutdown, tick=None):
    """stdin-JSONL front: one select loop over {stdin, replica sockets}
    — new requests and replica events interleave without polling sleeps.
    Same raw-fd line buffering as cli/serve.py (select()+readline()
    loses lines). EOF or a drain signal closes intake; the loop runs
    until the router settles everything it accepted."""
    out = sys.stdout
    eof = False
    drained = False
    buf = ""
    ticks = 0

    def emit(ev):
        out.write(json.dumps(ev) + "\n")
        out.flush()

    while True:
        if shutdown["flag"] and not drained:
            drained = True
            router.drain()
        if (eof or shutdown["flag"]) and not router.has_work:
            break
        rlist = ([] if (eof or shutdown["flag"]) else [sys.stdin])
        rlist += router.fds()
        # bounded wait: backoffs/reconnects need the loop to turn even
        # when no fd is hot
        timeout = 0.05 if router.has_work else 0.2
        try:
            if rlist:
                select.select(rlist, [], [], timeout)
        except OSError:
            pass  # a replica socket died between fds() and select
        while not eof and not shutdown["flag"]:
            nl = buf.find("\n")
            if nl < 0:
                try:
                    ready, _, _ = select.select([sys.stdin], [], [], 0.0)
                except OSError:
                    break
                if not ready:
                    break
                data = os.read(sys.stdin.fileno(), 65536)
                if not data:
                    eof = True
                    line, buf = buf, ""
                else:
                    buf += data.decode("utf-8", errors="replace")
                    continue
            else:
                line, buf = buf[:nl], buf[nl + 1:]
            if not line.strip():
                continue
            rej = _submit_obj(router, line)
            if rej is not None:
                emit(rej)
        for _, ev in router.poll():
            emit(ev)
        if tick is not None:
            tick()
        ticks += 1
        if metrics_every and ticks % metrics_every == 0:
            publish(ticks)


def _front_socket(router, socket_path, publish, metrics_every, shutdown,
                  tick=None):
    """Unix-socket front: each connection submits requests and receives
    exactly its own events (the router's per-request ``client`` handle
    is the connection fd). On drain the listener closes, the queue is
    shed, in-flight streams finish to their clients, then exit."""
    if os.path.exists(socket_path):
        os.unlink(socket_path)
    srv = socketlib.socket(socketlib.AF_UNIX, socketlib.SOCK_STREAM)
    srv.bind(socket_path)
    srv.listen(16)
    srv.setblocking(False)
    clients = {}  # fd -> (sock, recv_buffer)
    ticks = 0
    drained = False
    print(f"listening on {socket_path}", file=sys.stderr)

    def send(fd, ev):
        sock, _ = clients.get(fd, (None, None))
        if sock is None:
            return
        try:
            sock.sendall(json.dumps(ev).encode() + b"\n")
        except OSError:
            _drop(fd)

    def _drop(fd):
        sock, _ = clients.pop(fd, (None, None))
        if sock is not None:
            sock.close()

    try:
        while True:
            if shutdown["flag"] and not drained:
                drained = True
                srv.close()  # refuse new connections during drain
                router.drain()
            if shutdown["flag"] and not router.has_work:
                break
            rlist = ([] if drained else [srv])
            rlist += [s for s, _ in clients.values()]
            rlist += router.fds()
            timeout = 0.05 if router.has_work else 0.2
            try:
                ready, _, _ = (
                    select.select(rlist, [], [], timeout)
                    if rlist else ([], [], [])
                )
            except OSError:
                continue  # a peer vanished between list and select
            replica_socks = set(router.fds())
            for sock in ready:
                if sock is srv:
                    conn, _ = srv.accept()
                    conn.setblocking(False)
                    clients[conn.fileno()] = (conn, b"")
                    continue
                if sock in replica_socks:
                    continue  # router.poll() below reads these
                fd = sock.fileno()
                if fd not in clients:
                    continue
                try:
                    data = sock.recv(65536)
                except OSError:
                    data = b""
                if not data:
                    _drop(fd)
                    continue
                _, cbuf = clients[fd]
                cbuf += data
                *lines, cbuf = cbuf.split(b"\n")
                clients[fd] = (sock, cbuf)
                for raw in lines:
                    if not raw.strip():
                        continue
                    rej = _submit_obj(
                        router, raw.decode("utf-8", "replace"), client=fd
                    )
                    if rej is not None:
                        send(fd, rej)
            for client, ev in router.poll():
                if client is not None:
                    send(client, ev)
            if tick is not None:
                tick()
            ticks += 1
            if metrics_every and ticks % metrics_every == 0:
                publish(ticks)
    finally:
        for fd in list(clients):
            _drop(fd)
        srv.close()
        if os.path.exists(socket_path):
            os.unlink(socket_path)


if __name__ == "__main__":
    main()
