"""Router CLI — elastic multi-replica serving front-end.

Fans the same JSONL request protocol cli/serve.py speaks across N
serve replicas (each `cli/serve --socket ... --journal_dir ...` with
its OWN journal), and survives replica death by journal-ownership
handoff (serving/router.py). Two ways to get a fleet:

  * point at running replicas:

        progen-tpu-router \
          --replica sock=/run/r0.sock,journal=/var/r0,prom=/var/r0/m.prom \
          --replica sock=/run/r1.sock,journal=/var/r1,prom=/var/r1/m.prom

  * or spawn one (dev/smoke): ``--spawn 2 --checkpoint_path ./ckpts
    --fleet_dir ./fleet`` starts two serve subprocesses with per-replica
    socket/journal/prom files under ``fleet_dir/replica{i}/``;
    ``--respawn`` restarts a dead replica with ``--replay`` of its own
    journal — safe against double-serving because the handoff writes
    ``handed_off`` ownership marks BEFORE any restart can replay.

Requests arrive on stdin (default), a unix socket (--socket PATH), or
framed TCP (--listen_tcp HOST:PORT — fleet/transport.py), exactly as
cli/serve.py: one JSON object per line, ``id`` required, optional
``tenant`` for per-tenant quotas. Token/done/rejected events stream
back interleaved. Replicas may be remote too: ``--replica
tcp=HOST:PORT,...`` dials the framed transport a ``serve --tcp``
process listens on. Shedding reasons the router adds on top of the
replica's: ``router_queue_full``, ``tenant_quota``, ``draining``,
``no_replicas``, ``replica_lost``.

AUTOSCALING (fleet/autoscaler.py): ``--autoscale POLICY.toml
--autoscale_tsdb DIR`` runs a policy tick against the fleet
collector's ring TSDB inside the spawned-fleet loop. Scale-up revives
the lowest retired replica slot (or grows the fleet) and spawns its
serve process with ``--replay`` of its own journal; scale-down retires
the highest live index — no new work, queued requests released back to
the router (journaled ``handed_off``), SIGTERM once its slots drain
(or on the grace deadline; the EOF rides the normal handoff path
either way, so accepted work is never lost). Every up/down decision
(and each hold-reason change) lands as an ``ev:"scale"`` record in the
router's events.jsonl.

SIGTERM/SIGINT drains: intake closes, queued requests are shed with
reason ``draining``, in-flight streams (and any handoffs their
replicas' deaths force) run to completion, then exit 0. A second
signal kills immediately (open request tracks are closed with reason
``killed`` first, so the post-mortem trace is honest).

Router metrics render under the ``progen_router_`` Prometheus prefix
(--prom_file / --prom_port) and land in the tracker under ``router/``.

Run: python -m progen_tpu.cli.router --spawn 2 --checkpoint_path ./ckpts
"""

from __future__ import annotations

from progen_tpu.utils.env import load_env_file

load_env_file()  # env flags before any heavy import (ref serve.py)

import json
import os
import select
import signal
import socket as socketlib
import subprocess
import sys
import time

import click


@click.command()
@click.option("--replica", "replica_specs", multiple=True,
              help="replica endpoint, repeatable: 'sock=PATH' or "
                   "'tcp=HOST:PORT', plus "
                   "'[,journal=DIR][,prom=FILE][,name=N]', or a "
                   "bare socket path (no journal = no handoff, only "
                   "re-dispatch of never-accepted requests)")
@click.option("--spawn", default=0,
              help="spawn N serve replicas under --fleet_dir instead of "
                   "connecting to --replica endpoints")
@click.option("--checkpoint_path", default="./ckpts",
              help="checkpoint for spawned replicas")
@click.option("--fleet_dir", default="./fleet", type=str,
              help="per-replica socket/journal/prom/log files land in "
                   "FLEET_DIR/replica{i}/")
@click.option("--respawn/--no-respawn", default=False,
              help="restart a dead spawned replica with --replay of its "
                   "own journal (handed-off work is skipped via its "
                   "ownership marks)")
@click.option("--replica-max-slots", default=8,
              help="--max-slots for spawned replicas")
@click.option("--replica-max-queue", default=64,
              help="--max-queue for spawned replicas")
@click.option("--max-len", default=None, type=int,
              help="--max-len for spawned replicas")
@click.option("--replica_reload_watch", default=0.0, type=float,
              help="spawned replicas watch their checkpoint dir every N "
                   "seconds (serve --reload_watch) and honor a "
                   "FLEET_DIR/replica{i}/reload.pin control file "
                   "(serve --reload_pin) — the deploy controller's "
                   "per-replica seam (0 = off)")
@click.option("--replica_profile_watch", default=False, is_flag=True,
              help="spawned replicas watch "
                   "FLEET_DIR/replica{i}/profile.pin (serve "
                   "--profile_pin) for on-demand jax.profiler windows "
                   "and arm their flight recorders (dumps to "
                   "replica{i}/flight/) — the collector's auto-profile "
                   "and crash-forensics seam, per replica")
@click.option("--flight_dir", default=None, type=str,
              help="arm the ROUTER's own flight recorder: bounded ring "
                   "of recent routing telemetry, dumped atomically here "
                   "on crash paths")
@click.option("--max-queue", default=256,
              help="router admission queue bound (shed reason "
                   "'router_queue_full' beyond it)")
@click.option("--tenant_quota", default=0,
              help="max outstanding requests per 'tenant' field "
                   "(0 = unlimited; shed reason 'tenant_quota')")
@click.option("--heartbeat_timeout", default=30.0, type=float,
              help="deprioritize a replica whose prom-file heartbeat is "
                   "older than this many seconds")
@click.option("--socket", "socket_path", default=None, type=str,
              help="serve a unix domain socket at PATH instead of "
                   "stdin/stdout")
@click.option("--listen_tcp", default=None, type=str,
              help="serve framed TCP at HOST:PORT (fleet transport; "
                   "PORT 0 = ephemeral, bound port printed on stderr)")
@click.option("--autoscale", "autoscale_policy", default=None, type=str,
              help="autoscale the --spawn fleet from the [autoscaler] "
                   "table of this TOML policy file (fleet/autoscaler.py)")
@click.option("--autoscale_tsdb", default=None, type=str,
              help="the fleet collector's ring-TSDB directory the "
                   "autoscaler reads its signals from (required with "
                   "--autoscale)")
@click.option("--metrics-every", default=0,
              help="log a router/ metrics snapshot (and rewrite "
                   "--prom_file) every N loop ticks (0 = only at exit)")
@click.option("--prom_file", default=None, type=str,
              help="write progen_router_* Prometheus text here")
@click.option("--prom_port", default=0,
              help="serve progen_router_* metrics over HTTP on this "
                   "localhost port (0 = off)")
def main(replica_specs, spawn, checkpoint_path, fleet_dir, respawn,
         replica_max_slots, replica_max_queue, max_len,
         replica_reload_watch, replica_profile_watch, flight_dir,
         max_queue, tenant_quota,
         heartbeat_timeout, socket_path, listen_tcp,
         autoscale_policy, autoscale_tsdb, metrics_every,
         prom_file, prom_port):
    from progen_tpu import telemetry
    from progen_tpu.resilience.chaos import ChaosError, install_from_env
    from progen_tpu.serving.router import Router, parse_replica_spec
    from progen_tpu.telemetry import (
        prometheus_text,
        start_prometheus_server,
        write_prometheus,
    )
    from progen_tpu.tracking import make_tracker

    # router chaos sites (router/connect, router/dispatch,
    # router/handoff) arm from the environment, same as cli/serve.py
    install_from_env()

    if spawn and replica_specs:
        sys.exit("use --spawn or --replica, not both")
    if not spawn and not replica_specs:
        sys.exit("no fleet: pass --replica specs or --spawn N")
    if autoscale_policy and not spawn:
        sys.exit("--autoscale needs --spawn (the router must own the "
                 "replica processes it scales)")
    if autoscale_policy and not autoscale_tsdb:
        sys.exit("--autoscale needs --autoscale_tsdb DIR (the fleet "
                 "collector's TSDB is the policy's signal source)")

    procs = {}  # replica index -> (Popen, replica_dir, log file)

    def _spawn_replica(i, replay=False):
        rdir = os.path.join(fleet_dir, f"replica{i}")
        os.makedirs(rdir, exist_ok=True)
        args = [
            sys.executable, "-m", "progen_tpu.cli.serve",
            "--checkpoint_path", checkpoint_path,
            "--socket", os.path.join(rdir, "serve.sock"),
            "--journal_dir", rdir,
            "--prom_file", os.path.join(rdir, "metrics.prom"),
            "--metrics-every", "4",
            "--max-slots", str(replica_max_slots),
            "--max-queue", str(replica_max_queue),
        ]
        if max_len is not None:
            args += ["--max-len", str(max_len)]
        if replica_reload_watch:
            args += [
                "--reload_watch", str(replica_reload_watch),
                "--reload_pin", os.path.join(rdir, "reload.pin"),
            ]
        if replica_profile_watch:
            args += [
                "--profile_pin", os.path.join(rdir, "profile.pin"),
                "--flight_dir", os.path.join(rdir, "flight"),
            ]
        if replay:
            args += ["--replay", rdir]
        log = open(os.path.join(rdir, "replica.log"), "ab")
        proc = subprocess.Popen(
            args, stdin=subprocess.DEVNULL, stdout=log, stderr=log
        )
        procs[i] = (proc, rdir, log)
        print(
            f"replica{i}: pid {proc.pid}"
            + (" (replaying its journal)" if replay else ""),
            file=sys.stderr,
        )

    def _spawned_spec(i):
        rdir = os.path.join(fleet_dir, f"replica{i}")
        return parse_replica_spec(
            f"sock={os.path.join(rdir, 'serve.sock')},"
            f"journal={rdir},"
            f"prom={os.path.join(rdir, 'metrics.prom')}"
        )

    if spawn:
        specs = []
        for i in range(spawn):
            specs.append(_spawned_spec(i))
            _spawn_replica(i)
    else:
        specs = [parse_replica_spec(s) for s in replica_specs]

    router = Router(
        specs, max_queue=max_queue, tenant_quota=tenant_quota,
        heartbeat_timeout=heartbeat_timeout,
    )
    tracker = make_tracker("progen-router")
    telemetry.configure(sink=tracker.log_event)
    from progen_tpu.telemetry import flight as flight_mod
    if flight_dir:
        flight_mod.arm(flight_dir, metrics_fn=router.metrics.snapshot)
    run_dir = getattr(tracker, "path", None)
    if run_dir is not None:
        print(
            f"router traces: {run_dir}/events.jsonl "
            "(render with progen-tpu-telemetry export-trace)",
            file=sys.stderr,
        )

    def publish(step=None):
        router.metrics.log_to(tracker, step=step, prefix="router/")
        if prom_file:
            write_prometheus(
                prom_file,
                prometheus_text(router.metrics, prefix="progen_router_"),
            )

    prom_srv = None
    if prom_port:
        prom_srv = start_prometheus_server(
            lambda: prometheus_text(
                router.metrics, prefix="progen_router_"
            ),
            port=prom_port,
        )
        print(
            f"prometheus on http://127.0.0.1:"
            f"{prom_srv.server_address[1]}/metrics",
            file=sys.stderr,
        )
    print(
        f"routing across {len(specs)} replica(s): "
        + ", ".join(s.endpoint for s in specs),
        file=sys.stderr,
    )

    # ----- autoscaler executor (fleet/autoscaler.py decides, this
    # closure acts on the spawned fleet) -------------------------------
    autoscale_fn = None
    scale_state = {"next": 0.0, "draining": {}}  # index -> grace deadline
    if autoscale_policy:
        from progen_tpu.fleet.autoscaler import (
            ACTION_DOWN,
            ACTION_UP,
            Autoscaler,
            load_policy,
        )
        from progen_tpu.telemetry.tsdb import TsdbReader

        policy = load_policy(autoscale_policy)
        scaler = Autoscaler(policy, reader=TsdbReader(autoscale_tsdb))
        router.rebalance_max = policy.rebalance_max
        # a retiring replica gets this long to finish its decode slots
        # before SIGTERM stops waiting (SIGTERM itself is still a
        # graceful drain on the serve side)
        drain_grace_s = max(10.0, policy.interval_s * 5)
        print(
            f"autoscaler: {policy.min_replicas}..{policy.max_replicas} "
            f"replicas, tick {policy.interval_s}s, tsdb {autoscale_tsdb}",
            file=sys.stderr,
        )

        def _scale_up(n):
            for _ in range(n):
                reusable = sorted(
                    link.index for link in router.links
                    if link.retired and link.index not in procs
                    and link.index not in scale_state["draining"]
                )
                if reusable:
                    i = reusable[0]
                    router.revive_replica(i)
                else:
                    i = router.add_replica(_spawned_spec(len(router.links)))
                # --replay unconditionally: a no-op on a fresh journal,
                # and on a reused slot it resumes whatever the handoff
                # didn't settle (the handed_off ownership marks make
                # double-serving impossible)
                _spawn_replica(i, replay=True)

        def _scale_down(n, now):
            live = sorted(
                (link.index for link in router.links if not link.retired),
                reverse=True,
            )
            for i in live[:n]:
                router.retire_replica(i)
                scale_state["draining"][i] = now + drain_grace_s
                print(f"replica{i}: retiring (scale-down)",
                      file=sys.stderr)

        def _reap_draining(now):
            for i, deadline in list(scale_state["draining"].items()):
                entry = procs.get(i)
                if entry is None:
                    # already exited; tick() reaped the process
                    scale_state["draining"].pop(i)
                    continue
                if router.links[i].inflight and now < deadline:
                    continue  # still streaming: let it finish
                # SIGTERM = serve's graceful drain (in-flight slots run
                # to completion, journal/metrics flush, exit 0). What it
                # rejects as 'draining' the router re-routes; if it dies
                # instead, the EOF rides the normal handoff path. Zero
                # accepted requests lost either way.
                entry[0].terminate()
                scale_state["draining"].pop(i)

        def _autoscale_tick():
            now = time.monotonic()
            _reap_draining(now)
            if now < scale_state["next"]:
                return
            scale_state["next"] = now + policy.interval_s
            n_current = sum(
                1 for link in router.links if not link.retired
            )
            try:
                decision = scaler.decide(n_current)
            except ChaosError:
                # autoscaler/decide chaos: a transient fault costs one
                # tick, never the fleet
                return
            if decision.action == ACTION_UP:
                _scale_up(decision.target - n_current)
            elif decision.action == ACTION_DOWN:
                _scale_down(n_current - decision.target, now)

        autoscale_fn = _autoscale_tick

    shutdown = {"flag": False}

    def _request_drain(signum, frame):
        if shutdown["flag"]:
            print(f"signal {signum} again: exiting now", file=sys.stderr)
            try:
                router.close_tracks("killed")
            except Exception:
                pass  # a torn trace line beats a hung exit
            flight_mod.dump_now("killed", note=f"signal {signum}")
            sys.stderr.flush()
            os._exit(1)
        shutdown["flag"] = True
        print(
            f"signal {signum}: draining — intake closed, queued requests "
            "shed, in-flight streams finishing; signal again to kill",
            file=sys.stderr,
        )

    def tick():
        """Once per front-loop iteration, AFTER router.poll() — so a
        dead spawned replica's handoff (triggered by the socket EOF
        inside poll) has already written its ownership marks before any
        --respawn replay can read the journal."""
        if shutdown["flag"]:
            return
        for i, (proc, rdir, log) in list(procs.items()):
            if proc.poll() is None:
                continue
            del procs[i]
            log.close()
            print(
                f"replica{i}: exited rc={proc.returncode}",
                file=sys.stderr,
            )
            # a retired replica's exit is the scale-down completing,
            # not a death to heal
            if respawn and not router.links[i].up \
                    and not router.links[i].retired:
                _spawn_replica(i, replay=True)
        if autoscale_fn is not None:
            autoscale_fn()

    old_term = signal.signal(signal.SIGTERM, _request_drain)
    old_int = signal.signal(signal.SIGINT, _request_drain)
    try:
        if listen_tcp:
            _front_tcp(router, listen_tcp, publish, metrics_every,
                       shutdown, tick=tick)
        elif socket_path:
            _front_socket(router, socket_path, publish, metrics_every,
                          shutdown, tick=tick)
        else:
            _front_stdio(router, publish, metrics_every, shutdown,
                         tick=tick)
    finally:
        signal.signal(signal.SIGTERM, old_term)
        signal.signal(signal.SIGINT, old_int)
        publish()
        if prom_srv is not None:
            prom_srv.shutdown()
        for i, (proc, rdir, log) in procs.items():
            proc.terminate()
        for i, (proc, rdir, log) in procs.items():
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
            log.close()
        flight_mod.disarm()
        telemetry.configure()  # detach before the sink closes
        tracker.finish()


def _submit_obj(router, line, client=None):
    """Parse + submit one request line; returns a rejection event dict
    to answer immediately, or None."""
    try:
        obj = json.loads(line)
        if not isinstance(obj, dict):
            raise ValueError("request must be a JSON object")
    except ValueError as e:
        return {"event": "rejected", "id": None,
                "reason": f"bad request line: {e}"}
    return router.submit(obj, client=client)


def _front_stdio(router, publish, metrics_every, shutdown, tick=None):
    """stdin-JSONL front: one select loop over {stdin, replica sockets}
    — new requests and replica events interleave without polling sleeps.
    Same raw-fd line buffering as cli/serve.py (select()+readline()
    loses lines). EOF or a drain signal closes intake; the loop runs
    until the router settles everything it accepted."""
    out = sys.stdout
    eof = False
    drained = False
    buf = ""
    ticks = 0

    def emit(ev):
        out.write(json.dumps(ev) + "\n")
        out.flush()

    while True:
        if shutdown["flag"] and not drained:
            drained = True
            router.drain()
        if (eof or shutdown["flag"]) and not router.has_work:
            break
        rlist = ([] if (eof or shutdown["flag"]) else [sys.stdin])
        rlist += router.fds()
        # bounded wait: backoffs/reconnects need the loop to turn even
        # when no fd is hot
        timeout = 0.05 if router.has_work else 0.2
        try:
            if rlist:
                select.select(rlist, [], [], timeout)
        except OSError:
            pass  # a replica socket died between fds() and select
        while not eof and not shutdown["flag"]:
            nl = buf.find("\n")
            if nl < 0:
                try:
                    ready, _, _ = select.select([sys.stdin], [], [], 0.0)
                except OSError:
                    break
                if not ready:
                    break
                data = os.read(sys.stdin.fileno(), 65536)
                if not data:
                    eof = True
                    line, buf = buf, ""
                else:
                    buf += data.decode("utf-8", errors="replace")
                    continue
            else:
                line, buf = buf[:nl], buf[nl + 1:]
            if not line.strip():
                continue
            rej = _submit_obj(router, line)
            if rej is not None:
                emit(rej)
        for _, ev in router.poll():
            emit(ev)
        if tick is not None:
            tick()
        ticks += 1
        if metrics_every and ticks % metrics_every == 0:
            publish(ticks)


def _front_socket(router, socket_path, publish, metrics_every, shutdown,
                  tick=None):
    """Unix-socket front: each connection submits requests and receives
    exactly its own events (the router's per-request ``client`` handle
    is the connection fd). On drain the listener closes, the queue is
    shed, in-flight streams finish to their clients, then exit."""
    if os.path.exists(socket_path):
        os.unlink(socket_path)
    srv = socketlib.socket(socketlib.AF_UNIX, socketlib.SOCK_STREAM)
    srv.bind(socket_path)
    srv.listen(16)
    srv.setblocking(False)
    clients = {}  # fd -> (sock, recv_buffer)
    ticks = 0
    drained = False
    print(f"listening on {socket_path}", file=sys.stderr)

    def send(fd, ev):
        sock, _ = clients.get(fd, (None, None))
        if sock is None:
            return
        try:
            sock.sendall(json.dumps(ev).encode() + b"\n")
        except OSError:
            _drop(fd)

    def _drop(fd):
        sock, _ = clients.pop(fd, (None, None))
        if sock is not None:
            sock.close()

    try:
        while True:
            if shutdown["flag"] and not drained:
                drained = True
                srv.close()  # refuse new connections during drain
                router.drain()
            if shutdown["flag"] and not router.has_work:
                break
            rlist = ([] if drained else [srv])
            rlist += [s for s, _ in clients.values()]
            rlist += router.fds()
            timeout = 0.05 if router.has_work else 0.2
            try:
                ready, _, _ = (
                    select.select(rlist, [], [], timeout)
                    if rlist else ([], [], [])
                )
            except OSError:
                continue  # a peer vanished between list and select
            replica_socks = set(router.fds())
            for sock in ready:
                if sock is srv:
                    conn, _ = srv.accept()
                    conn.setblocking(False)
                    clients[conn.fileno()] = (conn, b"")
                    continue
                if sock in replica_socks:
                    continue  # router.poll() below reads these
                fd = sock.fileno()
                if fd not in clients:
                    continue
                try:
                    data = sock.recv(65536)
                except OSError:
                    data = b""
                if not data:
                    _drop(fd)
                    continue
                _, cbuf = clients[fd]
                cbuf += data
                *lines, cbuf = cbuf.split(b"\n")
                clients[fd] = (sock, cbuf)
                for raw in lines:
                    if not raw.strip():
                        continue
                    rej = _submit_obj(
                        router, raw.decode("utf-8", "replace"), client=fd
                    )
                    if rej is not None:
                        send(fd, rej)
            for client, ev in router.poll():
                if client is not None:
                    send(client, ev)
            if tick is not None:
                tick()
            ticks += 1
            if metrics_every and ticks % metrics_every == 0:
                publish(ticks)
    finally:
        for fd in list(clients):
            _drop(fd)
        srv.close()
        if os.path.exists(socket_path):
            os.unlink(socket_path)


def _front_tcp(router, hostport, publish, metrics_every, shutdown,
               tick=None):
    """Framed-TCP front (fleet/transport.py): the unix-socket front
    with frames instead of newlines. Each connection submits requests
    and receives exactly its own events; a framing violation reads as
    EOF and drops only that client."""
    from progen_tpu.fleet.transport import FramedListener, parse_hostport

    host, port = parse_hostport(hostport)
    listener = FramedListener(host, port)
    clients = {}  # fd -> FramedConnection
    ticks = 0
    drained = False
    print(f"listening on tcp {listener.host}:{listener.port}",
          file=sys.stderr)
    sys.stderr.flush()

    def send(fd, ev):
        conn = clients.get(fd)
        if conn is None:
            return
        try:
            conn.send_line(json.dumps(ev))
        except OSError:
            _drop(fd)

    def _drop(fd):
        conn = clients.pop(fd, None)
        if conn is not None:
            conn.close()

    try:
        while True:
            if shutdown["flag"] and not drained:
                drained = True
                listener.close()  # refuse new dials during drain
                router.drain()
            if shutdown["flag"] and not router.has_work:
                break
            rlist = ([] if drained else [listener])
            rlist += list(clients.values())
            rlist += router.fds()
            timeout = 0.05 if router.has_work else 0.2
            try:
                ready, _, _ = (
                    select.select(rlist, [], [], timeout)
                    if rlist else ([], [], [])
                )
            except OSError:
                continue  # a peer vanished between list and select
            replica_socks = set(router.fds())
            for obj in ready:
                if obj is listener:
                    conn = listener.accept()
                    if conn is not None:
                        clients[conn.fileno()] = conn
                    continue
                if obj in replica_socks:
                    continue  # router.poll() below reads these
                if getattr(obj, "sock", None) is None:
                    continue  # dropped earlier this iteration
                fd = obj.fileno()
                if fd not in clients:
                    continue
                lines, eof = obj.recv_lines()
                for line in lines:
                    if not line.strip():
                        continue
                    rej = _submit_obj(router, line, client=fd)
                    if rej is not None:
                        send(fd, rej)
                if eof:
                    _drop(fd)
            for client, ev in router.poll():
                if client is not None:
                    send(client, ev)
            if tick is not None:
                tick()
            ticks += 1
            if metrics_every and ticks % metrics_every == 0:
                publish(ticks)
    finally:
        for fd in list(clients):
            _drop(fd)
        listener.close()


if __name__ == "__main__":
    main()
