"""Deploy controller CLI — continuous deployment for a serve fleet.

Watches a checkpoint directory and runs the canary → probe → promote →
converge pipeline (progen_tpu/deploy/controller.py) against a fleet of
replicas that honor ``reload.pin`` control files (serve
``--reload_pin``, or router ``--spawn --replica_reload_watch``). Every
decision lands in the fsync'd ``deploy.jsonl`` ledger under
``--deploy_dir``; kill the controller at any phase and a restart
replays the ledger and resumes idempotently.

Point it at a spawned fleet's directory (replicas discovered as
``FLEET_DIR/replica*/``):

    progen-tpu-deploy --checkpoint_path ./ckpts --fleet_dir ./fleet \\
        --probe_fasta probe.fasta --policy configs/serving/deploy.toml \\
        --tsdb ./tsdb --alerts ./fleet/alerts.jsonl

or name replicas explicitly with ``--replica name=DIR`` (DIR holds the
replica's reload.pin / reload.pin.ack). Start the controller BEFORE
publishing candidate checkpoints: its adopt step pins every replica to
the current fleet checkpoint, so no replica's newest-wins watcher can
self-upgrade past the canary gate.

Rollbacks page through the alert pipeline: ``--alerts`` appends
``deploy_rollback`` alerts to an AlertSink ledger (edge-deduped =
exactly-once per checkpoint across restarts) and ``--alert_config``
additionally routes them (webhook/stderr/file + escalation chains,
telemetry/alert_router.py).

Run: python -m progen_tpu.cli.deploy --checkpoint_path ./ckpts \\
         --fleet_dir ./fleet --once
"""

from __future__ import annotations

from progen_tpu.utils.env import load_env_file

load_env_file()  # env flags before any heavy import (ref serve.py)

import glob
import os
import signal
import sys
import time

import click


@click.command()
@click.option("--checkpoint_path", default="./ckpts",
              help="the checkpoint dir the trainer publishes into")
@click.option("--fleet_dir", default=None, type=str,
              help="discover replicas as FLEET_DIR/replica*/ (the "
                   "router --spawn layout)")
@click.option("--replica", "replica_specs", multiple=True,
              help="explicit replica, repeatable: 'name=DIR' (DIR "
                   "holds reload.pin/reload.pin.ack)")
@click.option("--deploy_dir", default=None, type=str,
              help="ledger + probe outputs land here (default: "
                   "FLEET_DIR/deploy)")
@click.option("--probe_fasta", default=None, type=str,
              help="held-out probe set; without it the probe/ppl gate "
                   "is skipped (canary ack alone gates promotion)")
@click.option("--policy", "policy_path", default=None, type=str,
              help="[deploy] TOML policy (configs/serving/deploy.toml)")
@click.option("--tsdb", default=None, type=str,
              help="the fleet collector's ring-TSDB dir (live ttft "
                   "baseline; optional)")
@click.option("--alerts", "alerts_path", default=None, type=str,
              help="append deploy_rollback alerts to this AlertSink "
                   "ledger (alerts.jsonl)")
@click.option("--alert_config", default=None, type=str,
              help="route alerts through this [route_*] TOML "
                   "(webhooks/escalation; needs --alerts)")
@click.option("--canary", default=None, type=str,
              help="canary replica name (overrides the policy; "
                   "default: first replica)")
@click.option("--interval", default=None, type=float,
              help="tick cadence in seconds (overrides the policy)")
@click.option("--once", is_flag=True, default=False,
              help="one tick, then exit (smoke/CI)")
@click.option("--flight_dir", default=None, type=str,
              help="arm the deploy controller's flight recorder: "
                   "bounded ring of recent deploy telemetry, dumped "
                   "atomically here on crash paths and on anomaly "
                   "rollback")
@click.option("--max_ticks", default=0,
              help="exit after N ticks (0 = run until signalled)")
def main(checkpoint_path, fleet_dir, replica_specs, deploy_dir,
         probe_fasta, policy_path, tsdb, alerts_path, alert_config,
         canary, interval, once, flight_dir, max_ticks):
    import dataclasses

    from progen_tpu import telemetry
    from progen_tpu.deploy import (
        DeployController,
        DeployPolicy,
        Replica,
        load_deploy_policy,
    )
    from progen_tpu.resilience.chaos import install_from_env
    from progen_tpu.tracking import make_tracker

    # deploy chaos sites (deploy/canary, deploy/probe, deploy/promote,
    # deploy/rollback) arm from the environment, same as cli/serve.py
    install_from_env()

    replicas = []
    for spec in replica_specs:
        name, sep, path = spec.partition("=")
        if not sep or not name or not path:
            sys.exit(f"bad --replica {spec!r}: expected name=DIR")
        replicas.append(Replica(name, path))
    if fleet_dir:
        for rdir in sorted(glob.glob(os.path.join(fleet_dir, "replica*"))):
            if os.path.isdir(rdir):
                replicas.append(Replica(os.path.basename(rdir), rdir))
    if not replicas:
        sys.exit("no replicas: pass --fleet_dir or --replica name=DIR")
    if deploy_dir is None:
        if not fleet_dir:
            sys.exit("--deploy_dir is required without --fleet_dir")
        deploy_dir = os.path.join(fleet_dir, "deploy")

    policy = (
        load_deploy_policy(policy_path) if policy_path
        else DeployPolicy()
    )
    if canary is not None:
        policy = dataclasses.replace(policy, canary=canary)
    tick_s = policy.interval_s if interval is None else float(interval)

    reader = None
    if tsdb is not None:
        from progen_tpu.telemetry.tsdb import TsdbReader

        reader = TsdbReader(tsdb)
    alerts = None
    router = None
    if alert_config is not None and alerts_path is None:
        sys.exit("--alert_config needs --alerts (the sink the router "
                 "relays from)")
    if alerts_path is not None:
        from progen_tpu.telemetry.alerts import AlertSink

        if alert_config is not None:
            from progen_tpu.telemetry.alert_router import (
                AlertRouter,
                load_router_config,
            )

            severity, routes = load_router_config(alert_config)
            router = AlertRouter(
                os.path.join(
                    os.path.dirname(alerts_path) or ".",
                    "notifications.jsonl",
                ),
                routes, severity=severity,
            )
        alerts = AlertSink(
            alerts_path,
            relay=router.handle if router is not None else None,
        )

    tracker = make_tracker("progen-deploy")
    telemetry.configure(sink=tracker.log_event)
    from progen_tpu.telemetry import flight as flight_mod
    if flight_dir:
        flight_mod.arm(flight_dir)
    ctrl = DeployController(
        checkpoint_path, replicas, deploy_dir, policy,
        probe_fasta=probe_fasta, reader=reader, alerts=alerts,
    )
    click.echo(
        f"deploy: {len(replicas)} replica(s), canary "
        f"{ctrl.canary.name}, ledger {ctrl.ledger.path}"
        + (f", probe {probe_fasta}" if probe_fasta else ", no probe")
        + (f", tsdb {tsdb}" if tsdb else ""),
        err=True,
    )

    stop = {"flag": False}

    def _stop(signum, frame):
        stop["flag"] = True

    signal.signal(signal.SIGTERM, _stop)
    signal.signal(signal.SIGINT, _stop)

    ticks = 0
    ops = {"rollback": 0, "converged": 0}
    try:
        while not stop["flag"]:
            op = ctrl.tick()
            if op is not None:
                click.echo(
                    f"deploy: {op} "
                    f"(fleet {ctrl.state.fleet}, "
                    f"candidate {ctrl.state.candidate})",
                    err=True,
                )
                if op in ops:
                    ops[op] += 1
            if router is not None:
                router.tick()
            ticks += 1
            if once or (max_ticks and ticks >= max_ticks):
                break
            deadline = time.time() + tick_s
            while not stop["flag"] and time.time() < deadline:
                time.sleep(min(0.2, tick_s))
    finally:
        ctrl.close()
        if alerts is not None:
            alerts.close()
        if router is not None:
            router.close()
        flight_mod.disarm()
        telemetry.configure()  # detach before the sink closes
        tracker.finish()
    click.echo(
        f"deploy: {ticks} ticks, fleet {ctrl.state.fleet}, "
        f"{ops['converged']} converged, {ops['rollback']} rolled back",
        err=True,
    )
    sys.exit(0)


if __name__ == "__main__":
    main()
