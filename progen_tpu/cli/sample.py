"""Sampling CLI — generate a protein sequence from the latest checkpoint.

Parity with /root/reference/sample.py:23-71: the model is rebuilt purely
from the checkpoint's stored config (sample.py:46-47), the prime is
byte-tokenized, decode runs with top_k=25 and add_bos=True, and the output
after the prime is printed. Prime conventions (README.md:82-86):
``"[tax=Mammalia] #"`` generates a sequence; ``"SEQ #"`` generates
annotations.

Fixed-position infilling (progen_tpu/workloads/infill.py): ``--template
"MK?LV??G"`` keeps the non-``?`` characters verbatim and samples the
free slots; the leading frozen run primes the decode, so --prime and
--template are mutually exclusive.

Run: python -m progen_tpu.cli.sample --prime "[tax=Mammalia] #"
"""

from __future__ import annotations

from progen_tpu.utils.env import load_env_file

load_env_file()  # XLA/env flags before jax import (ref train.py:1-2)

import sys

import click
import numpy as np

import jax


@click.command()
@click.option("--seed", default=42)
@click.option("--checkpoint_path", default="./ckpts")
@click.option("--prime", default="")
@click.option("--top_k", default=25)
@click.option("--temperature", default=1.0,
              help="logit temperature before top-k/top-p filtering "
                   "(1.0 = reference parity)")
@click.option("--top_p", default=None, type=float,
              help="nucleus sampling: keep the smallest top-probability "
                   "set with cumulative mass >= p (combines with --top_k; "
                   "unset = reference parity)")
@click.option(
    "--naive",
    default=False,
    is_flag=True,
    help="reference-style full forward per token instead of the KV cache",
)
@click.option(
    "--num_samples",
    default=1,
    help="decode this many sequences from the prime in one batched "
    "KV-cache pass (--naive switches to the full-forward batched decode)",
)
@click.option("--template", default=None, type=str,
              help="infilling template: non-free characters are frozen "
                   "verbatim, --free_char slots are sampled (replaces "
                   "--prime; the frozen prefix primes the decode)")
@click.option("--free_char", default="?",
              help="the free-position sentinel inside --template")
def main(seed, checkpoint_path, prime, top_k, temperature, top_p,
         naive, num_samples, template, free_char):
    from progen_tpu.checkpoint import get_checkpoint_fns
    from progen_tpu.config import ProGenConfig
    from progen_tpu.data.tokenizer import decode_tokens, encode_tokens
    from progen_tpu.models.progen import ProGen
    from progen_tpu.sampling import (
        sample,
        sample_batched,
        sample_fast,
        sample_fast_batched,
    )

    _, get_last, _ = get_checkpoint_fns(checkpoint_path)
    # params-only restore: sampling never needs the optimizer moments
    pkg = get_last.restore_params()
    if pkg is None:
        sys.exit(f"no checkpoints found at {checkpoint_path}")

    config = ProGenConfig.from_dict(pkg.model_config)
    model = ProGen(config)
    params = pkg.state

    num_params = sum(int(np.size(x)) for x in jax.tree.leaves(params))
    print(f"params: {num_params:,}")
    print(f"sequence length: {config.seq_len}")
    print(f"trained for {max(pkg.next_seq_index, 0):,} sequences")

    length = config.seq_len
    tpl_arr = frz_arr = None
    if template is not None:
        from progen_tpu.workloads.infill import (
            infill_request_arrays,
            parse_template,
        )

        if prime:
            sys.exit("--template and --prime are mutually exclusive "
                     "(the template's frozen prefix is the prime)")
        if num_samples > 1:
            sys.exit("--template decodes one sequence (--num_samples 1)")
        toks, frz = parse_template(template, free_char)
        prime_tokens, length, tpl_arr, frz_arr = infill_request_arrays(
            toks, frz, add_bos=True
        )
        prime = decode_tokens(prime_tokens)
    else:
        prime_tokens = np.asarray(encode_tokens(prime), dtype=np.int32)
    prime_length = len(prime_tokens) + 1  # +1 for BOS (sample.py:67)

    if num_samples > 1:
        primes = np.tile(prime_tokens, (num_samples, 1))
        batched_fn = sample_batched if naive else sample_fast_batched
        sampled = batched_fn(
            jax.random.PRNGKey(seed), model, params, primes,
            config.seq_len, top_k=top_k, add_bos=True,
            temperature=temperature, top_p=top_p,
        )
        print("\n", prime, "\n", "*" * 40)
        for row in np.asarray(sampled):
            print(decode_tokens(row[prime_length:]), "\n", "-" * 40)
        return

    sample_fn = sample if naive else sample_fast
    sampled = sample_fn(
        jax.random.PRNGKey(seed),
        model,
        params,
        prime_tokens,
        length,
        top_k=top_k,
        add_bos=True,
        temperature=temperature,
        top_p=top_p,
        template=tpl_arr,
        frozen=frz_arr,
    )
    sampled_str = decode_tokens(np.asarray(sampled)[prime_length:])
    print("\n", prime, "\n", "*" * 40, "\n", sampled_str)


if __name__ == "__main__":
    main()
