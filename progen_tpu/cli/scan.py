"""Mutagenesis scan CLI — score every point mutant of a sequence.

In-silico deep mutational scanning (progen_tpu/workloads/mutagenesis.py):
the L x 20 substitution matrix is built and scored inside one compiled
program, ranked by ``delta_nll = wt_nll - mutant_nll`` (positive = the
model prefers the mutant). The full (positions x alphabet) NLL matrix
plus the top-K table can be written as JSON with ``--out``.

Run: python -m progen_tpu.cli.scan --checkpoint_path ./ckpts \
         --sequence MKTAYIAKQR --context "[tax=Mammalia]"
"""

from __future__ import annotations

from progen_tpu.utils.env import load_env_file

load_env_file()  # XLA/env flags before jax import (ref train.py:1-2)

import json
import sys

import click


def _parse_positions(spec, seq_len):
    """"START:END" (0-based, half-open) or a comma list -> indices."""
    if spec is None:
        return None
    if ":" in spec:
        start_s, end_s = spec.split(":", 1)
        start = int(start_s) if start_s else 0
        end = int(end_s) if end_s else seq_len
        return range(start, end)
    return [int(p) for p in spec.split(",") if p.strip()]


@click.command()
@click.option("--checkpoint_path", default="./ckpts")
@click.option("--sequence", default=None,
              help="the amino-acid sequence to scan (or use --fasta)")
@click.option("--fasta", default=None, type=str,
              help="take the sequence from this FASTA file instead")
@click.option("--index", default=0,
              help="which FASTA record to scan (0-based)")
@click.option("--context", default="",
              help="conditioning tag (scored as 'context # SEQ')")
@click.option("--positions", default=None, type=str,
              help="residues to scan: 'START:END' (0-based, half-open) "
                   "or 'p1,p2,...' (default: every position)")
@click.option("--top", default=20, help="report the K best substitutions")
@click.option("--chunk", default=32,
              help="mutants scored per lax.map step (peak-memory knob)")
@click.option("--out", "out_path", default=None, type=str,
              help="write the full report (NLL matrix + top table) as "
                   "JSON here")
def main(checkpoint_path, sequence, fasta, index, context, positions,
         top, chunk, out_path):
    from progen_tpu.checkpoint import get_checkpoint_fns
    from progen_tpu.config import ProGenConfig
    from progen_tpu.models.progen import ProGen
    from progen_tpu.workloads import mutagenesis_scan

    if (sequence is None) == (fasta is None):
        sys.exit("pass exactly one of --sequence / --fasta")
    if fasta is not None:
        from progen_tpu.data.fasta import parse_fasta

        recs = list(parse_fasta(fasta))
        if not 0 <= index < len(recs):
            sys.exit(f"--index {index} outside {len(recs)} FASTA records")
        sequence = recs[index][1]

    _, get_last, _ = get_checkpoint_fns(checkpoint_path)
    pkg = get_last.restore_params()  # params only: no optimizer moments
    if pkg is None:
        sys.exit(f"no checkpoints found at {checkpoint_path}")
    config = ProGenConfig.from_dict(pkg.model_config)
    model = ProGen(config)

    report = mutagenesis_scan(
        model, pkg.state, sequence, context=context,
        positions=_parse_positions(positions, len(sequence)),
        chunk=chunk, top=top,
    )
    print(f"wild-type NLL: {report['wt_nll']:.4f} "
          f"({len(report['positions'])} positions x "
          f"{len(report['alphabet'])} substitutions)")
    print(f"{'pos':>5} {'wt':>3} {'mut':>4} {'nll':>9} {'delta_nll':>10}")
    for e in report["top"]:
        print(f"{e['pos']:>5} {e['wt']:>3} {e['aa']:>4} "
              f"{e['nll']:>9.4f} {e['delta_nll']:>+10.4f}")

    if out_path:
        doc = dict(report)
        doc["nll"] = [[float(x) for x in row] for row in report["nll"]]
        doc["positions"] = [int(p) for p in report["positions"]]
        with open(out_path, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=2)
        print(f"report written to {out_path}")


if __name__ == "__main__":
    main()
