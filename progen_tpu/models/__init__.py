from progen_tpu.models.progen import ProGen

__all__ = ["ProGen"]
