"""The ProGen model: a decoder-only protein LM, batch-first, TPU-sharded.

Architecture parity with /root/reference/progen_transformer/progen.py:187-233:
token embed -> depth x (LocalAttention + FeedForward) with residual adds,
the last `global_mlp_depth` layers using gMLP (spatial-gate) feed-forwards
with GLU disabled (progen.py:211-212), then scale-only LayerNorm + linear
logits head (no weight tying).

TPU-first deltas:
  * real leading batch axis (the reference is single-sequence + external vmap,
    progen.py:224-227) so XLA sees one large MXU-friendly program;
  * mixed precision bf16 compute / f32 params / f32 logits (the jmp policy of
    progen.py:235 with bf16, which is native to the MXU);
  * flax logical-axis metadata on every weight, consumed by
    progen_tpu/parallel/partition.py to lay the model over a device mesh;
  * optional per-block rematerialization (config.remat) to trade FLOPs for
    HBM during backprop.
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import linen as nn

from progen_tpu.config import ProGenConfig
from progen_tpu.models.layers import (
    FeedForwardBlock,
    LocalAttentionBlock,
    ScaleNorm,
)
from progen_tpu.ops.rotary import fixed_pos_embedding


class ProGen(nn.Module):
    config: ProGenConfig

    @nn.compact
    def __call__(self, tokens: jnp.ndarray) -> jnp.ndarray:
        """tokens: (batch, seq_len) integer array. Returns float32 logits of
        shape (batch, seq_len, num_tokens)."""
        c = self.config
        n = tokens.shape[-1]

        x = nn.Embed(
            c.num_tokens,
            c.dim,
            dtype=c.compute_dtype,
            param_dtype=c.params_dtype,
            embedding_init=nn.with_logical_partitioning(
                nn.initializers.truncated_normal(stddev=0.02), ("vocab", "embed")
            ),
            name="embed",
        )(tokens)
        x = nn.with_logical_constraint(x, ("batch", "seq_act", "embed_act"))

        if c.decode:
            # one-token step: full-length RoPE tables (blocks slice their
            # row), one shared position counter advanced per call
            pos_var = self.variable(
                "cache", "pos", lambda: jnp.zeros((), jnp.int32)
            )
            pos = pos_var.value
            sin, cos = fixed_pos_embedding(c.seq_len, c.dim_head)
        else:
            pos = None
            # RoPE tables are tiny; build in f32 once per trace (progen.py:227)
            sin, cos = fixed_pos_embedding(n, c.dim_head)

        attn_cls, ff_cls = LocalAttentionBlock, FeedForwardBlock
        if c.remat and not c.decode:
            attn_cls = nn.remat(LocalAttentionBlock)
            ff_cls = nn.remat(FeedForwardBlock)

        for i in range(c.depth):
            use_gmlp = (c.depth - i) <= c.global_mlp_depth
            use_glu = (not use_gmlp) and c.ff_glu
            x = x + attn_cls(c, name=f"attn{i}")(x, sin, cos, pos)
            x = x + ff_cls(
                c, glu=use_glu, spatial_gate=use_gmlp, name=f"ff{i}"
            )(x, pos)
            x = nn.with_logical_constraint(x, ("batch", "seq_act", "embed_act"))

        if c.decode and not self.is_initializing():
            pos_var.value = pos + 1

        x = ScaleNorm(c.layer_norm_epsilon, c.compute_dtype, c.params_dtype)(x)
        logits = nn.Dense(
            c.num_tokens,
            dtype=c.compute_dtype,
            param_dtype=c.params_dtype,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), ("embed", "vocab")
            ),
            bias_init=nn.with_logical_partitioning(
                nn.initializers.zeros, ("vocab",)
            ),
            name="to_logits",
        )(x)
        return logits.astype(jnp.float32)
