"""The ProGen model: a decoder-only protein LM, batch-first, TPU-sharded.

Architecture parity with /root/reference/progen_transformer/progen.py:187-233:
token embed -> depth x (LocalAttention + FeedForward) with residual adds,
the last `global_mlp_depth` layers using gMLP (spatial-gate) feed-forwards
with GLU disabled (progen.py:211-212), then scale-only LayerNorm + linear
logits head (no weight tying).

TPU-first deltas:
  * real leading batch axis (the reference is single-sequence + external vmap,
    progen.py:224-227) so XLA sees one large MXU-friendly program;
  * mixed precision bf16 compute / f32 params / f32 logits (the jmp policy of
    progen.py:235 with bf16, which is native to the MXU);
  * flax logical-axis metadata on every weight, consumed by
    progen_tpu/parallel/partition.py to lay the model over a device mesh;
  * optional per-block rematerialization (config.remat) to trade FLOPs for
    HBM during backprop;
  * optional lax.scan over the uniform blocks (config.scan_layers) for
    O(1)-in-depth compile;
  * embedding init truncated_normal(stddev=0.02) — a deliberate delta from
    hk.Embed's TruncatedNormal(stddev=1.0) default (ref progen.py:207);
    the GPT-style small init trains more stably. Weight-transplant parity
    tests are init-independent (tests/test_reference_parity.py).
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import linen as nn

from progen_tpu.config import ProGenConfig
from progen_tpu.models.layers import (
    FeedForwardBlock,
    LocalAttentionBlock,
    ScaleNorm,
)
from progen_tpu.ops.rotary import fixed_pos_embedding


class UniformBlock(nn.Module):
    """One attention+FF residual pair — the scan body for the uniform
    (non-gMLP) prefix of the stack when config.scan_layers is set."""

    config: ProGenConfig
    glu: bool
    mesh: object = None

    @nn.compact
    def __call__(self, x, sin, cos):
        c = self.config
        x = x + LocalAttentionBlock(c, mesh=self.mesh, name="attn")(
            x, sin, cos, None
        )
        x = x + FeedForwardBlock(c, glu=self.glu, name="ff")(x, None)
        x = nn.with_logical_constraint(x, ("batch", "seq_act", "embed_act"))
        return x, None


def decode_model(model: "ProGen") -> "ProGen":
    """The decode-mode twin of a full-forward model: same weight tree
    (scan-stacked layouts convert via ``unstack_params`` — decode is always
    unrolled because its per-layer caches are), one token per call, state
    in a flax 'cache' collection (rolling 2-window K/V ring, token-shift
    states, SGU gate history, and a position counter — all allocated
    batch-shaped by ``init``, which is the cache-shape hook the sampling
    and serving layers build their buffers from)."""
    import dataclasses

    return ProGen(dataclasses.replace(model.config, decode=True),
                  mesh=model.mesh)


def unstack_params(params: dict, config: ProGenConfig) -> dict:
    """Convert a scan_layers param tree (stacked 'layers' subtree) to the
    unrolled attn{i}/ff{i} layout — needed by decode mode (per-layer caches
    are unrolled) and by checkpoint interchange with non-scan configs."""
    import jax

    if "layers" not in params:
        return params
    n_uniform = config.depth - config.global_mlp_depth
    out = {k: v for k, v in params.items() if k != "layers"}
    stacked = params["layers"]
    for i in range(n_uniform):
        out[f"attn{i}"] = jax.tree.map(lambda x: x[i], stacked["attn"])
        out[f"ff{i}"] = jax.tree.map(lambda x: x[i], stacked["ff"])
    return out


def stack_params(params: dict, config: ProGenConfig) -> dict:
    """Inverse of unstack_params: unrolled attn{i}/ff{i} -> stacked
    'layers' subtree for a scan_layers model."""
    import jax
    import jax.numpy as jnp

    n_uniform = config.depth - config.global_mlp_depth
    if n_uniform < 1 or "layers" in params:
        return params
    out = {
        k: v
        for k, v in params.items()
        if not any(
            k == f"{p}{i}" for p in ("attn", "ff") for i in range(n_uniform)
        )
    }
    out["layers"] = {
        "attn": jax.tree.map(
            lambda *xs: jnp.stack(xs),
            *(params[f"attn{i}"] for i in range(n_uniform)),
        ),
        "ff": jax.tree.map(
            lambda *xs: jnp.stack(xs),
            *(params[f"ff{i}"] for i in range(n_uniform)),
        ),
    }
    return out


class ProGen(nn.Module):
    config: ProGenConfig
    # physical mesh (jax.sharding.Mesh, hashable) — only consulted by the
    # explicit-collective attention path (config.use_ring_attn); the GSPMD
    # path needs no mesh on the model. Not serialized with the config.
    mesh: object = None

    @nn.compact
    def __call__(self, tokens: jnp.ndarray) -> jnp.ndarray:
        """tokens: (batch, seq_len) integer array. Returns float32 logits of
        shape (batch, seq_len, num_tokens)."""
        c = self.config
        n = tokens.shape[-1]

        x = nn.Embed(
            c.num_tokens,
            c.dim,
            dtype=c.compute_dtype,
            param_dtype=c.params_dtype,
            embedding_init=nn.with_logical_partitioning(
                nn.initializers.truncated_normal(stddev=0.02), ("vocab", "embed")
            ),
            name="embed",
        )(tokens)
        x = nn.with_logical_constraint(x, ("batch", "seq_act", "embed_act"))

        if c.decode:
            # one-token step: full-length RoPE tables (blocks slice their
            # row), one shared position counter advanced per call
            pos_var = self.variable(
                "cache", "pos", lambda: jnp.zeros((), jnp.int32)
            )
            pos = pos_var.value
            sin, cos = fixed_pos_embedding(c.seq_len, c.dim_head)
        else:
            pos = None
            # RoPE tables are tiny; build in f32 once per trace (progen.py:227)
            sin, cos = fixed_pos_embedding(n, c.dim_head)

        attn_cls, ff_cls = LocalAttentionBlock, FeedForwardBlock
        if c.remat and not c.decode:
            attn_cls = nn.remat(LocalAttentionBlock)
            ff_cls = nn.remat(FeedForwardBlock)

        n_uniform = c.depth - c.global_mlp_depth
        if c.scan_layers and not c.decode and n_uniform > 0:
            block_cls = nn.remat(UniformBlock) if c.remat else UniformBlock
            scan_cls = nn.scan(
                block_cls,
                variable_axes={"params": 0},
                split_rngs={"params": True},
                in_axes=(nn.broadcast, nn.broadcast),
                length=n_uniform,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )
            x, _ = scan_cls(c, glu=c.ff_glu, mesh=self.mesh, name="layers")(
                x, sin, cos
            )
            start = n_uniform
        else:
            start = 0

        for i in range(start, c.depth):
            use_gmlp = (c.depth - i) <= c.global_mlp_depth
            use_glu = (not use_gmlp) and c.ff_glu
            x = x + attn_cls(c, mesh=self.mesh, name=f"attn{i}")(
                x, sin, cos, pos
            )
            x = x + ff_cls(
                c, glu=use_glu, spatial_gate=use_gmlp, name=f"ff{i}"
            )(x, pos)
            x = nn.with_logical_constraint(x, ("batch", "seq_act", "embed_act"))

        if c.decode and not self.is_initializing():
            pos_var.value = pos + 1

        x = ScaleNorm(c.layer_norm_epsilon, c.compute_dtype, c.params_dtype)(x)
        logits = nn.Dense(
            c.num_tokens,
            dtype=c.compute_dtype,
            param_dtype=c.params_dtype,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), ("embed", "vocab")
            ),
            bias_init=nn.with_logical_partitioning(
                nn.initializers.zeros, ("vocab",)
            ),
            name="to_logits",
        )(x)
        return logits.astype(jnp.float32)
