"""ProGen building blocks as flax.linen modules, batch-first, TPU-sharded.

Behavioral parity targets (cited into /root/reference/progen_transformer/):
  * LocalAttentionBlock  <- progen.py:50-103  (pre-LN, token-shift, bias-free
    fused QKV, RoPE on q/k/v, windowed attention, output projection)
  * FeedForwardBlock     <- progen.py:105-149 (pre-LN, token-shift, GLU or
    GELU, optional spatial gating, output projection)
  * SpatialGatingUnit    <- progen.py:151-185 (gate LayerNorm, learned causal
    (n, n) spatial mix with uniform ±eps/n init and ones bias)

Every weight carries flax logical-axis metadata so the whole model shards
through one rule table (progen_tpu/parallel/partition.py). LayerNorms are
scale-only (create_offset=False in the reference, progen.py:22).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import linen as nn

from progen_tpu.config import ProGenConfig
from progen_tpu.ops.attention import local_attention
from progen_tpu.ops.rotary import apply_rotary_pos_emb
from progen_tpu.ops.sgu import causal_sgu_mix
from progen_tpu.ops.shift import shift_tokens


def _dense_init():
    # Matches the scale of hk.Linear's default TruncatedNormal(1/sqrt(fan_in)).
    return nn.initializers.lecun_normal()


def _cached_shift(module: nn.Module, x: jnp.ndarray) -> jnp.ndarray:
    """Token-shift for one-token decode: the shifted-in half comes from a
    cache variable holding the previous position's post-LN features (shared
    by the attention and feed-forward blocks)."""
    split = x.shape[-1] - x.shape[-1] // 2
    st = module.variable(
        "cache", "shift_state",
        lambda: jnp.zeros((x.shape[0], 1, split), x.dtype),
    )
    shifted = shift_tokens(x, shift_state=st.value)
    if not module.is_initializing():
        st.value = x[..., :split]
    return shifted


class _NormScale(nn.Module):
    """Parameter-only twin of ScaleNorm's inner nn.LayerNorm: same module
    name ("norm"), same param ("scale": ones init, ("embed",) logical
    partitioning, param_dtype) but NO compute — the fused layer kernels
    (ops/pallas_layers.py) normalize in-register and only need the scale
    vector. Because the param path and metadata are identical, checkpoints
    interchange freely across config.use_fused_layer_kernels."""

    features: int
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self):
        return self.param(
            "scale",
            nn.with_logical_partitioning(nn.initializers.ones, ("embed",)),
            (self.features,),
            self.param_dtype,
        )


class ScaleNorm(nn.Module):
    """Scale-only LayerNorm (hk.LayerNorm(create_scale=True, create_offset=False)).

    ``scale_only=True`` returns the scale PARAM instead of normalizing —
    the handle the fused Pallas paths use; only one of the two branches
    ever runs for a given (static) config, so the "norm" name is bound
    exactly once either way."""

    epsilon: float = 1e-5
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, scale_only: bool = False):
        if scale_only:
            return _NormScale(
                x.shape[-1], self.param_dtype, name="norm"
            )()
        return nn.LayerNorm(
            epsilon=self.epsilon,
            use_bias=False,
            use_scale=True,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            scale_init=nn.with_logical_partitioning(
                nn.initializers.ones, ("embed",)
            ),
            name="norm",
        )(x)


def _fused_layer_ok(c: ProGenConfig) -> bool:
    """The fused layer kernels apply only on the full-sequence path (the
    decode cache keeps the unfused ops) and only when the pallas API
    family is importable — the same degrade-don't-fail contract as
    use_pallas_attn, so a config shipping use_fused_layer_kernels=true
    stays runnable anywhere."""
    if not (c.use_fused_layer_kernels and not c.decode):
        return False
    from progen_tpu.ops.pallas_layers import LAYER_PALLAS_OK

    return LAYER_PALLAS_OK


def _norm_shift_head(module: nn.Module, x: jnp.ndarray) -> jnp.ndarray:
    """The pre-LN + token-shift head shared by the attention and FF
    blocks. With config.use_fused_layer_kernels the two ops run as ONE
    policy-dispatched Pallas pass (ops/pallas_layers.py); the norm's
    scale param is created through the same ScaleNorm module path either
    way, so the params tree is identical across the flag."""
    c = module.config
    norm = ScaleNorm(c.layer_norm_epsilon, c.compute_dtype, c.params_dtype)
    if c.shift_tokens and _fused_layer_ok(c):
        from progen_tpu.ops.pallas_layers import norm_shift

        return norm_shift(
            x, norm(x, scale_only=True),
            c.layer_norm_epsilon, c.compute_dtype,
            block_override=c.pallas_layer_block,
            interpret=jax.default_backend() not in ("tpu", "axon"),
        )
    x = norm(x)
    if c.shift_tokens:
        x = _cached_shift(module, x) if c.decode else shift_tokens(x)
    return x


class LocalAttentionBlock(nn.Module):
    """Windowed attention block. In config.decode mode the sequence axis is
    1 and a rolling 2-window K/V cache (flax 'cache' collection) replaces
    the windowed reshape — O(2w·d) per emitted token instead of a full
    forward (the reference samples with full-length forwards per token,
    utils.py:116-117)."""

    config: ProGenConfig
    # physical mesh, set by ProGen when built with one — enables the
    # explicit ring-collective attention path (config.use_ring_attn)
    mesh: object = None

    @nn.compact
    def __call__(self, x, sin, cos, pos=None):
        c = self.config
        b, n, _ = x.shape
        h, dh, w = c.heads, c.dim_head, c.window_size

        x = _norm_shift_head(self, x)

        qkv = nn.Dense(
            3 * c.inner_dim,
            use_bias=False,
            dtype=c.compute_dtype,
            param_dtype=c.params_dtype,
            kernel_init=nn.with_logical_partitioning(
                _dense_init(), ("embed", "qkv")
            ),
            name="to_qkv",
        )(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def split_heads(t):  # (b, n, h*dh) -> (b, h, n, dh); feature = (h, dh)
            return t.reshape(b, n, h, dh).transpose(0, 2, 1, 3)

        q, k, v = map(split_heads, (q, k, v))

        if c.decode:
            # slice the current position's RoPE row from the full tables
            sin = jax.lax.dynamic_slice_in_dim(sin, pos, 1, axis=0)
            cos = jax.lax.dynamic_slice_in_dim(cos, pos, 1, axis=0)

        q = apply_rotary_pos_emb(q, sin, cos)
        k = apply_rotary_pos_emb(k, sin, cos)
        if c.rotate_value:  # reference rotates v too (progen.py:87)
            v = apply_rotary_pos_emb(v, sin, cos)

        # one scope over every dispatch path: XProf rows read
        # "attention_core" whether the step ran XLA, ring, or Pallas
        with jax.named_scope("attention_core"):
            if c.decode:
                out = self._decode_attend(q, k, v, pos)  # (b, h, 1, dh)
            elif (
                c.use_ring_attn
                and self.mesh is not None
                and dict(getattr(self.mesh, "shape", {})).get("seq", 1) > 1
                and not self.is_initializing()
            ):
                # explicit one-hop halo exchange over the ``seq`` ring
                # instead of GSPMD-inferred collectives. Skipped during
                # init: the dummy init batch (1, L) doesn't divide over
                # the data axis, and the op is parameter-free so init
                # doesn't need it for shapes.
                from progen_tpu.parallel.ring_attention import (
                    ring_local_attention,
                )

                # use_pallas_attn composes: each ring shard runs the
                # measured kernel (halo-aware variant) instead of the XLA
                # dense path
                out = ring_local_attention(
                    q, k, v, window_size=w, mesh=self.mesh,
                    use_pallas=c.use_pallas_attn,
                )
            elif c.use_pallas_attn:
                from progen_tpu.ops.pallas_attention import (
                    PALLAS_API_OK,
                    measured_impls,
                    pallas_local_attention,
                )

                # positional args: custom_vjp nondiff_argnums are
                # positional. Mosaic-compiled on TPU; interpreter
                # elsewhere, so a config shipping use_pallas_attn=true
                # (long8k.toml) stays runnable on CPU hosts (tests, smoke
                # runs) without monkeypatching. use_pallas_attn means
                # "best measured kernel combo for this shape" —
                # per-direction winners from the policy table keyed on
                # (window, n, batch*heads); pallas_bh_block >= 1 (0 =
                # unset) overrides the policy's forward blocking, so an
                # explicit 1 can force one-window-per-program even where
                # the policy picked a batched forward.
                interpret = jax.default_backend() not in ("tpu", "axon")
                fwd_impl, bwd_impl, g = measured_impls(w, n=n, bh=b * h)
                if c.pallas_bh_block:
                    g = c.pallas_bh_block  # explicit config beats policy
                if not PALLAS_API_OK:
                    # installed jax predates the kernel API family: the
                    # XLA golden (same math) keeps the config runnable
                    out = local_attention(q, k, v, window_size=w)
                elif fwd_impl == "xla" and bwd_impl == "xla":
                    # both directions lost on-chip at this shape: plain
                    # XLA autodiff (going through the custom VJP would
                    # recompute the forward inside the backward for
                    # nothing)
                    out = local_attention(q, k, v, window_size=w)
                else:
                    out = pallas_local_attention(
                        q, k, v, w, None, interpret, bwd_impl, g, fwd_impl
                    )
            else:
                out = local_attention(q, k, v, window_size=w)

        out = out.transpose(0, 2, 1, 3).reshape(b, n, c.inner_dim)
        out = nn.with_logical_constraint(out, ("batch", "seq_act", None))
        return nn.Dense(
            c.dim,
            dtype=c.compute_dtype,
            param_dtype=c.params_dtype,
            kernel_init=nn.with_logical_partitioning(
                _dense_init(), ("qkv", "embed")
            ),
            bias_init=nn.with_logical_partitioning(
                nn.initializers.zeros, ("embed",)
            ),
            name="to_out",
        )(out)

    def _decode_attend(self, q, k, v, pos):
        """One-token attention against a rolling 2-window K/V ring buffer.

        Slot ``p % 2w`` holds position p; visibility is recomputed per step
        from the stored absolute positions. Window-0 queries' softmax is
        diluted by exactly ``w`` phantom zero-score/zero-value keys via an
        analytic denominator correction — the reference's zero-padded
        previous window (progen.py:90-96) without materializing it.
        """
        c = self.config
        b, h, _, dh = q.shape
        w = c.window_size
        ring = 2 * w

        ck = self.variable(
            "cache", "k", lambda: jnp.zeros((b, h, ring, dh), q.dtype)
        )
        cv = self.variable(
            "cache", "v", lambda: jnp.zeros((b, h, ring, dh), q.dtype)
        )
        cpos = self.variable(
            "cache", "slot_pos", lambda: jnp.full((ring,), -1, jnp.int32)
        )

        slot = pos % ring
        if not self.is_initializing():
            ck.value = jax.lax.dynamic_update_slice_in_dim(
                ck.value, k, slot, axis=2
            )
            cv.value = jax.lax.dynamic_update_slice_in_dim(
                cv.value, v, slot, axis=2
            )
            cpos.value = jax.lax.dynamic_update_index_in_dim(
                cpos.value, pos, slot, axis=0
            )

        slot_pos = cpos.value
        visible = (
            (slot_pos >= 0)
            & (slot_pos <= pos)
            & (pos // w - slot_pos // w <= 1)
        )
        scores = jnp.einsum(
            "bhqd,bhkd->bhqk", q, ck.value,
            preferred_element_type=jnp.float32,
        ) * (dh ** -0.5)
        scores = jnp.where(visible[None, None, None, :], scores, -1e10)

        first_window = (pos < w).astype(jnp.float32)
        # softmax with analytic phantom-key dilution: shift-invariant, so a
        # stable max including the phantoms' score 0 is fine
        m = jnp.maximum(
            scores.max(axis=-1, keepdims=True),
            jnp.where(first_window > 0, 0.0, -jnp.inf),
        )
        e = jnp.exp(scores - m)
        denom = e.sum(axis=-1, keepdims=True) + first_window * w * jnp.exp(-m)
        out = jnp.einsum(
            "bhqk,bhkd->bhqd", e, cv.value.astype(jnp.float32)
        ) / denom
        return out.astype(q.dtype)


class SpatialGatingUnit(nn.Module):
    config: ProGenConfig
    dim_out: int

    @nn.compact
    def __call__(self, x, pos=None):
        c = self.config
        n = c.seq_len
        assert c.decode or x.shape[-2] == n, (
            f"SGU is bound to seq_len={n} at init, got sequence {x.shape[-2]}"
        )
        x, gate = jnp.split(x, 2, axis=-1)

        norm = ScaleNorm(c.layer_norm_epsilon, c.compute_dtype, c.params_dtype)
        fused = _fused_layer_ok(c)
        # the fused tail normalizes the gate in-kernel; every other path
        # (incl. decode's gate_history, which stores NORMALIZED gates)
        # normalizes here
        gate_scale = norm(gate, scale_only=True) if fused else None
        if not fused:
            gate = norm(gate)

        init_scale = c.sgu_init_eps / n

        def symmetric_uniform(key, shape, dtype):
            return jax.random.uniform(
                key, shape, dtype, minval=-init_scale, maxval=init_scale
            )

        weights = self.param(
            "spatial_weights",
            nn.with_logical_partitioning(
                symmetric_uniform, ("sgu_seq_out", "sgu_seq_in")
            ),
            (n, n),
            c.params_dtype,
        )
        biases = self.param(
            "spatial_biases",
            nn.with_logical_partitioning(nn.initializers.ones, ("sgu_seq_out", None)),
            (n, 1),
            c.params_dtype,
        )

        with jax.named_scope("sgu_spatial_mix"):
            if c.decode:
                # incremental spatial mix: keep the LayerNormed gate
                # history and contract the current causal row of the
                # (n, n) matrix with it —
                # out[pos] = sum_{j<=pos} W[pos, j] * gate[j] + b[pos]
                b_sz, half = gate.shape[0], gate.shape[-1]
                hist = self.variable(
                    "cache", "gate_history",
                    lambda: jnp.zeros((b_sz, n, half), jnp.float32),
                )
                if not self.is_initializing():
                    hist.value = jax.lax.dynamic_update_slice_in_dim(
                        hist.value, gate.astype(jnp.float32), pos, axis=1
                    )
                row = jax.lax.dynamic_index_in_dim(
                    weights.astype(jnp.float32), pos, axis=0, keepdims=False
                )
                row = jnp.where(jnp.arange(n) <= pos, row, 0.0)
                mixed = jnp.einsum("bnd,n->bd", hist.value, row)
                mixed = mixed + jax.lax.dynamic_index_in_dim(
                    biases.astype(jnp.float32), pos, axis=0, keepdims=False
                )
                gate = mixed[:, None, :].astype(x.dtype)
                x = x * gate
            elif fused:
                from progen_tpu.ops.pallas_layers import sgu_mix_gate

                x = sgu_mix_gate(
                    x, gate, weights, biases, gate_scale,
                    c.layer_norm_epsilon, c.compute_dtype,
                    block_override=c.pallas_layer_block,
                    interpret=jax.default_backend() not in ("tpu", "axon"),
                )
            else:
                gate = causal_sgu_mix(
                    gate, weights, biases, c.sgu_block_size
                ).astype(x.dtype)
                x = x * gate
        return nn.Dense(
            self.dim_out,
            dtype=c.compute_dtype,
            param_dtype=c.params_dtype,
            kernel_init=nn.with_logical_partitioning(
                _dense_init(), ("sgu_hidden", "mlp")
            ),
            bias_init=nn.with_logical_partitioning(nn.initializers.zeros, ("mlp",)),
            name="proj_out",
        )(x)


class FeedForwardBlock(nn.Module):
    config: ProGenConfig
    glu: bool = False
    spatial_gate: bool = False

    @nn.compact
    def __call__(self, x, pos=None):
        c = self.config
        assert not (self.glu and self.spatial_gate), (
            "glu and sgu cannot be turned on at the same time"
        )
        hidden = c.dim * c.ff_mult * (2 if self.glu else 1)

        x = _norm_shift_head(self, x)

        x = nn.Dense(
            hidden,
            dtype=c.compute_dtype,
            param_dtype=c.params_dtype,
            kernel_init=nn.with_logical_partitioning(_dense_init(), ("embed", "mlp")),
            bias_init=nn.with_logical_partitioning(nn.initializers.zeros, ("mlp",)),
            name="proj_in",
        )(x)

        with jax.named_scope("ffn_activation"):
            if self.glu:
                x, gate = jnp.split(x, 2, axis=-1)
                x = x * jax.nn.gelu(gate)
            else:
                x = jax.nn.gelu(x)

        if self.spatial_gate:
            x = SpatialGatingUnit(c, dim_out=hidden // 2, name="sgu")(x, pos)

        x = nn.with_logical_constraint(x, ("batch", "seq_act", "mlp_act"))
        return nn.Dense(
            c.dim,
            dtype=c.compute_dtype,
            param_dtype=c.params_dtype,
            kernel_init=nn.with_logical_partitioning(_dense_init(), ("mlp", "embed")),
            bias_init=nn.with_logical_partitioning(nn.initializers.zeros, ("embed",)),
            name="proj_out",
        )(x)
