"""progen-tpu: a TPU-native framework for autoregressive protein language models.

A ground-up reimplementation of the capabilities of lucidrains/progen
(reference: /root/reference) designed for TPU hardware: batch-first models,
jit/pjit + GSPMD sharding over a (data, seq, model) mesh, Pallas kernels for
the windowed local attention, sharded checkpoints, and a multi-host sharded
data pipeline.

The reference model (progen_transformer/progen.py) is a decoder-only LM over
byte-tokenized protein sequences: token embedding -> depth x (windowed local
attention + feed-forward) -> LayerNorm + logits head, with RoPE applied to
q/k/v, token-shift, GLU feed-forwards, and gMLP (spatial-gating) feed-forwards
on the trailing `global_mlp_depth` layers.
"""

__version__ = "0.1.0"

__all__ = ["ProGen", "ProGenConfig", "ServeEngine", "Scheduler",
           "__version__"]


def __getattr__(name):  # PEP 562: lazy so that importing light submodules
    # (progen_tpu.utils.env, loaded by the CLIs BEFORE jax to honor .env
    # XLA flags) does not drag in jax via the model imports
    if name == "ProGen":
        from progen_tpu.models.progen import ProGen

        return ProGen
    if name == "ProGenConfig":
        from progen_tpu.config import ProGenConfig

        return ProGenConfig
    if name in ("ServeEngine", "Scheduler"):
        import progen_tpu.serving as serving

        return getattr(serving, name)
    raise AttributeError(f"module 'progen_tpu' has no attribute {name!r}")
