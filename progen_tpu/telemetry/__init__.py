"""Unified telemetry: spans, goodput ledger, stall watchdog, HBM gauges,
Prometheus exposition.

The reference has no observability beyond tqdm (SURVEY §5); this package
is the per-phase time accounting and span-level tracing that turns a
hung or slow run into a one-line diagnosis (MegaScale NSDI'24, Dapper
2010 — PAPERS.md "Observability"):

  * ``span("ckpt/save")`` — context-manager spans emitting begin/end
    records to a crash-safe ``events.jsonl`` (per-line flush) and
    entering ``jax.named_scope`` so XProf traces carry the same labels;
  * ``GoodputLedger`` — classifies train-loop wall clock into
    compile/step/data/checkpoint/eval/sample/log buckets and reports
    ``goodput_pct`` next to MFU;
  * ``StallWatchdog`` — heartbeat thread that dumps all-thread stacks
    (faulthandler) plus a last-spans report when no step completes
    within a deadline;
  * ``hbm_gauges`` — per-device HBM occupancy from
    ``device.memory_stats()``;
  * ``prometheus_text`` / ``start_prometheus_server`` — text exposition
    of any ``structured()`` metrics source (serving AND the train-loop
    ``MetricsRegistry``) for scraping (file and HTTP);
  * ``MetricsRegistry`` / ``get_registry`` — the process-wide counter/
    gauge/timing store shared by train, serve, bench, and resilience;
  * ``trace.build_trace`` / ``export_trace`` — events.jsonl → Chrome
    Trace Event / Perfetto JSON (the ``telemetry export-trace`` CLI);
  * ``per_host_reports`` / ``goodput_skew`` / ``emit_per_host_goodput``
    — MegaScale-style per-host goodput + straggler skew table;
  * ``stitch_trace`` / ``clock_offsets`` / ``emit_clock_beacon`` —
    N hosts' event files → ONE fleet trace on a common corrected clock
    (the ``telemetry stitch`` CLI), beacon-anchored skew correction,
    plus per-request journey flows across router → replica → survivor;
  * ``slo`` — the fleet SLO watchtower: objectives from TOML,
    multi-window burn rates over metrics.jsonl / Prometheus textfiles,
    ``ev: "slo"`` transition records, and the slo-report CI gate.

Everything is CPU-testable; nothing here imports jax at module scope.
"""

from progen_tpu.telemetry.goodput import (
    BUCKETS,
    GoodputLedger,
    emit_per_host_goodput,
    goodput_skew,
    per_host_reports,
)
from progen_tpu.telemetry.hbm import hbm_gauges
from progen_tpu.telemetry.prometheus import (
    prometheus_text,
    start_prometheus_server,
    write_prometheus,
)
from progen_tpu.telemetry.registry import MetricsRegistry, get_registry
from progen_tpu.telemetry.slo import (
    SloConfig,
    SloWatch,
    evaluate as evaluate_slos,
    exit_code as slo_exit_code,
    load_objectives,
)
from progen_tpu.telemetry.spans import (
    EventLog,
    Telemetry,
    configure,
    get_telemetry,
    host_index,
    span,
    step_print,
)
from progen_tpu.telemetry.stitch import (
    clock_offsets,
    emit_clock_beacon,
    stitch_streams,
    stitch_trace,
)
from progen_tpu.telemetry.trace import build_trace, export_trace
from progen_tpu.telemetry.watchdog import StallWatchdog

__all__ = [
    "BUCKETS",
    "GoodputLedger",
    "per_host_reports",
    "goodput_skew",
    "emit_per_host_goodput",
    "EventLog",
    "Telemetry",
    "configure",
    "get_telemetry",
    "host_index",
    "span",
    "step_print",
    "StallWatchdog",
    "hbm_gauges",
    "prometheus_text",
    "write_prometheus",
    "start_prometheus_server",
    "MetricsRegistry",
    "get_registry",
    "build_trace",
    "export_trace",
    "clock_offsets",
    "emit_clock_beacon",
    "stitch_streams",
    "stitch_trace",
    "SloConfig",
    "SloWatch",
    "evaluate_slos",
    "slo_exit_code",
    "load_objectives",
]
