"""Span API: context-manager tracing to events.jsonl + jax.named_scope.

A span is one timed region of host code (``span("ckpt/save")``). On
entry a ``B`` (begin) record goes to the sink; on exit an ``E`` (end)
record with the duration. Crash forensics fall out of the format: a
``B`` with no matching ``E`` in ``events.jsonl`` IS the phase the
process died in — no log-diving required (Dapper-style span trees,
sized for one process).

Device-side visibility rides the same call: the span body runs under
``jax.named_scope(name)``, so any op traced inside it carries the span
name into XProf/TensorBoard timelines. jax is imported lazily and its
absence is tolerated (pure-host tools can use spans too).

The module-level ``span()``/``configure()`` pair operates a process
global ``Telemetry`` so deep callees (checkpoint.py, bench phases) can
open spans without threading a handle through every signature. With no
sink configured spans still maintain the in-memory recent/open ring
(what the stall watchdog reports) at ~zero cost.
"""

from __future__ import annotations

import contextlib
import itertools
import json
import sys
import threading
import time
from collections import deque
from pathlib import Path
from typing import Callable, Optional

_HOST_INDEX: Optional[int] = None


def host_index() -> int:
    """This process's index in a multi-process run (0 single-process).

    Deliberately lazy and init-free: ``jax.process_index()`` would
    *initialize* the backend as a side effect, which telemetry must
    never do (tests assert backends stay uninitialized at import, and a
    pure-host tool reading a trace has no business dialing a
    coordinator). So we only ask jax if it is already imported AND its
    backends are already live, and cache the answer from then on —
    before that point every record is host 0, which is exactly right
    for the only process that can exist pre-init."""
    global _HOST_INDEX
    if _HOST_INDEX is not None:
        return _HOST_INDEX
    jax = sys.modules.get("jax")
    if jax is None:
        return 0
    try:
        from jax._src import xla_bridge

        if not xla_bridge.backends_are_initialized():
            return 0
        _HOST_INDEX = int(jax.process_index())
    except Exception:
        return 0
    return _HOST_INDEX


class EventLog:
    """Crash-safe append-only JSONL sink: one record per line, flushed
    per line (same discipline as tracking.JsonlTracker — a SIGKILL at
    any instant loses at most the line being written, never the file)."""

    def __init__(self, path):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._f = self.path.open("a")
        self._lock = threading.Lock()

    def emit(self, record: dict) -> None:
        # the watchdog thread emits concurrently with the main loop; the
        # lock keeps lines whole (write+flush is one critical section)
        with self._lock:
            if self._f.closed:
                return
            self._f.write(json.dumps(record) + "\n")
            self._f.flush()

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.close()


# span-entry hooks: called with the span name after the B record is
# emitted, INSIDE the span's try block — a hook that raises surfaces to
# the span's caller while the E record still closes the span. The chaos
# injector (resilience/chaos.py) registers here; empty list = no-op.
SPAN_ENTRY_HOOKS: list = []

# emit taps: called with every record that reaches Telemetry.emit —
# BEFORE the sink check, so a tap sees records even on a sink-less
# process (spans still maintain the ring with no events.jsonl). The
# flight recorder (telemetry/flight.py) registers here; a tap must
# never raise and never block (it runs on the training/serving hot
# path). Empty list = no-op.
EMIT_TAPS: list = []


def _named_scope(name: str):
    try:
        import jax

        return jax.named_scope(name)
    except Exception:  # jax absent or name rejected: spans still time
        return contextlib.nullcontext()


class Telemetry:
    """Span emitter + in-memory recent/open span state.

    ``sink`` is any ``callable(dict)`` — an ``EventLog.emit``, a
    ``JsonlTracker.log_event``, or None (records dropped, ring kept).
    """

    def __init__(self, sink: Optional[Callable[[dict], None]] = None,
                 max_recent: int = 64):
        self._sink = sink
        self._seq = itertools.count()
        self._lock = threading.Lock()
        self._open: dict[int, dict] = {}
        self._recent: deque = deque(maxlen=max_recent)

    def set_sink(self, sink: Optional[Callable[[dict], None]]) -> None:
        self._sink = sink

    def emit(self, record: dict) -> None:
        # every record carries its host: under multi-process training the
        # per-host event files merge into one trace, and pid is what the
        # trace/skew tooling groups on (MegaScale-style straggler
        # attribution needs the host on *every* retry/anomaly/stall line,
        # not just spans)
        record.setdefault("pid", host_index())
        for tap in EMIT_TAPS:
            tap(record)
        sink = self._sink
        if sink is None:
            return
        try:
            sink(record)
        except (OSError, ValueError):
            # a closed/broken sink must never take the training loop
            # down; drop the record and keep the in-memory state
            self._sink = None

    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        sid = next(self._seq)
        thread = threading.current_thread()
        begin = {
            "ev": "B", "span": name, "id": sid, "ts": time.time(),
            "tid": thread.ident, "thread": thread.name,
        }
        if attrs:
            begin.update(attrs)
        with self._lock:
            self._open[sid] = begin
        self.emit(begin)
        t0 = time.perf_counter()
        try:
            for hook in SPAN_ENTRY_HOOKS:
                hook(name)
            with _named_scope(name):
                yield
        finally:
            dur = time.perf_counter() - t0
            end = {
                "ev": "E", "span": name, "id": sid,
                "ts": time.time(), "dur_s": round(dur, 6),
                "tid": thread.ident, "thread": thread.name,
            }
            if attrs:
                end.update(attrs)
            with self._lock:
                self._open.pop(sid, None)
                self._recent.append(end)
            self.emit(end)

    # ----- watchdog-facing state ------------------------------------------

    def open_spans(self) -> list:
        """Spans currently inside their body — where the process is NOW."""
        with self._lock:
            return sorted(self._open.values(), key=lambda r: r["id"])

    def recent_spans(self, n: int = 16) -> list:
        """The last ``n`` completed spans, oldest first."""
        with self._lock:
            return list(self._recent)[-n:]


_GLOBAL = Telemetry()


def get_telemetry() -> Telemetry:
    return _GLOBAL


def configure(sink: Optional[Callable[[dict], None]] = None,
              path=None) -> Telemetry:
    """Point the process-global telemetry at a sink. ``path`` is a
    convenience that opens an ``EventLog`` there; ``sink`` wins when both
    are given; ``configure()`` with neither detaches (spans keep timing,
    records drop)."""
    if sink is None and path is not None:
        sink = EventLog(path).emit
    _GLOBAL.set_sink(sink)
    return _GLOBAL


def span(name: str, **attrs):
    """Module-level span on the process-global Telemetry."""
    return _GLOBAL.span(name, **attrs)


def step_print(step, msg: str) -> None:
    """Step-stamped console line, format-consistent with the tracker
    stream (the tracker carries ``_time``/``_step``; the console carries
    the same two, human-readable): ``[HH:MM:SS step N] msg``."""
    stamp = time.strftime("%H:%M:%S")
    print(f"[{stamp} step {step}] {msg}")
