"""Fleet SLO watchtower: objectives → multi-window burn rates → gates.

Serving telemetry so far answers "what happened" (traces, journals,
metrics streams); nothing answered "are we OK right now, and is it
getting worse fast enough to page?". This module is that layer —
MegaScale-style fleet supervision (PAPERS.md) applied to the serving
stack's own exhaust:

  * objectives load from a flat-table TOML (``configs/serving/slo.toml``
    is the shipped default): latency quantile ceilings (TTFT p95,
    request latency p99), error/shed-rate budgets, and fleet
    availability floors;
  * evidence comes from the files the fleet already writes — tracker
    ``metrics.jsonl`` rows (the windowed time series) and Prometheus
    textfiles (the freshest point sample; also the staleness signal:
    an exposition file nobody has rewritten lately means the process
    behind it is gone or wedged);
  * each objective gets a SHORT- and LONG-window burn rate (burn 1.0 =
    consuming exactly the error budget; the SRE-workbook multiwindow
    rule): ``burning`` needs BOTH windows over the hot threshold (a
    fast burn that also moved the long window — real, page), ``warn``
    is a long-window drift or a short-window spike (watch), anything
    without data is at least ``warn`` (an SLO you cannot evaluate is
    not "ok");
  * ``SloWatch`` turns per-tick states into ``ev: "slo"`` TRANSITION
    records on the telemetry stream (only edges, never steady-state
    spam; recovery emits ``state: "resolved"``) so the watchtower's own
    judgments land in the same events.jsonl the trace tooling reads;
  * ``exit_code`` maps a report to the CI contract: 0 all ok, 1 any
    warn, 2 any burning — ``progen-tpu-telemetry slo-report`` is a
    gate you can put in a pipeline.

Report-mode determinism: ``evaluate`` defaults ``now`` to the newest
sample timestamp, so re-running a report over archived artifacts always
judges the run "as of its end" — live ``watch`` mode passes wall clock
instead. Latency quantiles come from cumulative reservoirs (the
registry keeps running quantiles, not windowed ones), so both windows
see the same latest value; the windowing bites on the counter-delta and
availability objectives, which is where burn-rate math matters most.
"""

from __future__ import annotations

import re
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from progen_tpu.config import load_toml_config

STATE_OK = "ok"
STATE_WARN = "warn"
STATE_BURNING = "burning"
STATE_RESOLVED = "resolved"

# exposition prefixes stripped when reading prom textfiles so objective
# metric names match the registry's raw names ("ttft_s", "replicas_up")
_PROM_PREFIXES = ("progen_router_", "progen_serve_", "progen_")

# quantile label → the snapshot()-style suffix metrics.jsonl rows use,
# so one objective key addresses both evidence sources
_QUANTILE_KEYS = {"0.5": "p50_s", "0.95": "p95_s", "0.99": "p99_s"}

# the optional tail is an OpenMetrics exemplar (`# {trace_id="..."} v`)
# — tolerated on any sample line so exemplar-bearing expositions parse
# to the same values as plain ones (the exemplars themselves are read
# by parse_prom_exemplars)
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(\S+)"
    r"(?:\s+#\s*\{.*\}\s+\S+)?\s*$"
)
_QUANT_RE = re.compile(r'quantile="([^"]+)"')
_EXEMPLAR_RE = re.compile(
    r'#\s*\{trace_id="((?:[^"\\]|\\.)*)"\}\s+(\S+)\s*$'
)


def unescape_label_value(raw: str) -> str:
    """Inverse of ``telemetry.prometheus.escape_label_value`` — the
    scrape side of the exemplar trace_id round-trip."""
    out = []
    i = 0
    while i < len(raw):
        c = raw[i]
        if c == "\\" and i + 1 < len(raw):
            nxt = raw[i + 1]
            out.append("\n" if nxt == "n" else nxt)
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def parse_prom_text(text: str) -> Dict[str, float]:
    """Prometheus exposition text → flat {metric: value}.

    Names are normalized back to registry spellings: prefixes stripped,
    ``_total`` counters bared, ``*_seconds{quantile="0.95"}`` summary
    samples become ``*_s_p95_s`` (matching ``_Timing.stats()`` keys in
    metrics.jsonl rows). Torn or garbage lines are skipped, never fatal
    — the atomic-write contract allows a reader to race a dying writer,
    and a gate that crashes on its evidence is worse than one that
    reports the evidence thin."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        name, labels, raw = m.groups()
        try:
            value = float(raw)
        except ValueError:
            continue
        for p in _PROM_PREFIXES:
            if name.startswith(p):
                name = name[len(p):]
                break
        if name.endswith("_seconds"):
            name = name[: -len("_seconds")] + "_s"
        elif name.endswith("_seconds_sum"):
            name = name[: -len("_seconds_sum")] + "_s_sum"
        elif name.endswith("_seconds_count"):
            name = name[: -len("_seconds_count")] + "_s_count"
        if labels:
            q = _QUANT_RE.search(labels)
            suffix = None if q is None else _QUANTILE_KEYS.get(q.group(1))
            if suffix is None:
                continue
            out[f"{name}_{suffix}"] = value
        elif name.endswith("_total"):
            out[name[: -len("_total")]] = value
        else:
            out[name] = value
    return out


def parse_prom_exemplars(text: str) -> Dict[str, list]:
    """The exemplar side-channel of an exposition: normalized
    timing-family key (``ttft_s``) → worst-first
    ``[{"value", "trace_id"}]`` parsed from the OpenMetrics
    ``# {trace_id="..."} value`` suffixes the renderer attaches to
    summary quantile lines. Families without exemplars are absent."""
    fams: Dict[str, list] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        em = _EXEMPLAR_RE.search(line)
        if em is None or _SAMPLE_RE.match(line) is None:
            continue
        name = _SAMPLE_RE.match(line).group(1)
        try:
            value = float(em.group(2))
        except ValueError:
            continue
        for p in _PROM_PREFIXES:
            if name.startswith(p):
                name = name[len(p):]
                break
        if name.endswith("_seconds"):
            name = name[: -len("_seconds")] + "_s"
        fams.setdefault(name, []).append({
            "value": value,
            "trace_id": unescape_label_value(em.group(1)),
        })
    for exs in fams.values():
        exs.sort(key=lambda e: -e["value"])
    return fams


def read_prom_file(path, now: Optional[float] = None):
    """(age_s, values) for one exposition textfile — age from the file's
    mtime (the atomic-rename write refreshes it every publish), which is
    the watchtower's liveness signal for the process behind the file.
    Returns None when the file does not exist."""
    p = Path(path)
    try:
        stat = p.stat()
        text = p.read_text()
    except OSError:
        return None
    age = max(0.0, (time.time() if now is None else now) - stat.st_mtime)
    return age, parse_prom_text(text)


def samples_from_metrics(rows: Iterable[dict]) -> List[Tuple[float, Dict[str, float]]]:
    """tracking.py metrics.jsonl rows → time-sorted (t, values) samples
    with the ``router/``/``serve/`` stream prefixes stripped (one
    objective key addresses every process's stream)."""
    out: List[Tuple[float, Dict[str, float]]] = []
    for rec in rows:
        t = rec.get("_time")
        if t is None:
            continue
        vals: Dict[str, float] = {}
        for k, v in rec.items():
            if k.startswith("_") or isinstance(v, bool) \
                    or not isinstance(v, (int, float)):
                continue
            vals[k.split("/", 1)[1] if "/" in k else k] = float(v)
        if vals:
            out.append((float(t), vals))
    out.sort(key=lambda s: s[0])
    return out


@dataclass
class Objective:
    """One SLO. ``kind`` selects the burn-rate math:

    * ``latency`` — ``metric`` quantile (``quantile`` ∈ p50/p95/p99)
      must stay under ``threshold_s``; burn = value / threshold;
    * ``ratio`` — counter ``bad`` over counter ``total`` (windowed
      deltas, reset-safe) must stay under ``budget``; burn =
      rate / budget;
    * ``availability`` — fraction of window samples with gauge
      ``metric`` >= ``min_value`` must stay over ``target``; burn =
      unavailable fraction / allowed unavailable fraction."""

    name: str
    kind: str
    metric: str = ""
    quantile: str = "p95"
    threshold_s: float = 0.0
    bad: str = ""
    total: str = ""
    budget: float = 0.0
    min_value: float = 1.0
    target: float = 0.99


@dataclass
class SloConfig:
    short_s: float = 300.0
    long_s: float = 3600.0
    warn: float = 1.0
    hot: float = 2.0
    stale_after_s: float = 60.0
    objectives: List[Objective] = field(default_factory=list)


_KINDS = ("latency", "ratio", "availability")


def load_objectives(path) -> SloConfig:
    """SloConfig from a TOML file. Flat tables only — ``[windows]``,
    ``[burn]``, and one ``[objective_<name>]`` section per objective —
    the exact subset config.py's minimal fallback parser accepts, so
    the gate works identically on pre-tomllib hosts."""
    raw = load_toml_config(str(path))
    cfg = SloConfig()
    win = raw.get("windows", {})
    if isinstance(win, dict):
        cfg.short_s = float(win.get("short_s", cfg.short_s))
        cfg.long_s = float(win.get("long_s", cfg.long_s))
    burn = raw.get("burn", {})
    if isinstance(burn, dict):
        cfg.warn = float(burn.get("warn", cfg.warn))
        cfg.hot = float(burn.get("hot", cfg.hot))
        cfg.stale_after_s = float(
            burn.get("stale_after_s", cfg.stale_after_s)
        )
    for section, table in raw.items():
        if not section.startswith("objective_") \
                or not isinstance(table, dict):
            continue
        name = section[len("objective_"):]
        kind = str(table.get("kind", ""))
        if kind not in _KINDS:
            raise ValueError(
                f"{path}: objective {name!r} has unknown kind {kind!r} "
                f"(want one of {_KINDS})"
            )
        quantile = str(table.get("quantile", "p95"))
        if kind == "latency" and quantile not in ("p50", "p95", "p99"):
            raise ValueError(
                f"{path}: objective {name!r} quantile {quantile!r} "
                "(want p50/p95/p99)"
            )
        cfg.objectives.append(Objective(
            name=name,
            kind=kind,
            metric=str(table.get("metric", table.get("gauge", ""))),
            quantile=quantile,
            threshold_s=float(table.get("threshold_s", 0.0)),
            bad=str(table.get("bad", "")),
            total=str(table.get("total", "")),
            budget=float(table.get("budget", 0.0)),
            min_value=float(table.get("min_value", 1.0)),
            target=float(table.get("target", 0.99)),
        ))
    if not cfg.objectives:
        raise ValueError(f"{path}: no [objective_*] sections")
    return cfg


@dataclass
class SloResult:
    objective: str
    kind: str
    state: str
    burn_short: Optional[float]
    burn_long: Optional[float]
    value: Optional[float] = None
    detail: str = ""


def _window_delta(samples, key: str, start: float, end: float) -> float:
    """Counter increase over (start, end]: baseline is the last sample
    at or before ``start`` (0.0 when the counter predates the series),
    endpoint the last at or before ``end``. A negative delta means the
    counter reset mid-window (process restart) — the end value is the
    floor of what actually happened since, so use it rather than 0."""
    base = 0.0
    last = None
    for t, vals in samples:
        if key not in vals or t > end:
            continue
        if t <= start:
            base = vals[key]
        last = vals[key]
    if last is None:
        return 0.0
    delta = last - base
    return last if delta < 0 else delta


def _ratio_burn(
    obj: Objective, series, start: float, end: float
) -> Optional[float]:
    """None when NO stream has an in-window sample of the ``total``
    counter — an error budget judged on zero evidence is unevaluable
    (→ warn), which is different from evidence showing zero errors."""
    if not any(
        any(start <= t <= end and obj.total in vals for t, vals in s)
        for s in series
    ):
        return None
    bad = sum(_window_delta(s, obj.bad, start, end) for s in series)
    total = sum(_window_delta(s, obj.total, start, end) for s in series)
    if total <= 0:
        return 0.0
    rate = bad / total
    if obj.budget <= 0:
        return float("inf") if rate > 0 else 0.0
    return rate / obj.budget


def _availability_burn(
    obj: Objective, series, start: float, end: float
) -> Optional[Tuple[float, float]]:
    n = ok = 0
    for samples in series:
        for t, vals in samples:
            if start <= t <= end and obj.metric in vals:
                n += 1
                if vals[obj.metric] >= obj.min_value:
                    ok += 1
    if n == 0:
        return None
    frac = ok / n
    burn = (1.0 - frac) / max(1.0 - obj.target, 1e-9)
    return burn, frac


def _latency_value(
    obj: Objective, series, proms, stale_after_s: float,
    start: float, end: float,
):
    """Worst (max) observed quantile across sources: fresh prom
    textfiles win (they are the newest reservoir snapshot), else the
    last in-window metrics sample per stream. Returns (value, stale) —
    ``stale`` flags that the ONLY evidence sat in an expired textfile,
    which is a liveness problem, not a latency number."""
    key = f"{obj.metric}_{obj.quantile}_s"
    values: List[float] = []
    stale_only = False
    for age, vals in proms:
        if key not in vals:
            continue
        if age <= stale_after_s:
            values.append(vals[key])
        else:
            stale_only = True
    for samples in series:
        last = None
        for t, vals in samples:
            if start <= t <= end and key in vals:
                last = vals[key]
        if last is not None:
            values.append(last)
    if values:
        return max(values), False
    return None, stale_only


def _classify(cfg: SloConfig, burn_short, burn_long) -> str:
    if burn_short is None or burn_long is None:
        return STATE_WARN
    if burn_short >= cfg.hot and burn_long >= cfg.hot:
        return STATE_BURNING
    if burn_long >= cfg.warn or burn_short >= cfg.hot:
        return STATE_WARN
    return STATE_OK


def evaluate(
    cfg: SloConfig,
    series: Sequence[Sequence[Tuple[float, Dict[str, float]]]] = (),
    proms: Sequence[Tuple[float, Dict[str, float]]] = (),
    now: Optional[float] = None,
) -> List[SloResult]:
    """Judge every objective against the evidence.

    ``series`` are ``samples_from_metrics`` outputs (one per metrics
    stream), ``proms`` are ``read_prom_file`` outputs. ``now`` defaults
    to the newest sample timestamp so reports over archived artifacts
    are deterministic; live callers pass wall clock."""
    series = [list(s) for s in series]
    if now is None:
        tails = [s[-1][0] for s in series if s]
        now = max(tails) if tails else time.time()
    results: List[SloResult] = []
    for obj in cfg.objectives:
        burn_short: Optional[float]
        burn_long: Optional[float]
        value: Optional[float] = None
        detail = ""
        if obj.kind == "latency":
            value, stale = _latency_value(
                obj, series, proms, cfg.stale_after_s,
                now - cfg.long_s, now,
            )
            if value is None:
                burn_short = burn_long = None
                detail = "stale exposition" if stale else "no data"
            else:
                burn = (
                    value / obj.threshold_s if obj.threshold_s > 0
                    else float("inf") if value > 0 else 0.0
                )
                burn_short = burn_long = burn
        elif obj.kind == "ratio":
            burn_long = _ratio_burn(obj, series, now - cfg.long_s, now)
            if burn_long is None:
                burn_short = None
                detail = "no data"
            else:
                short = _ratio_burn(
                    obj, series, now - cfg.short_s, now
                )
                # an empty short window inherits the long-window burn
                # (sparse sampling must not fake a recovery)
                burn_short = burn_long if short is None else short
                value = burn_long * obj.budget
        else:  # availability
            short = _availability_burn(
                obj, series, now - cfg.short_s, now
            )
            long_ = _availability_burn(
                obj, series, now - cfg.long_s, now
            )
            if long_ is None:
                burn_short = burn_long = None
                detail = "no data"
            else:
                # an empty short window inherits the long-window burn
                # (sparse sampling must not fake a recovery)
                burn_long, value = long_
                burn_short = long_[0] if short is None else short[0]
        results.append(SloResult(
            objective=obj.name,
            kind=obj.kind,
            state=_classify(cfg, burn_short, burn_long),
            burn_short=burn_short,
            burn_long=burn_long,
            value=value,
            detail=detail,
        ))
    return results


def exit_code(results: Sequence[SloResult]) -> int:
    """The CI contract: 0 every objective ok, 1 any warn, 2 any burning."""
    if any(r.state == STATE_BURNING for r in results):
        return 2
    if any(r.state == STATE_WARN for r in results):
        return 1
    return 0


def _round(x: Optional[float]) -> Optional[float]:
    if x is None:
        return None
    if x != x or x in (float("inf"), float("-inf")):
        return x
    return round(float(x), 4)


def results_payload(results: Sequence[SloResult]) -> dict:
    """JSON-able report form shared by ``slo-report --json``, the ops
    console snapshot, and the collector's CI artifacts — one spelling
    of the result schema so scripts never chase two."""
    return {
        "exit": exit_code(results),
        "results": [
            {
                "objective": r.objective, "kind": r.kind,
                "state": r.state, "burn_short": r.burn_short,
                "burn_long": r.burn_long, "value": r.value,
                "detail": r.detail,
            }
            for r in results
        ],
    }


class SloWatch:
    """Objective-state machine emitting ``ev: "slo"`` records.

    Feed it ``evaluate`` results each tick; it emits ONE record per
    state transition (objectives start assumed ok, recovery emits
    ``state: "resolved"``) into the telemetry stream — edges only, so a
    week of healthy watching adds zero lines to events.jsonl."""

    def __init__(self, cfg: SloConfig, emit=None):
        self.cfg = cfg
        self._emit = emit
        self._last: Dict[str, str] = {}

    def seed(self, objective: str, state: str) -> None:
        """Prime the transition detector from persisted alert state
        (restart continuity): a watcher that reboots mid-burn must not
        re-announce the burn, and a persisted ``resolved`` means the
        objective is currently ok."""
        self._last[str(objective)] = (
            STATE_OK if state == STATE_RESOLVED else str(state)
        )

    def observe(
        self, results: Sequence[SloResult], now: Optional[float] = None
    ) -> List[dict]:
        emit = self._emit
        if emit is None:
            from progen_tpu.telemetry.spans import get_telemetry

            emit = get_telemetry().emit
        out: List[dict] = []
        ts = float(time.time() if now is None else now)
        for r in results:
            prev = self._last.get(r.objective, STATE_OK)
            if r.state == prev:
                continue
            self._last[r.objective] = r.state
            state = STATE_RESOLVED if r.state == STATE_OK else r.state
            rec = {
                "ev": "slo",
                "ts": ts,
                "objective": r.objective,
                "state": state,
                "prev": prev,
                "burn_short": _round(r.burn_short),
                "burn_long": _round(r.burn_long),
                "value": _round(r.value),
            }
            if r.detail:
                rec["detail"] = r.detail
            emit(rec)
            out.append(rec)
        return out


def render_report(
    cfg: SloConfig, results: Sequence[SloResult]
) -> str:
    """Human-readable gate report (the slo-report CLI's stdout)."""
    lines = [
        f"SLO report — windows {cfg.short_s:g}s/{cfg.long_s:g}s, "
        f"warn>={cfg.warn:g} hot>={cfg.hot:g}",
        f"{'objective':<22} {'kind':<13} {'state':<8} "
        f"{'burn_short':>10} {'burn_long':>10} {'value':>10}",
    ]

    def _cell(x: Optional[float]) -> str:
        return "-" if x is None else f"{x:.3f}"

    for r in results:
        row = (
            f"{r.objective:<22} {r.kind:<13} {r.state:<8} "
            f"{_cell(r.burn_short):>10} {_cell(r.burn_long):>10} "
            f"{_cell(r.value):>10}"
        )
        if r.detail:
            row += f"  ({r.detail})"
        lines.append(row)
    lines.append(f"gate: exit {exit_code(results)}")
    return "\n".join(lines)
