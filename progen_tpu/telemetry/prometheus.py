"""Prometheus text exposition (format 0.0.4) for ServingMetrics.

Two transports, both fed by the same renderer:

  * ``write_prometheus(path, text)`` — atomic file write (tmp+rename)
    for the node-exporter *textfile collector* pattern; a scraper never
    reads a half-written exposition;
  * ``start_prometheus_server(render_fn)`` — a daemon-thread HTTP
    server answering every GET with a fresh render; point a Prometheus
    scrape job at it directly.

The renderer consumes the structured form of
``serving.metrics.ServingMetrics`` (``structured()``), duck-typed so
this module stays import-free of the serving package: counters become
``counter`` samples, gauges ``gauge``, and timings ``summary`` families
with p50/p95/p99 quantile labels from the reservoir — which is how TTFT
tails finally become visible on a dashboard instead of only a mean.

Contract for fleet aggregation (the collector depends on this): every
summary family exposes ``_sum`` and ``_count`` alongside its quantiles.
Quantiles alone cannot be merged across replicas — fleet averages and
count-weighted quantile merges both need the (sum, count) pair — so a
renderer change that drops either breaks ``fleet_series``; the
merge-correctness tests in tests/test_telemetry.py pin it.
"""

from __future__ import annotations

import http.server
import math
import os
import re
import threading
from pathlib import Path
from typing import Callable

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _name(prefix: str, raw: str) -> str:
    n = _NAME_RE.sub("_", f"{prefix}{raw}")
    return n if not n[:1].isdigit() else f"_{n}"


def escape_label_value(raw: str) -> str:
    """OpenMetrics label-value escape (backslash, quote, newline) — the
    exemplar ``trace_id`` is operator-influenced text riding inside a
    quoted label, so it must round-trip exactly. The inverse lives in
    ``telemetry.slo.unescape_label_value``; both sides of the
    remote-write naming contract use this spelling."""
    return (
        str(raw)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _fmt(v: float) -> str:
    f = float(v)
    # Prometheus spellings for the non-finite values a gauge can carry
    # (an HBM limit on CPU is inf; a poisoned loss is NaN) — the int()
    # collapse below raises on both, so handle them first
    if math.isnan(f):
        return "NaN"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    return repr(int(f)) if f == int(f) else repr(f)


def prometheus_text(metrics, prefix: str = "progen_serve_") -> str:
    """Render a ServingMetrics (anything with ``structured()``) or an
    already-structured dict to Prometheus exposition text."""
    s = metrics.structured() if hasattr(metrics, "structured") else metrics
    lines = []
    for raw, v in sorted(s.get("counters", {}).items()):
        n = _name(prefix, raw + "_total")
        lines += [f"# TYPE {n} counter", f"{n} {_fmt(v)}"]
    gauges = dict(s.get("gauges", {}))
    # derived throughputs are gauges too (true rates, not sampled)
    gauges.update(s.get("derived", {}))
    for raw, v in sorted(gauges.items()):
        n = _name(prefix, raw)
        lines += [f"# TYPE {n} gauge", f"{n} {_fmt(v)}"]
    for raw, t in sorted(s.get("timings", {}).items()):
        base = raw[: -len("_s")] if raw.endswith("_s") else raw
        n = _name(prefix, base + "_seconds")
        lines.append(f"# TYPE {n} summary")
        # trace exemplars ride the quantile lines in OpenMetrics
        # `# {trace_id="..."} value` syntax: the worst observation on
        # the highest quantile, next-worst on the next, so a scrape of
        # "p99 is slow" carries the request ids that made it slow
        exemplars = list(t.get("exemplars") or [])
        qitems = sorted(t.get("quantiles", {}).items())
        ex_by_q = {
            q: exemplars[i]
            for i, (q, _) in enumerate(reversed(qitems))
            if i < len(exemplars)
        }
        for q, qv in qitems:
            line = f'{n}{{quantile="{q}"}} {_fmt(qv)}'
            ex = ex_by_q.get(q)
            if ex:
                tid = escape_label_value(ex.get("trace_id", ""))
                line += (
                    f' # {{trace_id="{tid}"}} '
                    f'{_fmt(ex.get("value", 0.0))}'
                )
            lines.append(line)
        lines.append(f"{n}_sum {_fmt(t['sum'])}")
        lines.append(f"{n}_count {_fmt(t['count'])}")
    return "\n".join(lines) + "\n"


def write_prometheus(path, text: str) -> None:
    """Atomic exposition-file write (textfile-collector contract)."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_name(path.name + ".tmp")
    tmp.write_text(text)
    os.replace(tmp, path)


class _Handler(http.server.BaseHTTPRequestHandler):
    render: Callable[[], str]  # set per-server via subclassing

    def do_GET(self):  # camelCase: BaseHTTPRequestHandler contract
        try:
            body = type(self).render().encode()
        except Exception as e:  # a render bug must not kill the server
            self.send_response(500)
            self.end_headers()
            self.wfile.write(repr(e).encode())
            return
        self.send_response(200)
        self.send_header(
            "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
        )
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):  # scrapes are not log events
        pass


def start_prometheus_server(
    render_fn: Callable[[], str], port: int = 0, host: str = "127.0.0.1"
):
    """Serve ``render_fn()`` on every GET from a daemon thread. Returns
    the server; ``server.server_address[1]`` is the bound port (useful
    with ``port=0``), ``server.shutdown()`` stops it."""
    handler = type("_BoundHandler", (_Handler,), {"render": staticmethod(render_fn)})
    srv = http.server.ThreadingHTTPServer((host, port), handler)
    srv.daemon_threads = True
    t = threading.Thread(
        target=srv.serve_forever, name="prometheus-exporter", daemon=True
    )
    t.start()
    return srv
