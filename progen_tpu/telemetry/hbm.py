"""HBM gauges from ``device.memory_stats()``.

TPU PJRT devices expose allocator stats (bytes in use / peak / limit);
CPU devices usually return nothing, and this degrades to ``{}`` there —
callers can always splat the result into a metrics dict. The per-step
reading costs one local C++ call, so the train loop logs it on every
tracker flush and the serve loop on every snapshot; OOMs then come with
a trajectory, not just a death.
"""

from __future__ import annotations

from typing import Optional


def hbm_gauges(device=None, prefix: str = "hbm/") -> dict:
    """Flat gauge dict (GB, rounded) for ``device`` (default: first
    visible device). Empty when the backend exposes no memory stats."""
    if device is None:
        try:
            import jax

            device = jax.devices()[0]
        except Exception:
            return {}
    stats = getattr(device, "memory_stats", lambda: None)
    try:
        stats = stats() or {}
    except Exception:
        return {}
    out = {}

    def _gb(key: str) -> Optional[float]:
        v = stats.get(key)
        return round(v / 2**30, 3) if v is not None else None

    for src, dst in (
        ("bytes_in_use", "in_use_gb"),
        ("peak_bytes_in_use", "peak_gb"),
        ("bytes_limit", "limit_gb"),
        ("largest_alloc_size", "largest_alloc_gb"),
    ):
        v = _gb(src)
        if v is not None:
            out[f"{prefix}{dst}"] = v
    limit = stats.get("bytes_limit")
    if limit:
        out[f"{prefix}used_pct"] = round(
            100.0 * stats.get("bytes_in_use", 0) / limit, 2
        )
    return out
