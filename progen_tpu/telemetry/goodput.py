"""Goodput ledger: classify every second of loop wall clock.

MegaScale (NSDI'24) frames large-run efficiency as *goodput* — the
fraction of wall clock the accelerators spend on actual training steps
— and gets there by accounting for everything else explicitly. This
ledger is that accounting for one process: the train loop wraps each
kind of work in ``ledger.track(bucket)`` and ``report()`` divides.

Buckets (``BUCKETS``): ``compile`` (trace+first-step), ``step`` (device
step dispatch + the host sync that observes it), ``data`` (host input
pipeline), ``checkpoint``, ``eval``, ``sample``, ``log`` (tracker/
console IO). Whatever no one claimed lands in ``other`` — the report
always sums to wall clock exactly, so a low ``coverage_pct`` is itself
a finding (unattributed time), not a bookkeeping artifact.

MFU says how fast the step is; ``goodput_pct`` says how often the loop
is actually stepping. Both are needed: a 40%-MFU step inside a
50%-goodput loop is a 20%-efficient run.
"""

from __future__ import annotations

import contextlib
import time
from typing import Callable, Dict

BUCKETS = (
    "compile", "step", "data", "checkpoint", "eval", "sample", "log",
)


class _Tracked:
    """Handle yielded by ``track`` — ``seconds`` is set on exit so the
    caller can forward the same measurement elsewhere (e.g.
    ``StepTimer.exclude``) without re-timing."""

    __slots__ = ("seconds",)

    def __init__(self):
        self.seconds = 0.0


class GoodputLedger:
    def __init__(self, clock: Callable[[], float] = time.perf_counter):
        self._clock = clock
        self._t0 = clock()
        self._acc: Dict[str, float] = {}

    def account(self, bucket: str, seconds: float) -> None:
        self._acc[bucket] = self._acc.get(bucket, 0.0) + max(seconds, 0.0)

    @contextlib.contextmanager
    def track(self, bucket: str):
        t0 = self._clock()
        handle = _Tracked()
        try:
            yield handle
        finally:
            handle.seconds = self._clock() - t0
            self.account(bucket, handle.seconds)

    @property
    def wall_s(self) -> float:
        return self._clock() - self._t0

    def report(self) -> dict:
        """Flat dict, tracker-loggable. ``bucket_s/*`` (incl. ``other``)
        sums to ``wall_s`` exactly; ``goodput_pct`` = step share;
        ``coverage_pct`` = attributed share (the ≥95% health check)."""
        wall = max(self.wall_s, 1e-9)
        tracked = sum(self._acc.values())
        out = {"wall_s": round(wall, 4)}
        for b in (*BUCKETS, *sorted(set(self._acc) - set(BUCKETS))):
            if b in self._acc:
                out[f"bucket_s/{b}"] = round(self._acc[b], 4)
        out["bucket_s/other"] = round(max(wall - tracked, 0.0), 4)
        out["goodput_pct"] = round(
            100.0 * self._acc.get("step", 0.0) / wall, 2
        )
        out["coverage_pct"] = round(100.0 * min(tracked / wall, 1.0), 2)
        return out


def _report_from_vector(wall: float, bucket_s: Dict[str, float]) -> dict:
    """Rebuild a ``report()``-shaped dict from raw (wall, bucket) seconds
    — used for the *other* hosts' vectors after the allgather."""
    wall = max(wall, 1e-9)
    tracked = sum(bucket_s.values())
    out = {"wall_s": round(wall, 4)}
    for b in BUCKETS:
        if bucket_s.get(b, 0.0) > 0.0:
            out[f"bucket_s/{b}"] = round(bucket_s[b], 4)
    out["bucket_s/other"] = round(max(wall - tracked, 0.0), 4)
    out["goodput_pct"] = round(100.0 * bucket_s.get("step", 0.0) / wall, 2)
    out["coverage_pct"] = round(100.0 * min(tracked / wall, 1.0), 2)
    return out


def per_host_reports(ledger: "GoodputLedger") -> list:
    """One ``report()`` dict per host, index == ``jax.process_index()``.

    COLLECTIVE under multi-process jax — every process must reach this
    call (it rides a fixed-width ``process_allgather`` of
    ``[wall_s, *bucket seconds]``). Single-process (or jax absent /
    uninitialized) it degrades to ``[ledger.report()]`` with no jax
    dependency at all, so pure-CPU tests exercise the same code path.

    Custom buckets beyond ``BUCKETS`` stay host-local (the wire format
    is fixed-width so hosts can't disagree on vector length); their
    time lands in that host's ``other``, which is still attributed
    wall clock — the cross-host *skew* story is unaffected.
    """
    try:
        import jax

        if jax.process_count() <= 1:
            return [ledger.report()]
        import numpy as np
        from jax.experimental import multihost_utils

        vec = np.asarray(
            [ledger.wall_s]
            + [ledger._acc.get(b, 0.0) for b in BUCKETS],
            dtype=np.float64,
        )
        gathered = np.asarray(multihost_utils.process_allgather(vec))
    except Exception:
        return [ledger.report()]
    reports = []
    for row in gathered:
        bucket_s = {b: float(row[1 + i]) for i, b in enumerate(BUCKETS)}
        reports.append(_report_from_vector(float(row[0]), bucket_s))
    return reports


def goodput_skew(host_reports: list) -> dict:
    """Per-bucket min/max/skew across hosts + the straggler for each.

    ``skew_s`` is max-min bucket seconds; the host holding the max is
    the straggler (a host stuck in ``data`` or ``checkpoint`` shows up
    here as its own skew line — MegaScale's straggler table)."""
    out: dict = {"hosts": len(host_reports)}
    if not host_reports:
        return out
    buckets = sorted(
        {k for rep in host_reports for k in rep if k.startswith("bucket_s/")}
    )
    for key in ("goodput_pct", *buckets):
        vals = [float(rep.get(key, 0.0)) for rep in host_reports]
        lo, hi = min(vals), max(vals)
        name = key.split("/", 1)[-1] if "/" in key else key
        out[name] = {
            "min": round(lo, 4),
            "max": round(hi, 4),
            "skew": round(hi - lo, 4),
            "straggler": int(vals.index(hi)),
        }
    return out


def emit_per_host_goodput(ledger: "GoodputLedger", emit=None) -> list:
    """Gather per-host reports (collective — see ``per_host_reports``)
    and emit one ``{"ev": "goodput_host", "host": i, ...}`` record per
    host through the process telemetry (or an explicit ``emit``). Every
    host emits the full table into its own event file, so any single
    host's ``events.jsonl`` is enough to reconstruct the skew."""
    reports = per_host_reports(ledger)
    if emit is None:
        from progen_tpu.telemetry import spans

        emit = spans.get_telemetry().emit
    now = time.time()
    for i, rep in enumerate(reports):
        emit({"ev": "goodput_host", "ts": now, "host": i, **rep})
    return reports
