"""Goodput ledger: classify every second of loop wall clock.

MegaScale (NSDI'24) frames large-run efficiency as *goodput* — the
fraction of wall clock the accelerators spend on actual training steps
— and gets there by accounting for everything else explicitly. This
ledger is that accounting for one process: the train loop wraps each
kind of work in ``ledger.track(bucket)`` and ``report()`` divides.

Buckets (``BUCKETS``): ``compile`` (trace+first-step), ``step`` (device
step dispatch + the host sync that observes it), ``data`` (host input
pipeline), ``checkpoint``, ``eval``, ``sample``, ``log`` (tracker/
console IO). Whatever no one claimed lands in ``other`` — the report
always sums to wall clock exactly, so a low ``coverage_pct`` is itself
a finding (unattributed time), not a bookkeeping artifact.

MFU says how fast the step is; ``goodput_pct`` says how often the loop
is actually stepping. Both are needed: a 40%-MFU step inside a
50%-goodput loop is a 20%-efficient run.
"""

from __future__ import annotations

import contextlib
import time
from typing import Callable, Dict

BUCKETS = (
    "compile", "step", "data", "checkpoint", "eval", "sample", "log",
)


class _Tracked:
    """Handle yielded by ``track`` — ``seconds`` is set on exit so the
    caller can forward the same measurement elsewhere (e.g.
    ``StepTimer.exclude``) without re-timing."""

    __slots__ = ("seconds",)

    def __init__(self):
        self.seconds = 0.0


class GoodputLedger:
    def __init__(self, clock: Callable[[], float] = time.perf_counter):
        self._clock = clock
        self._t0 = clock()
        self._acc: Dict[str, float] = {}

    def account(self, bucket: str, seconds: float) -> None:
        self._acc[bucket] = self._acc.get(bucket, 0.0) + max(seconds, 0.0)

    @contextlib.contextmanager
    def track(self, bucket: str):
        t0 = self._clock()
        handle = _Tracked()
        try:
            yield handle
        finally:
            handle.seconds = self._clock() - t0
            self.account(bucket, handle.seconds)

    @property
    def wall_s(self) -> float:
        return self._clock() - self._t0

    def report(self) -> dict:
        """Flat dict, tracker-loggable. ``bucket_s/*`` (incl. ``other``)
        sums to ``wall_s`` exactly; ``goodput_pct`` = step share;
        ``coverage_pct`` = attributed share (the ≥95% health check)."""
        wall = max(self.wall_s, 1e-9)
        tracked = sum(self._acc.values())
        out = {"wall_s": round(wall, 4)}
        for b in (*BUCKETS, *sorted(set(self._acc) - set(BUCKETS))):
            if b in self._acc:
                out[f"bucket_s/{b}"] = round(self._acc[b], 4)
        out["bucket_s/other"] = round(max(wall - tracked, 0.0), 4)
        out["goodput_pct"] = round(
            100.0 * self._acc.get("step", 0.0) / wall, 2
        )
        out["coverage_pct"] = round(100.0 * min(tracked / wall, 1.0), 2)
        return out
