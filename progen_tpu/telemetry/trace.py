"""events.jsonl → Chrome Trace Event / Perfetto JSON.

The span stream (``spans.py``) already writes Dapper-style B/E pairs;
this module is the ``json.dumps`` between that file and a real trace
viewer (``ui.perfetto.dev`` or ``chrome://tracing``). The mapping is
direct by design:

  * span ``B``/``E`` records → duration-begin/end slices, ``ts`` in
    microseconds, ``pid`` = host (``jax.process_index()`` tag stamped
    by ``Telemetry.emit``), ``tid`` = OS thread — so a two-host run
    renders as two process tracks and the watchdog/checkpoint threads
    get their own rows;
  * one-shot records (``retry``, ``anomaly``, ``stall``, ``chaos``,
    ``ckpt_commit_failed``, ``clock_beacon``, …) → instant events
    (``ph: "i"``) pinned to their host track;
  * serving ``req`` records (scheduler lifecycle: queued → prefill →
    decode, emitted with ``ph: "b"/"n"/"e"`` and the request id) →
    async trace events (``cat: "request"``, ``id`` = request) — every
    accepted request renders as ONE async track with its phases nested
    under it, instants for first_token/deadline_exceeded riding the
    same track;
  * ``slots`` records → a ``slot_occupancy`` counter track (in-use vs
    free decode lanes over time);
  * each ``retry`` additionally opens a flow arrow (``ph: "s"`` →
    ``ph: "f"``, ``bp: "e"``) from the retry instant to the END of the
    innermost span open on that host when it fired — the viewer draws
    the line from the fault to the operation that absorbed its latency
    (an IO retry inside ``ckpt/save`` visibly bills the save, not the
    step). A retry outside any open span stays a bare instant;
  * ``goodput_host`` records and metrics.jsonl rows → counter tracks
    (``ph: "C"``): ``step_ms``, ``mfu``, ``tokens_per_sec_per_chip``,
    ``goodput_pct``, stacked ``goodput_bucket_s`` series, and the HBM
    gauges;
  * ``M`` metadata names each pid ``host N`` and each tid by its
    recorded thread name.

The per-host goodput *skew* table rides along as an extra top-level key
(``progenGoodputSkew``) — trace viewers ignore unknown top-level keys,
so one file serves both the viewer and the summarize tooling.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Iterable, Iterator, Optional

from progen_tpu.telemetry.goodput import goodput_skew

# record keys that map onto trace-event structure rather than args
_STRUCTURAL = {"ev", "span", "id", "ts", "pid", "tid", "thread"}

# req-record keys that map onto async-event structure rather than args
_REQ_STRUCTURAL = _STRUCTURAL | {"ph", "name", "req"}

# one-shot telemetry records rendered as instant events on the host track
INSTANT_EVENTS = (
    "retry", "anomaly", "anomaly_rollback", "stall", "stall_escalation",
    "ckpt_quarantine", "ckpt_commit_failed", "chaos", "goodput",
    "clock_beacon", "request_rejected", "reload", "journal_replay",
    "route", "slo", "alert", "flight", "profile",
)

# metrics.jsonl columns that get their own counter track
_SCALAR_COUNTERS = (
    "step_ms", "mfu", "tokens_per_sec_per_chip", "goodput_pct",
)


class LineDrops:
    """Tally of torn/garbage lines ``iter_jsonl`` skipped. A trace that
    quietly lost records is an observability bug, so every CLI surface
    (export-trace, summarize, stitch) threads one of these through its
    reads and reports the total."""

    __slots__ = ("count",)

    def __init__(self):
        self.count = 0


def iter_jsonl(path, drops: Optional[LineDrops] = None) -> Iterator[dict]:
    """Parsed records, one per line; a torn final line (the crash-safety
    contract allows exactly one) or stray garbage is skipped, not fatal
    — a trace of a crashed run is the whole point. Skips are counted
    into ``drops`` so callers can surface how many lines the trace is
    missing."""
    with Path(path).open() as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                if drops is not None:
                    drops.count += 1
                continue
            if isinstance(rec, dict):
                yield rec
            elif drops is not None:
                drops.count += 1


def iter_events_any(
    path, drops: Optional[LineDrops] = None
) -> Iterator[dict]:
    """Telemetry records from events.jsonl OR a flight-recorder dump.

    A crashed host leaves no events.jsonl tail past its last flush —
    its black box (``flight-<host>-<ts>.json``, telemetry/flight.py)
    holds the final seconds instead. Dumps replay their captured ring
    through the same iterator shape, so export-trace and stitch render
    a dead host's last moments exactly like a survivor's stream. A dump
    that fails digest verification counts as one dropped line rather
    than raising: a torn dump from a badly-timed kill must not take the
    rest of a fleet trace down with it."""
    from progen_tpu.telemetry import flight

    if flight.is_dump_path(path):
        try:
            records = flight.dump_records(path)
        except (OSError, ValueError):
            if drops is not None:
                drops.count += 1
            return
        for rec in records:
            if isinstance(rec, dict):
                yield rec
            elif drops is not None:
                drops.count += 1
        return
    yield from iter_jsonl(path, drops)


def _us(ts: float) -> float:
    return float(ts) * 1e6


def _args(rec: dict) -> dict:
    return {k: v for k, v in rec.items() if k not in _STRUCTURAL}


def _counter(name: str, ts: float, pid: int, series: dict) -> dict:
    return {
        "ph": "C", "name": name, "ts": _us(ts), "pid": pid, "tid": 0,
        "args": series,
    }


def _goodput_counters(rec: dict, ts: float, pid: int) -> list:
    out = []
    if "goodput_pct" in rec:
        out.append(_counter(
            "goodput_pct", ts, pid, {"goodput_pct": rec["goodput_pct"]}
        ))
    buckets = {
        k.split("/", 1)[1]: v
        for k, v in rec.items() if k.startswith("bucket_s/")
    }
    if buckets:
        out.append(_counter("goodput_bucket_s", ts, pid, buckets))
    return out


def build_trace(
    events: Iterable[dict], metrics: Iterable[dict] = ()
) -> dict:
    """Assemble the Trace Event JSON object from parsed events.jsonl
    records (and optionally metrics.jsonl rows for the perf counter
    tracks). Returns the dict — callers ``json.dump`` it."""
    trace_events: list = []
    meta: list = []
    seen_pids: set = set()
    seen_tids: set = set()
    host_reports: dict = {}
    # retry→absorbing-span flow state: per-pid stack of open spans in
    # input order; a retry binds to the innermost one, and the matching
    # flow-end lands when that span's E arrives. Spans that never close
    # (crash mid-span) leave an s-only flow — viewers render the start
    # arrowhead, which is the honest picture.
    open_spans: dict = {}
    flow_id = 0

    def _note_pid(pid: int) -> None:
        if pid not in seen_pids:
            seen_pids.add(pid)
            meta.append({
                "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
                "args": {"name": f"host {pid}"},
            })

    for rec in events:
        ev = rec.get("ev")
        ts = rec.get("ts")
        if ev is None or ts is None:
            continue
        pid = int(rec.get("pid", 0))
        if ev in ("B", "E"):
            tid = int(rec.get("tid", 0) or 0)
            _note_pid(pid)
            thread = rec.get("thread")
            if thread and (pid, tid) not in seen_tids:
                seen_tids.add((pid, tid))
                meta.append({
                    "ph": "M", "name": "thread_name", "pid": pid,
                    "tid": tid, "args": {"name": str(thread)},
                })
            trace_events.append({
                "ph": ev, "name": str(rec.get("span", "?")),
                "cat": "span", "ts": _us(ts), "pid": pid, "tid": tid,
                "args": _args(rec),
            })
            if ev == "B":
                open_spans.setdefault(pid, []).append(
                    {"tid": tid, "pending": []}
                )
            else:
                stack = open_spans.get(pid) or []
                for i in range(len(stack) - 1, -1, -1):
                    if stack[i]["tid"] != tid:
                        continue
                    for fid in stack.pop(i)["pending"]:
                        trace_events.append({
                            "ph": "f", "bp": "e", "cat": "flow",
                            "name": "retry_absorbed", "id": fid,
                            "ts": _us(ts), "pid": pid, "tid": tid,
                        })
                    break
        elif ev == "req":
            # serving request lifecycle: async begin/instant/end keyed
            # on the request id, one async track per request
            ph = rec.get("ph")
            rid = rec.get("req")
            if ph not in ("b", "n", "e") or rid is None:
                continue
            _note_pid(pid)
            trace_events.append({
                "ph": ph, "cat": "request",
                "name": str(rec.get("name", "request")),
                "id": str(rid), "ts": _us(ts), "pid": pid, "tid": 0,
                "args": {
                    k: v for k, v in rec.items()
                    if k not in _REQ_STRUCTURAL
                },
            })
        elif ev == "slots":
            _note_pid(pid)
            trace_events.append(_counter("slot_occupancy", ts, pid, {
                k: rec[k] for k in ("in_use", "free") if k in rec
            }))
        elif ev == "goodput_host":
            host = int(rec.get("host", pid))
            _note_pid(host)
            host_reports[host] = {
                k: v for k, v in rec.items()
                if k not in ("ev", "ts", "host", "pid")
            }
            trace_events.extend(_goodput_counters(rec, ts, host))
        elif ev in INSTANT_EVENTS:
            _note_pid(pid)
            trace_events.append({
                "ph": "i", "name": str(ev), "cat": "event",
                "ts": _us(ts), "pid": pid, "tid": 0, "s": "p",
                "args": _args(rec),
            })
            if ev == "retry":
                stack = open_spans.get(pid) or []
                if stack:
                    frame = stack[-1]
                    flow_id += 1
                    frame["pending"].append(flow_id)
                    trace_events.append({
                        "ph": "s", "cat": "flow",
                        "name": "retry_absorbed", "id": flow_id,
                        "ts": _us(ts), "pid": pid, "tid": frame["tid"],
                    })

    for rec in metrics:
        ts = rec.get("_time")
        if ts is None:
            continue
        pid = int(rec.get("pid", 0))
        _note_pid(pid)
        for key in _SCALAR_COUNTERS:
            if key in rec:
                trace_events.append(
                    _counter(key, ts, pid, {key: rec[key]})
                )
        buckets = {
            k.split("/", 1)[1]: v
            for k, v in rec.items() if k.startswith("bucket_s/")
        }
        if buckets:
            trace_events.append(
                _counter("goodput_bucket_s", ts, pid, buckets)
            )
        hbm = {
            k.split("/", 1)[1]: v
            for k, v in rec.items() if k.startswith("hbm/")
        }
        if hbm:
            trace_events.append(_counter("hbm", ts, pid, hbm))

    # stable sort: records at the same ts keep file order, so a B always
    # precedes its zero-duration E and viewers never see a negative nest
    trace_events.sort(key=lambda e: e["ts"])
    out = {
        "traceEvents": meta + trace_events,
        "displayTimeUnit": "ms",
    }
    if host_reports:
        reports = [host_reports[h] for h in sorted(host_reports)]
        out["progenGoodputSkew"] = goodput_skew(reports)
    return out


def export_trace(
    events_path, out_path, metrics_path: Optional[str] = None
) -> dict:
    """File-to-file convenience used by the CLI: read events.jsonl (and
    metrics.jsonl when present), write Trace Event JSON, return the
    trace dict. ``progenDroppedLines`` on the result counts torn/
    garbage lines the readers had to skip."""
    drops = LineDrops()
    metrics: list = []
    if metrics_path is not None and Path(metrics_path).exists():
        metrics = list(iter_jsonl(metrics_path, drops))
    trace = build_trace(iter_events_any(events_path, drops), metrics)
    trace["progenDroppedLines"] = drops.count
    out_path = Path(out_path)
    out_path.parent.mkdir(parents=True, exist_ok=True)
    with out_path.open("w") as f:
        json.dump(trace, f)
    return trace
