"""Bounded ring-buffer time-series store for fleet samples.

The collector scrapes every few seconds forever; an unbounded JSONL
would eat the disk in a day. This store keeps hours of fleet history
in a fixed byte budget by trading *resolution* for *retention*, never
the reverse:

  * records append to fixed-size JSONL **block** files
    (``block-<seq>-l<level>.jsonl``); when the active block passes
    ``block_bytes`` it is sealed and a new one opened — the journal's
    write discipline (one ``write``+``flush`` per line) so a SIGKILL
    tears at most the final line;
  * when total bytes pass ``budget_bytes``, the sealed block with the
    LOWEST compaction level (ties → oldest) is **downsampled 2:1**:
    consecutive samples from the same source merge pairwise, keeping
    the later sample (cumulative counters and timing reservoirs lose
    nothing), summing the merged-sample tally ``n``, and keeping the
    *worst* ``up`` of the pair so availability degradation is never
    compacted away. The rewrite is tmp + ``os.replace`` (atomic) and
    bumps the filename's level;
  * a block that reaches ``max_level`` and is still over budget is
    deleted oldest-first — the ring wraps;
  * torn tails never poison reads: reopening an active block truncates
    a partial final line (counted), and block reads go through
    ``iter_jsonl`` so garbage lines are skipped and tallied into
    ``dropped_lines`` for the console to surface.

Single-writer by design (one collector process owns a store directory);
readers (``progen-tpu-top``, ``slo-report --tsdb``) only ever see whole
lines thanks to the flush-per-line contract.
"""

from __future__ import annotations

import json
import os
import re
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Tuple

from progen_tpu.telemetry.trace import LineDrops, iter_jsonl

_BLOCK_RE = re.compile(r"^block-(\d{8})-l(\d+)\.jsonl$")


def _block_name(seq: int, level: int) -> str:
    return f"block-{seq:08d}-l{level}.jsonl"


def merge_pair(a: dict, b: dict) -> dict:
    """Downsample two consecutive same-source records into one. ``b``
    (the later sample) wins wholesale — counters/timings are cumulative
    so dropping ``a`` loses no totals — except the fields where "keep
    the later" would hide a degradation: ``up`` keeps the pair's worst
    and ``n`` keeps the tally of raw samples this record stands for."""
    out = dict(b)
    na = int(a.get("n", 1))
    nb = int(b.get("n", 1))
    out["n"] = na + nb
    if "up" in a or "up" in b:
        out["up"] = min(int(a.get("up", 1)), int(b.get("up", 1)))
    return out


class TsdbReader:
    """Read-only view of a store directory — what ``progen-tpu-top``
    and ``slo-report --tsdb`` open, so inspecting a live collector's
    store never races its writer (no truncation, no file handles kept).
    A torn final line shows up in ``drops``, exactly as a crashed
    writer's journal would."""

    def __init__(self, root):
        self.root = Path(root)
        self.dropped_lines = 0

    def _scan(self) -> List[Tuple[int, int, Path]]:
        out = []
        try:
            entries = list(self.root.iterdir())
        except OSError:
            return []
        for p in entries:
            m = _BLOCK_RE.match(p.name)
            if m:
                out.append((int(m.group(1)), int(m.group(2)), p))
        out.sort(key=lambda b: b[0])
        return out

    def total_bytes(self) -> int:
        return sum(p.stat().st_size for _, _, p in self._scan())

    def blocks(self) -> List[Dict[str, int]]:
        return [
            {"seq": seq, "level": level, "bytes": p.stat().st_size}
            for seq, level, p in self._scan()
        ]

    def read(self, drops: Optional[LineDrops] = None) -> Iterator[dict]:
        own = LineDrops()
        for _, _, path in self._scan():
            for rec in iter_jsonl(path, own):
                yield rec
        if drops is not None:
            drops.count += own.count


class RingTSDB:
    """Append-only facade over the block directory; see module doc."""

    def __init__(
        self,
        root,
        budget_bytes: int = 8 << 20,
        block_bytes: int = 256 << 10,
        max_level: int = 4,
    ):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self.budget_bytes = int(budget_bytes)
        self.block_bytes = int(block_bytes)
        self.max_level = int(max_level)
        self.dropped_lines = 0
        self._fh = None
        self._active_seq = 0
        self._active_bytes = 0
        self._open_active()

    # -- block bookkeeping ------------------------------------------------

    def _scan(self) -> List[Tuple[int, int, Path]]:
        """Sorted (seq, level, path) for every block file on disk."""
        out = []
        for p in self.root.iterdir():
            m = _BLOCK_RE.match(p.name)
            if m:
                out.append((int(m.group(1)), int(m.group(2)), p))
        out.sort(key=lambda b: b[0])
        return out

    def _open_active(self) -> None:
        blocks = self._scan()
        if blocks:
            seq, level, path = blocks[-1]
            size = path.stat().st_size
            if level == 0 and size < self.block_bytes:
                self._truncate_torn_tail(path)
                self._active_seq = seq
                self._fh = path.open("a")
                self._active_bytes = path.stat().st_size
                return
            self._active_seq = seq + 1
        else:
            self._active_seq = 1
        path = self.root / _block_name(self._active_seq, 0)
        self._fh = path.open("a")
        self._active_bytes = path.stat().st_size

    def _truncate_torn_tail(self, path: Path) -> None:
        """Drop a partial final line left by a killed writer so the
        reopened block appends on a clean line boundary."""
        try:
            data = path.read_bytes()
        except OSError:
            return
        if not data or data.endswith(b"\n"):
            return
        keep = data.rfind(b"\n") + 1
        with path.open("r+b") as f:
            f.truncate(keep)
        self.dropped_lines += 1

    def _seal_active(self) -> None:
        self._fh.close()
        self._active_seq += 1
        path = self.root / _block_name(self._active_seq, 0)
        self._fh = path.open("a")
        self._active_bytes = 0

    # -- public API -------------------------------------------------------

    def append(self, rec: dict) -> None:
        line = json.dumps(rec, separators=(",", ":")) + "\n"
        self._fh.write(line)
        self._fh.flush()
        self._active_bytes += len(line.encode("utf-8"))
        if self._active_bytes >= self.block_bytes:
            self._seal_active()
            self._enforce_budget()

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def total_bytes(self) -> int:
        return sum(p.stat().st_size for _, _, p in self._scan())

    def blocks(self) -> List[Dict[str, int]]:
        return [
            {"seq": seq, "level": level, "bytes": p.stat().st_size}
            for seq, level, p in self._scan()
        ]

    def read(self, drops: Optional[LineDrops] = None) -> Iterator[dict]:
        """Every record, oldest block first. Skipped lines are counted
        into ``drops`` (and mirrored on ``dropped_lines``)."""
        own = LineDrops()
        for _, _, path in self._scan():
            for rec in iter_jsonl(path, own):
                yield rec
        if drops is not None:
            drops.count += own.count

    # -- compaction -------------------------------------------------------

    def _enforce_budget(self) -> None:
        """Downsample (then, at max level, drop) sealed blocks until the
        directory fits the budget again. Every pass either shrinks a
        block, bumps its level, or deletes it — so this terminates."""
        while self.total_bytes() > self.budget_bytes:
            sealed = [
                b for b in self._scan() if b[0] != self._active_seq
            ]
            if not sealed:
                return
            seq, level, path = min(sealed, key=lambda b: (b[1], b[0]))
            if level >= self.max_level:
                path.unlink()
                continue
            self._downsample(seq, level, path)

    def _downsample(self, seq: int, level: int, path: Path) -> None:
        drops = LineDrops()
        recs = list(iter_jsonl(path, drops))
        self.dropped_lines += drops.count
        merged: List[dict] = []
        pending: Dict[object, int] = {}
        for rec in recs:
            key = (rec.get("ev"), rec.get("source"))
            slot = pending.pop(key, None)
            if slot is None:
                pending[key] = len(merged)
                merged.append(rec)
            else:
                merged[slot] = merge_pair(merged[slot], rec)
        tmp = path.with_suffix(".tmp")
        with tmp.open("w") as f:
            for rec in merged:
                f.write(json.dumps(rec, separators=(",", ":")) + "\n")
            f.flush()
            os.fsync(f.fileno())
        dst = self.root / _block_name(seq, level + 1)
        os.replace(tmp, dst)
        path.unlink()
