"""Bounded ring-buffer time-series store for fleet samples.

The collector scrapes every few seconds forever; an unbounded JSONL
would eat the disk in a day. This store keeps hours of fleet history
in a fixed byte budget by trading *resolution* for *retention*, never
the reverse:

  * records append to fixed-size JSONL **block** files
    (``block-<seq>-l<level>.jsonl``); when the active block passes
    ``block_bytes`` it is sealed and a new one opened — the journal's
    write discipline (one ``write``+``flush`` per line) so a SIGKILL
    tears at most the final line;
  * when total bytes pass ``budget_bytes``, the sealed block with the
    LOWEST compaction level (ties → oldest) is **downsampled 2:1**:
    consecutive samples from the same source merge pairwise, keeping
    the later sample (cumulative counters and timing reservoirs lose
    nothing), summing the merged-sample tally ``n``, and keeping the
    *worst* ``up`` of the pair so availability degradation is never
    compacted away. The rewrite is tmp + ``os.replace`` (atomic) and
    bumps the filename's level;
  * a block that reaches ``max_level`` and is still over budget is
    deleted oldest-first — the ring wraps;
  * torn tails never poison reads: reopening an active block truncates
    a partial final line (counted), and block reads go through
    ``iter_jsonl`` so garbage lines are skipped and tallied into
    ``dropped_lines`` for the console to surface.

**Retention tiering** (optional): with a :class:`BlockShipper`
attached, every sealed block is uploaded VERBATIM to an archive
directory *before* the ring degrades it — so downsampling trades
resolution only in the hot store, never in history. The archive
carries a ``manifest.json`` of ``{block: [size, sha256]}`` entries
(the checkpoint digest-manifest pattern: a copy is only as good as its
worst byte, and verification happens at ship time, not at the restore
emergency). Each ship decision is one ``ev:"ship"`` record
(``op`` ∈ ``shipped``/``skipped``/``verify_failed``, built only in
this module — PGL006). The ring writes an ``archive.json`` pointer
beside its blocks so :class:`TsdbReader` (hence ``slo-report --tsdb``
and ``progen-tpu-top``) transparently reads archive+ring as ONE
continuous store: for a block seq present in both, the lowest
compaction level wins (the archive's verbatim copy beats the ring's
downsampled survivor), and seqs the ring already dropped replay from
the archive alone.

Single-writer by design (one collector process owns a store directory);
readers (``progen-tpu-top``, ``slo-report --tsdb``) only ever see whole
lines thanks to the flush-per-line contract.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import time
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Tuple

from progen_tpu.telemetry.spans import EventLog
from progen_tpu.telemetry.trace import LineDrops, iter_jsonl

_BLOCK_RE = re.compile(r"^block-(\d{8})-l(\d+)\.jsonl$")


def _block_name(seq: int, level: int) -> str:
    return f"block-{seq:08d}-l{level}.jsonl"


ARCHIVE_POINTER = "archive.json"
MANIFEST_NAME = "manifest.json"


def _sha256_file(path: Path) -> Tuple[int, str]:
    h = hashlib.sha256()
    size = 0
    with path.open("rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
            size += len(chunk)
    return size, h.hexdigest()


def verify_archive(dest) -> Dict[str, bool]:
    """``{block_name: digest_ok}`` for every manifest entry — what the
    CI egress smoke and restore tooling call before trusting an
    archive. Missing files and size/digest mismatches are ``False``."""
    dest = Path(dest)
    try:
        manifest = json.loads((dest / MANIFEST_NAME).read_text())
    except (OSError, ValueError):
        return {}
    out: Dict[str, bool] = {}
    for name, entry in manifest.items():
        try:
            size, digest = _sha256_file(dest / name)
            out[name] = (
                size == int(entry[0]) and digest == str(entry[1])
            )
        except (OSError, ValueError, IndexError):
            out[name] = False
    return out


class BlockShipper:
    """Verbatim block archival with a digest manifest; see module doc.
    One shipper owns one archive directory (same single-writer contract
    as the ring itself)."""

    def __init__(self, dest, log: bool = True):
        self.dest = Path(dest)
        self.dest.mkdir(parents=True, exist_ok=True)
        self.manifest_path = self.dest / MANIFEST_NAME
        try:
            self._manifest = json.loads(self.manifest_path.read_text())
        except (OSError, ValueError):
            self._manifest = {}
        self._log = EventLog(self.dest / "ship.jsonl") if log else None
        self.shipped = 0
        self.skipped = 0
        self.verify_failed = 0

    def close(self) -> None:
        if self._log is not None:
            self._log.close()

    def _best_level(self, seq: int) -> Optional[int]:
        """Lowest (best) archived compaction level for ``seq``."""
        best = None
        for name in self._manifest:
            m = _BLOCK_RE.match(name)
            if m and int(m.group(1)) == seq:
                lvl = int(m.group(2))
                best = lvl if best is None else min(best, lvl)
        return best

    def _save_manifest(self) -> None:
        tmp = self.manifest_path.with_suffix(".tmp")
        with tmp.open("w") as f:
            f.write(json.dumps(self._manifest, sort_keys=True))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.manifest_path)

    def _record(self, op: str, seq: int, level: int, name: str,
                size: int, digest: str, error: str = "") -> str:
        rec = {
            "ev": "ship",
            "ts": round(time.time(), 3),
            "op": op,
            "block": name,
            "seq": int(seq),
            "level": int(level),
            "bytes": int(size),
            "sha256": digest,
        }
        if error:
            rec["error"] = error
        if self._log is not None:
            self._log.emit(rec)
        self.shipped += op == "shipped"
        self.skipped += op == "skipped"
        self.verify_failed += op == "verify_failed"
        return op

    def ship(self, seq: int, level: int, path: Path) -> str:
        """Archive one sealed block about to be degraded; returns the
        op recorded. Never raises — a broken archive costs history
        tiering, not the collector's scrape loop."""
        name = path.name
        best = self._best_level(seq)
        if best is not None and best <= level:
            # an as-good-or-better copy is already archived (the l0
            # original shipped at first downsample; its l1 survivor
            # coming around again adds nothing)
            return self._record("skipped", seq, level, name, 0, "")
        try:
            src_size, src_digest = _sha256_file(path)
            dst = self.dest / name
            tmp = dst.with_suffix(".tmp")
            with path.open("rb") as fsrc, tmp.open("wb") as fdst:
                for chunk in iter(lambda: fsrc.read(1 << 20), b""):
                    fdst.write(chunk)
                fdst.flush()
                os.fsync(fdst.fileno())
            os.replace(tmp, dst)
            dst_size, dst_digest = _sha256_file(dst)
        except OSError as exc:
            return self._record(
                "verify_failed", seq, level, name, 0, "", error=str(exc)
            )
        if (dst_size, dst_digest) != (src_size, src_digest):
            return self._record(
                "verify_failed", seq, level, name, dst_size, dst_digest,
                error="digest mismatch after copy",
            )
        self._manifest[name] = [src_size, src_digest]
        self._save_manifest()
        return self._record(
            "shipped", seq, level, name, src_size, src_digest
        )


def merge_pair(a: dict, b: dict) -> dict:
    """Downsample two consecutive same-source records into one. ``b``
    (the later sample) wins wholesale — counters/timings are cumulative
    so dropping ``a`` loses no totals — except the fields where "keep
    the later" would hide a degradation: ``up`` keeps the pair's worst
    and ``n`` keeps the tally of raw samples this record stands for."""
    out = dict(b)
    na = int(a.get("n", 1))
    nb = int(b.get("n", 1))
    out["n"] = na + nb
    if "up" in a or "up" in b:
        out["up"] = min(int(a.get("up", 1)), int(b.get("up", 1)))
    return out


class TsdbReader:
    """Read-only view of a store directory — what ``progen-tpu-top``
    and ``slo-report --tsdb`` open, so inspecting a live collector's
    store never races its writer (no truncation, no file handles kept).
    A torn final line shows up in ``drops``, exactly as a crashed
    writer's journal would.

    With an archive (explicit ``archive=`` or the ring's
    ``archive.json`` pointer) the view is the archive+ring UNION: per
    block seq the lowest compaction level wins, so replay sees the
    verbatim history for everything that was shipped before the ring
    degraded it — one continuous store across the retention seam."""

    def __init__(self, root, archive=None):
        self.root = Path(root)
        self.archive = Path(archive) if archive else self._pointer()
        self.dropped_lines = 0

    def _pointer(self) -> Optional[Path]:
        try:
            raw = json.loads((self.root / ARCHIVE_POINTER).read_text())
            return Path(raw["path"])
        except (OSError, ValueError, KeyError, TypeError):
            return None

    @staticmethod
    def _scan_dir(root: Optional[Path]) -> List[Tuple[int, int, Path]]:
        out = []
        if root is None:
            return out
        try:
            entries = list(root.iterdir())
        except OSError:
            return []
        for p in entries:
            m = _BLOCK_RE.match(p.name)
            if m:
                out.append((int(m.group(1)), int(m.group(2)), p))
        out.sort(key=lambda b: b[0])
        return out

    def _scan(self) -> List[Tuple[int, int, Path]]:
        # archive first, ring second: on equal (seq, level) the ring's
        # live copy wins the dict insert below
        by_seq: Dict[int, Tuple[int, int, Path]] = {}
        for seq, level, p in (
            self._scan_dir(self.archive) + self._scan_dir(self.root)
        ):
            cur = by_seq.get(seq)
            if cur is None or level <= cur[1]:
                by_seq[seq] = (seq, level, p)
        return sorted(by_seq.values())

    def total_bytes(self) -> int:
        return sum(p.stat().st_size for _, _, p in self._scan())

    def blocks(self) -> List[Dict[str, int]]:
        ring = {p for _, _, p in self._scan_dir(self.root)}
        return [
            {
                "seq": seq,
                "level": level,
                "bytes": p.stat().st_size,
                "archived": int(p not in ring),
            }
            for seq, level, p in self._scan()
        ]

    def read(self, drops: Optional[LineDrops] = None) -> Iterator[dict]:
        own = LineDrops()
        for _, _, path in self._scan():
            for rec in iter_jsonl(path, own):
                yield rec
        if drops is not None:
            drops.count += own.count


class RingTSDB:
    """Append-only facade over the block directory; see module doc."""

    def __init__(
        self,
        root,
        budget_bytes: int = 8 << 20,
        block_bytes: int = 256 << 10,
        max_level: int = 4,
        shipper: Optional[BlockShipper] = None,
    ):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self.budget_bytes = int(budget_bytes)
        self.block_bytes = int(block_bytes)
        self.max_level = int(max_level)
        self.shipper = shipper
        self.dropped_lines = 0
        self._fh = None
        self._active_seq = 0
        self._active_bytes = 0
        self._open_active()
        if shipper is not None:
            # pointer beside the blocks: readers follow it to the
            # archive without needing a flag threaded through every CLI
            pointer = self.root / ARCHIVE_POINTER
            tmp = pointer.with_suffix(".tmp")
            tmp.write_text(
                json.dumps({"path": str(shipper.dest.resolve())})
            )
            os.replace(tmp, pointer)

    # -- block bookkeeping ------------------------------------------------

    def _scan(self) -> List[Tuple[int, int, Path]]:
        """Sorted (seq, level, path) for every block file on disk."""
        out = []
        for p in self.root.iterdir():
            m = _BLOCK_RE.match(p.name)
            if m:
                out.append((int(m.group(1)), int(m.group(2)), p))
        out.sort(key=lambda b: b[0])
        return out

    def _open_active(self) -> None:
        blocks = self._scan()
        if blocks:
            seq, level, path = blocks[-1]
            size = path.stat().st_size
            if level == 0 and size < self.block_bytes:
                self._truncate_torn_tail(path)
                self._active_seq = seq
                self._fh = path.open("a")
                self._active_bytes = path.stat().st_size
                return
            self._active_seq = seq + 1
        else:
            self._active_seq = 1
        path = self.root / _block_name(self._active_seq, 0)
        self._fh = path.open("a")
        self._active_bytes = path.stat().st_size

    def _truncate_torn_tail(self, path: Path) -> None:
        """Drop a partial final line left by a killed writer so the
        reopened block appends on a clean line boundary."""
        try:
            data = path.read_bytes()
        except OSError:
            return
        if not data or data.endswith(b"\n"):
            return
        keep = data.rfind(b"\n") + 1
        with path.open("r+b") as f:
            f.truncate(keep)
        self.dropped_lines += 1

    def _seal_active(self) -> None:
        self._fh.close()
        self._active_seq += 1
        path = self.root / _block_name(self._active_seq, 0)
        self._fh = path.open("a")
        self._active_bytes = 0

    # -- public API -------------------------------------------------------

    def append(self, rec: dict) -> None:
        line = json.dumps(rec, separators=(",", ":")) + "\n"
        self._fh.write(line)
        self._fh.flush()
        self._active_bytes += len(line.encode("utf-8"))
        if self._active_bytes >= self.block_bytes:
            self._seal_active()
            self._enforce_budget()

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        if self.shipper is not None:
            self.shipper.close()

    def total_bytes(self) -> int:
        return sum(p.stat().st_size for _, _, p in self._scan())

    def blocks(self) -> List[Dict[str, int]]:
        return [
            {"seq": seq, "level": level, "bytes": p.stat().st_size}
            for seq, level, p in self._scan()
        ]

    def read(self, drops: Optional[LineDrops] = None) -> Iterator[dict]:
        """Every record, oldest block first. Skipped lines are counted
        into ``drops`` (and mirrored on ``dropped_lines``)."""
        own = LineDrops()
        for _, _, path in self._scan():
            for rec in iter_jsonl(path, own):
                yield rec
        if drops is not None:
            drops.count += own.count

    # -- compaction -------------------------------------------------------

    def _enforce_budget(self) -> None:
        """Downsample (then, at max level, drop) sealed blocks until the
        directory fits the budget again. Every pass either shrinks a
        block, bumps its level, or deletes it — so this terminates."""
        while self.total_bytes() > self.budget_bytes:
            sealed = [
                b for b in self._scan() if b[0] != self._active_seq
            ]
            if not sealed:
                return
            seq, level, path = min(sealed, key=lambda b: (b[1], b[0]))
            if self.shipper is not None:
                # tier out the verbatim bytes BEFORE resolution is lost
                self.shipper.ship(seq, level, path)
            if level >= self.max_level:
                path.unlink()
                continue
            self._downsample(seq, level, path)

    def _downsample(self, seq: int, level: int, path: Path) -> None:
        drops = LineDrops()
        recs = list(iter_jsonl(path, drops))
        self.dropped_lines += drops.count
        merged: List[dict] = []
        pending: Dict[object, int] = {}
        for rec in recs:
            key = (rec.get("ev"), rec.get("source"))
            slot = pending.pop(key, None)
            if slot is None:
                pending[key] = len(merged)
                merged.append(rec)
            else:
                merged[slot] = merge_pair(merged[slot], rec)
        tmp = path.with_suffix(".tmp")
        with tmp.open("w") as f:
            for rec in merged:
                f.write(json.dumps(rec, separators=(",", ":")) + "\n")
            f.flush()
            os.fsync(f.fileno())
        dst = self.root / _block_name(seq, level + 1)
        os.replace(tmp, dst)
        path.unlink()
