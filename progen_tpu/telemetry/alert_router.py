"""Alert routing: fingerprints, dedup, severity, silences, fan-out.

``AlertSink`` (``telemetry/alerts.py``) produces edge-triggered
``ev:"alert"`` records; this module decides who HEARS them. The
pipeline, per alert:

  1. **fingerprint** — ``kind:source:objective`` (stable across
     restarts and repeats; the identity operators silence and CI greps
     count by);
  2. **dedup** — an alert whose fingerprint is still in the state the
     router last routed is a repeat (collector restart, replayed
     stream), recorded once with ``status:"deduped"`` and delivered
     nowhere;
  3. **severity** — alert state → ``info``/``warning``/``critical``
     (overridable per state in the TOML), so routes can subscribe by
     floor instead of enumerating states;
  4. **per-route gates** — kind filter, ``min_severity`` floor, a
     per-fingerprint **silence window** (quiet period after each
     delivery on that route) and a per-route **rate limit**
     (deliveries/minute); a gated notification is recorded with
     ``status:"silenced"`` — suppression is itself evidence;
  5. **delivery** — ``webhook`` (HTTP POST with
     ``resilience/retry.py`` backoff, ``PROGEN_RETRY_*`` env knobs
     honored), ``stderr`` (one line for a terminal operator), or
     ``file`` (the ledger itself is the delivery).

**Escalation chains**: ``[route_X] escalate_to = "Y",
escalate_after_s = N`` — a warning/critical alert delivered through X
that is still in the same state after N seconds (nothing resolved or
changed it) re-fires through route Y, bypassing Y's kind/severity
gates, recorded with ``status:"escalated"`` and reason
``escalated_from:X``. The owning loop drives this by calling
``tick()``; pending escalations are rebuilt from the ledger on
restart (armed by the original ``sent`` record, disarmed by a later
state change or by the escalation's own record), so the re-fire
happens exactly once across router restarts. Chains do not cascade:
an escalated delivery does not arm Y's own ``escalate_to``.

Every routing decision — sent, failed, silenced, deduped, escalated —
lands as one ``ev:"notify"`` record in ``notifications.jsonl`` (the
ledger the console tails and CI asserts on). PGL006 enforces the
grammar: notify records are built only here, status from the
sent/failed/silenced/deduped/escalated alphabet. On construction the
router replays its own ledger to rebuild dedup + silence state, so a
restarted collector does not re-deliver what was already delivered.
"""

from __future__ import annotations

import dataclasses
import json
import sys
import time
import urllib.error
import urllib.request
from pathlib import Path
from typing import Dict, List, Optional, Tuple

from progen_tpu.resilience.retry import (
    is_transient,
    policy_from_env,
    retry_call,
)
from progen_tpu.telemetry.spans import EventLog
from progen_tpu.telemetry.trace import iter_jsonl

NOTIFY_STATUSES = ("sent", "failed", "silenced", "deduped", "escalated")
SEVERITIES = ("info", "warning", "critical")
ROUTE_SINKS = ("webhook", "file", "stderr")

# alert state -> default severity; TOML [alert_router] severity_<state>
# keys override per state
DEFAULT_SEVERITY = {
    "fresh": "info",
    "resolved": "info",
    "warn": "warning",
    "stale": "critical",
    "burning": "critical",
    "rolled_back": "critical",
}


def fingerprint(alert: dict) -> str:
    """Stable identity of an alert across repeats and restarts."""
    return ":".join((
        str(alert.get("kind", "")),
        str(alert.get("source", "")),
        str(alert.get("objective", "")),
    ))


def _severity_rank(sev: str) -> int:
    try:
        return SEVERITIES.index(sev)
    except ValueError:
        return 0


@dataclasses.dataclass
class RouteSpec:
    """One fan-out destination, parsed from a ``[route_<name>]``
    table. ``url`` is required only for the webhook sink."""

    name: str
    sink: str = "file"
    url: str = ""
    min_severity: str = "info"
    kinds: str = ""  # comma list; empty = all kinds
    silence_s: float = 0.0
    rate_limit_per_min: float = 0.0
    timeout_s: float = 5.0
    escalate_to: str = ""  # re-fire through this route when unacked
    escalate_after_s: float = 0.0

    def __post_init__(self):
        if self.sink not in ROUTE_SINKS:
            raise ValueError(
                f"route {self.name!r}: sink {self.sink!r} not in "
                f"{ROUTE_SINKS}"
            )
        if self.min_severity not in SEVERITIES:
            raise ValueError(
                f"route {self.name!r}: min_severity "
                f"{self.min_severity!r} not in {SEVERITIES}"
            )
        if self.sink == "webhook" and not self.url:
            raise ValueError(
                f"route {self.name!r}: webhook sink requires url"
            )
        if bool(self.escalate_to) != (self.escalate_after_s > 0):
            raise ValueError(
                f"route {self.name!r}: escalate_to and "
                "escalate_after_s must be set together"
            )
        if self.escalate_to == self.name:
            raise ValueError(
                f"route {self.name!r}: cannot escalate to itself"
            )

    def kind_set(self) -> Tuple[str, ...]:
        return tuple(
            k.strip() for k in self.kinds.split(",") if k.strip()
        )


def load_router_config(path) -> Tuple[Dict[str, str], List[RouteSpec]]:
    """``configs/serving/alert_router.toml`` → (severity overrides,
    routes). Unknown keys raise — a typo'd knob silently at its default
    is an operator who believes a silence is in force when it is not."""
    from progen_tpu.config import load_toml_config

    raw = load_toml_config(str(path))
    severity = dict(DEFAULT_SEVERITY)
    top = raw.get("alert_router", {})
    if not isinstance(top, dict):
        raise ValueError(f"{path}: [alert_router] is not a table")
    for key, value in top.items():
        if not key.startswith("severity_"):
            raise ValueError(f"{path}: unknown alert_router key {key!r}")
        state = key[len("severity_"):]
        if state not in DEFAULT_SEVERITY:
            raise ValueError(
                f"{path}: severity override for unknown state {state!r}"
            )
        if value not in SEVERITIES:
            raise ValueError(
                f"{path}: severity_{state} = {value!r} not in "
                f"{SEVERITIES}"
            )
        severity[state] = value
    routes: List[RouteSpec] = []
    names = {f.name for f in dataclasses.fields(RouteSpec)} - {"name"}
    for table_name, table in raw.items():
        if table_name == "alert_router":
            continue
        if not table_name.startswith("route_"):
            raise ValueError(f"{path}: unknown table [{table_name}]")
        if not isinstance(table, dict):
            raise ValueError(f"{path}: [{table_name}] is not a table")
        unknown = set(table) - names
        if unknown:
            raise ValueError(
                f"{path}: unknown key(s) {sorted(unknown)} in "
                f"[{table_name}]"
            )
        routes.append(
            RouteSpec(name=table_name[len("route_"):], **table)
        )
    if not routes:
        raise ValueError(f"{path}: no [route_<name>] tables")
    return severity, routes


def _webhook_classify(exc: BaseException) -> bool:
    """Retry 5xx/429 and transport faults; a 4xx is a contract error
    that retrying cannot fix."""
    code = getattr(exc, "code", None)
    if code is not None:
        return int(code) >= 500 or int(code) == 429
    return is_transient(exc)


class AlertRouter:
    """See module doc. ``handle(alert)`` is wired as the
    :class:`AlertSink` relay; it must never raise into the scrape
    loop — delivery failures become ``status:"failed"`` records."""

    def __init__(
        self,
        ledger_path,
        routes: List[RouteSpec],
        severity: Optional[Dict[str, str]] = None,
        opener=None,
    ):
        self.ledger_path = Path(ledger_path)
        self.routes = list(routes)
        self._route_map = {r.name: r for r in self.routes}
        for r in self.routes:
            if r.escalate_to and r.escalate_to not in self._route_map:
                raise ValueError(
                    f"route {r.name!r}: escalate_to names unknown "
                    f"route {r.escalate_to!r}"
                )
        self.severity_map = dict(severity or DEFAULT_SEVERITY)
        self._opener = opener or urllib.request.urlopen
        self._policy = policy_from_env()
        self._policy = dataclasses.replace(
            self._policy, classify=_webhook_classify
        )
        # fingerprint -> last routed state (dedup across repeats)
        self._last_state: Dict[str, str] = {}
        # (route, fingerprint) -> last successful delivery ts (silence)
        self._last_sent: Dict[Tuple[str, str], float] = {}
        # route -> recent delivery timestamps (rate limit)
        self._sent_times: Dict[str, List[float]] = {}
        # (origin route, fingerprint) -> (deadline, alert) for armed
        # escalations; disarmed by a state change on the fingerprint
        # or by the escalation firing (tick)
        self._pending: Dict[Tuple[str, str], Tuple[float, dict]] = {}
        self.counts: Dict[str, int] = {s: 0 for s in NOTIFY_STATUSES}
        self._reload()
        self._ledger = EventLog(self.ledger_path)

    def close(self) -> None:
        self._ledger.close()

    # -- state reload -----------------------------------------------------

    def _reload(self) -> None:
        """Rebuild dedup/silence/rate state from the ledger so a router
        restart keeps the one-notification-per-edge promise."""
        if not self.ledger_path.exists():
            return
        for rec in iter_jsonl(self.ledger_path):
            if rec.get("ev") != "notify":
                continue
            fp = rec.get("fingerprint", "")
            status = rec.get("status", "")
            ts = float(rec.get("ts", 0.0))
            if status in self.counts:
                self.counts[status] += 1
            if status != "deduped":
                state = str(rec.get("state", ""))
                if self._last_state.get(fp) != state:
                    # a new edge acks everything armed on the old one
                    self._disarm(fp)
                self._last_state[fp] = state
            if status == "sent":
                route = str(rec.get("route", ""))
                self._last_sent[(route, fp)] = ts
                self._sent_times.setdefault(route, []).append(ts)
                spec = self._route_map.get(route)
                if spec is not None and spec.escalate_to:
                    sev = str(rec.get("severity", ""))
                    if _severity_rank(sev) >= _severity_rank("warning"):
                        # alert payload reconstructed from the notify
                        # record (not a new ev:"alert" — the original
                        # already fired; this is re-delivery material)
                        self._pending[(route, fp)] = (
                            ts + spec.escalate_after_s,
                            {
                                "ts": ts,
                                "kind": rec.get("kind", ""),
                                "state": rec.get("state", ""),
                                "source": rec.get("source", ""),
                                "objective": rec.get("objective", ""),
                            },
                        )
            reason = str(rec.get("reason", ""))
            if reason.startswith("escalated_from:"):
                # the escalation already fired (or terminally failed)
                origin = reason.split(":", 1)[1].split()[0]
                self._pending.pop((origin, fp), None)

    def _disarm(self, fp: str) -> None:
        for key in [k for k in self._pending if k[1] == fp]:
            del self._pending[key]

    # -- pipeline ---------------------------------------------------------

    def severity(self, state: str) -> str:
        return self.severity_map.get(str(state), "warning")

    def handle(self, alert: dict) -> List[dict]:
        """Route one alert record; returns the notify records written."""
        try:
            return self._handle(alert)
        except Exception as exc:  # the scrape loop must survive routing
            print(
                f"[alert-router] dropped alert: {exc}",
                file=sys.stderr,
            )
            return []

    def _handle(self, alert: dict) -> List[dict]:
        fp = fingerprint(alert)
        state = str(alert.get("state", ""))
        now = float(alert.get("ts", time.time()))
        sev = self.severity(state)
        if self._last_state.get(fp) == state:
            return [self._note(alert, fp, sev, now, route="",
                               status="deduped", reason="repeat")]
        self._last_state[fp] = state
        self._disarm(fp)  # the state edge acks any armed escalation
        out: List[dict] = []
        for route in self.routes:
            kinds = route.kind_set()
            if kinds and alert.get("kind") not in kinds:
                continue
            if _severity_rank(sev) < _severity_rank(route.min_severity):
                continue
            gate = self._gate(route, fp, now)
            if gate:
                out.append(self._note(alert, fp, sev, now,
                                      route=route.name,
                                      status="silenced", reason=gate))
                continue
            ok, detail = self._deliver(route, alert, fp, sev)
            status = "sent" if ok else "failed"
            if ok:
                self._last_sent[(route.name, fp)] = now
                self._sent_times.setdefault(route.name, []).append(now)
                if route.escalate_to and _severity_rank(sev) >= \
                        _severity_rank("warning"):
                    self._pending[(route.name, fp)] = (
                        now + route.escalate_after_s, dict(alert)
                    )
            out.append(self._note(alert, fp, sev, now,
                                  route=route.name, status=status,
                                  reason=detail))
        return out

    def tick(self, now: Optional[float] = None) -> List[dict]:
        """Fire due escalations. The owning loop (the collector CLI)
        calls this every iteration; it must never raise into it."""
        try:
            return self._tick(time.time() if now is None else float(now))
        except Exception as exc:
            print(
                f"[alert-router] escalation tick failed: {exc}",
                file=sys.stderr,
            )
            return []

    def _tick(self, now: float) -> List[dict]:
        out: List[dict] = []
        for (origin, fp), (deadline, alert) in list(self._pending.items()):
            if now < deadline:
                continue
            del self._pending[(origin, fp)]
            target = self._route_map.get(
                self._route_map[origin].escalate_to
            )
            if target is None:
                continue
            sev = self.severity(str(alert.get("state", "")))
            # escalation bypasses the target's kind/severity/silence
            # gates — it exists precisely because the normal path did
            # not get the alert acknowledged
            ok, detail = self._deliver(target, alert, fp, sev)
            reason = f"escalated_from:{origin}"
            if detail:
                reason += f" {detail}"
            out.append(self._note(
                alert, fp, sev, now, route=target.name,
                status="escalated" if ok else "failed", reason=reason,
            ))
        return out

    def _gate(self, route: RouteSpec, fp: str, now: float) -> str:
        """Route-level suppression reason, or '' to deliver."""
        if route.silence_s > 0:
            last = self._last_sent.get((route.name, fp))
            if last is not None and now - last < route.silence_s:
                return "silence_window"
        if route.rate_limit_per_min > 0:
            times = self._sent_times.setdefault(route.name, [])
            times[:] = [t for t in times if now - t < 60.0]
            if len(times) >= route.rate_limit_per_min:
                return "rate_limit"
        return ""

    # -- delivery ---------------------------------------------------------

    def _deliver(
        self, route: RouteSpec, alert: dict, fp: str, sev: str
    ) -> Tuple[bool, str]:
        if route.sink == "file":
            return True, ""  # the ledger write IS the delivery
        if route.sink == "stderr":
            print(
                f"[alert] {sev.upper()} {fp} -> "
                f"{alert.get('state')} (route {route.name})",
                file=sys.stderr,
            )
            return True, ""
        body = json.dumps(
            {
                "fingerprint": fp,
                "severity": sev,
                "route": route.name,
                "alert": alert,
            },
            separators=(",", ":"),
        ).encode("utf-8")

        def post():
            req = urllib.request.Request(
                route.url,
                data=body,
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            with self._opener(req, timeout=route.timeout_s) as resp:
                status = int(getattr(resp, "status", 200))
                if status >= 300:
                    raise urllib.error.HTTPError(
                        route.url, status, "webhook rejected", None, None
                    )

        try:
            retry_call(post, label="alert/webhook", policy=self._policy)
            return True, ""
        except Exception as exc:
            return False, str(exc)

    # -- ledger -----------------------------------------------------------

    def _note(
        self,
        alert: dict,
        fp: str,
        sev: str,
        now: float,
        route: str,
        status: str,
        reason: str = "",
    ) -> dict:
        rec = {
            "ev": "notify",
            "ts": round(now, 3),
            "route": route,
            "status": status,
            "fingerprint": fp,
            "kind": alert.get("kind", ""),
            "state": alert.get("state", ""),
            "source": alert.get("source", ""),
            "objective": alert.get("objective", ""),
            "severity": sev,
        }
        if reason:
            rec["reason"] = reason
        self._ledger.emit(rec)
        self.counts[status] = self.counts.get(status, 0) + 1
        return rec


def read_notifications(path, limit: int = 0) -> List[dict]:
    """Tail helper for the console/CLI: the ledger's ``ev:"notify"``
    records, oldest first (last ``limit`` when set)."""
    p = Path(path)
    if not p.exists():
        return []
    recs = [r for r in iter_jsonl(p) if r.get("ev") == "notify"]
    return recs[-limit:] if limit else recs
