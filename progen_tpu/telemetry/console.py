"""Ops console rendering: TSDB → snapshot dict → ANSI dashboard.

``build_snapshot`` is the single source of truth for what the console
knows — ``progen-tpu-top`` renders it as a live ANSI screen for humans
and dumps it verbatim as JSON for scripts (``--once --json``), so CI
asserts against exactly what an operator would see:

  * one row per source: up bit, exposition age, slot occupancy, queue
    depth, ttft/itl p95, completed requests, decode tokens;
  * fleet rollup from ``fleet_series`` (reset-safe summed counters,
    merged quantiles, liveness gauges) — the totals line equals the
    sum of the per-source Prometheus files at scrape time;
  * SLO states when an objectives TOML is given (same ``evaluate``
    path as ``slo-report --tsdb``);
  * the alert tail and the TSDB's own health (blocks, bytes, torn
    lines dropped) — a console that silently lost history is itself
    an outage;
  * when an alert-router ledger exists, a notifications tail plus the
    delivery state-machine counts (``sent``/``failed``/``silenced``/
    ``deduped`` and the ``routed`` total) so CI can assert WHO was
    told, not just what fired; ``--alerts-only`` renders just the
    alerting panes for an on-call terminal.

Rendering is pure string-building (no curses): the watch loop clears
the screen between frames, which keeps the console dumb enough to pipe.
"""

from __future__ import annotations

import json
import time
from typing import Dict, List, Optional

from progen_tpu.telemetry.collector import (
    fleet_exemplars,
    fleet_series,
    latest_by_source,
)
from progen_tpu.telemetry.slo import evaluate, results_payload
from progen_tpu.telemetry.trace import LineDrops, iter_jsonl

_RESET = "\x1b[0m"
_BOLD = "\x1b[1m"
_GREEN = "\x1b[32m"
_RED = "\x1b[31m"
_YELLOW = "\x1b[33m"
_DIM = "\x1b[2m"

CLEAR_SCREEN = "\x1b[2J\x1b[H"


def build_snapshot(
    tsdb,
    slo_cfg=None,
    alerts_path=None,
    max_alerts: int = 8,
    notifications_path=None,
    max_notifications: int = 8,
) -> dict:
    """Everything the console shows, as one JSON-able dict."""
    drops = LineDrops()
    samples = [r for r in tsdb.read(drops) if r.get("ev") == "sample"]
    per_source = latest_by_source(samples)
    fleet = fleet_series(samples)
    fleet_now: Dict[str, float] = fleet[-1][1] if fleet else {}
    as_of = fleet[-1][0] if fleet else None
    sources = [
        {
            "name": rec["source"],
            "role": rec.get("role", ""),
            "up": bool(rec.get("up")),
            "age_s": rec.get("age_s", 0.0),
            "counters": rec.get("counters", {}),
            "gauges": rec.get("gauges", {}),
            "timings": rec.get("timings", {}),
        }
        for rec in sorted(
            per_source.values(), key=lambda r: (r.get("role", ""), r["source"])
        )
    ]
    slo: List[dict] = []
    gate = None
    if slo_cfg is not None and fleet:
        payload = results_payload(evaluate(slo_cfg, [fleet]))
        gate = payload["exit"]
        slo = payload["results"]
    alerts: List[dict] = []
    if alerts_path is not None:
        try:
            alerts = [
                rec for rec in iter_jsonl(alerts_path, drops)
                if rec.get("ev") == "alert"
            ][-max_alerts:]
        except OSError:
            pass
    notifications: List[dict] = []
    notify_counts = {
        "sent": 0, "failed": 0, "silenced": 0, "deduped": 0, "routed": 0
    }
    if notifications_path is not None:
        try:
            all_notes = [
                rec for rec in iter_jsonl(notifications_path, drops)
                if rec.get("ev") == "notify"
            ]
        except OSError:
            all_notes = []
        for rec in all_notes:
            status = rec.get("status", "")
            if status in notify_counts:
                notify_counts[status] += 1
        notify_counts["routed"] = (
            notify_counts["sent"] + notify_counts["failed"]
        )
        notifications = all_notes[-max_notifications:]
    return {
        "as_of": as_of,
        "sources": sources,
        "fleet": fleet_now,
        # worst-K trace exemplars per timing family, fleet-wide: the
        # request ids behind the merged p99 (per-source exemplars ride
        # each source's timings dict above)
        "exemplars": fleet_exemplars(samples),
        "slo": slo,
        "slo_exit": gate,
        "alerts": alerts,
        "notifications": notifications,
        "notify_counts": notify_counts,
        "tsdb": {
            "blocks": len(tsdb.blocks()),
            "bytes": tsdb.total_bytes(),
            "dropped_lines": tsdb.dropped_lines + drops.count,
        },
    }


def _c(s: str, code: str, color: bool) -> str:
    return f"{code}{s}{_RESET}" if color else s


def _num(v, fmt: str = "{:.0f}") -> str:
    if v is None:
        return "-"
    try:
        return fmt.format(float(v))
    except (TypeError, ValueError):
        return "-"


def _tq(rec: dict, fam: str, key: str):
    return rec.get("timings", {}).get(fam, {}).get(key)


def _delivery_tags(snap: dict) -> Dict[tuple, List[str]]:
    """(kind, who, state) → delivery statuses seen in the notification
    tail, so the alerts pane can show routed/silenced state inline."""
    tags: Dict[tuple, List[str]] = {}
    for n in snap.get("notifications", []):
        key = (
            n.get("kind", ""),
            n.get("objective") or n.get("source") or "",
            n.get("state", ""),
        )
        status = n.get("status", "")
        if status and status not in tags.setdefault(key, []):
            tags[key].append(status)
    return tags


def render(snap: dict, color: bool = True,
           alerts_only: bool = False) -> str:
    """Snapshot → dashboard text (no trailing clear; the watch loop
    owns the screen). ``alerts_only`` keeps the header, SLO, alert and
    notification panes and drops the per-source/fleet tables."""
    lines: List[str] = []
    as_of = snap.get("as_of")
    stamp = (
        time.strftime("%H:%M:%S", time.localtime(as_of))
        if as_of else "--:--:--"
    )
    fleet = snap.get("fleet", {})
    n_up = int(fleet.get("fleet_up", 0))
    n_all = int(fleet.get("fleet_sources", 0))
    head = f"progen-tpu-top  as of {stamp}  sources {n_up}/{n_all} up"
    lines.append(_c(head, _BOLD, color))
    if alerts_only:
        lines.extend(_render_alert_panes(snap, color))
        return "\n".join(lines)
    hdr = (
        f"{'SOURCE':<10} {'ROLE':<8} {'UP':<5} {'AGE':>6} {'SLOTS':>6} "
        f"{'QUEUE':>6} {'TTFT95':>8} {'ITL95':>8} {'DONE':>8} {'TOKENS':>9}"
    )
    lines.append(_c(hdr, _DIM, color))
    for src in snap.get("sources", []):
        up = src.get("up")
        g = src.get("gauges", {})
        c = src.get("counters", {})
        row = (
            f"{src.get('name', '?'):<10} {src.get('role', ''):<8} "
            f"{_c('up', _GREEN, color) if up else _c('DOWN', _RED, color):<5}"
            f"{'' if color else ''} "
            f"{_num(src.get('age_s'), '{:.1f}s'):>6} "
            f"{_num(g.get('slot_occupancy', g.get('active_slots'))):>6} "
            f"{_num(g.get('queue_depth')):>6} "
            f"{_num(_tq(src, 'ttft_s', 'p95_s'), '{:.3f}'):>8} "
            f"{_num(_tq(src, 'itl_s', 'p95_s'), '{:.3f}'):>8} "
            f"{_num(c.get('requests_completed')):>8} "
            f"{_num(c.get('decode_tokens', c.get('tokens_forwarded'))):>9}"
        )
        lines.append(row)
    lines.append(
        "fleet: "
        f"replicas {int(fleet.get('replicas_live', 0))}/"
        f"{int(fleet.get('replicas_total', 0))} live  "
        f"done {_num(fleet.get('requests_completed'))}  "
        f"tokens {_num(fleet.get('decode_tokens'))}  "
        f"ttft p95 {_num(fleet.get('ttft_s_p95_s'), '{:.3f}')}s  "
        f"queue max {_num(fleet.get('queue_depth'))}"
    )
    exemplars = snap.get("exemplars", {})
    if exemplars:
        lines.append(_c("slowest traces", _BOLD, color))
        for fam in sorted(exemplars):
            worst = exemplars[fam][:3]
            tail = "  ".join(
                f"{e.get('trace_id', '?')} ({_num(e.get('value'), '{:.3f}')}s)"
                for e in worst
            )
            lines.append(f"  {fam:<12} {tail}")
    lines.extend(_render_alert_panes(snap, color))
    t = snap.get("tsdb", {})
    lines.append(_c(
        f"tsdb: {t.get('blocks', 0)} blocks, {t.get('bytes', 0)} bytes, "
        f"{t.get('dropped_lines', 0)} torn lines dropped",
        _DIM, color,
    ))
    return "\n".join(lines)


def _render_alert_panes(snap: dict, color: bool) -> List[str]:
    """SLO states, the alert tail (annotated with delivery status when
    a router ledger is present), and the notifications tail."""
    lines: List[str] = []
    slo = snap.get("slo", [])
    if slo:
        lines.append(_c("SLO", _BOLD, color))
        for r in slo:
            state = r.get("state", "?")
            code = {
                "ok": _GREEN, "warn": _YELLOW, "burning": _RED
            }.get(state, _DIM)
            burn = r.get("burn_long")
            lines.append(
                f"  {r.get('objective', '?'):<22} "
                f"{_c(state, code, color):<8} "
                f"burn {_num(burn, '{:.2f}')}"
                + (f"  ({r['detail']})" if r.get("detail") else "")
            )
    tags = _delivery_tags(snap)
    alerts = snap.get("alerts", [])
    if alerts:
        lines.append(_c("recent alerts", _BOLD, color))
        for a in alerts[-5:]:
            ts = time.strftime(
                "%H:%M:%S", time.localtime(a.get("ts", 0))
            )
            who = a.get("objective") or a.get("source") or "?"
            state = a.get("state", "?")
            code = _GREEN if state in ("fresh", "resolved") else _RED
            delivered = tags.get((a.get("kind", ""), who, state), [])
            suffix = (
                "  [" + ",".join(delivered) + "]" if delivered else ""
            )
            lines.append(
                f"  {ts} {a.get('kind', '?'):<10} {who:<18} "
                f"{_c(state, code, color)}"
                + _c(suffix, _DIM, color)
            )
    notes = snap.get("notifications", [])
    if notes:
        counts = snap.get("notify_counts", {})
        lines.append(_c(
            "notifications  "
            f"routed {counts.get('routed', 0)}  "
            f"silenced {counts.get('silenced', 0)}  "
            f"deduped {counts.get('deduped', 0)}  "
            f"failed {counts.get('failed', 0)}",
            _BOLD, color,
        ))
        for n in notes[-5:]:
            ts = time.strftime(
                "%H:%M:%S", time.localtime(n.get("ts", 0))
            )
            status = n.get("status", "?")
            code = {
                "sent": _GREEN, "failed": _RED, "silenced": _YELLOW
            }.get(status, _DIM)
            route = n.get("route") or "-"
            lines.append(
                f"  {ts} {route:<10} "
                f"{n.get('fingerprint', '?'):<28} "
                f"{n.get('state', '?'):<9} {_c(status, code, color)}"
                + (f" ({n['reason']})" if n.get("reason") else "")
            )
    return lines


def snapshot_json(snap: dict) -> str:
    return json.dumps(snap, indent=2, sort_keys=True, default=str)


def watch(
    tsdb,
    slo_cfg=None,
    alerts_path=None,
    refresh_s: float = 2.0,
    color: bool = True,
    max_frames: Optional[int] = None,
    out=None,
    notifications_path=None,
    alerts_only: bool = False,
):
    """Live loop: clear screen, render, wait. ``q`` quits when stdin is
    a TTY; otherwise runs until ``max_frames`` (None = forever) — the
    headless path CI and tests use."""
    import sys

    out = sys.stdout if out is None else out
    frames = 0
    while max_frames is None or frames < max_frames:
        snap = build_snapshot(
            tsdb, slo_cfg=slo_cfg, alerts_path=alerts_path,
            notifications_path=notifications_path,
        )
        out.write(
            CLEAR_SCREEN
            + render(snap, color=color, alerts_only=alerts_only)
            + "\n"
        )
        out.flush()
        frames += 1
        if max_frames is not None and frames >= max_frames:
            break
        if _wait_or_quit(refresh_s):
            break


def _wait_or_quit(timeout_s: float) -> bool:
    """Sleep ``timeout_s``; True means the operator pressed ``q``."""
    import select
    import sys

    stdin = sys.stdin
    if not hasattr(stdin, "fileno"):
        time.sleep(timeout_s)
        return False
    try:
        is_tty = stdin.isatty()
    except (ValueError, OSError):
        is_tty = False
    if not is_tty:
        time.sleep(timeout_s)
        return False
    import termios
    import tty

    fd = stdin.fileno()
    old = termios.tcgetattr(fd)
    try:
        tty.setcbreak(fd)
        r, _, _ = select.select([stdin], [], [], timeout_s)
        if r:
            ch = stdin.read(1)
            return ch in ("q", "Q")
        return False
    finally:
        termios.tcsetattr(fd, termios.TCSADRAIN, old)
