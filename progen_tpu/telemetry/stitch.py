"""Fleet stitching: N per-host event streams → ONE aligned trace.

A multi-host run leaves one ``events.jsonl`` per process, each stamped
with that host's wall clock — and host wall clocks disagree (NTP slew,
VM drift), so naively concatenating the files renders host 1's step 40
overlapping host 0's step 38. MegaScale-style fleet diagnosis
(PAPERS.md) needs all hosts on ONE timeline before a straggler is even
visible; this module is that merge.

Clock alignment rides on a shared reference event. The train loop emits
a ``clock_beacon`` record at every step boundary, immediately after the
host sync that observes the step's collective result — the gradient
all-reduce is a barrier every host crosses together, so the *true* time
of "step N done" is (to within the collective's skew, microseconds on a
healthy fabric) the same on every host, while the *recorded* times
differ by exactly the clock offsets. Per host, the offset is the median
over shared steps of (host's beacon ts − reference host's beacon ts):
the median is robust to the handful of steps where a host genuinely
lagged the barrier (a straggler step must not bend the clock). The
offset is then subtracted from ALL of that host's timestamps.

The stitched trace additionally gets:

  * a ``clock_beacon`` slice per (host, step) plus ``step_sync`` flow
    arrows from the reference host's beacon to every other host's —
    after correction the arrows are near-vertical; a straggling host
    renders as a visible arrow fan tilting toward it;
  * the fleet-wide ``progenGoodputSkew`` table (every host's
    ``goodput_host`` record is in the merged stream, deduped);
  * per-request JOURNEYS: ``req`` records carrying a ``trace_id``
    (router intake, replica lifecycle — serving/router.py mints the id,
    the wire carries it) are grouped per trace and linked with
    ``dispatch``/``handoff`` flow arrows from each router dispatch hop
    into the replica-side track it started — a midstream replica death
    renders as ONE contiguous journey: router queued → dispatch arrow →
    dead replica's partial decode → handoff arrow → the survivor's
    resumed track. The per-trace table rides along as ``progenTraces``;
  * ``progenClockOffsets`` (seconds subtracted per host) and
    ``progenDroppedLines`` (torn/garbage input lines) as top-level
    keys — trace viewers ignore unknown keys.

A serving fleet is N processes on (usually) one machine, all stamping
``pid`` 0 — ``force_hosts=True`` (CLI ``--force-hosts``) re-stamps each
stream with its argument position so router and replicas get distinct
process tracks (required for the journey arrows to have two ends).
"""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import Dict, Iterable, List, Sequence, Tuple

from progen_tpu.telemetry.trace import (
    LineDrops,
    build_trace,
    iter_events_any,
    iter_jsonl,
)

# beacon anchor slices get a small fixed width so the step_sync flows
# have a slice to bind to and stay clickable at fleet zoom
_BEACON_DUR_US = 200.0


def emit_clock_beacon(step, emit=None) -> dict:
    """Emit one ``clock_beacon`` record for ``step`` and return it.

    Contract (see training/__init__.py): call this at each step
    boundary, immediately after the host-side sync on the step's
    collective result — that barrier is the shared reference event the
    stitcher aligns host clocks on. ``emit`` defaults to the
    process-global telemetry sink."""
    if emit is None:
        from progen_tpu.telemetry.spans import get_telemetry

        emit = get_telemetry().emit
    rec = {"ev": "clock_beacon", "ts": time.time(), "step": int(step)}
    emit(rec)
    return rec


def collect_beacons(
    records: Iterable[dict],
) -> Dict[int, Dict[int, float]]:
    """host → {step → beacon ts} from ``clock_beacon`` records (the
    last record wins when a step repeats, e.g. after a rollback)."""
    out: Dict[int, Dict[int, float]] = {}
    for rec in records:
        if rec.get("ev") != "clock_beacon":
            continue
        ts = rec.get("ts")
        step = rec.get("step")
        if ts is None or step is None:
            continue
        out.setdefault(int(rec.get("pid", 0)), {})[int(step)] = float(ts)
    return out


def _median(xs: Sequence[float]) -> float:
    s = sorted(xs)
    n = len(s)
    mid = n // 2
    if n % 2:
        return s[mid]
    return (s[mid - 1] + s[mid]) / 2.0


def clock_offsets(
    beacons: Dict[int, Dict[int, float]], reference: int = 0
) -> Dict[int, float]:
    """Per-host clock offset in seconds, to SUBTRACT from that host's
    timestamps. Robust median of per-step beacon deltas vs the
    reference host (host 0 unless absent); hosts sharing no step with
    the reference keep offset 0 (nothing to align on beats a wild
    guess)."""
    if not beacons:
        return {}
    if reference not in beacons:
        reference = min(beacons)
    ref = beacons[reference]
    offsets: Dict[int, float] = {}
    for host, own in beacons.items():
        shared = [s for s in own if s in ref]
        if host == reference or not shared:
            offsets[host] = 0.0
        else:
            offsets[host] = _median([own[s] - ref[s] for s in shared])
    return offsets


def stream_host(records: Sequence[dict], default: int = 0) -> int:
    """The host that wrote a stream: the majority ``pid`` stamp over its
    records (``Telemetry.emit`` stamps every record with the writer)."""
    votes: Dict[int, int] = {}
    for rec in records:
        pid = rec.get("pid")
        if pid is not None:
            votes[int(pid)] = votes.get(int(pid), 0) + 1
    if not votes:
        return default
    return max(votes, key=lambda h: (votes[h], -h))


# a dispatch arrow binds to the first replica-side request begin at or
# after the router's dispatch instant; the slack absorbs same-host
# scheduling jitter between the router's send and the replica's accept
_DISPATCH_SLACK_S = 0.005


def request_journeys(
    merged: Sequence[dict],
) -> Tuple[List[dict], Dict[str, dict]]:
    """Per-trace journey flows from corrected ``req`` records.

    Groups records by ``trace_id``, then pairs the router's k-th
    ``dispatched`` begin with the earliest unconsumed replica-side
    ``request`` begin at ts >= dispatch − slack, emitting one
    ``s``/``f`` flow arrow per hop (named ``handoff`` when the dispatch
    was a journal-ownership resume, ``dispatch`` otherwise). Returns
    (flow events, per-trace table for ``progenTraces``). The router pid
    is wherever the ``dispatched`` phases live — replica begins on that
    pid are the router's own envelope, not a hop target."""
    per: Dict[str, dict] = {}
    for rec in merged:
        if rec.get("ev") != "req":
            continue
        tr = rec.get("trace_id")
        ts = rec.get("ts")
        if tr is None or ts is None:
            continue
        j = per.setdefault(str(tr), {
            "dispatches": [], "begins": [], "pids": set(), "sheds": 0,
        })
        j["pids"].add(int(rec.get("pid", 0)))
        name = rec.get("name")
        ph = rec.get("ph")
        if ph == "b" and name == "dispatched":
            j["dispatches"].append({
                "ts": float(ts), "pid": int(rec.get("pid", 0)),
                "resumed": bool(rec.get("resumed")),
            })
        elif ph == "b" and name == "request":
            j["begins"].append(
                {"ts": float(ts), "pid": int(rec.get("pid", 0))}
            )
        elif ph == "n" and name == "shed":
            j["sheds"] += 1

    flows: List[dict] = []
    table: Dict[str, dict] = {}
    for tr in sorted(per):
        j = per[tr]
        dispatches = sorted(j["dispatches"], key=lambda d: d["ts"])
        router_pid = dispatches[0]["pid"] if dispatches else None
        begins = sorted(
            (b for b in j["begins"] if b["pid"] != router_pid),
            key=lambda b: b["ts"],
        )
        used = [False] * len(begins)
        arrows = 0
        handoffs = 0
        for k, d in enumerate(dispatches):
            target = None
            for i, b in enumerate(begins):
                if not used[i] and b["ts"] >= d["ts"] - _DISPATCH_SLACK_S:
                    target = i
                    break
            if target is None:
                continue
            used[target] = True
            b = begins[target]
            name = "handoff" if d["resumed"] else "dispatch"
            fid = f"trace:{tr}:{k}"
            flows.append({
                "ph": "s", "cat": "request_flow", "name": name,
                "id": fid, "ts": d["ts"] * 1e6, "pid": d["pid"],
                "tid": 0,
            })
            flows.append({
                "ph": "f", "bp": "e", "cat": "request_flow",
                "name": name, "id": fid, "ts": b["ts"] * 1e6,
                "pid": b["pid"], "tid": 0,
            })
            arrows += 1
            if d["resumed"]:
                handoffs += 1
        table[tr] = {
            "pids": sorted(j["pids"]),
            "hops": len(dispatches),
            "handoffs": handoffs,
            "flows": arrows,
            "shed": j["sheds"] > 0,
        }
    return flows, table


def stitch_streams(
    event_streams: Sequence[Sequence[dict]],
    metrics_streams: Sequence[Tuple[int, Sequence[dict]]] = (),
    reference: int = 0,
    force_hosts: bool = False,
) -> dict:
    """Merge already-parsed per-host record streams into one trace dict.

    Each event stream keeps its file order (B/E pairing in build_trace
    is per-pid, so per-host order is all that matters); every record's
    ``ts`` is corrected by its writer's clock offset. ``goodput_host``
    records are deduped across streams (each host's own copy wins) so
    the fleet skew table counts every host exactly once.
    ``metrics_streams`` pairs each row set with the host it came from —
    metrics.jsonl rows carry no pid of their own. ``force_hosts``
    re-stamps stream ``i`` with pid ``i`` regardless of what the records
    say — the serving fleet is N processes on one host, all stamping
    pid 0, and the journey flow arrows need distinct tracks."""
    streams = [list(s) for s in event_streams]
    if force_hosts:
        streams = [
            [{**rec, "pid": i} for rec in stream]
            for i, stream in enumerate(streams)
        ]
    beacons = collect_beacons(r for s in streams for r in s)
    offsets = clock_offsets(beacons, reference=reference)

    def corrected(rec: dict) -> dict:
        off = offsets.get(int(rec.get("pid", 0)), 0.0)
        if off and rec.get("ts") is not None:
            return {**rec, "ts": float(rec["ts"]) - off}
        return rec

    merged: List[dict] = []
    goodput: Dict[int, dict] = {}
    for stream in streams:
        for rec in stream:
            ev = rec.get("ev")
            if ev == "clock_beacon":
                continue  # re-rendered below as anchor slices + flows
            if ev == "goodput_host" and "host" in rec:
                host = int(rec["host"])
                if (
                    host not in goodput
                    or int(rec.get("pid", -1)) == host
                ):
                    goodput[host] = corrected(rec)
                continue
            merged.append(corrected(rec))
    merged.extend(goodput[h] for h in sorted(goodput))

    metrics_merged: List[dict] = []
    for host, rows in metrics_streams:
        off = offsets.get(int(host), 0.0)
        for rec in rows:
            if rec.get("_time") is None:
                continue
            metrics_merged.append(
                {**rec, "pid": int(host), "_time": float(rec["_time"]) - off}
            )

    trace = build_trace(merged, metrics_merged)

    # beacon anchors + cross-host step_sync arrows on corrected clocks
    extra: List[dict] = []
    steps = sorted({s for per in beacons.values() for s in per})
    arrows = 0
    for step in steps:
        present = sorted(h for h, per in beacons.items() if step in per)
        t = {
            h: beacons[h][step] - offsets.get(h, 0.0) for h in present
        }
        ref = reference if reference in present else present[0]
        for h in present:
            extra.append({
                "ph": "X", "name": "clock_beacon", "cat": "beacon",
                "ts": t[h] * 1e6, "dur": _BEACON_DUR_US,
                "pid": h, "tid": 0,
                "args": {
                    "step": step,
                    "skew_ms": round((t[h] - t[ref]) * 1e3, 3),
                },
            })
        for h in present:
            if h == ref:
                continue
            fid = f"step{step}:{h}"
            mid = _BEACON_DUR_US / 2.0
            extra.append({
                "ph": "s", "cat": "step_flow", "name": "step_sync",
                "id": fid, "ts": t[ref] * 1e6 + mid, "pid": ref,
                "tid": 0,
            })
            extra.append({
                "ph": "f", "bp": "e", "cat": "step_flow",
                "name": "step_sync", "id": fid,
                "ts": t[h] * 1e6 + mid, "pid": h, "tid": 0,
            })
            arrows += 1

    journey_flows, journeys = request_journeys(merged)
    extra.extend(journey_flows)

    meta = [e for e in trace["traceEvents"] if e["ph"] == "M"]
    timed = [e for e in trace["traceEvents"] if e["ph"] != "M"] + extra
    timed.sort(key=lambda e: e["ts"])  # stable: file order at equal ts
    trace["traceEvents"] = meta + timed
    trace["progenClockOffsets"] = {
        str(h): round(off, 6) for h, off in sorted(offsets.items())
    }
    if journeys:
        trace["progenTraces"] = journeys
    trace["progenStitch"] = {
        "hosts": len(streams),
        "beacon_steps": len(steps),
        "flow_arrows": arrows,
        "request_flows": len(journey_flows) // 2,
    }
    return trace


def stitch_trace(
    event_paths: Sequence,
    out_path=None,
    metrics_paths: Sequence = (),
    reference: int = 0,
    force_hosts: bool = False,
) -> dict:
    """File-level stitch: read N hosts' events.jsonl (and optionally
    their metrics.jsonl, zipped positionally with ``event_paths``),
    merge onto the reference host's clock, optionally write the trace
    JSON, and return the trace dict. ``force_hosts`` assigns each file
    its argument position as its pid (serving fleets share a host, so
    every process stamps pid 0 — indistinguishable tracks otherwise)."""
    drops = LineDrops()
    # iter_events_any: a stream argument may be a flight-recorder dump
    # (flight-*.json) instead of events.jsonl — a SIGKILLed host's black
    # box stitches in as its own track next to the survivors
    streams = [list(iter_events_any(p, drops)) for p in event_paths]
    if force_hosts:
        hosts = list(range(len(streams)))
    else:
        hosts = [stream_host(s, i) for i, s in enumerate(streams)]
    metrics_streams: List[Tuple[int, List[dict]]] = []
    for host, mp in zip(hosts, metrics_paths or ()):
        if mp is not None and Path(mp).exists():
            metrics_streams.append((host, list(iter_jsonl(mp, drops))))
    trace = stitch_streams(
        streams, metrics_streams, reference=reference,
        force_hosts=force_hosts,
    )
    trace["progenDroppedLines"] = drops.count
    if out_path is not None:
        out_path = Path(out_path)
        out_path.parent.mkdir(parents=True, exist_ok=True)
        with out_path.open("w") as f:
            json.dump(trace, f)
    return trace
