"""Alert sink: transition records → an alerts JSONL (webhook file).

Alerts are the collector's *actionable* output — everything else it
writes is evidence. Three kinds, all edge-triggered (a condition that
holds for an hour produces exactly two lines: onset and recovery):

  * ``kind:"staleness"`` — a source's ``up`` bit flipped: its
    exposition file stopped refreshing (process dead or wedged) or
    came back;
  * ``kind:"slo_burn"`` — the fleet-SLO watchtower crossed a state
    edge (``warn``/``burning``/``resolved``), forwarded from
    ``SloWatch`` so the paging decision rides the *merged* fleet
    series, not any single replica's file;
  * ``kind:"deploy_rollback"`` — the deploy controller reverted a
    canary checkpoint (state ``rolled_back``, objective = the
    checkpoint name), so a bad rollout pages through the same
    pipeline as a burning SLO.

The sink file uses the journal's write discipline (append, one line,
flush) so a tail -f or a webhook relay can follow it live; ``ev:
"alert"`` records are built only here (PGL006 enforces the grammar:
kind/state alphabets, source/objective always present).

Edge-triggering survives restarts: the sink persists its last-known
state per alert identity (``kind|source|objective``) in a small JSON
file beside ``alerts.jsonl`` and reloads it on start, so a restarted
collector neither re-fires an alert for a condition it already
reported (``suppressed`` counts those) nor misses the recovery edge of
a condition that flipped while it was down. An optional ``relay``
callable (the alert router) receives every record that survives the
dedup.
"""

from __future__ import annotations

import json
import os
import time
from pathlib import Path
from typing import Callable, Dict, List, Optional

from progen_tpu.telemetry.spans import EventLog

ALERT_KINDS = ("staleness", "slo_burn", "deploy_rollback")
ALERT_STATES = (
    "stale", "fresh", "warn", "burning", "resolved", "rolled_back"
)


class AlertSink:
    """Append-only ``ev:"alert"`` writer over an :class:`EventLog`;
    keeps the most recent records in memory for the console and the
    last state per alert identity on disk for restart dedup."""

    def __init__(
        self,
        path,
        keep: int = 64,
        state_path=None,
        relay: Optional[Callable[[dict], object]] = None,
    ):
        self._log = EventLog(path)
        self.path = self._log.path
        self.keep = int(keep)
        self.recent: List[dict] = []
        self.relay = relay
        self.suppressed = 0
        self.state_path = (
            Path(state_path) if state_path
            else self.path.with_suffix(".state.json")
        )
        try:
            self._states: Dict[str, str] = json.loads(
                self.state_path.read_text()
            )
        except (OSError, ValueError):
            self._states = {}

    def close(self) -> None:
        self._log.close()

    @staticmethod
    def _key(kind: str, source: str, objective: str = "") -> str:
        return f"{kind}|{source}|{objective}"

    def last_state(
        self, kind: str, source: str, objective: str = ""
    ) -> Optional[str]:
        return self._states.get(self._key(kind, source, objective))

    def last_states(self, kind: str) -> Dict[str, str]:
        """``{source-or-objective: state}`` for one alert kind — what
        the collector seeds its transition detectors from on start."""
        out: Dict[str, str] = {}
        for key, state in self._states.items():
            k, source, objective = key.split("|", 2)
            if k == kind:
                out[objective if k == "slo_burn" else source] = state
        return out

    def _save_states(self) -> None:
        tmp = self.state_path.with_suffix(".tmp")
        tmp.write_text(json.dumps(self._states, sort_keys=True))
        os.replace(tmp, self.state_path)

    def _emit(self, rec: dict) -> Optional[dict]:
        key = self._key(rec["kind"], rec["source"], rec["objective"])
        if self._states.get(key) == rec["state"]:
            # identical state already on record (typically: a restart
            # replayed the same transition) — the alert fired once
            self.suppressed += 1
            return None
        self._states[key] = rec["state"]
        self._save_states()
        self._log.emit(rec)
        self.recent.append(rec)
        del self.recent[: -self.keep]
        if self.relay is not None:
            self.relay(rec)
        return rec

    def staleness(
        self,
        source: str,
        up: bool,
        age_s: float,
        now: Optional[float] = None,
    ) -> dict:
        return self._emit({
            "ev": "alert",
            "ts": float(time.time() if now is None else now),
            "kind": "staleness",
            "state": "fresh" if up else "stale",
            "source": str(source),
            "objective": "",
            "age_s": round(float(age_s), 3),
        })

    def deploy_rollback(
        self, ckpt: str, reason: str, now: Optional[float] = None
    ) -> Optional[dict]:
        """The deploy controller reverted ``ckpt`` — exactly-once per
        checkpoint across controller restarts (the identity is
        ``deploy_rollback|deploy|<ckpt>`` and a replayed rollback hits
        the same-state dedup)."""
        return self._emit({
            "ev": "alert",
            "ts": float(time.time() if now is None else now),
            "kind": "deploy_rollback",
            "state": "rolled_back",
            "source": "deploy",
            "objective": str(ckpt),
            "reason": str(reason),
        })

    def slo_transition(
        self, slo_rec: dict, exemplars: Optional[dict] = None
    ) -> dict:
        """Forward one ``ev:"slo"`` transition record (SloWatch output)
        as an alert; the original burn numbers ride along, and when the
        caller has fleet trace exemplars (the collector does) the worst
        trace ids land in the payload — the page names the requests
        behind the burn, not just the quantile."""
        rec = {
            "ev": "alert",
            "ts": float(slo_rec.get("ts", time.time())),
            "kind": "slo_burn",
            "state": str(slo_rec.get("state", "warn")),
            "source": "fleet",
            "objective": str(slo_rec.get("objective", "")),
            "burn_short": slo_rec.get("burn_short"),
            "burn_long": slo_rec.get("burn_long"),
            "value": slo_rec.get("value"),
        }
        if exemplars:
            rec["exemplars"] = {
                fam: list(exs) for fam, exs in exemplars.items() if exs
            }
        return self._emit(rec)
