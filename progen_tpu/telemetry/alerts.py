"""Alert sink: transition records → an alerts JSONL (webhook file).

Alerts are the collector's *actionable* output — everything else it
writes is evidence. Two kinds, both edge-triggered (a condition that
holds for an hour produces exactly two lines: onset and recovery):

  * ``kind:"staleness"`` — a source's ``up`` bit flipped: its
    exposition file stopped refreshing (process dead or wedged) or
    came back;
  * ``kind:"slo_burn"`` — the fleet-SLO watchtower crossed a state
    edge (``warn``/``burning``/``resolved``), forwarded from
    ``SloWatch`` so the paging decision rides the *merged* fleet
    series, not any single replica's file.

The sink file uses the journal's write discipline (append, one line,
flush) so a tail -f or a webhook relay can follow it live; ``ev:
"alert"`` records are built only here (PGL006 enforces the grammar:
kind/state alphabets, source/objective always present).
"""

from __future__ import annotations

import time
from typing import List, Optional

from progen_tpu.telemetry.spans import EventLog

ALERT_KINDS = ("staleness", "slo_burn")
ALERT_STATES = ("stale", "fresh", "warn", "burning", "resolved")


class AlertSink:
    """Append-only ``ev:"alert"`` writer over an :class:`EventLog`;
    keeps the most recent records in memory for the console."""

    def __init__(self, path, keep: int = 64):
        self._log = EventLog(path)
        self.path = self._log.path
        self.keep = int(keep)
        self.recent: List[dict] = []

    def close(self) -> None:
        self._log.close()

    def _emit(self, rec: dict) -> dict:
        self._log.emit(rec)
        self.recent.append(rec)
        del self.recent[: -self.keep]
        return rec

    def staleness(
        self,
        source: str,
        up: bool,
        age_s: float,
        now: Optional[float] = None,
    ) -> dict:
        return self._emit({
            "ev": "alert",
            "ts": float(time.time() if now is None else now),
            "kind": "staleness",
            "state": "fresh" if up else "stale",
            "source": str(source),
            "objective": "",
            "age_s": round(float(age_s), 3),
        })

    def slo_transition(self, slo_rec: dict) -> dict:
        """Forward one ``ev:"slo"`` transition record (SloWatch output)
        as an alert; the original burn numbers ride along."""
        return self._emit({
            "ev": "alert",
            "ts": float(slo_rec.get("ts", time.time())),
            "kind": "slo_burn",
            "state": str(slo_rec.get("state", "warn")),
            "source": "fleet",
            "objective": str(slo_rec.get("objective", "")),
            "burn_short": slo_rec.get("burn_short"),
            "burn_long": slo_rec.get("burn_long"),
            "value": slo_rec.get("value"),
        })
