"""Fleet metrics collector: scrape N sources → stamped samples → TSDB.

The fleet's signals are scattered — one Prometheus textfile per replica
and per router, one ``metrics.jsonl`` per run — and each answers only
for its own process. This module is the aggregation layer the
autoscaler / canary controller / ops console all read:

  * ``Collector`` scrapes every configured :class:`SourceSpec` on a
    tick: Prometheus textfiles via the existing ``parse_prom_text``
    (with a ``# TYPE`` scan so counter/gauge/summary identity survives
    the name normalization), ``metrics.jsonl`` tails incrementally by
    byte offset via the same torn-line rules as ``iter_jsonl``;
  * every scrape becomes ONE ``ev:"sample"`` record per source —
    stamped with source name, role, staleness age and an ``up`` bit
    (exposition mtime is the liveness heartbeat) — appended to a
    :class:`~progen_tpu.telemetry.tsdb.RingTSDB`. ``make_sample`` is
    the single constructor for these records; PGL006 enforces that no
    other module fabricates them;
  * ``fleet_series`` folds the per-source samples into ONE aggregated
    time series in the exact ``samples_from_metrics`` shape
    ``slo.evaluate`` consumes: counters **sum** across sources with
    counter-reset rebasing (a respawned replica restarting from zero
    must never drive a fleet rate negative — its pre-reset total is
    carried as a base), gauges aggregate **max**/**min**/**sum**,
    timing reservoirs merge exactly on ``sum``/``count`` and
    approximately on quantiles (count-weighted mixture-CDF inversion
    via ``merge_quantiles``), and derived fleet gauges
    (``fleet_up``, ``replicas_live``, …) carry the liveness story;
  * staleness transitions and fleet-SLO burn transitions fan into an
    :class:`~progen_tpu.telemetry.alerts.AlertSink`.

Deliberately jax-free: the collector is a host-side sidecar, startable
anywhere the exposition files are visible.
"""

from __future__ import annotations

import math
import re
import time
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from progen_tpu.telemetry.remote_write import fleet_kinds
from progen_tpu.telemetry.slo import (
    SloConfig,
    SloWatch,
    evaluate,
    parse_prom_exemplars,
    parse_prom_text,
)

_TYPE_RE = re.compile(r"^#\s*TYPE\s+(\S+)\s+(\S+)\s*$")
_PROM_PREFIXES = ("progen_router_", "progen_serve_", "progen_")
_QUANTILE_KEYS = ("p50_s", "p95_s", "p99_s")
_ROLES = ("replica", "router", "run")

# metrics.jsonl rows carry no TYPE metadata, so counter identity for
# tailed sources comes from this list (the serving/router/workload
# counter families that matter to fleet rates)
_JSONL_COUNTERS = (
    "requests_submitted", "requests_completed", "requests_rejected",
    "requests_admitted", "requests_expired", "decode_steps",
    "decode_tokens", "prefill_tokens", "tokens_forwarded",
    "dispatched_total", "handoffs_total", "replica_down_total",
    "journal_replayed", "reloads", "reload_rejected", "retries",
    "sequences_scored", "tokens_scored",
)


@dataclass
class SourceSpec:
    """One scrape target. ``prom`` and ``metrics`` are both optional but
    at least one must be set; ``prom`` drives the ``up`` heartbeat."""

    name: str
    role: str = "replica"
    prom: Optional[str] = None
    metrics: Optional[str] = None

    def __post_init__(self):
        if self.role not in _ROLES:
            raise ValueError(
                f"source {self.name!r}: role {self.role!r} "
                f"(want one of {_ROLES})"
            )
        if not self.prom and not self.metrics:
            raise ValueError(
                f"source {self.name!r}: need prom= and/or metrics="
            )


def parse_source_spec(spec: str) -> SourceSpec:
    """``name=r0,role=replica,prom=/p/metrics.prom[,metrics=/m.jsonl]``
    → SourceSpec (the --source CLI syntax, mirroring the router's
    --replica specs)."""
    kv: Dict[str, str] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"bad --source fragment {part!r} in {spec!r}")
        k, v = part.split("=", 1)
        kv[k.strip()] = v.strip()
    unknown = set(kv) - {"name", "role", "prom", "metrics"}
    if unknown:
        raise ValueError(f"unknown --source keys {sorted(unknown)} in {spec!r}")
    if "name" not in kv:
        raise ValueError(f"--source needs name=: {spec!r}")
    return SourceSpec(
        name=kv["name"],
        role=kv.get("role", "replica"),
        prom=kv.get("prom"),
        metrics=kv.get("metrics"),
    )


def prom_families(text: str) -> Dict[str, str]:
    """``# TYPE`` lines → {normalized family name: kind}. Names are
    normalized exactly like ``parse_prom_text`` normalizes samples
    (prefix stripped, ``_total`` bared, ``_seconds`` → ``_s``) so the
    two maps join on the same keys."""
    out: Dict[str, str] = {}
    for line in text.splitlines():
        m = _TYPE_RE.match(line.strip())
        if m is None:
            continue
        name, kind = m.groups()
        for p in _PROM_PREFIXES:
            if name.startswith(p):
                name = name[len(p):]
                break
        if name.endswith("_total"):
            name = name[: -len("_total")]
        elif name.endswith("_seconds"):
            name = name[: -len("_seconds")] + "_s"
        out[name] = kind
    return out


def make_sample(
    ts: float,
    source: str,
    role: str,
    up: bool,
    age_s: float,
    counters: Optional[Dict[str, float]] = None,
    gauges: Optional[Dict[str, float]] = None,
    timings: Optional[Dict[str, dict]] = None,
) -> dict:
    """The one constructor for ``ev:"sample"`` records (PGL006 keeps it
    that way). ``timings`` values are ``{"sum","count","p50_s",...}``."""
    return {
        "ev": "sample",
        "ts": float(ts),
        "source": str(source),
        "role": str(role),
        "up": int(bool(up)),
        "age_s": round(float(age_s), 3),
        "counters": dict(counters or {}),
        "gauges": dict(gauges or {}),
        "timings": {k: dict(v) for k, v in (timings or {}).items()},
    }


def split_prom_values(
    vals: Dict[str, float], families: Dict[str, str]
) -> Tuple[Dict[str, float], Dict[str, float], Dict[str, dict]]:
    """parse_prom_text output + TYPE map → (counters, gauges, timings).
    Samples without a TYPE line fall back to gauge (the conservative
    reading: a mistaken counter only loses rate math, a mistaken gauge
    would corrupt fleet sums after restarts)."""
    counters: Dict[str, float] = {}
    gauges: Dict[str, float] = {}
    timings: Dict[str, dict] = {}
    summary_keys = set()
    for fam, kind in families.items():
        if kind != "summary":
            continue
        t: dict = {}
        for q in _QUANTILE_KEYS:
            k = f"{fam}_{q}"
            if k in vals:
                t[q] = vals[k]
                summary_keys.add(k)
        for suffix in ("sum", "count"):
            k = f"{fam}_{suffix}"
            if k in vals:
                t[suffix] = vals[k]
                summary_keys.add(k)
        if t:
            timings[fam] = t
    for k, v in vals.items():
        if k in summary_keys:
            continue
        kind = families.get(k)
        if kind == "counter":
            counters[k] = v
        else:
            gauges[k] = v
    return counters, gauges, timings


def _timings_from_row(vals: Dict[str, float]) -> Dict[str, dict]:
    """Reassemble ``_Timing.stats()`` flat keys from a metrics.jsonl row
    into per-family dicts; families are detected by their ``_count`` +
    ``_p50_s`` pair. Pre-PR-12 rows lack ``_sum`` — reconstruct it from
    the mean so fleet averages stay mergeable across old artifacts."""
    out: Dict[str, dict] = {}
    for k in list(vals):
        if not k.endswith("_count"):
            continue
        fam = k[: -len("_count")]
        if f"{fam}_p50_s" not in vals:
            continue
        t: dict = {"count": vals[k]}
        for q in _QUANTILE_KEYS:
            qk = f"{fam}_{q}"
            if qk in vals:
                t[q] = vals[qk]
        if f"{fam}_sum" in vals:
            t["sum"] = vals[f"{fam}_sum"]
        elif f"{fam}_mean_s" in vals:
            t["sum"] = vals[f"{fam}_mean_s"] * vals[k]
        out[fam] = t
    return out


_TIMING_STAT_SUFFIXES = (
    "_p50_s", "_p95_s", "_p99_s", "_mean_s", "_max_s", "_min_s",
    "_count", "_sum",
)


class _Tail:
    """Incremental reader for a metrics.jsonl stream: remembers the
    byte offset, tolerates a torn final line by leaving it unread until
    the writer finishes it, and survives truncation (file rewritten)
    by rewinding to zero."""

    def __init__(self, path):
        self.path = Path(path)
        self.offset = 0
        self.dropped = 0

    def read_new(self) -> List[dict]:
        import json

        try:
            size = self.path.stat().st_size
        except OSError:
            return []
        if size < self.offset:
            self.offset = 0
        if size == self.offset:
            return []
        with self.path.open("rb") as f:
            f.seek(self.offset)
            data = f.read()
        end = data.rfind(b"\n") + 1
        if end == 0:
            return []
        self.offset += end
        rows: List[dict] = []
        for line in data[:end].splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                self.dropped += 1
                continue
            if isinstance(rec, dict):
                rows.append(rec)
            else:
                self.dropped += 1
        return rows


class Collector:
    """Scrape loop state: per-source tails, last-known ``up`` bits for
    staleness transitions, a bounded in-memory sample window for live
    SLO evaluation, and the TSDB + alert sinks."""

    def __init__(
        self,
        tsdb,
        sources: Sequence[SourceSpec],
        stale_after_s: float = 10.0,
        slo_cfg: Optional[SloConfig] = None,
        alerts=None,
        window_s: Optional[float] = None,
        remote_write=None,
        profile_pins: Sequence[str] = (),
        profile_min_interval_s: float = 300.0,
    ):
        names = [s.name for s in sources]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate source names: {names}")
        self.tsdb = tsdb
        self.sources = list(sources)
        self.stale_after_s = float(stale_after_s)
        self.slo_cfg = slo_cfg
        self.alerts = alerts
        self.remote_write = remote_write
        self._tails = {
            s.name: _Tail(s.metrics) for s in self.sources if s.metrics
        }
        self._last_row: Dict[str, Tuple[float, dict]] = {}
        self._up_last: Dict[str, int] = {}
        self._window: List[dict] = []
        self._window_s = float(
            window_s if window_s is not None
            else (slo_cfg.long_s if slo_cfg else 3600.0) * 1.25
        )
        self._watch = (
            SloWatch(slo_cfg, emit=self._emit_slo) if slo_cfg else None
        )
        # on-demand forensics: pins to raise when an SLO starts burning
        # (one per serve/train process we can ask to self-profile)
        self.profile_pins = [str(p) for p in profile_pins]
        self.profile_min_interval_s = float(profile_min_interval_s)
        self._profile_last = -math.inf
        # restart continuity: seed the transition detectors from the
        # sink's persisted states so an edge that happened while this
        # collector was down still fires (and a condition it already
        # reported does not re-fire)
        if alerts is not None and hasattr(alerts, "last_states"):
            for name, state in alerts.last_states("staleness").items():
                if name in set(names):
                    self._up_last[name] = 1 if state == "fresh" else 0
            if self._watch is not None:
                for obj, state in alerts.last_states("slo_burn").items():
                    self._watch.seed(obj, state)

    # -- scraping ---------------------------------------------------------

    def _scrape_prom(self, path, now: float):
        p = Path(path)
        try:
            stat = p.stat()
            text = p.read_text()
        except OSError:
            return None
        age = max(0.0, now - stat.st_mtime)
        return (
            age,
            parse_prom_text(text),
            prom_families(text),
            parse_prom_exemplars(text),
        )

    def _scrape_source(self, src: SourceSpec, now: float) -> dict:
        counters: Dict[str, float] = {}
        gauges: Dict[str, float] = {}
        timings: Dict[str, dict] = {}
        age = float("inf")
        seen = False
        if src.prom:
            got = self._scrape_prom(src.prom, now)
            if got is not None:
                prom_age, vals, families, exemplars = got
                counters, gauges, timings = split_prom_values(
                    vals, families
                )
                # trace exemplars ride the timing dicts (schema-free
                # values) so they reach the TSDB / console / alerts
                # without touching the sample record shape
                for fam, exs in exemplars.items():
                    if fam in timings and exs:
                        timings[fam]["exemplars"] = exs
                age = prom_age
                seen = True
        tail = self._tails.get(src.name)
        if tail is not None:
            rows = tail.read_new()
            for rec in rows:
                t = rec.get("_time")
                if t is not None:
                    self._last_row[src.name] = (float(t), rec)
            last = self._last_row.get(src.name)
            if last is not None:
                row_t, rec = last
                vals: Dict[str, float] = {}
                for k, v in rec.items():
                    if k.startswith("_") or isinstance(v, bool) \
                            or not isinstance(v, (int, float)):
                        continue
                    vals[k.split("/", 1)[1] if "/" in k else k] = float(v)
                row_timings = _timings_from_row(vals)
                for fam, t in row_timings.items():
                    timings.setdefault(fam, t)
                for k, v in vals.items():
                    if any(k.endswith(s) for s in _TIMING_STAT_SUFFIXES):
                        continue
                    if k in _JSONL_COUNTERS:
                        counters.setdefault(k, v)
                    else:
                        gauges.setdefault(k, v)
                age = min(age, max(0.0, now - row_t))
                seen = True
        up = seen and age <= self.stale_after_s
        return make_sample(
            ts=now,
            source=src.name,
            role=src.role,
            up=up,
            age_s=0.0 if age == float("inf") else age,
            counters=counters,
            gauges=gauges,
            timings=timings,
        )

    def scrape_once(self, now: Optional[float] = None) -> List[dict]:
        """One tick: scrape every source, append samples to the TSDB,
        fire staleness/SLO alert transitions. Returns the samples."""
        now = time.time() if now is None else float(now)
        samples = [self._scrape_source(s, now) for s in self.sources]
        for rec in samples:
            self.tsdb.append(rec)
        self._window.extend(samples)
        cutoff = now - self._window_s
        if self._window and self._window[0]["ts"] < cutoff:
            self._window = [
                r for r in self._window if r["ts"] >= cutoff
            ]
        self._staleness_transitions(samples, now)
        fleet = None
        if self._watch is not None or self.remote_write is not None:
            fleet = fleet_series(self._window)
        if self._watch is not None:
            results = evaluate(self.slo_cfg, [fleet], now=now)
            self._watch.observe(results, now=now)
        if self.remote_write is not None and fleet:
            counters, timings = fleet_kinds(self._window)
            t, vals = fleet[-1]
            self.remote_write.offer(t, vals, counters, timings)
            self.remote_write.flush(now)
        return samples

    # -- alerting ---------------------------------------------------------

    def _staleness_transitions(self, samples: List[dict], now: float):
        for rec in samples:
            name = rec["source"]
            prev = self._up_last.get(name)
            self._up_last[name] = rec["up"]
            if prev is None or prev == rec["up"]:
                continue
            if self.alerts is not None:
                self.alerts.staleness(
                    source=name,
                    up=bool(rec["up"]),
                    age_s=rec["age_s"],
                    now=now,
                )

    def _emit_slo(self, rec: dict) -> None:
        if self.alerts is not None:
            self.alerts.slo_transition(
                rec, exemplars=fleet_exemplars(self._window)
            )
        # also forward through the telemetry stream: the SloWatch above
        # is wired to this method *instead of* get_telemetry().emit, so
        # without this the flight recorder's tap (which dumps on the
        # burning edge) would never see collector-side transitions
        from progen_tpu.telemetry.spans import get_telemetry

        get_telemetry().emit(rec)
        if rec.get("state") == "burning":
            self._auto_profile(rec)

    def _auto_profile(self, rec: dict) -> None:
        """First burning edge → raise ``profile.pin`` on every
        configured target so the processes behind the burn capture a
        bounded trace window while the badness is still happening.
        Rate-limited so a flapping objective cannot spam windows."""
        if not self.profile_pins:
            return
        now = float(rec.get("ts", time.time()))
        if now - self._profile_last < self.profile_min_interval_s:
            return
        self._profile_last = now
        from progen_tpu.telemetry import flight

        for pin in self.profile_pins:
            try:
                flight.request_profile(
                    pin, token=f"slo-{rec.get('objective', 'burn')}-{int(now)}"
                )
            except OSError:
                continue


# -- fleet aggregation ----------------------------------------------------


def merge_quantiles(
    parts: Sequence[Tuple[float, Dict[str, float]]],
    quantiles: Sequence[Tuple[float, str]] = (
        (0.5, "p50_s"), (0.95, "p95_s"), (0.99, "p99_s")
    ),
) -> Dict[str, float]:
    """Merge per-source quantile summaries into fleet quantiles.

    Exact quantile merging needs the raw reservoirs, which never leave
    the source process — what crosses the wire is (count, p50, p95,
    p99). Each part is treated as a piecewise-linear CDF anchored at
    (0 → 0), its known quantile points, and (p99 → 1); the fleet CDF is
    the count-weighted mixture, inverted by bisection. Degenerate but
    safe at the edges: identical parts merge to themselves, disjoint
    parts land between, and the p99 of the slowest source bounds the
    result."""
    anchored = []
    total_w = 0.0
    for weight, qs in parts:
        w = float(weight)
        if w <= 0:
            continue
        pts: List[Tuple[float, float]] = [(0.0, 0.0)]
        hi = 0.0
        for q, key in quantiles:
            if key in qs:
                v = max(float(qs[key]), hi)  # enforce monotone values
                hi = v
                pts.append((v, float(q)))
        if len(pts) == 1:
            continue
        pts.append((hi, 1.0))
        anchored.append((w, pts))
        total_w += w
    if not anchored:
        return {}

    def cdf(pts: List[Tuple[float, float]], v: float) -> float:
        if v >= pts[-1][0]:
            return 1.0
        q = 0.0
        for (v0, q0), (v1, q1) in zip(pts, pts[1:]):
            if v < v0:
                break
            if v >= v1:
                q = q1
            else:
                q = q0 if v1 <= v0 else q0 + (q1 - q0) * (v - v0) / (v1 - v0)
        return q

    def mixture(v: float) -> float:
        return sum(w * cdf(pts, v) for w, pts in anchored) / total_w

    hi_all = max(pts[-1][0] for _, pts in anchored)
    out: Dict[str, float] = {}
    for q, key in quantiles:
        lo, hi = 0.0, hi_all
        for _ in range(48):
            mid = (lo + hi) / 2
            if mixture(mid) >= q:
                hi = mid
            else:
                lo = mid
        out[key] = hi
    return out


class _CounterBank:
    """Reset-safe cumulative view of one source's counters/timing sums:
    when a raw value decreases (process respawned and restarted from
    zero) the pre-reset total folds into a base so the rebased series
    stays monotone and the fleet sum never dips or spikes."""

    __slots__ = ("base", "raw")

    def __init__(self):
        self.base: Dict[str, float] = {}
        self.raw: Dict[str, float] = {}

    def update(self, vals: Dict[str, float]) -> None:
        for k, v in vals.items():
            last = self.raw.get(k)
            if last is not None and v < last:
                self.base[k] = self.base.get(k, 0.0) + last
            self.raw[k] = v

    def rebased(self) -> Dict[str, float]:
        return {
            k: self.base.get(k, 0.0) + v for k, v in self.raw.items()
        }


def fleet_series(
    samples: Iterable[dict],
) -> List[Tuple[float, Dict[str, float]]]:
    """Per-source ``ev:"sample"`` records → ONE aggregated (t, values)
    series in the ``samples_from_metrics`` shape ``slo.evaluate``
    consumes. See module docstring for the aggregation rules."""
    recs = sorted(
        (r for r in samples if r.get("ev") == "sample" and "ts" in r),
        key=lambda r: r["ts"],
    )
    counters: Dict[str, _CounterBank] = {}
    tsums: Dict[str, _CounterBank] = {}
    state: Dict[str, dict] = {}
    out: List[Tuple[float, Dict[str, float]]] = []
    i = 0
    while i < len(recs):
        t = recs[i]["ts"]
        while i < len(recs) and recs[i]["ts"] == t:
            rec = recs[i]
            name = rec["source"]
            bank = counters.setdefault(name, _CounterBank())
            bank.update(rec.get("counters", {}))
            tbank = tsums.setdefault(name, _CounterBank())
            cum = {}
            for fam, tv in rec.get("timings", {}).items():
                if "count" in tv:
                    cum[f"{fam}_count"] = float(tv["count"])
                if "sum" in tv:
                    cum[f"{fam}_sum"] = float(tv["sum"])
            tbank.update(cum)
            state[name] = rec
            i += 1
        vals: Dict[str, float] = {}
        # counters: fleet total = sum of reset-rebased per-source totals
        # (a dead source keeps contributing its last known total — work
        # already done does not vanish with the process)
        for bank in counters.values():
            for k, v in bank.rebased().items():
                vals[k] = vals.get(k, 0.0) + v
        for tbank in tsums.values():
            for k, v in tbank.rebased().items():
                vals[k] = vals.get(k, 0.0) + v
        # gauges: max is the headline (pressure reads as worst-of-fleet),
        # min/sum ride along under suffixed names; only live sources
        # vote — a frozen exposition is history, not load
        gnames = set()
        for rec in state.values():
            if rec["up"]:
                gnames.update(rec.get("gauges", {}))
        for g in gnames:
            vs = [
                rec["gauges"][g] for rec in state.values()
                if rec["up"] and g in rec.get("gauges", {})
            ]
            vals[g] = max(vs)
            vals[f"{g}_min"] = min(vs)
            vals[f"{g}_sum"] = sum(vs)
        # timing quantiles: count-weighted mixture merge over live
        # sources (sum/count already aggregated exactly above)
        fams = set()
        for rec in state.values():
            if rec["up"]:
                fams.update(rec.get("timings", {}))
        for fam in fams:
            parts = []
            for rec in state.values():
                tv = rec.get("timings", {}).get(fam)
                if rec["up"] and tv and tv.get("count", 0) > 0:
                    parts.append((float(tv["count"]), tv))
            merged = merge_quantiles(parts)
            for key, v in merged.items():
                vals[f"{fam}_{key}"] = v
            ckey = f"{fam}_count"
            if ckey in vals and vals[ckey] > 0:
                vals[f"{fam}_mean_s"] = vals.get(f"{fam}_sum", 0.0) / vals[ckey]
        # liveness rollup
        ups = {n: rec["up"] for n, rec in state.items()}
        vals["fleet_sources"] = float(len(state))
        vals["fleet_up"] = float(sum(ups.values()))
        vals["replicas_total"] = float(sum(
            1 for rec in state.values() if rec["role"] == "replica"
        ))
        vals["replicas_live"] = float(sum(
            1 for rec in state.values()
            if rec["role"] == "replica" and rec["up"]
        ))
        out.append((t, vals))
    return out


def load_collector_config(path) -> Tuple[dict, List[SourceSpec]]:
    """Flat-TOML collector config → (settings, sources). One
    ``[collector]`` table (interval_s, stale_after_s, budget_bytes,
    block_bytes, slo) plus one ``[source_<name>]`` table per target —
    the same flat subset config.py's minimal parser accepts."""
    from progen_tpu.config import load_toml_config

    raw = load_toml_config(str(path))
    settings = raw.get("collector", {})
    if not isinstance(settings, dict):
        settings = {}
    sources: List[SourceSpec] = []
    for section, table in raw.items():
        if not section.startswith("source_") or not isinstance(table, dict):
            continue
        sources.append(SourceSpec(
            name=section[len("source_"):],
            role=str(table.get("role", "replica")),
            prom=str(table["prom"]) if table.get("prom") else None,
            metrics=str(table["metrics"]) if table.get("metrics") else None,
        ))
    return settings, sources


def fleet_exemplars(samples: Iterable[dict]) -> Dict[str, List[dict]]:
    """Union per-source trace exemplars into the fleet's worst-K per
    timing family. ``fleet_series`` flattens everything to floats, so
    exemplars need this parallel rollup: the latest sample per source
    contributes its exemplar list, and the fleet's worst-K is the
    worst-K of the parts' worst-Ks (same invariant as
    ``_Timing.merged`` — max is order-insensitive)."""
    from progen_tpu.telemetry.registry import _Timing

    pairs: Dict[str, List[Tuple[float, str]]] = {}
    for rec in latest_by_source(samples).values():
        for fam, tv in rec.get("timings", {}).items():
            for ex in tv.get("exemplars") or []:
                try:
                    pairs.setdefault(fam, []).append(
                        (float(ex["value"]), str(ex["trace_id"]))
                    )
                except (KeyError, TypeError, ValueError):
                    continue
    return {
        fam: [
            {"value": v, "trace_id": tid}
            for v, tid in _Timing._worst_k(ps)
        ]
        for fam, ps in pairs.items()
    }


def latest_by_source(samples: Iterable[dict]) -> Dict[str, dict]:
    """Last sample per source (console's per-replica rows)."""
    out: Dict[str, dict] = {}
    for rec in samples:
        if rec.get("ev") == "sample" and "source" in rec:
            prev = out.get(rec["source"])
            if prev is None or rec.get("ts", 0) >= prev.get("ts", 0):
                out[rec["source"]] = rec
    return out
