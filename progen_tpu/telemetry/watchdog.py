"""Stall watchdog: stack dumps + last-spans report when steps stop.

BASELINE.md records a round that went "dead all window" with no
diagnostic trail, and bench phases have been timeout-killed mid-wedge
twice — in every case the post-mortem question was the same: *where was
the process when it stopped making progress?* The watchdog answers it
while the process is still alive to be asked.

A daemon thread watches a heartbeat the owning loop pings via
``beat()`` (once per completed step, or per progress marker in bench
phases). When no beat lands within ``deadline_s`` it fires ONCE:

  * all-thread Python stacks via ``faulthandler.dump_traceback`` — this
    does not need the stalled threads' cooperation, so it works even
    when the main thread is stuck inside a device call;
  * a last-spans report from the process Telemetry: the spans currently
    OPEN (where the process is now) and the most recent completed ones
    (how it got there);
  * an optional ``on_stall`` callback.

It re-arms if beats resume (a transient stall logs one report and the
run continues). The thread never kills the process — the surrounding
timeout machinery (driver, bench phase kill) owns that decision; the
watchdog's job is to make sure the kill leaves evidence.

Escalation (``escalate_after=N``): instead of reporting once per
stall, the watchdog re-reports every further ``deadline_s`` the stall
persists, and on the Nth consecutive report for the SAME stall it
snapshots ``device.memory_stats()`` for every visible device plus the
open-span list into the telemetry sink (events.jsonl) and the report
stream — the full forensic record, captured BEFORE the surrounding
timeout kills the run (ROADMAP "watchdog escalation hook").
"""

from __future__ import annotations

import faulthandler
import sys
import threading
import time
from typing import Callable, Optional

from progen_tpu.telemetry.registry import get_registry
from progen_tpu.telemetry.spans import Telemetry, get_telemetry, host_index


class StallWatchdog:
    def __init__(
        self,
        deadline_s: float,
        *,
        file=None,
        telemetry: Optional[Telemetry] = None,
        on_stall: Optional[Callable[[dict], None]] = None,
        poll_s: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
        escalate_after: int = 0,
        memory_stats_fn: Optional[Callable[[], list]] = None,
    ):
        if deadline_s <= 0:
            raise ValueError(f"deadline_s must be > 0, got {deadline_s}")
        self.deadline_s = float(deadline_s)
        self._file = file  # None -> stderr at fire time
        self._telemetry = telemetry
        self._on_stall = on_stall
        self._poll_s = poll_s if poll_s is not None else min(
            self.deadline_s / 4.0, 1.0
        )
        self._clock = clock
        self._last_beat = clock()
        self._fired_for_beat: Optional[float] = None
        self._fires_this_stall = 0
        self.fire_count = 0
        self.escalate_after = int(escalate_after)
        self.escalation_count = 0
        self._memory_stats_fn = memory_stats_fn
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ----- lifecycle ------------------------------------------------------

    def start(self) -> "StallWatchdog":
        self._last_beat = self._clock()
        self._thread = threading.Thread(
            target=self._run, name="stall-watchdog", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def __enter__(self) -> "StallWatchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ----- heartbeat ------------------------------------------------------

    def beat(self) -> None:
        """Progress ping; call once per completed unit of work."""
        self._last_beat = self._clock()

    @property
    def fired(self) -> bool:
        return self.fire_count > 0

    # ----- the watcher ----------------------------------------------------

    def _run(self) -> None:
        while not self._stop.wait(self._poll_s):
            last = self._last_beat
            stalled_s = self._clock() - last
            if stalled_s < self.deadline_s:
                continue
            if self._fired_for_beat != last:
                # a NEW stall (beats resumed since the last report)
                self._fired_for_beat = last
                self._fires_this_stall = 0
            if self.escalate_after > 0:
                # periodic re-report: the (n+1)-th fires once the stall
                # has lasted (n+1) deadlines
                if stalled_s < self.deadline_s * (self._fires_this_stall + 1):
                    continue
            elif self._fires_this_stall:
                continue  # legacy: once per stall; re-arm on beat
            self._fires_this_stall += 1
            self.fire_count += 1
            try:
                self._fire(stalled_s)
            except Exception:
                pass  # a broken reporter must not crash the daemon
            if (
                self.escalate_after > 0
                and self._fires_this_stall == self.escalate_after
            ):
                self.escalation_count += 1
                try:
                    self._escalate(stalled_s)
                except Exception:
                    pass

    def _fire(self, stalled_s: float) -> None:
        get_registry().inc("stalls")
        out = self._file if self._file is not None else sys.stderr
        tel = (
            self._telemetry
            if self._telemetry is not None
            else get_telemetry()
        )
        report = {
            "ev": "stall",
            "ts": time.time(),
            # explicit host stamp (not just the sink's pid tag): a
            # fleet-merged trace must pin the stall to the right track
            # even when the report is read outside the emitting process
            "host": host_index(),
            "stalled_s": round(stalled_s, 3),
            "deadline_s": self.deadline_s,
            "open_spans": [
                {"span": r["span"], "ts": r["ts"]}
                for r in tel.open_spans()
            ],
            "recent_spans": [
                {"span": r["span"], "dur_s": r.get("dur_s")}
                for r in tel.recent_spans(8)
            ],
        }
        print(
            f"[stall-watchdog] host {report['host']}: no step completed "
            f"in {stalled_s:.1f}s "
            f"(deadline {self.deadline_s:.0f}s); open spans: "
            f"{[r['span'] for r in report['open_spans']] or ['<none>']}; "
            "all-thread stacks follow",
            file=out,
            flush=True,
        )
        try:
            # fd-level dump: works even when stalled threads hold locks
            faulthandler.dump_traceback(file=out, all_threads=True)
        except (AttributeError, ValueError, OSError):
            # sink has no usable fileno (StringIO, wrapped streams):
            # same information via the interpreter's frame snapshot
            import traceback

            for tid, frame in sys._current_frames().items():
                print(f"Thread {tid}:", file=out)
                traceback.print_stack(frame, file=out)
        try:
            out.flush()
        except (OSError, ValueError):
            pass
        tel.emit(report)
        if self._on_stall is not None:
            self._on_stall(report)

    def _escalate(self, stalled_s: float) -> None:
        """Nth consecutive report for one stall: snapshot per-device
        allocator state + the open spans into the telemetry sink, so the
        record survives the kill that usually follows."""
        get_registry().inc("stall_escalations")
        out = self._file if self._file is not None else sys.stderr
        tel = (
            self._telemetry
            if self._telemetry is not None
            else get_telemetry()
        )
        mem = (
            self._memory_stats_fn
            if self._memory_stats_fn is not None
            else _device_memory_stats
        )()
        record = {
            "ev": "stall_escalation",
            "ts": time.time(),
            "host": host_index(),
            "stalled_s": round(stalled_s, 3),
            "consecutive_reports": self._fires_this_stall,
            "memory_stats": mem,
            "open_spans": [
                {"span": r["span"], "ts": r["ts"]}
                for r in tel.open_spans()
            ],
        }
        print(
            f"[stall-watchdog] host {record['host']}: ESCALATION after "
            f"{self._fires_this_stall} consecutive stall reports "
            f"({stalled_s:.1f}s): device memory + open spans snapshotted "
            "to the event stream",
            file=out,
            flush=True,
        )
        tel.emit(record)


def _device_memory_stats() -> list:
    """Per-device ``memory_stats()`` snapshot; [] when jax/backend
    offers none (CPU) — the escalation record is still useful for its
    open-span list."""
    try:
        import jax

        devices = jax.devices()
    except Exception:
        return []
    out = []
    for d in devices:
        try:
            stats = d.memory_stats() or {}
        except Exception:
            stats = {}
        out.append({"device": str(d.id), **{
            k: v for k, v in stats.items() if isinstance(v, (int, float))
        }})
    return out
