"""Stall watchdog: stack dumps + last-spans report when steps stop.

BASELINE.md records a round that went "dead all window" with no
diagnostic trail, and bench phases have been timeout-killed mid-wedge
twice — in every case the post-mortem question was the same: *where was
the process when it stopped making progress?* The watchdog answers it
while the process is still alive to be asked.

A daemon thread watches a heartbeat the owning loop pings via
``beat()`` (once per completed step, or per progress marker in bench
phases). When no beat lands within ``deadline_s`` it fires ONCE:

  * all-thread Python stacks via ``faulthandler.dump_traceback`` — this
    does not need the stalled threads' cooperation, so it works even
    when the main thread is stuck inside a device call;
  * a last-spans report from the process Telemetry: the spans currently
    OPEN (where the process is now) and the most recent completed ones
    (how it got there);
  * an optional ``on_stall`` callback.

It re-arms if beats resume (a transient stall logs one report and the
run continues). The thread never kills the process — the surrounding
timeout machinery (driver, bench phase kill) owns that decision; the
watchdog's job is to make sure the kill leaves evidence.
"""

from __future__ import annotations

import faulthandler
import sys
import threading
import time
from typing import Callable, Optional

from progen_tpu.telemetry.spans import Telemetry, get_telemetry


class StallWatchdog:
    def __init__(
        self,
        deadline_s: float,
        *,
        file=None,
        telemetry: Optional[Telemetry] = None,
        on_stall: Optional[Callable[[dict], None]] = None,
        poll_s: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        if deadline_s <= 0:
            raise ValueError(f"deadline_s must be > 0, got {deadline_s}")
        self.deadline_s = float(deadline_s)
        self._file = file  # None -> stderr at fire time
        self._telemetry = telemetry
        self._on_stall = on_stall
        self._poll_s = poll_s if poll_s is not None else min(
            self.deadline_s / 4.0, 1.0
        )
        self._clock = clock
        self._last_beat = clock()
        self._fired_for_beat: Optional[float] = None
        self.fire_count = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ----- lifecycle ------------------------------------------------------

    def start(self) -> "StallWatchdog":
        self._last_beat = self._clock()
        self._thread = threading.Thread(
            target=self._run, name="stall-watchdog", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def __enter__(self) -> "StallWatchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ----- heartbeat ------------------------------------------------------

    def beat(self) -> None:
        """Progress ping; call once per completed unit of work."""
        self._last_beat = self._clock()

    @property
    def fired(self) -> bool:
        return self.fire_count > 0

    # ----- the watcher ----------------------------------------------------

    def _run(self) -> None:
        while not self._stop.wait(self._poll_s):
            last = self._last_beat
            stalled_s = self._clock() - last
            if stalled_s < self.deadline_s:
                continue
            if self._fired_for_beat == last:
                continue  # already reported THIS stall; re-arm on beat
            self._fired_for_beat = last
            self.fire_count += 1
            try:
                self._fire(stalled_s)
            except Exception:
                pass  # a broken reporter must not crash the daemon

    def _fire(self, stalled_s: float) -> None:
        out = self._file if self._file is not None else sys.stderr
        tel = (
            self._telemetry
            if self._telemetry is not None
            else get_telemetry()
        )
        report = {
            "ev": "stall",
            "ts": time.time(),
            "stalled_s": round(stalled_s, 3),
            "deadline_s": self.deadline_s,
            "open_spans": [
                {"span": r["span"], "ts": r["ts"]}
                for r in tel.open_spans()
            ],
            "recent_spans": [
                {"span": r["span"], "dur_s": r.get("dur_s")}
                for r in tel.recent_spans(8)
            ],
        }
        print(
            f"[stall-watchdog] no step completed in {stalled_s:.1f}s "
            f"(deadline {self.deadline_s:.0f}s); open spans: "
            f"{[r['span'] for r in report['open_spans']] or ['<none>']}; "
            "all-thread stacks follow",
            file=out,
            flush=True,
        )
        try:
            # fd-level dump: works even when stalled threads hold locks
            faulthandler.dump_traceback(file=out, all_threads=True)
        except (AttributeError, ValueError, OSError):
            # sink has no usable fileno (StringIO, wrapped streams):
            # same information via the interpreter's frame snapshot
            import traceback

            for tid, frame in sys._current_frames().items():
                print(f"Thread {tid}:", file=out)
                traceback.print_stack(frame, file=out)
        try:
            out.flush()
        except (OSError, ValueError):
            pass
        tel.emit(report)
        if self._on_stall is not None:
            self._on_stall(report)
