"""Prometheus remote-write bridge for the merged fleet series.

The collector (``telemetry/collector.py``) already produces the hard
part — a reset-safe merged fleet series (counters rebased across
respawns, exact ``sum``/``count``, mixture-CDF quantile merge). This
module gets that series OFF the box: each scrape tick becomes one
remote-write *point* (a timestamped set of timeseries) pushed over
plain HTTP to a configurable endpoint.

Wire format: remote-write v1's shape without the protobuf+snappy
framing — a JSON body ``{"timeseries": [{"labels": {...},
"samples": [[ms, value], ...]}, ...]}`` where labels carry
``__name__`` (and ``quantile`` for summary series). Series names use
Prometheus conventions so a scrape-side ``parse_prom_text`` of the
rendered payload round-trips to the collector's own normalized keys:

  * counters        → ``progen_<name>_total``
  * gauges          → ``progen_<name>``
  * timing families → ``progen_<fam>_seconds{quantile="0.5|0.95|0.99"}``
    plus ``progen_<fam>_seconds_sum`` / ``_count`` (the derived
    ``<fam>_mean_s`` gauge is omitted — it is ``sum/count`` in PromQL)

One deliberate omission from this bridge: the worst-K trace exemplars
that ride the scrape-side exposition as OpenMetrics
``# {trace_id="..."} value`` annotations (see
``telemetry.prometheus.escape_label_value`` for the backslash/quote/
newline escaping both sides of that contract must share — the
trace_id is operator-influenced text inside a quoted label, so a raw
``"`` or ``\\n`` would tear the exposition line). Remote-write v1 has
no exemplar field; the fleet's exemplars stay queryable locally via
the TSDB samples and ``progen-tpu-telemetry query --trace``, and the
escape/unescape pair (``telemetry.slo.unescape_label_value``) is what
keeps them intact from replica exposition through collector merge.

Delivery discipline (the part that keeps the scrape loop honest):

  * ``offer()`` never blocks and never raises — points land in a
    bounded in-memory spool; overflow drops the OLDEST point and
    counts it (``dropped_points``), so a dead endpoint costs history,
    never liveness;
  * ``flush()`` pushes up to ``batch_points`` spooled points per call
    and returns immediately on failure — the failed batch goes back to
    the spool head and the next attempt waits out an exponential
    backoff computed from :class:`resilience.retry.RetryPolicy`
    (``PROGEN_RETRY_BASE_S``/``_MAX_S`` env knobs apply), so a flapping
    receiver sees capped-rate retries instead of a tick-rate hammer.
"""

from __future__ import annotations

import json
import random
import urllib.error
import urllib.request
from typing import Dict, Iterable, List, Optional, Set, Tuple

from progen_tpu.resilience.retry import RetryPolicy, policy_from_env

# quantile suffix (collector's normalized key) <-> remote-write label
QUANTILE_SUFFIXES = (("_p50_s", "0.5"), ("_p95_s", "0.95"),
                     ("_p99_s", "0.99"))
SERIES_PREFIX = "progen_"


def fleet_kinds(samples: Iterable[dict]) -> Tuple[Set[str], Set[str]]:
    """(counter key names, timing family names) observed across a
    window of ``ev:"sample"`` records — the type information
    ``encode_point`` needs to pick Prometheus naming for each flat
    fleet-series key."""
    counters: Set[str] = set()
    timings: Set[str] = set()
    for rec in samples:
        counters.update(rec.get("counters") or {})
        timings.update(rec.get("timings") or {})
    return counters, timings


def encode_point(
    ts: float,
    vals: Dict[str, float],
    counters: Set[str],
    timings: Set[str],
) -> List[dict]:
    """One fleet-series point → a list of remote-write timeseries (one
    sample each). Timing-family keys expand to quantile-labeled
    ``_seconds`` series; counters gain ``_total``; everything else
    ships as a plain gauge under ``SERIES_PREFIX``."""
    ms = int(round(float(ts) * 1000.0))
    out: List[dict] = []

    def series(name: str, value, quantile: Optional[str] = None):
        labels = {"__name__": name}
        if quantile is not None:
            labels["quantile"] = quantile
        out.append({"labels": labels, "samples": [[ms, float(value)]]})

    handled: Set[str] = set()
    for fam in sorted(timings):
        base = SERIES_PREFIX + (
            fam[:-2] + "_seconds" if fam.endswith("_s")
            else fam + "_seconds"
        )
        for suffix, q in QUANTILE_SUFFIXES:
            key = fam + suffix
            handled.add(key)
            if key in vals:
                series(base, vals[key], quantile=q)
        for part in ("sum", "count"):
            key = f"{fam}_{part}"
            handled.add(key)
            if key in vals:
                series(f"{base}_{part}", vals[key])
        # mean is derivable (sum/count); omitted so the payload
        # round-trips through parse_prom_text without a synthetic name
        handled.add(f"{fam}_mean_s")
    for key in sorted(vals):
        if key in handled:
            continue
        if key in counters:
            series(f"{SERIES_PREFIX}{key}_total", vals[key])
        elif key.endswith(
            ("_total", "_seconds", "_seconds_sum", "_seconds_count")
        ):
            # a gauge whose own name ends in a suffix the scrape-side
            # normalizer rewrites (e.g. replicas_total): append one
            # _total — parse_prom_text strips exactly one, restoring
            # the original key, so round-trip equality holds
            series(f"{SERIES_PREFIX}{key}_total", vals[key])
        else:
            series(SERIES_PREFIX + key, vals[key])
    return out


def merge_timeseries(points: Iterable[List[dict]]) -> List[dict]:
    """Batch several points into one payload body: same-label series
    concatenate their samples in time order."""
    merged: Dict[Tuple, dict] = {}
    for point in points:
        for ts_entry in point:
            labels = ts_entry["labels"]
            key = tuple(sorted(labels.items()))
            slot = merged.get(key)
            if slot is None:
                merged[key] = {
                    "labels": dict(labels),
                    "samples": list(ts_entry["samples"]),
                }
            else:
                slot["samples"].extend(ts_entry["samples"])
    out = list(merged.values())
    for entry in out:
        entry["samples"].sort(key=lambda s: s[0])
    out.sort(key=lambda e: sorted(e["labels"].items()))
    return out


def payload_to_prom_text(payload: dict) -> str:
    """Render a payload body back to exposition text (latest sample per
    series) — what a test or a fake receiver feeds ``parse_prom_text``
    to prove the encoding round-trips to the collector's keys."""
    lines = []
    for entry in payload.get("timeseries", []):
        labels = dict(entry.get("labels") or {})
        name = labels.pop("__name__", "")
        samples = entry.get("samples") or []
        if not name or not samples:
            continue
        label_txt = ""
        if labels:
            inner = ",".join(
                f'{k}="{v}"' for k, v in sorted(labels.items())
            )
            label_txt = "{" + inner + "}"
        lines.append(f"{name}{label_txt} {samples[-1][1]}")
    return "\n".join(lines) + "\n"


class RemoteWriteBridge:
    """Bounded spool + batched HTTP push; see module doc for the
    delivery discipline."""

    def __init__(
        self,
        url: str,
        spool_points: int = 240,
        batch_points: int = 30,
        timeout_s: float = 5.0,
        policy: Optional[RetryPolicy] = None,
        opener=None,
    ):
        self.url = str(url)
        self.spool_points = max(1, int(spool_points))
        self.batch_points = max(1, int(batch_points))
        self.timeout_s = float(timeout_s)
        self.policy = policy if policy is not None else policy_from_env()
        # urlopen-compatible hook so tests can fail pushes hermetically
        self._opener = opener or urllib.request.urlopen
        self._rng = random.Random(f"{self.policy.seed}:remote_write")
        self._spool: List[List[dict]] = []
        self._failures = 0
        self._next_due = 0.0
        self.sent_points = 0
        self.sent_batches = 0
        self.dropped_points = 0
        self.push_failures = 0
        self.last_error = ""

    # -- spool ------------------------------------------------------------

    def offer(
        self,
        ts: float,
        vals: Dict[str, float],
        counters: Set[str],
        timings: Set[str],
    ) -> None:
        """Enqueue one fleet point. Never blocks, never raises; on
        overflow the OLDEST spooled point is dropped and counted."""
        try:
            point = encode_point(ts, vals, counters, timings)
        except Exception as exc:  # malformed vals must not kill a scrape
            self.last_error = f"encode: {exc}"
            return
        if not point:
            return
        self._spool.append(point)
        while len(self._spool) > self.spool_points:
            self._spool.pop(0)
            self.dropped_points += 1

    def spooled(self) -> int:
        return len(self._spool)

    # -- push -------------------------------------------------------------

    def _backoff_s(self) -> float:
        attempt = min(self._failures, self.policy.max_attempts) - 1
        return self.policy.delay(max(0, attempt), self._rng)

    def flush(self, now: float) -> str:
        """One bounded push attempt: ``"sent"``, ``"empty"``,
        ``"backoff"`` (still waiting out the last failure), or
        ``"failed"``. Failure re-spools the batch at the head so order
        is preserved; it never raises."""
        if not self._spool:
            return "empty"
        if now < self._next_due:
            return "backoff"
        batch = self._spool[: self.batch_points]
        payload = {"timeseries": merge_timeseries(batch)}
        body = json.dumps(payload, separators=(",", ":")).encode("utf-8")
        req = urllib.request.Request(
            self.url,
            data=body,
            headers={
                "Content-Type": "application/json",
                "X-Progen-Remote-Write": "v1-json",
            },
            method="POST",
        )
        try:
            with self._opener(req, timeout=self.timeout_s) as resp:
                status = getattr(resp, "status", 200)
                if int(status) >= 300:
                    raise urllib.error.HTTPError(
                        self.url, int(status), "push rejected", None, None
                    )
        except Exception as exc:
            self.push_failures += 1
            self._failures += 1
            self.last_error = str(exc)
            self._next_due = float(now) + self._backoff_s()
            return "failed"
        del self._spool[: len(batch)]
        self._failures = 0
        self._next_due = float(now)
        self.sent_points += len(batch)
        self.sent_batches += 1
        return "sent"

    def stats(self) -> Dict[str, float]:
        return {
            "sent_points": self.sent_points,
            "sent_batches": self.sent_batches,
            "dropped_points": self.dropped_points,
            "push_failures": self.push_failures,
            "spooled": len(self._spool),
        }
