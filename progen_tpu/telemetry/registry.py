"""Shared metrics registry: counters, gauges, timing reservoirs.

Before this module, every subsystem kept its own tally — serving had
``ServingMetrics``, resilience had ``retry_counts``, the train loop's
anomaly/rollback counts lived only as ``events.jsonl`` lines. A
Prometheus exposition of the *train* loop needs them in one place, so
this registry is the process-global home for anything that should end
up on a dashboard: monotonic counters (retries, rollbacks,
quarantines, chaos injections, stalls), gauges (goodput %, MFU, HBM
occupancy), and timing reservoirs (step time quantiles).

``_Timing`` — the bounded-memory Vitter Algorithm-R reservoir that
serving grew for TTFT tails — lives here now and is re-exported by
``serving.metrics`` unchanged; the ``telemetry summarize`` CLI reuses
it for per-span-name p50/p95/p99 over ``events.jsonl``.

Thread-safe by a single lock: the retry path, the watchdog thread, and
the async-checkpoint error poll all increment concurrently with the
train loop. ``structured()`` matches the shape
``telemetry.prometheus.prometheus_text`` consumes, so the registry
plugs straight into the existing file/HTTP exposition machinery.
"""

from __future__ import annotations

import math
import random
import threading
from typing import Dict

_RESERVOIR_CAP = 512
_QUANTILES = ((0.5, "p50_s"), (0.95, "p95_s"), (0.99, "p99_s"))
# worst-K trace exemplars retained per family: enough to name the
# requests behind a burning p99, small enough to ride every exposition
_EXEMPLAR_CAP = 4


class _Timing:
    """Running sum/count/min/max plus a fixed-size uniform reservoir
    (Vitter's Algorithm R) for tail quantiles — latency SLOs live at
    p99, where a mean is actively misleading. Seeded RNG keeps runs
    reproducible; memory is bounded at ``_RESERVOIR_CAP`` floats per
    timing family regardless of observation count.

    Observations that carry a ``trace_id`` additionally compete for the
    worst-K exemplar slots (Dapper's aggregate→trace link): the K
    largest values seen, each with the trace that produced it, so "p99
    is slow" resolves to specific request ids."""

    __slots__ = ("sum", "count", "min", "max", "_reservoir", "_rng",
                 "_exemplars")

    def __init__(self):
        self.sum = 0.0
        self.count = 0
        self.min = math.inf
        self.max = 0.0
        self._reservoir: list = []
        self._rng = random.Random(0)
        self._exemplars: list = []  # [(value, trace_id)], worst first

    @staticmethod
    def _worst_k(pairs) -> list:
        """Top-``_EXEMPLAR_CAP`` (value, trace_id) pairs, one slot per
        trace (a trace observed twice keeps its worst value)."""
        best: Dict[str, float] = {}
        for v, tid in pairs:
            if tid not in best or v > best[tid]:
                best[tid] = v
        ranked = sorted(((v, t) for t, v in best.items()), reverse=True)
        return ranked[:_EXEMPLAR_CAP]

    def observe(self, v: float, trace_id=None) -> None:
        self.sum += v
        self.count += 1
        self.min = min(self.min, v)
        self.max = max(self.max, v)
        if len(self._reservoir) < _RESERVOIR_CAP:
            self._reservoir.append(v)
        else:
            j = self._rng.randrange(self.count)
            if j < _RESERVOIR_CAP:
                self._reservoir[j] = v
        if trace_id:
            self._exemplars = self._worst_k(
                self._exemplars + [(float(v), str(trace_id))]
            )

    def exemplars(self) -> list:
        """Worst-K observations with their traces, worst first."""
        return [
            {"value": v, "trace_id": tid} for v, tid in self._exemplars
        ]

    def quantile(self, q: float) -> float:
        if not self._reservoir:
            return 0.0
        xs = sorted(self._reservoir)
        return xs[min(int(q * len(xs)), len(xs) - 1)]

    def stats(self) -> Dict[str, float]:
        mean = self.sum / self.count if self.count else 0.0
        out = {
            "mean_s": mean,
            "max_s": self.max,
            "min_s": self.min if self.count else 0.0,
            "count": float(self.count),
            # cumulative sum rides along because fleet-level averages
            # are only mergeable from (sum, count) pairs — a mean (or
            # quantiles) per source cannot be combined after the fact
            "sum": self.sum,
        }
        for q, key in _QUANTILES:
            out[key] = self.quantile(q)
        return out

    @classmethod
    def merged(cls, parts) -> "_Timing":
        """Combine reservoirs from several sources into one _Timing.
        sum/count/min/max merge exactly; the merged reservoir is a
        count-weighted subsample (Efraimidis–Spirakis keys, seeded) of
        the parts' reservoirs, so each part's influence on the merged
        quantiles matches its share of observations, not its share of
        reservoir slots."""
        out = cls()
        parts = [p for p in parts if p.count > 0]
        if not parts:
            return out
        out.count = sum(p.count for p in parts)
        out.sum = sum(p.sum for p in parts)
        out.min = min(p.min for p in parts)
        out.max = max(p.max for p in parts)
        # exemplars union exactly: the fleet's worst-K is the worst-K
        # of the parts' worst-Ks (max is order-insensitive)
        out._exemplars = cls._worst_k(
            pair for p in parts for pair in p._exemplars
        )
        pool = []
        for p in parts:
            if not p._reservoir:
                continue
            w = p.count / len(p._reservoir)
            pool.extend((v, w) for v in p._reservoir)
        if len(pool) <= _RESERVOIR_CAP:
            out._reservoir = [v for v, _ in pool]
        else:
            rng = random.Random(0)
            keyed = sorted(
                pool,
                key=lambda vw: rng.random() ** (1.0 / vw[1]),
                reverse=True,
            )
            out._reservoir = [v for v, _ in keyed[:_RESERVOIR_CAP]]
        return out


class MetricsRegistry:
    """Process-wide counters (monotonic), gauges (last value), and
    timings (reservoir quantiles), safe under concurrent writers."""

    def __init__(self):
        self._lock = threading.Lock()
        self.counters: Dict[str, float] = {}
        self.gauges: Dict[str, float] = {}
        self._timings: Dict[str, _Timing] = {}

    def inc(self, name: str, by: float = 1) -> None:
        """Increment a counter; ``by=0`` declares it (so an exposition
        shows the zero instead of omitting the family)."""
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + by

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self.gauges[name] = float(value)

    def set_gauges(self, mapping: Dict[str, float]) -> None:
        with self._lock:
            for k, v in mapping.items():
                self.gauges[k] = float(v)

    def observe(self, name: str, seconds: float, trace_id=None) -> None:
        with self._lock:
            self._timings.setdefault(name, _Timing()).observe(
                seconds, trace_id=trace_id
            )

    def snapshot(self) -> Dict[str, float]:
        """Flat dict of everything — tracker-loggable."""
        with self._lock:
            out: Dict[str, float] = {
                k: float(v) for k, v in self.counters.items()
            }
            out.update(self.gauges)
            for name, t in self._timings.items():
                for stat, v in t.stats().items():
                    out[f"{name}_{stat}"] = v
            return out

    def structured(self) -> dict:
        """Typed view in the shape ``prometheus_text`` consumes."""
        with self._lock:
            return {
                "counters": {
                    k: float(v) for k, v in self.counters.items()
                },
                "gauges": dict(self.gauges),
                "derived": {},
                "timings": {
                    name: {
                        "sum": t.sum,
                        "count": t.count,
                        "quantiles": {
                            str(q): t.quantile(q) for q, _ in _QUANTILES
                        },
                        **(
                            {"exemplars": t.exemplars()}
                            if t._exemplars else {}
                        ),
                    }
                    for name, t in self._timings.items()
                },
            }

    def reset(self) -> None:
        """Zero everything — called at CLI entry so one process running
        several runs (tests via CliRunner) never bleeds counts across."""
        with self._lock:
            self.counters.clear()
            self.gauges.clear()
            self._timings.clear()


_GLOBAL = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _GLOBAL
