"""Flight recorder + on-demand profiling: the black box in every
long-lived process.

When a page fires or a replica dies, the evidence trail is usually
whatever happened to be flushed. This module keeps the evidence
*resident* and gets it to disk at the moment it matters:

**Flight recorder.** A bounded, lock-light in-memory ring of every
record that flows through ``Telemetry.emit`` — spans, per-request
``ev:"req"`` events, retry/chaos/anomaly/stall instants — captured via
the ``EMIT_TAPS`` seam in spans.py, so the ring fills even on a
sink-less process. ``dump()`` writes an atomic, digest-stamped
``flight-<host>-<ts>.json`` (tmp + fsync + rename: a SIGKILL at any
instant leaves either no file or a complete verifiable one, never a
torn one). The payload carries the ring, the currently OPEN spans,
all-thread Python stacks, ``device.memory_stats()``, and an optional
metrics snapshot — everything a post-mortem asks for, and the records
render in Perfetto next to surviving hosts (``export-trace`` /
``stitch`` accept dumps directly).

Dumps fire automatically on the crash-adjacent edges the ring itself
observes (the tap doubles as the trigger):

  * an imminent chaos ``kill`` injection (the injector emits its
    ``ev:"chaos"`` record BEFORE the SIGKILL — the recorder dumps in
    that window, which is how a SIGKILLed serve replica still leaves
    its black box);
  * a watchdog ``stall_escalation`` (the stacks that used to reach
    only stderr now land on disk);
  * an ``anomaly_rollback``;
  * an SLO ``burning`` edge.

plus explicit calls from fatal-signal handlers and an installed
``sys.excepthook``. Arming costs one deque append per emitted record —
the ``flight-overhead`` bench phase holds it to <=1% of serve
throughput.

**On-demand profiling.** :class:`ProfilePinWatcher` mirrors the
``reload.pin`` control seam (serving/reload.py): an operator — or the
collector, automatically on the first ``burning`` edge via
:func:`request_profile` — writes a ``profile.pin`` file; the live
serve/train loop polls it between steps, starts a bounded
``jax.profiler`` trace window, answers through an atomic
``profile.pin.ack``, and stops the window at its deadline. No restart,
no wedge: a pin that cannot start (profiler unavailable, window
already active, rate limit) is REJECTED with a reason and not retried
until its content changes.

The ``ev:"flight"`` (op armed/dumped/truncated) and ``ev:"profile"``
(op requested/started/stopped/rejected) record grammars live HERE
(linted by PGL006). ``flight/dump`` and ``profile/window`` are chaos
sites: the dump path and the profiler window are both rehearsable
failure points.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
import threading
import time
from collections import deque
from pathlib import Path
from typing import Callable, Optional

from progen_tpu.telemetry.registry import get_registry
from progen_tpu.telemetry.spans import (
    EMIT_TAPS,
    get_telemetry,
    host_index,
    span,
)
from progen_tpu.telemetry.watchdog import _device_memory_stats

# ring size: at serve's per-token event rate this is the last few
# hundred requests' worth of context — enough to reconstruct what the
# process was doing, small enough that a dump is a few hundred KB
DEFAULT_RING = 1024

DUMP_PREFIX = "flight-"


# ---------------------------------------------------------------------------
# dump format: {"payload": {...}, "digest": sha256(canonical payload)}
# ---------------------------------------------------------------------------


def _canonical(payload: dict) -> bytes:
    return json.dumps(
        payload, sort_keys=True, separators=(",", ":"), default=str
    ).encode("utf-8")


def seal(payload: dict) -> dict:
    """Wrap a payload with its content digest — the reader's proof the
    dump is complete (a torn write cannot produce a matching digest)."""
    return {
        "payload": payload,
        "digest": hashlib.sha256(_canonical(payload)).hexdigest(),
    }


def verify_dump(path) -> dict:
    """Load + digest-verify a flight dump; returns the payload.
    Raises ``ValueError`` on unreadable/torn/forged files."""
    try:
        doc = json.loads(Path(path).read_text())
    except (OSError, ValueError) as e:
        raise ValueError(f"unreadable flight dump {path}: {e}")
    payload = doc.get("payload") if isinstance(doc, dict) else None
    digest = doc.get("digest") if isinstance(doc, dict) else None
    if not isinstance(payload, dict) or not digest:
        raise ValueError(f"not a flight dump: {path}")
    want = hashlib.sha256(_canonical(payload)).hexdigest()
    if want != digest:
        raise ValueError(
            f"flight dump digest mismatch: {path} "
            f"(file {digest[:12]}.. != computed {want[:12]}..)"
        )
    return payload


def dump_records(path) -> list:
    """The events.jsonl-equivalent record stream inside a verified
    dump — what export-trace/stitch/query consume."""
    return list(verify_dump(path).get("records") or [])


def is_dump_path(path) -> bool:
    p = Path(path)
    return p.name.startswith(DUMP_PREFIX) and p.suffix == ".json"


def find_dumps(directory) -> list:
    """All flight dumps under ``directory`` (recursive), oldest first."""
    root = Path(directory)
    try:
        paths = sorted(root.rglob(DUMP_PREFIX + "*.json"))
    except OSError:
        return []
    return [p for p in paths if p.is_file()]


def _thread_stacks() -> dict:
    """All-thread Python stacks as strings — the watchdog's stderr
    payload, but on disk."""
    import traceback

    out = {}
    try:
        frames = sys._current_frames()
    except Exception:
        return out
    for tid, frame in frames.items():
        try:
            out[str(tid)] = "".join(traceback.format_stack(frame))[-8000:]
        except Exception:
            continue
    return out


# ---------------------------------------------------------------------------
# the recorder
# ---------------------------------------------------------------------------


class FlightRecorder:
    """Bounded in-memory ring of recent telemetry records + atomic
    crash-path dumps. Lock-light by construction: the hot path is one
    GIL-atomic ``deque.append``; only ``dump()`` takes a lock."""

    def __init__(
        self,
        out_dir,
        *,
        ring: int = DEFAULT_RING,
        metrics_fn: Optional[Callable[[], dict]] = None,
        host: Optional[int] = None,
        clock: Callable[[], float] = time.time,
    ):
        self.ring = max(1, int(ring))
        self.out_dir = Path(out_dir)
        self._ring: deque = deque(maxlen=self.ring)
        self._metrics_fn = metrics_fn
        self._host = host
        self._clock = clock
        self._seen = 0
        self.dump_count = 0
        self._dump_lock = threading.Lock()
        self._armed = False
        self._old_excepthook = None

    @property
    def host(self) -> int:
        return self._host if self._host is not None else host_index()

    # ----- arming ---------------------------------------------------------

    def arm(self) -> "FlightRecorder":
        """Register the emit tap + excepthook and announce. Idempotent."""
        if self._armed:
            return self
        EMIT_TAPS.append(self.tap)
        self._old_excepthook = sys.excepthook
        sys.excepthook = self._excepthook
        self._armed = True
        get_telemetry().emit({
            "ev": "flight", "ts": self._clock(), "op": "armed",
            "ring": self.ring, "host": self.host,
        })
        return self

    def disarm(self) -> None:
        if not self._armed:
            return
        try:
            EMIT_TAPS.remove(self.tap)
        except ValueError:
            pass
        if sys.excepthook is self._excepthook \
                and self._old_excepthook is not None:
            sys.excepthook = self._old_excepthook
        self._armed = False

    @property
    def armed(self) -> bool:
        return self._armed

    # ----- hot path -------------------------------------------------------

    def tap(self, record: dict) -> None:
        """The EMIT_TAPS hook: one append, then edge detection for the
        auto-dump triggers. Must never raise (it runs inside every
        ``Telemetry.emit`` on the serving/training hot path)."""
        try:
            self._seen += 1
            self._ring.append(record)
            ev = record.get("ev")
            if ev == "chaos":
                if record.get("kind") == "kill":
                    # the injector SIGKILLs right after this emit
                    # returns: this is the black box's last chance
                    self.dump("chaos_kill",
                              note=str(record.get("site", "")))
            elif ev == "stall_escalation":
                self.dump("stall_escalation")
            elif ev == "anomaly_rollback":
                self.dump("anomaly_rollback")
            elif ev == "slo" and record.get("state") == "burning":
                self.dump("slo_burning",
                          note=str(record.get("objective", "")))
        except Exception:
            pass

    def _excepthook(self, exc_type, exc, tb) -> None:
        try:
            self.dump("unhandled_exception", note=repr(exc)[:300])
        except Exception:
            pass
        hook = self._old_excepthook or sys.__excepthook__
        hook(exc_type, exc, tb)

    # ----- dumping --------------------------------------------------------

    def payload(self, reason: str, note: str = "") -> dict:
        tel = get_telemetry()
        records = list(self._ring)
        payload = {
            "flight": 1,  # format version for readers
            "reason": str(reason),
            "host": self.host,
            "ts": self._clock(),
            "ring": self.ring,
            "truncated": max(0, self._seen - len(records)),
            "records": records,
            "open_spans": tel.open_spans(),
            "stacks": _thread_stacks(),
            "memory_stats": _device_memory_stats(),
        }
        if note:
            payload["note"] = note
        if self._metrics_fn is not None:
            try:
                payload["metrics"] = self._metrics_fn()
            except Exception:
                payload["metrics"] = None
        return payload

    def dump(self, reason: str, note: str = "") -> Optional[Path]:
        """Atomic forensic dump; returns the path or None. Never raises
        — a broken dump path must not take down the process it is
        trying to describe. Non-blocking on the lock: a dump triggered
        from INSIDE a dump (the chaos injector's own ev:"chaos" emit at
        the flight/dump span re-enters the tap on the same thread) must
        skip, not deadlock — one black box is enough."""
        if not self._dump_lock.acquire(blocking=False):
            return None
        try:
            return self._dump(reason, note)
        except Exception:
            return None
        finally:
            self._dump_lock.release()

    def _dump(self, reason: str, note: str) -> Path:
        payload = self.payload(reason, note)
        self.out_dir.mkdir(parents=True, exist_ok=True)
        stamp = int(payload["ts"] * 1000)
        final = self.out_dir / f"{DUMP_PREFIX}{self.host}-{stamp}.json"
        n = 0
        while final.exists():  # same-ms collision: bump, never clobber
            n += 1
            final = self.out_dir / (
                f"{DUMP_PREFIX}{self.host}-{stamp}-{n}.json"
            )
        tmp = final.with_name(final.name + ".tmp")
        # the span makes the dump path a chaos site (flight/dump): a
        # kill at entry leaves no file; the fsync+rename below means a
        # kill mid-write leaves only the .tmp — a reader never sees a
        # torn flight-*.json
        with span("flight/dump", reason=str(reason)):
            data = json.dumps(seal(payload)).encode("utf-8")
            with open(tmp, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, final)
        self.dump_count += 1
        get_registry().inc("flight_dumps")
        get_telemetry().emit({
            "ev": "flight", "ts": self._clock(), "op": "dumped",
            "reason": str(reason), "path": str(final),
            "records": len(payload["records"]),
        })
        if payload["truncated"]:
            get_telemetry().emit({
                "ev": "flight", "ts": self._clock(), "op": "truncated",
                "dropped": payload["truncated"],
            })
        return final


# process-global recorder: CLIs arm once at startup; deep code
# (signal handlers, watchdogs) reaches it without threading a handle
_RECORDER: Optional[FlightRecorder] = None


def arm(out_dir, *, ring: int = DEFAULT_RING,
        metrics_fn: Optional[Callable[[], dict]] = None) -> FlightRecorder:
    """Arm the process-global flight recorder (replacing any prior)."""
    global _RECORDER
    if _RECORDER is not None:
        _RECORDER.disarm()
    _RECORDER = FlightRecorder(
        out_dir, ring=ring, metrics_fn=metrics_fn
    ).arm()
    return _RECORDER


def disarm() -> None:
    global _RECORDER
    if _RECORDER is not None:
        _RECORDER.disarm()
        _RECORDER = None


def get_recorder() -> Optional[FlightRecorder]:
    return _RECORDER


def dump_now(reason: str, note: str = "") -> Optional[Path]:
    """Dump the process-global recorder, if armed (fatal-signal
    handlers call this — it never raises)."""
    rec = _RECORDER
    if rec is None:
        return None
    return rec.dump(reason, note)


# ---------------------------------------------------------------------------
# on-demand profiling: the profile.pin seam
# ---------------------------------------------------------------------------


def request_profile(pin_path, duration_s: Optional[float] = None,
                    token: Optional[str] = None) -> str:
    """Write a ``profile.pin`` atomically (the operator/collector side
    of the seam) and ledger the request. Returns the pin token the ack
    will echo."""
    pin_path = Path(pin_path)
    if token is None:
        token = f"prof-{int(time.time() * 1000)}-{os.getpid()}"
    content = token if duration_s is None else f"{token} {duration_s:g}"
    pin_path.parent.mkdir(parents=True, exist_ok=True)
    tmp = pin_path.with_name(pin_path.name + ".tmp")
    with tmp.open("w") as f:
        f.write(content)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, pin_path)
    get_telemetry().emit({
        "ev": "profile", "ts": time.time(), "op": "requested",
        "pin": token, "path": str(pin_path),
    })
    return token


class ProfilePinWatcher:
    """Poll a ``profile.pin`` control file and run bounded
    ``jax.profiler`` trace windows on a live process — the
    ``reload.pin`` seam (serving/reload.py), aimed at the profiler.

    Pin content: ``<token>[ <seconds>]`` — the token names the request
    (acks echo it; :func:`request_profile` mints unique ones), the
    optional seconds bound the window (clamped to ``max_window_s``).
    A handled or rejected pin is not re-run until its content changes.
    """

    def __init__(
        self,
        pin_path,
        out_dir,
        *,
        max_window_s: float = 10.0,
        min_interval_s: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
        profiler=None,
    ):
        self.pin_path = Path(pin_path)
        self.out_dir = Path(out_dir)
        self.max_window_s = float(max_window_s)
        self.min_interval_s = float(min_interval_s)
        self._clock = clock
        # test seam: any object with start_trace(dir)/stop_trace();
        # None -> jax.profiler, resolved lazily at window start
        self._profiler = profiler
        self._watch_mark = 0.0
        self._acked: Optional[tuple] = None  # (pin, status) last written
        self._failed_pin: Optional[str] = None
        self._done_pin: Optional[str] = None
        self._last_start = float("-inf")
        # active window: (token, deadline, trace_dir, span_cm, t0)
        self._active: Optional[tuple] = None
        self.window_count = 0

    # ----- pin file (the reload.py idioms) --------------------------------

    def read_pin(self) -> Optional[str]:
        try:
            content = self.pin_path.read_text().strip()
        except OSError:
            return None
        return content or None

    def _write_ack(self, pin: str, status: str, reason: str = "") -> None:
        if self._acked == (pin, status):
            return
        rec = {"pin": pin, "status": status, "ts": time.time()}
        if reason:
            rec["reason"] = reason
        ack = self.pin_path.with_name(self.pin_path.name + ".ack")
        tmp = ack.with_name(ack.name + ".tmp")
        try:
            with tmp.open("w") as f:
                f.write(json.dumps(rec))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, ack)
        except OSError:
            return
        self._acked = (pin, status)

    def _reject(self, content: str, token: str, reason: str) -> None:
        self._failed_pin = content
        get_registry().inc("profile_rejected")
        get_telemetry().emit({
            "ev": "profile", "ts": time.time(), "op": "rejected",
            "pin": token, "reason": reason,
        })
        self._write_ack(token, "rejected", reason)

    # ----- the window -----------------------------------------------------

    @property
    def active(self) -> bool:
        return self._active is not None

    def _parse_pin(self, content: str) -> tuple:
        """(token, window_s) from pin content; bad durations clamp."""
        parts = content.split()
        token = parts[0]
        window_s = self.max_window_s
        if len(parts) > 1:
            try:
                window_s = float(parts[1])
            except ValueError:
                pass
        window_s = min(max(window_s, 0.1), self.max_window_s)
        return token, window_s

    def _start(self, content: str, token: str, window_s: float) -> bool:
        trace_dir = self.out_dir / f"profile-{token}"
        span_cm = span("profile/window", pin=token)
        try:
            span_cm.__enter__()  # chaos site: a fault here is rejected
        except Exception as e:
            self._reject(content, token, f"{type(e).__name__}: {e}")
            return False
        try:
            profiler = self._profiler
            if profiler is None:
                from jax import profiler as jax_profiler

                profiler = jax_profiler
            trace_dir.mkdir(parents=True, exist_ok=True)
            profiler.start_trace(str(trace_dir))
        except Exception as e:
            span_cm.__exit__(None, None, None)
            self._reject(content, token,
                         f"profiler_unavailable: {type(e).__name__}: {e}")
            return False
        self._profiler = profiler
        now = self._clock()
        self._last_start = now
        self._active = (token, now + window_s, trace_dir, span_cm,
                        time.perf_counter())
        self.window_count += 1
        get_registry().inc("profile_windows")
        get_telemetry().emit({
            "ev": "profile", "ts": time.time(), "op": "started",
            "pin": token, "window_s": round(window_s, 3),
            "trace_dir": str(trace_dir),
        })
        self._write_ack(token, "started")
        return True

    def _stop(self) -> None:
        token, _, trace_dir, span_cm, t0 = self._active
        self._active = None
        try:
            self._profiler.stop_trace()
        except Exception:
            pass  # a broken stop must not wedge the loop
        span_cm.__exit__(None, None, None)
        get_telemetry().emit({
            "ev": "profile", "ts": time.time(), "op": "stopped",
            "pin": token,
            "duration_s": round(time.perf_counter() - t0, 3),
            "trace_dir": str(trace_dir),
        })
        self._write_ack(token, "stopped")

    def close(self) -> None:
        """Shutdown seam: stop an in-flight window so the trace flushes."""
        if self._active is not None:
            self._stop()

    # ----- loop-thread poll -----------------------------------------------

    def poll_watch(self, interval_s: float = 2.0) -> bool:
        """Called by the owning loop between steps. Finishes a due
        window, then (throttled) checks the pin for new work. Returns
        True when a window was started."""
        now = self._clock()
        if self._active is not None:
            _, deadline, _, _, _ = self._active
            if now >= deadline:
                self._stop()
            return False
        if now - self._watch_mark < interval_s:
            return False
        self._watch_mark = now
        content = self.read_pin()
        if content is None or content == self._failed_pin \
                or content == self._done_pin:
            return False
        token, window_s = self._parse_pin(content)
        if now - self._last_start < self.min_interval_s:
            self._reject(content, token, "rate_limited")
            return False
        if not self._start(content, token, window_s):
            return False
        self._done_pin = content
        return True


# ---------------------------------------------------------------------------
# trace query: one timeline per trace_id across every evidence stream
# ---------------------------------------------------------------------------


def _describe(rec: dict) -> str:
    ev = rec.get("ev")
    if ev in ("B", "E"):
        return (
            f"span {rec.get('span', '?')} "
            f"{'begin' if ev == 'B' else 'end'}"
            + (f" ({rec['dur_s']:.4f}s)" if "dur_s" in rec else "")
        )
    if ev == "req":
        phase = {"b": "begin", "n": "", "e": "end"}.get(
            rec.get("ph"), rec.get("ph", "?")
        )
        return f"req {rec.get('name', '?')} {phase}".rstrip()
    if ev == "journal":
        extra = rec.get("status") or ""
        return f"journal {rec.get('op', '?')} {extra}".rstrip()
    tail = (
        rec.get("op") or rec.get("status") or rec.get("state")
        or rec.get("kind") or ""
    )
    return f"{ev} {tail}".rstrip()


def _entry(ts, src, what, record=None) -> dict:
    out = {"ts": float(ts), "src": str(src), "what": str(what)}
    if record is not None:
        out["record"] = record
    return out


def trace_timeline(
    trace_id: str,
    events=(),
    journals=(),
    tsdb_dir=None,
    extra_jsonl=(),
    drops=None,
) -> list:
    """Join every evidence stream on one ``trace_id`` into a single
    chronological timeline — the post-mortem question ("what happened
    to request X?") as one call.

    ``events`` entries may be events.jsonl files OR flight dumps (a
    killed host's ring replays through the same reader). ``journals``
    are serving journal.jsonl files: the accept carrying the trace_id
    binds its request id, and that request's token stream is summarized
    (first/last journaled token) rather than listed. ``tsdb_dir``
    surfaces collector samples whose exemplars name the trace;
    ``extra_jsonl`` (alerts.jsonl / notifications.jsonl) surfaces any
    record that mentions it. Entries are ``{ts, src, what[, record]}``,
    sorted by ts."""
    from progen_tpu.telemetry.trace import iter_events_any, iter_jsonl

    tid = str(trace_id)
    timeline: list = []

    for path in events:
        recs = list(iter_events_any(path, drops))
        req_ids = {
            str(r["req"]) for r in recs
            if r.get("trace_id") == tid and r.get("req") is not None
        }
        src = Path(path).name
        for r in recs:
            ts = r.get("ts")
            if ts is None:
                continue
            if r.get("trace_id") == tid or (
                r.get("ev") in ("req", "journal")
                and str(r.get("req")) in req_ids
            ):
                timeline.append(_entry(ts, src, _describe(r), r))

    for path in journals:
        recs = list(iter_jsonl(path, drops))
        req_ids = {
            str(r["req"]) for r in recs
            if r.get("op") == "accept" and r.get("trace_id") == tid
            and r.get("req") is not None
        }
        src = Path(path).name
        tokens: dict = {}  # req -> [n, (ts0, i0), (ts1, i1)]
        for r in recs:
            if r.get("ev") != "journal" or str(r.get("req")) not in req_ids:
                continue
            ts = r.get("ts")
            if ts is None:
                continue
            if r.get("op") == "token":
                slot = tokens.setdefault(str(r["req"]), [0, None, None])
                slot[0] += 1
                mark = (float(ts), int(r.get("index", -1)))
                if slot[1] is None:
                    slot[1] = mark
                slot[2] = mark
            else:
                timeline.append(_entry(ts, src, _describe(r), r))
        for req, (n, first, last) in tokens.items():
            timeline.append(_entry(
                first[0], src,
                f"journal token first (req {req}, index {first[1]})",
            ))
            if n > 1:
                timeline.append(_entry(
                    last[0], src,
                    f"journal token last (req {req}, index {last[1]}, "
                    f"{n} journaled)",
                ))

    if tsdb_dir is not None:
        from progen_tpu.telemetry.tsdb import TsdbReader

        seen_ex = set()  # same exemplar rides every later scrape too
        for r in TsdbReader(tsdb_dir).read(drops):
            if r.get("ev") != "sample":
                continue
            for fam, tv in (r.get("timings") or {}).items():
                for ex in tv.get("exemplars") or []:
                    key = (r.get("source"), fam, ex.get("value"))
                    if ex.get("trace_id") == tid and key not in seen_ex:
                        seen_ex.add(key)
                        timeline.append(_entry(
                            r.get("ts", 0.0), "tsdb",
                            f"exemplar {fam}={ex.get('value')} "
                            f"(source {r.get('source', '?')})",
                        ))

    for path in extra_jsonl:
        src = Path(path).name
        for r in iter_jsonl(path, drops):
            ts = r.get("ts")
            if ts is None:
                continue
            if tid in json.dumps(r):
                timeline.append(_entry(ts, src, _describe(r), r))

    timeline.sort(key=lambda e: e["ts"])
    return timeline
