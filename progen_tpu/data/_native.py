"""ctypes loader/builder for the native TFRecord engine.

Compiles native/tfrecord_io.cc with g++ on first use (no pybind11 in the
image; plain C ABI + ctypes) and caches the .so next to the source keyed by
a content hash, so editing the C++ transparently rebuilds. Set
``PROGEN_TPU_NATIVE=0`` to force the pure-Python codec.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
from pathlib import Path
from typing import Optional

_SRC = Path(__file__).resolve().parents[2] / "native" / "tfrecord_io.cc"
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _build(src: Path) -> Path:
    digest = hashlib.sha256(src.read_bytes()).hexdigest()[:16]
    out = src.parent / f"libtfrecord_io_{digest}.so"
    if not out.exists():
        # per-process tmp: concurrent builders each write their own file and
        # the atomic rename publishes whichever finishes (identical content)
        tmp = out.with_suffix(f".so.tmp.{os.getpid()}")
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-o", str(tmp), str(src)],
            check=True,
            capture_output=True,
        )
        os.replace(tmp, out)
        for stale in src.parent.glob("libtfrecord_io_*.so"):
            if stale != out:
                stale.unlink(missing_ok=True)
    return out


def load() -> Optional[ctypes.CDLL]:
    """The native library, or None (missing toolchain/source, or opted out)."""
    global _lib, _load_failed
    if _lib is not None:
        return _lib
    if _load_failed or os.environ.get("PROGEN_TPU_NATIVE") == "0":
        return None
    try:
        lib = ctypes.CDLL(str(_build(_SRC)))
    except (OSError, subprocess.CalledProcessError, FileNotFoundError):
        _load_failed = True
        return None

    lib.tfio_crc32c.restype = ctypes.c_uint32
    lib.tfio_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_long]
    lib.tfio_masked_crc.restype = ctypes.c_uint32
    lib.tfio_masked_crc.argtypes = [ctypes.c_char_p, ctypes.c_long]
    lib.tfio_parse_records.restype = ctypes.c_long
    lib.tfio_parse_records.argtypes = [
        ctypes.c_char_p,
        ctypes.c_long,
        ctypes.POINTER(ctypes.c_long),
        ctypes.POINTER(ctypes.c_long),
        ctypes.c_long,
        ctypes.c_int,
    ]
    lib.tfio_example_seq.restype = ctypes.c_long
    lib.tfio_example_seq.argtypes = [
        ctypes.c_void_p,  # payload pointer (base + offset, zero-copy)
        ctypes.c_long,
        ctypes.c_char_p,
        ctypes.c_long,
        ctypes.POINTER(ctypes.c_long),
    ]
    lib.tfio_encoded_size.restype = ctypes.c_long
    lib.tfio_encoded_size.argtypes = [ctypes.c_long, ctypes.c_long]
    lib.tfio_encode_record.restype = ctypes.c_long
    lib.tfio_encode_record.argtypes = [
        ctypes.c_char_p,
        ctypes.c_long,
        ctypes.c_char_p,
        ctypes.c_long,
        ctypes.c_char_p,
        ctypes.c_long,
    ]
    lib.tfio_collate.restype = None
    lib.tfio_collate.argtypes = [
        ctypes.POINTER(ctypes.c_char_p),  # per-record base pointers
        ctypes.POINTER(ctypes.c_long),
        ctypes.c_long,
        ctypes.c_long,
        ctypes.c_long,
        ctypes.c_void_p,  # int32 (n, seq_len+1) output buffer
    ]
    _lib = lib
    return _lib


def parse_file(data: bytes, key: bytes = b"seq", verify_crc: bool = True):
    """Decompressed TFRecord buffer -> list of `key` feature bytes, all
    framing/proto work in C++. Returns None if the library is unavailable.

    Memory bound: the caller's buffer + 16 bytes of offset bookkeeping per
    record (records are >= 16 bytes, so <= 1x buffer) + one extracted
    sequence at a time; shard size is capped by the ETL's
    num_sequences_per_file, so whole-shard buffers are intended."""
    lib = load()
    if lib is None:
        return None
    max_records = max(1, len(data) // 16)  # min framed record = 16 bytes
    offsets = (ctypes.c_long * max_records)()
    lengths = (ctypes.c_long * max_records)()
    n = lib.tfio_parse_records(
        data, len(data), offsets, lengths, max_records, int(verify_crc)
    )
    if n < 0:
        raise ValueError(f"corrupt tfrecord buffer at byte {-(n + 1)}")
    # zero-copy payload access: pass base_address + offset into the same
    # buffer; only the final per-sequence bytes are copied out
    base = ctypes.cast(ctypes.c_char_p(data), ctypes.c_void_p).value
    out = []
    seq_off = ctypes.c_long()
    for i in range(n):
        slen = lib.tfio_example_seq(
            ctypes.c_void_p(base + offsets[i]),
            lengths[i],
            key,
            len(key),
            ctypes.byref(seq_off),
        )
        if slen < 0:
            raise KeyError(f"feature {key!r} not found in record {i}")
        start = offsets[i] + seq_off.value
        out.append(data[start : start + slen])
    return out


def encode_record(seq: bytes, key: bytes = b"seq") -> Optional[bytes]:
    """One framed TFRecord (header+crc+Example+crc) built in C++, or None."""
    lib = load()
    if lib is None:
        return None
    size = lib.tfio_encoded_size(len(seq), len(key))
    buf = ctypes.create_string_buffer(size)
    written = lib.tfio_encode_record(
        seq, len(seq), key, len(key), buf, size
    )
    if written < 0:
        raise RuntimeError("native encode buffer undersized (bug)")
    return buf.raw[:written]


def collate(records, seq_len: int, offset: int = 1):
    """Batch collation in C++: list of raw sequence bytes -> (n, seq_len+1)
    int32 (truncate, +offset, right-pad 0, BOS column — the semantics of
    dataset.collate). Returns None if the library is unavailable."""
    import numpy as np

    lib = load()
    if lib is None:
        return None
    n = len(records)
    out = np.empty((n, seq_len + 1), dtype=np.int32)
    if n:
        ptrs = (ctypes.c_char_p * n)(*records)
        lens = (ctypes.c_long * n)(*(len(r) for r in records))
        lib.tfio_collate(
            ptrs, lens, n, seq_len, offset,
            out.ctypes.data_as(ctypes.c_void_p),
        )
    return out
