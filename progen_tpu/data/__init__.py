from progen_tpu.data.tokenizer import (
    PAD_ID,
    decode_tokens,
    encode_tokens,
)
from progen_tpu.data.tfrecord import (
    read_tfrecords,
    tfrecord_writer,
)
from progen_tpu.data.dataset import iterator_from_tfrecords_folder

__all__ = [
    "PAD_ID",
    "encode_tokens",
    "decode_tokens",
    "read_tfrecords",
    "tfrecord_writer",
    "iterator_from_tfrecords_folder",
]
