"""TFRecord I/O from scratch — no TensorFlow in the data path.

The reference leans on TF's C++ runtime for record IO
(/root/reference/progen_transformer/data.py:7-21 writer, :48-62 tf.data
reader). A TPU-native JAX framework should not drag TensorFlow in for a
container format, so this module implements the format directly and stays
wire-compatible (tests verify both directions against tf.io when TF is
available in the environment):

  * Record framing: ``uint64le length | uint32le masked_crc32c(length) |
    payload | uint32le masked_crc32c(payload)``, with the TFRecord mask
    ``((crc >> 15 | crc << 17) + 0xa282ead8) & 0xffffffff`` over CRC-32C
    (Castagnoli).
  * Payload: a ``tf.train.Example`` protobuf holding one bytes feature
    ``'seq'`` — hand-encoded here (wire format is stable and tiny: nested
    length-delimited fields), no protobuf runtime needed.
  * Whole-file gzip, matching ``TFRecordOptions(compression_type='GZIP')``.

CRC-32C uses the ``google_crc32c`` C extension when present, else a
pure-Python table fallback.
"""

from __future__ import annotations

import gzip
import struct
from contextlib import contextmanager
from typing import Iterator

try:  # C-accelerated CRC (present in this environment)
    import google_crc32c

    def _crc32c(data: bytes) -> int:
        return google_crc32c.value(data)

except ImportError:  # pragma: no cover - fallback
    _CRC_TABLE = []

    def _build_table():
        poly = 0x82F63B78  # reversed Castagnoli
        for i in range(256):
            crc = i
            for _ in range(8):
                crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
            _CRC_TABLE.append(crc)

    _build_table()

    def _crc32c(data: bytes) -> int:
        crc = 0xFFFFFFFF
        for b in data:
            crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
        return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# Minimal protobuf wire codec for tf.train.Example{features{feature{'seq'}}}
# ---------------------------------------------------------------------------

_LEN = 2  # wire type: length-delimited


def _tag(field: int, wire: int = _LEN) -> bytes:
    return _varint((field << 3) | wire)


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _read_varint(buf: bytes, pos: int) -> tuple[int, int]:
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _ld(field: int, payload: bytes) -> bytes:
    """One length-delimited field."""
    return _tag(field) + _varint(len(payload)) + payload


def encode_example(seq: bytes, key: str = "seq") -> bytes:
    """Serialize tf.train.Example{features: {key: bytes_list([seq])}}.

    Message graph (tensorflow/core/example/example.proto + feature.proto):
    Example.features(1) -> Features.feature(1) map entry {key(1), value(2)}
    -> Feature.bytes_list(1) -> BytesList.value(1).
    """
    bytes_list = _ld(1, seq)
    feature = _ld(1, bytes_list)
    entry = _ld(1, key.encode()) + _ld(2, feature)
    features = _ld(1, entry)
    return _ld(1, features)


def decode_example(payload: bytes, key: str = "seq") -> bytes:
    """Extract the ``key`` bytes feature from a serialized Example.

    Parses only the subset this framework writes/reads; unknown fields are
    skipped by wire type so TF-written files with extra features still parse.
    """

    def fields(buf: bytes) -> Iterator[tuple[int, int, bytes | int]]:
        pos = 0
        while pos < len(buf):
            tag, pos = _read_varint(buf, pos)
            field, wire = tag >> 3, tag & 0x7
            if wire == _LEN:
                ln, pos = _read_varint(buf, pos)
                yield field, wire, buf[pos : pos + ln]
                pos += ln
            elif wire == 0:  # varint
                val, pos = _read_varint(buf, pos)
                yield field, wire, val
            elif wire == 5:  # 32-bit
                yield field, wire, buf[pos : pos + 4]
                pos += 4
            elif wire == 1:  # 64-bit
                yield field, wire, buf[pos : pos + 8]
                pos += 8
            else:
                raise ValueError(f"unsupported wire type {wire}")

    for f, _, features in fields(payload):
        if f != 1:
            continue
        for f2, _, entry in fields(features):
            if f2 != 1:
                continue
            entry_key = None
            value = None
            for f3, _, v in fields(entry):
                if f3 == 1:
                    entry_key = v
                elif f3 == 2:
                    value = v
            if entry_key != key.encode():
                continue
            for f4, _, blist in fields(value):
                if f4 == 1:  # bytes_list
                    for f5, _, item in fields(blist):
                        if f5 == 1:
                            return bytes(item)
    raise KeyError(f"feature {key!r} not found in example")


# ---------------------------------------------------------------------------
# Record framing
# ---------------------------------------------------------------------------


def write_record(fp, payload: bytes) -> None:
    header = struct.pack("<Q", len(payload))
    fp.write(header)
    fp.write(struct.pack("<I", _masked_crc(header)))
    fp.write(payload)
    fp.write(struct.pack("<I", _masked_crc(payload)))


def read_records(fp) -> Iterator[bytes]:
    while True:
        header = fp.read(8)
        if not header:
            return
        if len(header) < 8:
            raise EOFError("truncated record header")
        (length,) = struct.unpack("<Q", header)
        (crc,) = struct.unpack("<I", fp.read(4))
        if crc != _masked_crc(header):
            raise ValueError("corrupt record: length crc mismatch")
        payload = fp.read(length)
        if len(payload) < length:
            raise EOFError("truncated record payload")
        (crc,) = struct.unpack("<I", fp.read(4))
        if crc != _masked_crc(payload):
            raise ValueError("corrupt record: payload crc mismatch")
        yield payload


@contextmanager
def tfrecord_writer(path: str, key: str = "seq"):
    """Context manager yielding ``write(seq_bytes)`` — gzip TFRecord file of
    single-bytes-feature Examples, like the reference's
    ``with_tfrecord_writer`` (data.py:16-21). Record encoding (proto +
    framing + CRC) runs in the native C++ engine when available."""
    from progen_tpu.data import _native

    with gzip.open(path, "wb") as fp:

        def write(seq: bytes) -> None:
            rec = _native.encode_record(seq, key.encode())
            if rec is not None:
                fp.write(rec)
            else:
                write_record(fp, encode_example(seq, key))

        yield write


def _read_file_bytes(path: str) -> bytes:
    with gzip.open(path, "rb") as fp:
        return fp.read()


def read_tfrecords(path: str, key: str = "seq") -> Iterator[bytes]:
    """Yield the ``key`` feature of every Example in a gzip TFRecord file.

    Fast path: decompress the whole file and batch-parse framing + proto in
    the native C++ engine (one ctypes call for all records); falls back to
    the pure-Python codec. Either way the whole-file read happens up front
    under the resilience retry policy (label ``data/read``): a transient
    network-filesystem hiccup is re-tried with backoff instead of killing
    the input pipeline mid-epoch, and a retry restarts from byte 0 so no
    record is ever yielded twice."""
    import io

    from progen_tpu.data import _native
    from progen_tpu.resilience.retry import retry_call

    data = retry_call(_read_file_bytes, path, label="data/read")
    if _native.load() is not None:
        yield from _native.parse_file(data, key.encode())
        return
    for payload in read_records(io.BytesIO(data)):
        yield decode_example(payload, key)
