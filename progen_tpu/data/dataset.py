"""TFRecord dataset iterator: resumable, multi-host sharded, prefetched.

Capability target (/root/reference/progen_transformer/data.py:25-72):
  * glob ``{folder}/**/*.{train|valid}.tfrecord.gz`` on local FS or gs://;
  * total sequence count parsed from the ``{i}.{count}.{split}.tfrecord.gz``
    filename contract (data.py:46, written by generate_data.py:142);
  * ``iter_fn(seq_len, batch_size, skip, loop)`` yielding int batches of
    shape (batch, seq_len+1): truncate to seq_len, +1 tokenizer offset,
    right-pad with 0, prepend a 0-valued BOS column (data.py:30-35,64-70);
  * ``skip`` counts records for mid-epoch resume (README.md:112).

TPU-first deltas:
  * no tf.data — records stream through the from-scratch codec in
    tfrecord.py, with a background-thread prefetcher standing in for
    ``prefetch(AUTOTUNE)``;
  * deterministic file order (numeric sort on the file index; the reference
    inherits glob order, which is filesystem-dependent — resume exactness
    needs determinism);
  * first-class multi-host sharding: records are dealt round-robin by
    global record index (``index % process_count == process_index``), so the
    reference's global ``skip`` semantics survive sharding — resuming with a
    different process count still replays the same global record stream.
"""

from __future__ import annotations

import queue
import re
import threading
from pathlib import Path
from typing import Callable, Iterator, List, Tuple

import numpy as np

from progen_tpu.data import _native
from progen_tpu.data.tfrecord import read_tfrecords

_FILENAME_RE = re.compile(r"(\d+)\.(\d+)\.(train|valid)\.tfrecord\.gz$")


def _local_glob(folder: str, data_type: str) -> List[str]:
    return [str(p) for p in Path(folder).glob(f"**/*.{data_type}.tfrecord.gz")]


def _gcs_glob(folder: str, data_type: str) -> List[str]:
    from google.cloud import storage  # deferred; optional dependency

    bucket_name, _, prefix = folder[len("gs://") :].partition("/")
    # GCS prefix match is a raw string prefix: anchor to the directory so
    # gs://b/run1 does not swallow gs://b/run10/ or gs://b/run1_old/
    if prefix and not prefix.endswith("/"):
        prefix += "/"
    client = storage.Client()
    names = [
        f"gs://{bucket_name}/{b.name}"
        for b in client.list_blobs(bucket_name, prefix=prefix or None)
    ]
    return [n for n in names if n.endswith(f".{data_type}.tfrecord.gz")]


def count_from_filename(path: str) -> int:
    """Sequence count from the {i}.{count}.{split} contract (data.py:46)."""
    m = _FILENAME_RE.search(path)
    if not m:
        raise ValueError(f"filename breaks the count contract: {path}")
    return int(m.group(2))


def _sort_key(path: str) -> Tuple[int, str]:
    m = _FILENAME_RE.search(path)
    return (int(m.group(1)) if m else 0, path)


def collate(
    records: List[bytes], seq_len: int, offset: int = 1
) -> np.ndarray:
    """Raw sequence bytes -> (batch, seq_len+1) int32: truncate, +offset,
    right-pad 0, prepend BOS 0 column (data.py:30-35,67-69).

    Dispatches to the native C++ engine when available (one pass, no
    per-record numpy temporaries — this is the per-batch hot loop of the
    input pipeline); the numpy path below is the fallback and the golden
    for the native one (tests/test_native.py)."""
    native_out = _native.collate(records, seq_len, offset)
    if native_out is not None:
        return native_out
    out = np.zeros((len(records), seq_len + 1), dtype=np.int32)
    for i, rec in enumerate(records):
        arr = np.frombuffer(rec, dtype=np.uint8)[:seq_len].astype(np.int32)
        out[i, 1 : 1 + len(arr)] = arr + offset
    return out


def _prefetch(
    gen: Iterator, depth: int, stop: "threading.Event | None" = None
) -> Iterator:
    """Run ``gen`` in a daemon thread, buffering up to ``depth`` items.

    The worker has a real lifecycle: closing (or garbage-collecting) the
    returned iterator sets ``stop``, which the worker observes before
    advancing the source generator and when unblocked from a full queue
    (the consumer drains one slot after setting stop, so the steady-state
    put stays a cheap blocking wait, not a poll). Pass the same ``stop``
    event into the source generator to also interrupt long per-item work.
    Without this, every abandoned ``loop=True`` iterator (e.g. a
    validation stream recreated per eval) leaks a thread that keeps
    reading shards forever — a real resource leak in a long trainer, and
    the cross-test race that intermittently failed the resume suite under
    machine load."""
    q: queue.Queue = queue.Queue(maxsize=max(depth, 1))
    _END = object()
    if stop is None:
        stop = threading.Event()

    def worker():
        try:
            while not stop.is_set():
                try:
                    item = next(gen)
                except StopIteration:
                    q.put(_END)
                    return
                q.put(item)
        except BaseException as e:  # propagate into the consumer
            if not stop.is_set():
                q.put(e)
        finally:
            gen.close()

    thread = threading.Thread(
        target=worker, daemon=True, name="progen-prefetch"
    )
    thread.start()
    try:
        while True:
            item = q.get()
            if item is _END:
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()
        try:  # unblock a worker waiting on a full queue
            q.get_nowait()
        except queue.Empty:
            pass
        thread.join(timeout=1.0)


def iterator_from_tfrecords_folder(
    folder: str, data_type: str = "train"
) -> Tuple[int, Callable]:
    """Returns (total_num_seqs, iter_fn) — interface parity with data.py:37."""
    if folder.startswith("gs://"):
        # the listing is the run's first network IO; a transient GCS blip
        # here used to kill the job before a single step ran
        from progen_tpu.resilience.retry import retry_call

        filenames = retry_call(
            _gcs_glob, folder, data_type, label="data/glob"
        )
    else:
        filenames = _local_glob(folder, data_type)
    filenames = sorted(filenames, key=_sort_key)
    num_seqs = sum(count_from_filename(f) for f in filenames)

    file_counts = [count_from_filename(f) for f in filenames]

    def iter_fn(
        seq_len: int,
        batch_size: int,
        skip: int = 0,
        loop: bool = False,
        process_index: int = 0,
        process_count: int = 1,
        prefetch: int = 2,
        shuffle_seed: int | None = None,
    ) -> Iterator[np.ndarray]:
        """Yield (batch_size, seq_len+1) int32 batches of this process's
        shard. ``skip``/``batch_size`` are GLOBAL record counts; each process
        keeps records with global_index % process_count == process_index and
        yields its batch_size/process_count slice of every global batch.

        ``shuffle_seed``: deterministic per-pass reshuffle — pass e draws
        permutation ``default_rng((seed, e))``, so every process computes
        the identical order and the global record-index bookkeeping (skip /
        resume) stays exact: index k of the shuffled stream is the same
        record on every run with that seed. Costs one full decode of the
        split into host memory (fine at the reference's 25k-sequence scale;
        leave unset to stream — the reference shuffles at ETL time only,
        generate_data.py)."""
        if batch_size % process_count:
            raise ValueError(
                f"global batch {batch_size} not divisible by "
                f"{process_count} processes"
            )
        if shuffle_seed is not None and shuffle_seed < 0:
            # numpy's SeedSequence rejects negatives with a traceback that
            # never names the flag — fail at the API boundary instead
            raise ValueError(
                f"shuffle_seed must be a non-negative int, got {shuffle_seed}"
            )
        local_bs = batch_size // process_count
        stop = threading.Event()  # set when the returned iterator closes

        def batches() -> Iterator[np.ndarray]:
            # The record index is GLOBAL across passes, so ``skip`` resumes
            # into the right epoch (a resume index may exceed one epoch's
            # record count under --epochs) and later passes replay the FULL
            # stream instead of re-applying the skip every epoch.
            #
            # loop=True is a CONTINUOUS stream: the buffer carries across
            # the rewind, so every batch is full and covers exactly records
            # [k*batch, (k+1)*batch) of the looped stream — record
            # bookkeeping (checkpoint resume) is exact for any epoch count,
            # and batch shapes stay static (no ragged-tail recompiles on
            # TPU; a deliberate delta from the reference's tail batch,
            # which loop=False preserves).
            #
            # Resume fast-forward pays no IO for completed passes (the
            # stream is periodic) and none for whole files below ``skip``
            # (counts come from the filename contract).
            gidx = (skip // num_seqs) * num_seqs if (loop and num_seqs) else 0
            buf: List[bytes] = []
            shuffled: List[bytes] | None = None
            if shuffle_seed is not None:
                shuffled = []
                for path in filenames:
                    if stop.is_set():  # interrupt the full-split decode
                        return
                    shuffled.extend(read_tfrecords(path))

            def pass_records(pass_index: int) -> Iterator[bytes]:
                if shuffled is None:
                    for path, cnt in zip(filenames, file_counts):
                        if stop.is_set():
                            return
                        if gidx_box[0] + cnt <= skip:
                            # whole file before the skip: no read
                            gidx_box[0] += cnt
                            continue
                        yield from read_tfrecords(path)
                    return
                order = np.random.default_rng(
                    (shuffle_seed, pass_index)
                ).permutation(len(shuffled))
                for i in order:
                    yield shuffled[i]

            gidx_box = [gidx]
            while not stop.is_set():
                for rec in pass_records(gidx_box[0] // max(num_seqs, 1)):
                    idx = gidx_box[0]
                    gidx_box[0] = idx + 1
                    if idx < skip:
                        continue
                    if idx % process_count != process_index:
                        continue
                    buf.append(rec)
                    if len(buf) == local_bs:
                        yield collate(buf, seq_len)
                        buf = []
                if not loop:
                    if buf:  # ragged tail (the reference yields it too)
                        yield collate(buf, seq_len)
                    return

        return _prefetch(batches(), prefetch, stop=stop)

    return num_seqs, iter_fn
