"""FASTA -> TFRecord ETL.

Capability target (/root/reference/generate_data.py): read a (Uniref50)
FASTA, filter by max length, cap sample count, turn each record into
training strings with the taxonomy-annotation grammar, then shuffle-split
into train/valid TFRecord shards named ``{i}.{count}.{split}.tfrecord.gz``.

Annotation grammar parity (generate_data.py:37-79):
  * taxonomy extracted from the description with
    ``Tax=([a-zA-Z\\s]*)\\s[a-zA-Z\\=]`` (note the trailing context — the
    match stops one token before the next ``Key=`` field);
  * annotated string ``"[tax=X] # SEQ"``, with annotation and sequence
    swapped with probability ``prob_invert_seq_annotation``;
  * an unannotated ``"# SEQ"`` is ALWAYS also emitted, so every protein
    appears at least once without conditioning.

Deltas from the reference, all deliberate:
  * no Prefect/pyfaidx — a streaming FASTA parser (no index build, one pass)
    and plain functions; sequences are NOT spilled one-file-per-string to a
    tmp dir (generate_data.py:76-79) but kept in a list (25k strings is MBs);
  * the reference's ``from random import random`` shadowing bug (its
    ``random.shuffle`` crashes when sort_annotations=false,
    generate_data.py:5,14,55) is fixed by using an explicit
    ``random.Random`` instance, which also makes the ETL seedable;
  * GCS upload accepts any ``gs://`` write path via the same client the
    checkpointer uses (optional import, local-FS first).
"""

from __future__ import annotations

import gzip
import random as _random
import re
from math import ceil
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from progen_tpu.data.tfrecord import tfrecord_writer

_TAX_RE = re.compile(r"Tax=([a-zA-Z\s]*)\s[a-zA-Z\=]")


def parse_fasta(path: str) -> Iterator[Tuple[str, str]]:
    """Stream (description, sequence) pairs; sequences uppercased
    (pyfaidx ``sequence_always_upper`` parity, generate_data.py:92)."""
    opener = gzip.open if str(path).endswith(".gz") else open
    desc: Optional[str] = None
    chunks: List[str] = []
    with opener(path, "rt") as fp:
        for line in fp:
            line = line.strip()
            if not line:
                continue
            if line.startswith(">"):
                if desc is not None:
                    yield desc, "".join(chunks).upper()
                desc = line[1:]
                chunks = []
            else:
                chunks.append(line)
        if desc is not None:
            yield desc, "".join(chunks).upper()


def annotations_from_description(description: str) -> Dict[str, str]:
    """{'tax': <taxonomy>} when present (generate_data.py:37-44)."""
    m = _TAX_RE.findall(description)
    return {"tax": m[0]} if m else {}


def sequence_strings(
    description: str,
    seq: str,
    *,
    prob_invert_seq_annotation: float,
    sort_annotations: bool,
    rng: _random.Random,
) -> List[bytes]:
    """The training strings for one FASTA record (generate_data.py:46-79)."""
    out: List[bytes] = []
    annotations = annotations_from_description(description)
    if annotations:
        keys = list(annotations.keys())
        if sort_annotations:
            keys = sorted(keys)
        else:
            rng.shuffle(keys)
        annot_str = " ".join(f"[{k}={annotations[k]}]" for k in keys)
        pair = (annot_str, seq)
        if rng.random() <= prob_invert_seq_annotation:
            pair = tuple(reversed(pair))
        out.append(" # ".join(pair).encode("utf-8"))
    out.append(f"# {seq}".encode("utf-8"))
    return out


def write_tfrecord_shards(
    sequences: List[bytes],
    write_to: str,
    *,
    fraction_valid_data: float,
    num_sequences_per_file: int,
    seed: Optional[int] = None,
) -> List[str]:
    """Permute, split train/valid, shard into
    ``{file_index}.{count}.{split}.tfrecord.gz`` (generate_data.py:115-149).
    Returns the written paths."""
    n = len(sequences)
    num_valid = ceil(fraction_valid_data * n)
    perm = np.random.RandomState(seed).permutation(n)
    valid_idx, train_idx = np.split(perm, [num_valid])

    gcs_bucket = None
    staging = None
    if write_to.startswith("gs://"):
        import tempfile

        from google.cloud import storage

        bucket_name, _, prefix = write_to[len("gs://") :].partition("/")
        gcs_bucket = storage.Client().get_bucket(bucket_name)
        staging = tempfile.TemporaryDirectory(prefix="tfrecord_staging_")
        local_dir = Path(staging.name)
    else:
        local_dir = Path(write_to)
        prefix = ""
    local_dir.mkdir(parents=True, exist_ok=True)

    written: List[str] = []
    for split, idx in (("train", train_idx), ("valid", valid_idx)):
        if len(idx) == 0:
            continue
        num_files = ceil(len(idx) / num_sequences_per_file)
        for file_index, shard in enumerate(np.array_split(idx, num_files)):
            name = f"{file_index}.{len(shard)}.{split}.tfrecord.gz"
            path = local_dir / name
            with tfrecord_writer(str(path)) as write:
                for i in shard:
                    write(sequences[int(i)])
            if gcs_bucket is not None:
                blob_name = f"{prefix}/{name}" if prefix else name
                gcs_bucket.blob(blob_name).upload_from_filename(str(path))
                written.append(f"gs://{gcs_bucket.name}/{blob_name}")
            else:
                written.append(str(path))
    if staging is not None:
        staging.cleanup()
    return written


def generate_data(config: dict, *, seed: Optional[int] = None) -> List[str]:
    """Full ETL with the reference TOML schema
    (/root/reference/configs/data/default.toml): read_from, write_to,
    num_samples, max_seq_len, prob_invert_seq_annotation,
    fraction_valid_data, num_sequences_per_file, sort_annotations."""
    rng = _random.Random(seed)
    sequences: List[bytes] = []
    kept = 0
    for desc, seq in parse_fasta(config["read_from"]):
        if len(seq) > config["max_seq_len"]:
            continue
        sequences.extend(
            sequence_strings(
                desc,
                seq,
                prob_invert_seq_annotation=config["prob_invert_seq_annotation"],
                sort_annotations=config["sort_annotations"],
                rng=rng,
            )
        )
        kept += 1
        if kept >= config["num_samples"]:
            break
    return write_tfrecord_shards(
        sequences,
        config["write_to"],
        fraction_valid_data=config["fraction_valid_data"],
        num_sequences_per_file=config["num_sequences_per_file"],
        seed=seed,
    )
