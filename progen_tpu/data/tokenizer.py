"""Byte-level protein tokenizer.

Contract (/root/reference/progen_transformer/data.py:76-88): token =
``ord(char) + 1``; id 0 is reserved and triple-duty as BOS / padding / EOS
(the loss learns EOS from the first pad position, see training/loss.py).
Decoding subtracts the offset and drops any id that falls below zero (pads
vanish). Vocab size is therefore 256 (`num_tokens` in the model config) —
bytes 0..254 shifted up by one.
"""

from __future__ import annotations

import numpy as np

PAD_ID = 0  # also BOS and EOS
OFFSET = 1


def encode_tokens(text: str) -> np.ndarray:
    """str -> int32 token ids (no BOS prepended; the data pipeline adds it)."""
    raw = np.frombuffer(text.encode("utf-8"), dtype=np.uint8)
    return raw.astype(np.int32) + OFFSET


def decode_tokens(tokens, offset: int = OFFSET) -> str:
    """Token ids -> str. Ids below ``offset`` (pad/BOS/EOS) decode to ''."""
    toks = np.asarray(tokens, dtype=np.int64).reshape(-1) - offset
    return "".join(chr(t) for t in toks if t >= 0)
