"""GPipe-style pipeline parallelism over a stacked layer axis.

The reference has no pipeline parallelism (SURVEY §2.5: PP "NO"); this is
the TPU-native formulation for when a model's layers outgrow one chip's
HBM even after TP: the ``scan_layers`` stacked parameter axis (L, ...) is
sharded over a mesh axis into P stages of L/P layers, and microbatches
flow through the stages with a rotating ``ppermute`` schedule.

Schedule (classic GPipe, M microbatches, P stages, M+P-1 ticks):

  tick t: stage p runs microbatch (t - p) through its local layers when
  0 <= t-p < M — stage 0 injects microbatch t from the input, every other
  stage consumes the activation its left neighbor sent last tick; after
  computing, every stage sends its activation one hop right. The first
  P-1 and last P-1 ticks are the pipeline bubble.

Differentiable end-to-end: the backward pass is jax's transpose of the
scan-of-ppermute (activations flow left, cotangents flow right). The backward
schedule is the autodiff TRANSPOSE of GPipe — all forwards then all
backwards, so activations for all M microbatches stay live until the
backward sweep (O(M) activation memory, not 1F1B's O(P)); pair with
remat on the block_fn when that matters.

This module is deliberately a standalone op + tests (like
parallel/ring_attention.py): the production train step covers dp/tp/sp via
GSPMD; pipeline_apply is the building block for depth-sharded deployments.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from progen_tpu.parallel.partition import pcast, shard_map


def pipeline_apply(
    block_fn: Callable,
    stacked_params,
    x: jnp.ndarray,
    *,
    mesh: Mesh,
    axis: str,
    n_microbatches: int,
    data_axis: str | None = None,
) -> jnp.ndarray:
    """Run L stacked layers as a P-stage pipeline over microbatches.

    block_fn(params_one_layer, x) -> x : one layer's forward.
    stacked_params: pytree with leading axis L on every leaf (the
      scan_layers layout), sharded/split over mesh axis ``axis`` (P stages,
      L % P == 0 — each stage owns L/P consecutive layers).
    x: (B, ...) global batch, B % n_microbatches == 0.
    data_axis: optional mesh axis to ALSO shard each microbatch's row dim
      over (PP x DP composition): every data row then pipelines its own
      1/D slice of each microbatch instead of redundantly recomputing the
      full batch. None or a size-1 axis = pure pipeline.

    Returns block-sequential-equivalent output (B, ...).
    """
    n_stages = mesh.shape[axis]
    L = jax.tree.leaves(stacked_params)[0].shape[0]
    if L % n_stages:
        raise ValueError(f"{L} layers not divisible by {n_stages} stages")
    B = x.shape[0]
    M = n_microbatches
    if B % M:
        raise ValueError(f"batch {B} not divisible by {M} microbatches")
    mb = B // M
    dp = (data_axis is not None and data_axis in mesh.shape
          and mesh.shape[data_axis] > 1)
    if dp and mb % mesh.shape[data_axis]:
        raise ValueError(
            f"microbatch rows {mb} not divisible by data axis "
            f"{mesh.shape[data_axis]}"
        )
    x_mb = x.reshape((M, mb) + x.shape[1:])

    def stage_fn(local_params, x_mb):
        # local_params leaves: (L/P, ...); x_mb replicated (M, mb, ...)
        p = jax.lax.axis_index(axis)
        T = M + n_stages - 1

        def local_layers(h):
            def body(h, layer_params):
                return block_fn(layer_params, h), None

            h, _ = jax.lax.scan(body, h, local_params)
            return h

        def tick(carry, t):
            left_buf = carry  # activation received from the left neighbor
            mb_idx = jnp.clip(t - p, 0, M - 1)
            inject = jax.lax.dynamic_index_in_dim(
                x_mb, mb_idx, axis=0, keepdims=False
            )
            h = jnp.where(p == 0, inject, left_buf)
            out = local_layers(h)
            # rotate one hop right for the next tick
            left_buf = jax.lax.ppermute(
                out, axis,
                perm=[(i, (i + 1) % n_stages) for i in range(n_stages)],
            )
            return left_buf, out

        # carry must be marked device-varying over the pipeline axis (jax
        # 0.9 varying-manual-axes typing for scan-of-ppermute); under DP
        # composition the zeros_like already inherits the data-varying type
        # from the sharded input, so only the stage axis needs the cast
        init = pcast(jnp.zeros_like(x_mb[0]), (axis,), to="varying")
        _, outs = jax.lax.scan(tick, init, jnp.arange(T))
        # the LAST stage's outputs at ticks P-1 .. P-1+M-1 are the finished
        # microbatches; other stages' rows are bubble garbage that the
        # (P, ...)-stacked out_spec lets the caller discard
        return outs[None]  # (1, T, mb, ...) -> stage-stacked by out_spec

    outs = shard_map(
        stage_fn,
        mesh=mesh,
        in_specs=(P(axis), P(None, data_axis) if dp else P()),
        out_specs=P(axis, None, data_axis) if dp else P(axis),
        # without lax.pcast (jax < 0.7) the scan carry can't be typed as
        # stage-varying, so the replication checker false-positives on
        # the scan-of-ppermute; its own error prescribes disabling it
        check_vma=hasattr(jax.lax, "pcast"),
    )(stacked_params, x_mb)
    # outs: (P, T, mb, ...); finished microbatches live on the last stage
    final = outs[n_stages - 1, n_stages - 1 : n_stages - 1 + M]
    return final.reshape((B,) + x.shape[1:])


def pipeline_forward(
    model,
    params,
    tokens: jnp.ndarray,
    *,
    mesh: Mesh,
    axis: str = "model",
    n_microbatches: int,
    data_axis: str | None = "data",
) -> jnp.ndarray:
    """Full ProGen forward with the uniform block stack executed as a
    pipeline — the model-level integration of ``pipeline_apply``.

    ``model`` is a ``ProGen`` built with ``config.scan_layers=True`` (the
    stacked ``params['layers']`` subtree IS the pipeline's layer axis;
    ``models/progen.stack_params`` converts unrolled checkpoints). Embedding,
    RoPE tables, the trailing gMLP blocks, and the logits head run outside
    the pipeline (they are O(1) in depth — the uniform stack is what
    outgrows a chip); each is the SAME flax module the plain forward uses,
    applied to the same param subtrees, so outputs match
    ``model.apply({'params': params}, tokens)`` exactly.

    Run OUTSIDE any ``nn.logical_axis_rules`` context: stages execute inside
    ``shard_map``, where GSPMD sharding constraints don't apply (the
    modules' ``with_logical_constraint`` calls no-op without active rules).
    """
    from flax import linen as nn

    from progen_tpu.models.layers import (
        FeedForwardBlock,
        LocalAttentionBlock,
        ScaleNorm,
    )
    from progen_tpu.models.progen import UniformBlock
    from progen_tpu.ops.rotary import fixed_pos_embedding

    c = model.config
    if "layers" not in params:
        raise ValueError(
            "pipeline_forward needs the scan_layers stacked param layout "
            "(use models.progen.stack_params to convert)"
        )
    n = tokens.shape[-1]
    n_uniform = c.depth - c.global_mlp_depth

    x = nn.Embed(
        c.num_tokens,
        c.dim,
        dtype=c.compute_dtype,
        param_dtype=c.params_dtype,
        name="embed",
    ).apply({"params": params["embed"]}, tokens)
    sin, cos = fixed_pos_embedding(n, c.dim_head)

    block = UniformBlock(c, glu=c.ff_glu)

    def block_fn(layer_params, h):
        h, _ = block.apply({"params": layer_params}, h, sin, cos)
        return h

    if c.remat:
        # the backward sweep only keeps each layer's INPUT boundary and
        # recomputes its internals — the same per-block remat the plain
        # scan_layers path gets (models/progen.py), which is what bounds
        # the GPipe transpose's live activations to microbatch boundaries
        block_fn = jax.checkpoint(block_fn)

    x = pipeline_apply(
        block_fn,
        params["layers"],
        x,
        mesh=mesh,
        axis=axis,
        n_microbatches=n_microbatches,
        data_axis=data_axis,
    )

    for i in range(n_uniform, c.depth):
        use_gmlp = (c.depth - i) <= c.global_mlp_depth
        x = x + LocalAttentionBlock(c).apply(
            {"params": params[f"attn{i}"]}, x, sin, cos, None
        )
        x = x + FeedForwardBlock(
            c, glu=(not use_gmlp) and c.ff_glu, spatial_gate=use_gmlp
        ).apply({"params": params[f"ff{i}"]}, x, None)

    x = ScaleNorm(c.layer_norm_epsilon, c.compute_dtype, c.params_dtype).apply(
        {"params": params["ScaleNorm_0"]}, x
    )
    logits = nn.Dense(
        c.num_tokens,
        dtype=c.compute_dtype,
        param_dtype=c.params_dtype,
        name="to_logits",
    ).apply({"params": params["to_logits"]}, x)
    return logits.astype(jnp.float32)


def make_pipeline_train_step(
    model,
    optimizer,
    *,
    mesh: Mesh,
    axis: str = "model",
    n_microbatches: int,
):
    """The production train step (EOS-masked CE, grad-accum scan, clip,
    masked AdamW — training/step.make_train_step) with the forward replaced
    by ``pipeline_forward``: the depth-sharded deployment path when the
    layer stack outgrows one chip even after TP. Composes with data
    parallelism: on a mesh with ``data > 1`` each microbatch's rows are
    sharded over the data axis inside the pipeline (every chip does 1/D of
    the work; grads psum over data via the shard_map transpose).

    Uses ``rules=()``: sharding is explicit (shard_map over ``axis``), so
    GSPMD logical constraints must stay inert — they cannot apply inside
    manual axes. Gradients flow through the pipeline as its autodiff
    transpose (cotangents ride the reversed ppermute ring)."""
    from progen_tpu.training.step import make_train_step

    def forward(params, ids):
        return pipeline_forward(
            model, params, ids,
            mesh=mesh, axis=axis, n_microbatches=n_microbatches,
        )

    return make_train_step(model, optimizer, rules=(), forward_fn=forward)


def compile_pipeline_train_step(
    model,
    optimizer,
    shardings,
    mesh: Mesh,
    *,
    axis: str = "model",
    n_microbatches: int,
):
    """jit ``make_pipeline_train_step`` with explicit state/batch shardings
    and a donated state — the pipeline twin of
    ``training/step.compile_train_step``. ``shardings`` must be built with
    ``partition.PIPELINE_RULES`` (stacked layer axis over ``axis``; TP rules
    off). MEMORY NOTE: the backward is GPipe's autodiff transpose — all M
    microbatches' stage activations stay live until the backward sweep
    (O(M) activation memory, not 1F1B's O(stages)); pair with
    ``config.remat`` when that matters."""
    from progen_tpu.parallel.partition import batch_sharding

    step = make_pipeline_train_step(
        model, optimizer, mesh=mesh, axis=axis,
        n_microbatches=n_microbatches,
    )
    return jax.jit(
        step,
        in_shardings=(shardings, batch_sharding(mesh, accum_axis=True)),
        out_shardings=(shardings, None),
        donate_argnums=(0,),
    )
