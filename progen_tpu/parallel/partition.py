"""Mesh construction + the logical→mesh sharding rule table.

This is the single place where the logical axis names scattered through the
model (see progen_tpu/models/layers.py, progen_tpu/models/progen.py) are bound
to physical mesh axes. The reference's entire distribution story is a
single-host `pmap` (/root/reference/progen_transformer/utils.py:70); here the
equivalent and its superset are expressed as a `jax.sharding.Mesh` over up to
three axes:

  * ``data``  — batch-parallel axis (DP). Gradients are reduced over it by
    GSPMD-inserted collectives (the psum the reference leaves implicit in the
    pmap transpose).
  * ``model`` — tensor-parallel axis (the reference's open TODO,
    /root/reference/README.md:104). QKV/FF projections are sharded
    Megatron-style: column-parallel in, row-parallel out, so each
    attention+FF block needs exactly one all-reduce on its output.
  * ``seq``   — sequence-parallel axis for long-context configs: activations
    are sharded along the sequence; the windowed attention only needs its
    previous window as halo, so the collective footprint is one
    `ppermute`-shaped exchange per layer (see ops/attention docs).

Rule-table decisions (each is deliberate):
  * ``embed`` (feature dim of residual stream weights) is replicated — the
    residual stream stays whole so LayerNorms need no collective.
  * ``qkv`` / ``mlp`` (projection output dims) shard over ``model``.
  * ``vocab`` shards the embedding + logits head over ``model`` (the largest
    single matrices at 1.2B scale).
  * SGU spatial ``(n, n)`` weights shard their *output* sequence axis over
    ``seq`` and replicate over ``model`` — the matrix is sequence-structured,
    not head-structured, and row-sharding it matches a sequence-sharded
    activation layout (out[m] only needs local rows m).
  * activations: ``batch``→data, ``seq_act``→seq, ``mlp_act``→model,
    ``embed_act`` replicated.

Multi-host: `initialize_distributed` wraps `jax.distributed.initialize`;
`make_mesh` builds a hybrid DCN×ICI layout when multiple slices are present
(data-parallel outermost over DCN, model-parallel innermost over ICI, the
standard TPU recipe).
"""

from __future__ import annotations

import os
from contextlib import contextmanager
from typing import Any, Optional, Sequence

import jax
import numpy as np
from flax import linen as nn
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec

MESH_AXES = ("data", "seq", "model")

# logical axis name -> mesh axis (None = replicate). Order matters only for
# readability; flax resolves each logical name independently.
DEFAULT_RULES = (
    # --- weights ---
    ("layers", None),  # scan_layers stacked axis (future pipeline axis)
    ("vocab", "model"),
    ("embed", None),
    ("qkv", "model"),
    ("mlp", "model"),
    ("sgu_hidden", None),
    ("sgu_seq_out", "seq"),
    ("sgu_seq_in", None),
    # --- activations ---
    ("batch", "data"),
    ("seq_act", "seq"),
    ("embed_act", None),
    ("mlp_act", "model"),
)

# GPipe deployment (parallel/pipeline.py): the ``model`` mesh axis holds
# PIPELINE STAGES, so the scan_layers stacked axis shards over it and every
# tensor-parallel rule is off (a dimension cannot be both a stage index and
# a TP shard; stages run inside shard_map where GSPMD constraints are inert
# anyway). Used for STATE layout (init / restore / jit in-out shardings);
# the step itself runs with rules=().
PIPELINE_RULES = (
    ("layers", "model"),
    ("vocab", None),
    ("embed", None),
    ("qkv", None),
    ("mlp", None),
    ("sgu_hidden", None),
    ("sgu_seq_out", None),
    ("sgu_seq_in", None),
    ("batch", "data"),
    ("seq_act", None),
    ("embed_act", None),
    ("mlp_act", None),
)


# device files whose presence marks a TPU VM (tests monkeypatch this)
_TPU_DEV_PATHS = ("/dev/accel0", "/dev/vfio/0")


def shard_map(fn, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` across jax versions: the top-level export with
    ``check_vma`` (jax >= 0.6) or ``jax.experimental.shard_map`` where the
    same knob is spelled ``check_rep`` (older releases)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )


def pcast(x, axes, *, to: str):
    """``jax.lax.pcast`` where it exists (the varying-manual-axes typing
    of jax >= 0.7); identity on older releases, whose shard_map has no
    vma types — replication is tracked by check_rep instead, so the cast
    has nothing to record."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axes, to=to)
    return x


def _tpu_pod_worker_count() -> int:
    """Worker count from the TPU runtime env (GKE sets
    ``TPU_WORKER_HOSTNAMES`` as a comma list on every pod worker; single
    hosts carry one entry or none)."""
    hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    return len([h for h in hosts.split(",") if h.strip()])


def initialize_distributed() -> None:
    """Bootstrap multi-process JAX when launched under a multi-host runtime.

    Safe to call unconditionally; must run before any backend query — even
    ``jax.process_count()`` initializes backends, after which
    ``jax.distributed.initialize()`` raises — so the guards below only touch
    env/config state. Decision matrix:

      1. already initialized                      -> no-op.
      2. ``JAX_COORDINATOR_ADDRESS`` /
         ``COORDINATOR_ADDRESS`` set              -> initialize (explicit
         path: the Gloo CPU tests, manual launches, schedulers that export
         the coordinator themselves).
      3. ``TPU_WORKER_HOSTNAMES`` lists >1 host   -> initialize via JAX's
         cluster auto-detect (GKE TPU pod). Failure here RAISES — a pod
         launch silently degrading to N independent single-process jobs is
         the worst outcome, per v5e pod postmortems.
      4. TPU device files present and metadata
         queries not disabled (``TPU_SKIP_MDS_QUERY``) -> best-effort
         auto-detect (GCE TPU VM, where only the metadata server knows the
         topology: jax's GceTpuCluster queries it with no env var set).
         A single host initializes as 1 process, which is harmless; an
         undetectable cluster raises inside jax and is re-raised when the
         host looks multi-worker, swallowed otherwise.
      5. anything else (CPU hosts, the single-chip relay) -> no-op.
    """
    from jax._src import distributed as _dist

    if _dist.global_state.coordinator_address is not None:
        return  # already initialized

    explicit = os.environ.get("JAX_COORDINATOR_ADDRESS") or os.environ.get(
        "COORDINATOR_ADDRESS"
    )
    if explicit:
        jax.distributed.initialize()
        return

    workers = _tpu_pod_worker_count()
    if workers > 1:
        try:
            jax.distributed.initialize()
        except Exception as e:  # blind on purpose — converted to a loud abort
            raise RuntimeError(
                f"TPU_WORKER_HOSTNAMES lists {workers} workers but "
                "jax.distributed.initialize() failed; refusing to run as "
                f"{workers} independent single-process jobs"
            ) from e
        return

    metadata_ok = os.environ.get("TPU_SKIP_MDS_QUERY") != "1"
    has_tpu_dev = any(os.path.exists(p) for p in _TPU_DEV_PATHS)
    if metadata_ok and has_tpu_dev:
        try:
            jax.distributed.initialize()
        except Exception as e:  # blind on purpose, same abort as above
            if os.environ.get("TPU_WORKER_ID"):
                # a pod runtime set a worker id: this host IS part of a
                # multi-worker slice, so a detect failure must not degrade
                # to independent single-process jobs
                raise RuntimeError(
                    "TPU_WORKER_ID is set (pod worker) but "
                    "jax.distributed.initialize() failed"
                ) from e
            # no multi-worker evidence: a bare single-host TPU VM outside
            # GCE — single-process is correct, but say so in case this IS
            # a slice whose metadata server was transiently unreachable
            import sys

            print(
                "initialize_distributed: TPU present but no cluster "
                f"detected ({type(e).__name__}); continuing single-process",
                file=sys.stderr,
            )


def is_coordinator() -> bool:
    """True on process 0 — gate logging/checkpoint-commit/tracker on this."""
    return jax.process_index() == 0


def make_mesh(
    data: int = -1,
    seq: int = 1,
    model: int = 1,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
    allow_split_physical_axes: bool = False,
) -> Mesh:
    """Build a ``(data, seq, model)`` mesh.

    ``data=-1`` absorbs all remaining devices. On multi-slice TPU systems the
    data axis is laid over DCN (slices) and seq/model over ICI, via
    ``create_hybrid_device_mesh``; on a single slice or CPU the mesh comes
    from ``create_device_mesh`` / a plain reshape.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if data == -1:
        rest = seq * model
        if n % rest != 0:
            raise ValueError(f"{n} devices not divisible by seq*model={rest}")
        data = n // rest
    shape = (data, seq, model)
    total = int(np.prod(shape))
    if total > n:
        raise ValueError(f"mesh shape {shape} needs {total} > {n} devices")
    if total < n:
        # explicit smaller mesh: use the first `total` devices (e.g. the
        # reference-parity single-device default on a multi-device host)
        devices = devices[:total]
        n = total

    num_slices = len({getattr(d, "slice_index", 0) for d in devices})
    if num_slices > 1 and data % num_slices == 0:
        dev_array = mesh_utils.create_hybrid_device_mesh(
            (data // num_slices, seq, model),
            (num_slices, 1, 1),
            devices=devices,
        )
    else:
        try:
            dev_array = mesh_utils.create_device_mesh(
                shape,
                devices=devices,
                allow_split_physical_axes=allow_split_physical_axes,
            )
        except (ValueError, AssertionError):
            # CPU simulation / odd topologies: any assignment is fine.
            dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, MESH_AXES)


@contextmanager
def logical_rules(rules=DEFAULT_RULES):
    """Context in which flax `with_logical_constraint` annotations resolve."""
    with nn.logical_axis_rules(rules):
        yield


def state_shardings(abstract_state: Any, mesh: Mesh, rules=DEFAULT_RULES) -> Any:
    """Shardings for any pytree mixing flax ``Partitioned`` boxes (annotated
    weights — and optimizer moments, which inherit the boxes because optax
    builds them with structure-preserving tree maps) and plain leaves
    (step counters, norm scales), the latter pinned fully-replicated.

    Each box becomes ONE NamedSharding leaf at the box's position, i.e. the
    result is a pytree *prefix* of the state — exactly what jit's
    in/out_shardings accept.
    """
    from flax.core import meta
    from flax.linen import spmd

    def to_sharding(leaf):
        if isinstance(leaf, meta.AxisMetadata):
            logical = leaf.get_partition_spec()
            mesh_spec = spmd.logical_to_mesh_axes(logical, tuple(rules))
            return NamedSharding(mesh, mesh_spec)
        return NamedSharding(mesh, PartitionSpec())

    return jax.tree.map(
        to_sharding,
        abstract_state,
        is_leaf=lambda x: isinstance(x, meta.AxisMetadata),
    )


def param_shardings(
    abstract_variables: Any, mesh: Mesh, rules=DEFAULT_RULES
) -> Any:
    """Map a flax variables pytree (with logical-axis metadata, e.g. from
    ``jax.eval_shape(model.init, ...)``) to a pytree of `NamedSharding`s."""
    return state_shardings(abstract_variables, mesh, rules)


def zero1_opt_shardings(
    abstract_opt_state: Any,
    base_opt_shardings: Any,
    mesh: Mesh,
) -> Any:
    """ZeRO-1: upgrade OPTIMIZER-STATE shardings so param-shaped moments
    (AdamW m/v) also shard over the ``data`` axis. Params/grads keep their
    base layout (replicated over ``data``), so the forward/backward is
    untouched; only the optimizer's elementwise update runs on 1/data-size
    of each moment, and GSPMD turns the gradient all-reduce + sharded
    update + param add into the reduce-scatter / all-gather pattern — same
    collective bandwidth, 1/data-size the moment memory. (Beyond the
    reference, whose optimizer state is host-resident and whole,
    /root/reference/train.py:113-121; at 1.2B the f32 m+v are 9.1 GB,
    the single biggest state tensor group.)

    For each moment leaf the LARGEST dimension that is still unsharded in
    the base spec and divisible by the data-axis size is sharded over
    ``data``; leaves with no such dimension keep their base sharding
    (correct, just not memory-reduced).
    """
    from flax.core import meta

    data_size = mesh.shape.get("data", 1)
    if data_size == 1:
        return base_opt_shardings

    def upgrade(leaf, sharding):
        shape = getattr(leaf, "shape", ())
        if not isinstance(sharding, NamedSharding) or not shape:
            return sharding
        spec = list(sharding.spec) + [None] * (len(shape) - len(sharding.spec))
        free = [
            i
            for i, (dim, ax) in enumerate(zip(shape, spec))
            if ax is None and dim > 0 and dim % data_size == 0
        ]
        if not free:
            return sharding
        pick = max(free, key=lambda i: shape[i])
        spec[pick] = "data"
        return NamedSharding(mesh, PartitionSpec(*spec))

    return jax.tree.map(
        upgrade, meta.unbox(abstract_opt_state), base_opt_shardings
    )


def batch_sharding(mesh: Mesh, *, accum_axis: bool = False) -> NamedSharding:
    """Sharding for an integer token batch: (mb, L) or (accum, mb, L),
    micro-batch dim over ``data``, sequence replicated (the attention wants
    whole windows; sequence parallelism shards activations, not input ids)."""
    if accum_axis:
        return NamedSharding(mesh, PartitionSpec(None, "data", None))
    return NamedSharding(mesh, PartitionSpec("data", None))


def put_batch(batch, mesh: Mesh, *, accum_axis: bool = False):
    """Place a host batch onto the mesh. Single-process: a device_put with
    the batch sharding. Multi-host: each process holds only its shard of the
    global batch (the data iterator dealt records per-process, see
    data/dataset.py) and `make_array_from_process_local_data` assembles the
    logical global array without any cross-host transfer."""
    sharding = batch_sharding(mesh, accum_axis=accum_axis)
    if jax.process_count() == 1:
        return jax.device_put(batch, sharding)
    return jax.make_array_from_process_local_data(sharding, batch)
