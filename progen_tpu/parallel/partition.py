"""Mesh construction + the logical→mesh sharding rule table.

This is the single place where the logical axis names scattered through the
model (see progen_tpu/models/layers.py, progen_tpu/models/progen.py) are bound
to physical mesh axes. The reference's entire distribution story is a
single-host `pmap` (/root/reference/progen_transformer/utils.py:70); here the
equivalent and its superset are expressed as a `jax.sharding.Mesh` over up to
three axes:

  * ``data``  — batch-parallel axis (DP). Gradients are reduced over it by
    GSPMD-inserted collectives (the psum the reference leaves implicit in the
    pmap transpose).
  * ``model`` — tensor-parallel axis (the reference's open TODO,
    /root/reference/README.md:104). QKV/FF projections are sharded
    Megatron-style: column-parallel in, row-parallel out, so each
    attention+FF block needs exactly one all-reduce on its output.
  * ``seq``   — sequence-parallel axis for long-context configs: activations
    are sharded along the sequence; the windowed attention only needs its
    previous window as halo, so the collective footprint is one
    `ppermute`-shaped exchange per layer (see ops/attention docs).

Rule-table decisions (each is deliberate):
  * ``embed`` (feature dim of residual stream weights) is replicated — the
    residual stream stays whole so LayerNorms need no collective.
  * ``qkv`` / ``mlp`` (projection output dims) shard over ``model``.
  * ``vocab`` shards the embedding + logits head over ``model`` (the largest
    single matrices at 1.2B scale).
  * SGU spatial ``(n, n)`` weights shard their *output* sequence axis over
    ``seq`` and replicate over ``model`` — the matrix is sequence-structured,
    not head-structured, and row-sharding it matches a sequence-sharded
    activation layout (out[m] only needs local rows m).
  * activations: ``batch``→data, ``seq_act``→seq, ``mlp_act``→model,
    ``embed_act`` replicated.

Multi-host: `initialize_distributed` wraps `jax.distributed.initialize`;
`make_mesh` builds a hybrid DCN×ICI layout when multiple slices are present
(data-parallel outermost over DCN, model-parallel innermost over ICI, the
standard TPU recipe).
"""

from __future__ import annotations

import os
from contextlib import contextmanager
from typing import Any, Optional, Sequence

import jax
import numpy as np
from flax import linen as nn
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec

MESH_AXES = ("data", "seq", "model")

# logical axis name -> mesh axis (None = replicate). Order matters only for
# readability; flax resolves each logical name independently.
DEFAULT_RULES = (
    # --- weights ---
    ("layers", None),  # scan_layers stacked axis (future pipeline axis)
    ("vocab", "model"),
    ("embed", None),
    ("qkv", "model"),
    ("mlp", "model"),
    ("sgu_hidden", None),
    ("sgu_seq_out", "seq"),
    ("sgu_seq_in", None),
    # --- activations ---
    ("batch", "data"),
    ("seq_act", "seq"),
    ("embed_act", None),
    ("mlp_act", "model"),
)


def initialize_distributed() -> None:
    """Bootstrap multi-process JAX when launched under a multi-host runtime.

    Safe to call unconditionally: no-ops when single-process (no coordinator
    address configured) or when already initialized. Must run before any
    backend query — even ``jax.process_count()`` initializes backends, after
    which ``jax.distributed.initialize()`` raises — so the guards here only
    touch env/config state.
    """
    addr = os.environ.get("JAX_COORDINATOR_ADDRESS") or os.environ.get(
        "COORDINATOR_ADDRESS"
    )
    if not addr:
        return
    from jax._src import distributed as _dist

    if _dist.global_state.coordinator_address is not None:
        return  # already initialized
    jax.distributed.initialize()


def is_coordinator() -> bool:
    """True on process 0 — gate logging/checkpoint-commit/tracker on this."""
    return jax.process_index() == 0


def make_mesh(
    data: int = -1,
    seq: int = 1,
    model: int = 1,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
    allow_split_physical_axes: bool = False,
) -> Mesh:
    """Build a ``(data, seq, model)`` mesh.

    ``data=-1`` absorbs all remaining devices. On multi-slice TPU systems the
    data axis is laid over DCN (slices) and seq/model over ICI, via
    ``create_hybrid_device_mesh``; on a single slice or CPU the mesh comes
    from ``create_device_mesh`` / a plain reshape.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if data == -1:
        rest = seq * model
        if n % rest != 0:
            raise ValueError(f"{n} devices not divisible by seq*model={rest}")
        data = n // rest
    shape = (data, seq, model)
    total = int(np.prod(shape))
    if total > n:
        raise ValueError(f"mesh shape {shape} needs {total} > {n} devices")
    if total < n:
        # explicit smaller mesh: use the first `total` devices (e.g. the
        # reference-parity single-device default on a multi-device host)
        devices = devices[:total]
        n = total

    num_slices = len({getattr(d, "slice_index", 0) for d in devices})
    if num_slices > 1 and data % num_slices == 0:
        dev_array = mesh_utils.create_hybrid_device_mesh(
            (data // num_slices, seq, model),
            (num_slices, 1, 1),
            devices=devices,
        )
    else:
        try:
            dev_array = mesh_utils.create_device_mesh(
                shape,
                devices=devices,
                allow_split_physical_axes=allow_split_physical_axes,
            )
        except (ValueError, AssertionError):
            # CPU simulation / odd topologies: any assignment is fine.
            dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, MESH_AXES)


@contextmanager
def logical_rules(rules=DEFAULT_RULES):
    """Context in which flax `with_logical_constraint` annotations resolve."""
    with nn.logical_axis_rules(rules):
        yield


def state_shardings(abstract_state: Any, mesh: Mesh, rules=DEFAULT_RULES) -> Any:
    """Shardings for any pytree mixing flax ``Partitioned`` boxes (annotated
    weights — and optimizer moments, which inherit the boxes because optax
    builds them with structure-preserving tree maps) and plain leaves
    (step counters, norm scales), the latter pinned fully-replicated.

    Each box becomes ONE NamedSharding leaf at the box's position, i.e. the
    result is a pytree *prefix* of the state — exactly what jit's
    in/out_shardings accept.
    """
    from flax.core import meta
    from flax.linen import spmd

    def to_sharding(leaf):
        if isinstance(leaf, meta.AxisMetadata):
            logical = leaf.get_partition_spec()
            mesh_spec = spmd.logical_to_mesh_axes(logical, tuple(rules))
            return NamedSharding(mesh, mesh_spec)
        return NamedSharding(mesh, PartitionSpec())

    return jax.tree.map(
        to_sharding,
        abstract_state,
        is_leaf=lambda x: isinstance(x, meta.AxisMetadata),
    )


def param_shardings(
    abstract_variables: Any, mesh: Mesh, rules=DEFAULT_RULES
) -> Any:
    """Map a flax variables pytree (with logical-axis metadata, e.g. from
    ``jax.eval_shape(model.init, ...)``) to a pytree of `NamedSharding`s."""
    return state_shardings(abstract_variables, mesh, rules)


def batch_sharding(mesh: Mesh, *, accum_axis: bool = False) -> NamedSharding:
    """Sharding for an integer token batch: (mb, L) or (accum, mb, L),
    micro-batch dim over ``data``, sequence replicated (the attention wants
    whole windows; sequence parallelism shards activations, not input ids)."""
    if accum_axis:
        return NamedSharding(mesh, PartitionSpec(None, "data", None))
    return NamedSharding(mesh, PartitionSpec("data", None))


def put_batch(batch, mesh: Mesh, *, accum_axis: bool = False):
    """Place a host batch onto the mesh. Single-process: a device_put with
    the batch sharding. Multi-host: each process holds only its shard of the
    global batch (the data iterator dealt records per-process, see
    data/dataset.py) and `make_array_from_process_local_data` assembles the
    logical global array without any cross-host transfer."""
    sharding = batch_sharding(mesh, accum_axis=accum_axis)
    if jax.process_count() == 1:
        return jax.device_put(batch, sharding)
    return jax.make_array_from_process_local_data(sharding, batch)
