"""Explicit sequence-parallel windowed attention via ring halo exchange.

The GSPMD path (mesh ``seq`` axis + logical constraints) already handles
sequence-sharded training automatically — see the seq-parallel parity test
in tests/test_train.py. This module is the EXPLICIT collective formulation
of the same computation, the windowed-attention specialization of ring
attention: because each query window attends to at most the previous
window, a sequence shard needs exactly ONE window of halo from its left
neighbor, exchanged with a single ``ppermute`` hop over the ring (rides ICI
on a TPU torus). No iteration over the ring is needed — the window
structure collapses ring attention's S-step pipeline to one step.

Per shard (inside ``shard_map`` over the ``seq`` axis):
  1. send my LAST window's k/v to my right neighbor (ppermute, one hop);
  2. shard 0 zeroes the received halo (window 0's "previous window" is
     zeros in the reference semantics — progen.py:90-96);
  3. run the standard windowed attention locally, overriding window 0's
     previous window with the halo.

Requires local_seq_len % window_size == 0 (i.e. shard boundaries align
with window boundaries: seq_len % (S * window_size) == 0).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from progen_tpu.ops.attention import local_attention
from progen_tpu.parallel.partition import shard_map

# one ring_check_vma telemetry event per distinct configuration per
# process: ring_local_attention is traced once per layer per compile,
# and the evidence record only needs to exist, not repeat
_CHECK_VMA_SEEN: set = set()
_LAST_EVENTS: list = []


def _record_check_vma(*, use_pallas: bool, interpret: bool,
                      check_vma: bool, override) -> None:
    try:
        backend = jax.default_backend()
    except Exception:
        backend = "unknown"
    config = (backend, bool(use_pallas), bool(interpret),
              bool(check_vma), override)
    if config in _CHECK_VMA_SEEN:
        return
    _CHECK_VMA_SEEN.add(config)
    event = {
        "ev": "ring_check_vma",
        "backend": backend,
        "use_pallas": bool(use_pallas),
        "interpret": bool(interpret),
        "check_vma": bool(check_vma),
        "override": override,
    }
    _LAST_EVENTS.append(event)
    from progen_tpu.telemetry import get_telemetry

    get_telemetry().emit(event)


def ring_vma_events() -> list:
    """The ring_check_vma evidence records emitted so far this process
    (one per distinct configuration) — bench/dryrun read these to carry
    the compiled-path check_vma outcome into their result JSON."""
    return list(_LAST_EVENTS)


def record_ring_vma_policy(event: dict, path=None) -> None:
    """Persist one ring_check_vma evidence record into the policy table
    (ops/pallas_policy.json), keyed (backend, use_pallas, interpret) so a
    re-run replaces its own configuration and never duplicates. This is
    ADVICE r5's durable half: the compiled-TPU check_vma outcome survives
    the process so a later CPU session can read what the chip accepted."""
    import json as _json

    from progen_tpu.ops.pallas_attention import _POLICY_PATH

    path = path or _POLICY_PATH
    try:
        doc = _json.loads(path.read_text())
        assert isinstance(doc, dict)
    except (OSError, ValueError, AssertionError):
        doc = {"schema": "pallas-policy-v1", "entries": []}
    key = lambda e: (e.get("backend"), e.get("use_pallas"),
                     e.get("interpret"))
    kept = [
        e for e in doc.get("ring_check_vma", [])
        if isinstance(e, dict) and key(e) != key(event)
    ]
    doc["ring_check_vma"] = sorted(
        kept + [dict(event)], key=lambda e: _json.dumps(key(e))
    )
    tmp = path.with_suffix(".json.tmp")
    tmp.write_text(_json.dumps(doc, indent=1))
    tmp.replace(path)


def ring_local_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    window_size: int,
    mesh: Mesh,
    seq_axis: str = "seq",
    batch_axis: str | None = "data",
    scale: float | None = None,
    use_pallas: bool = False,
) -> jnp.ndarray:
    """q, k, v: (batch, heads, n, dim_head), n sharded over ``seq_axis``
    (batch over ``batch_axis`` when given). Returns same shape/sharding.
    Exactly equal to ``local_attention`` on the gathered arrays.

    ``use_pallas`` runs each shard's local attention through the measured
    Pallas kernel (ops/pallas_attention.pallas_local_attention_halo — the
    halo-aware variant, impls chosen by the policy table at the SHARD's
    shapes), so long-context multi-chip training composes the two flagship
    paths instead of falling back to the XLA dense attention per shard."""
    n_shards = mesh.shape[seq_axis]
    _, _, n, _ = q.shape
    w = window_size
    if n % (n_shards * w) != 0:
        raise ValueError(
            f"seq_len {n} must divide into {n_shards} shards of whole "
            f"{w}-token windows"
        )
    # decided OUTSIDE shard_map so check_vma below can stay on for
    # compiled TPU runs (the checker only trips on the interpret-mode
    # pallas lowering)
    interpret = jax.default_backend() not in ("tpu", "axon")

    def shard_fn(q, k, v):
        # NOTE: deliberately TWO ppermutes. Fusing the k/v halos into one
        # collective (stack or concat) trips a shard_map transpose
        # sharding-inference assertion in jax 0.9 when differentiated;
        # XLA's collective combiner merges adjacent small ppermutes anyway.
        perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]
        halo_k = jax.lax.ppermute(k[:, :, -w:], seq_axis, perm=perm)
        halo_v = jax.lax.ppermute(v[:, :, -w:], seq_axis, perm=perm)
        is_first = jax.lax.axis_index(seq_axis) == 0
        zero = jnp.zeros((), halo_k.dtype)
        halo_k = jnp.where(is_first, zero, halo_k)
        halo_v = jnp.where(is_first, zero, halo_v)
        if use_pallas:
            from progen_tpu.ops.pallas_attention import (
                PALLAS_API_OK,
                measured_impls,
                pallas_local_attention_halo,
            )

            # policy lookup at the LOCAL (per-shard) shapes — what the
            # kernel actually runs; trace-time Python, so file reads are
            # fine inside shard_map
            b_l, h_l, n_l, _ = q.shape
            fwd_impl, bwd_impl, g = measured_impls(
                w, n=n_l, bh=b_l * h_l
            )
            # installed jax may predate the kernel API family — the XLA
            # halo path below computes the same math, so requesting
            # pallas stays runnable instead of failing at trace time
            if PALLAS_API_OK and not (
                fwd_impl == "xla" and bwd_impl == "xla"
            ):
                return pallas_local_attention_halo(
                    q, k, v, halo_k, halo_v, w, scale, interpret,
                    bwd_impl, g, fwd_impl,
                )
        return local_attention(
            q, k, v,
            window_size=w,
            scale=scale,
            first_prev_k=halo_k,
            first_prev_v=halo_v,
        )

    spec = P(batch_axis, None, seq_axis, None)
    # check_vma off ONLY for the interpret-mode Pallas path: that lowering
    # mixes kernel-internal constants (no vma) with varying operands under
    # jax 0.9's varying-manual-axes checker, which rejects the mul
    # ("Primitive mul requires varying manual axes to match"); jax's own
    # error message prescribes check_vma=False. Compiled TPU runs and the
    # XLA path keep the checker on.
    # Residual risk, documented: the compiled-pallas + checker combination
    # is untestable off-TPU (multi-chip TPU only). If that lowering ever
    # trips the checker too, it surfaces at train-step COMPILE time (the
    # transpose is traced inside the same jit) with jax's own message
    # prescribing check_vma=False — an immediate startup failure, not a
    # mid-run one. (A try/except here could not help: the backward is
    # traced at grad time, outside this frame.) PROGEN_RING_CHECK_VMA=0/1
    # force-overrides the default, so a failing window can be rescued
    # without a code change.
    check_vma = not (use_pallas and interpret)
    override = os.environ.get("PROGEN_RING_CHECK_VMA")
    if override in ("0", "1"):
        check_vma = override == "1"
    out = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=check_vma,
    )(q, k, v)
    # evidence for the policy above: the shard_map applied cleanly WITH
    # this checker setting, on this backend. Emitted at trace time (once
    # per compiled configuration, deduped below), so a TPU bench/dryrun
    # trace carries a positive record that the compiled-pallas + checker
    # combination survived — the case that is untestable off-TPU.
    _record_check_vma(
        use_pallas=use_pallas,
        interpret=interpret,
        check_vma=check_vma,
        override=override,
    )
    return out
