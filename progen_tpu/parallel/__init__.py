from progen_tpu.parallel.partition import (
    DEFAULT_RULES,
    make_mesh,
    logical_rules,
    param_shardings,
    state_shardings,
)

__all__ = [
    "DEFAULT_RULES",
    "make_mesh",
    "logical_rules",
    "param_shardings",
    "state_shardings",
]
