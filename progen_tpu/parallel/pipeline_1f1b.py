"""1F1B (one-forward-one-backward) pipeline-parallel training schedule.

`parallel/pipeline.py` runs GPipe: all forwards, then the autodiff
transpose — every microbatch's stage activations stay live until the
backward sweep (O(M) per stage, bounded to boundary activations by remat).
This module owns BOTH directions in one manually-scheduled loop instead:
the last stage computes its microbatch loss the moment the activation
arrives and the cotangent immediately flows back, so a stage holds at most
``2*(P-1)`` in-flight boundary activations — **O(stages), independent of
the microbatch count**. The reference has no pipeline parallelism at all
(SURVEY §2.5: PP "NO"); this is the TPU-native deployment path for depth
that outgrows a chip at large M.

Schedule (unit tick = one F slot + one B slot per stage, SPMD-uniform):

  stage p forwards  microbatch f = t - p                while 0 <= f < M
  stage p backwards microbatch b = t - 2*(P-1) + p      while 0 <= b < M

  * activations hop one stage right per tick (ppermute), cotangents hop
    one stage left — both produced and consumed on consecutive ticks;
  * the LAST stage's f and b coincide (b = f), so its loss head runs
    fused with the forward slot and no cotangent is ever stored;
  * total ticks T = M + 2*(P-1); in-flight activations at stage p are
    f - b = 2*(P-1-p) <= 2*(P-1), kept in a ring buffer of 2P slots.

Gradient exactness: the backward slot RECOMPUTES its stage's forward from
the saved boundary input (remat-style, same trade as jax.checkpoint) and
applies ``jax.vjp`` — no approximation anywhere; the parity tests pin the
grads against ``jax.grad`` of the sequential composition. Non-participating
slots compute on finite garbage (zero-initialized buffers) and are masked
out of every accumulator, the standard SPMD-uniform trick.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from progen_tpu.parallel.partition import pcast, shard_map


def _tree_add_masked(acc, new, mask):
    return jax.tree.map(lambda a, n: a + n * mask.astype(n.dtype), acc, new)


def pipeline_1f1b_loss_and_grads(
    fn_pre: Callable,
    block_fn: Callable,
    fn_loss: Callable,
    params_pre,
    stacked_params,
    params_post,
    tokens: jnp.ndarray,
    *,
    mesh: Mesh,
    axis: str,
    n_microbatches: int,
    data_axis: str | None = "data",
):
    """One 1F1B pass: mean microbatch loss + grads for all three param
    groups.

    fn_pre(params_pre, ids) -> h          : embedding etc., runs on stage 0
      (ids = tokens[..., :-1], the model inputs).
    block_fn(one_layer_params, h) -> h    : one uniform layer.
    fn_loss(params_post, h, tokens_mb) -> scalar : trailing layers + head +
      loss for ONE microbatch, runs fused with the last stage's forward.
    stacked_params: leaves with leading axis L, sharded over ``axis`` into
      P stages of L/P layers (the scan_layers layout).
    tokens: (B, L+1) int rows (inputs+targets), B % n_microbatches == 0.
    data_axis: optional mesh axis to ALSO shard each microbatch's row dim
      over (PP x DP composition): every data row pipelines its own 1/D
      slice of each microbatch and grads/loss psum-mean over the axis.
      None or a size-1 axis = pure pipeline.

    Returns (loss, (g_pre, g_stack, g_post)): loss is the mean over
    microbatches; g_stack leaves keep the stacked (L, ...) layout;
    g_pre/g_post are replicated (psum over the stage axis of the one
    participating stage's accumulation).
    """
    n_stages = mesh.shape[axis]
    L = jax.tree.leaves(stacked_params)[0].shape[0]
    if L % n_stages:
        raise ValueError(f"{L} layers not divisible by {n_stages} stages")
    B = tokens.shape[0]
    M = n_microbatches
    if B % M:
        raise ValueError(f"batch {B} not divisible by {M} microbatches")
    mb = B // M
    dp = (data_axis is not None and data_axis in mesh.shape
          and mesh.shape[data_axis] > 1)
    n_data = mesh.shape[data_axis] if dp else 1
    if dp and mb % n_data:
        raise ValueError(
            f"microbatch rows {mb} not divisible by data axis {n_data}"
        )
    tokens_mb = tokens.reshape((M, mb) + tokens.shape[1:])

    def stage_fn(params_pre, local_params, params_post, tokens_mb):
        p = jax.lax.axis_index(axis)
        last = n_stages - 1
        ring_slots = 2 * n_stages
        T = M + 2 * (n_stages - 1)

        def local_apply(lp, h):
            def body(h_, layer):
                return block_fn(layer, h_), None

            return jax.lax.scan(body, h, lp)[0]

        # probe shapes with one dummy application (trace-time only)
        h_shape = jax.eval_shape(
            lambda pp: fn_pre(pp, tokens_mb[0][..., :-1]), params_pre
        )
        zero_h = jnp.zeros(h_shape.shape, h_shape.dtype)
        # under DP composition every carried value mixes with data-varying
        # token shards inside the loop, so the scan carry's vma must carry
        # BOTH axes from the start (scan requires a fixed carry type)
        vaxes = (axis, data_axis) if dp else (axis,)
        varying = lambda x: pcast(x, vaxes, to="varying")

        # CRITICAL: differentiate against VARYING copies of the replicated
        # param groups. vjp wrt an invariant input with a varying cotangent
        # makes jax insert a cross-stage psum in the transpose — which
        # would sum every stage's masked-out garbage head/embed gradients
        # into the real one. Varying copies keep d_pre/d_post per-stage;
        # the single participating stage's accumulation is psum'd once,
        # explicitly, at the end.
        params_pre = jax.tree.map(varying, params_pre)
        params_post = jax.tree.map(varying, params_post)
        # same trap under DP composition: local_params arrive varying over
        # ``axis`` only, so a data-varying cotangent would make the vjp
        # implicitly psum d_local over data — and the explicit psum at the
        # end would then double-count by exactly n_data. A data-varying
        # copy keeps d_local per-shard. (pcast rejects already-varying
        # axes, so cast over data alone.)
        if dp:
            data_varying = lambda x: pcast(x, (data_axis,), to="varying")
            local_params = jax.tree.map(data_varying, local_params)

        perm_right = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        perm_left = [(i, (i - 1) % n_stages) for i in range(n_stages)]

        def tick(carry, t):
            (act_in, ct_in, ring, g_stack, g_pre, g_post, loss_acc) = carry
            f = t - p
            b = t - 2 * (n_stages - 1) + p
            f_valid = (f >= 0) & (f < M)
            b_valid = (b >= 0) & (b < M)
            f_idx = jnp.clip(f, 0, M - 1)
            b_idx = jnp.clip(b, 0, M - 1)

            # ---- forward slot: stage 0 injects, others consume the hop
            toks_f = jax.lax.dynamic_index_in_dim(
                tokens_mb, f_idx, axis=0, keepdims=False
            )
            pre_out = fn_pre(params_pre, toks_f[..., :-1])
            h_in = jnp.where(p == 0, pre_out, act_in)
            h_out = local_apply(local_params, h_in)
            # invalid forward slots (warmup/drain) write to the dead slot
            # ``ring_slots`` — a clipped f_idx would clobber slot M-1 % R,
            # which trailing stages' backwards still need during drain
            write_idx = jnp.where(f_valid, f_idx % ring_slots, ring_slots)
            ring = jax.lax.dynamic_update_index_in_dim(
                ring, h_in, write_idx, axis=0
            )

            # ---- loss head (meaningful on the last stage, whose b == f):
            # loss + d(post) + the cotangent that starts the backward
            loss_mb, vjp_post = jax.vjp(
                lambda pp, h: fn_loss(pp, h, toks_f), params_post, h_out
            )
            d_post, d_hout = vjp_post(varying(jnp.ones((), loss_mb.dtype)))

            # ---- backward slot: recompute this stage's forward from the
            # saved boundary input, then vjp (remat-style, grad-exact)
            h_saved = jax.lax.dynamic_index_in_dim(
                ring, b_idx % ring_slots, axis=0, keepdims=False
            )
            ct = jnp.where(p == last, d_hout, ct_in)
            _, vjp_local = jax.vjp(local_apply, local_params, h_saved)
            d_local, d_hin = vjp_local(ct)

            # stage 0's d_hin is the gradient at fn_pre's output
            toks_b = jax.lax.dynamic_index_in_dim(
                tokens_mb, b_idx, axis=0, keepdims=False
            )
            _, vjp_pre = jax.vjp(
                lambda pp: fn_pre(pp, toks_b[..., :-1]), params_pre
            )
            (d_pre,) = vjp_pre(d_hin)

            g_stack = _tree_add_masked(g_stack, d_local, b_valid)
            g_pre = _tree_add_masked(g_pre, d_pre, b_valid & (p == 0))
            head_valid = f_valid & (p == last)
            g_post = _tree_add_masked(g_post, d_post, head_valid)
            loss_acc = loss_acc + loss_mb * head_valid.astype(loss_mb.dtype)

            act_in = jax.lax.ppermute(h_out, axis, perm=perm_right)
            ct_in = jax.lax.ppermute(d_hin, axis, perm=perm_left)
            return (
                (act_in, ct_in, ring, g_stack, g_pre, g_post, loss_acc),
                None,
            )

        zeros_like_f32 = lambda tree: jax.tree.map(
            lambda x: varying(jnp.zeros(x.shape, x.dtype)), tree
        )
        init = (
            varying(zero_h),                                   # act_in
            varying(zero_h),                                   # ct_in
            varying(
                # +1: the dead slot absorbing invalid-slot writes
                jnp.zeros((ring_slots + 1,) + zero_h.shape, zero_h.dtype)
            ),                                                 # ring
            zeros_like_f32(local_params),                      # g_stack
            zeros_like_f32(params_pre),                        # g_pre
            zeros_like_f32(params_post),                       # g_post
            varying(jnp.zeros((), jnp.float32)),               # loss
        )
        carry, _ = jax.lax.scan(tick, init, jnp.arange(T))
        _, _, _, g_stack, g_pre, g_post, loss_acc = carry

        # only one stage accumulated each of these — psum replicates.
        # grads were accumulated with unit cotangent per microbatch while
        # the reported loss is the MEAN over M (and, under DP, over the
        # n_data per-shard means): scale to match. Under DP the psums also
        # reduce over data — each data row holds grads of ITS 1/D rows.
        inv_m = 1.0 / (M * n_data)
        scale_m = lambda tree: jax.tree.map(
            lambda x: x * jnp.asarray(inv_m, x.dtype), tree
        )
        reduce_axes = vaxes
        g_pre = scale_m(jax.lax.psum(g_pre, reduce_axes))
        g_post = scale_m(jax.lax.psum(g_post, reduce_axes))
        if dp:
            g_stack = jax.tree.map(
                lambda x: jax.lax.psum(x, data_axis), g_stack
            )
        g_stack = scale_m(g_stack)
        loss = jax.lax.psum(loss_acc, reduce_axes) / (M * n_data)
        # g_stack stays stage-local; the (1, ...) leading axis is
        # re-stacked to (L, ...) by the P(axis) out_spec
        g_stack = jax.tree.map(lambda x: x[None], g_stack)
        return loss, g_pre, g_stack, g_post

    loss, g_pre, g_stack, g_post = shard_map(
        stage_fn,
        mesh=mesh,
        in_specs=(P(), P(axis), P(),
                  P(None, data_axis) if dp else P()),
        out_specs=(P(), P(), P(axis), P()),
    )(params_pre, stacked_params, params_post, tokens_mb)
    g_stack = jax.tree.map(
        lambda x: x.reshape((L,) + x.shape[2:]), g_stack
    )
    return loss, (g_pre, g_stack, g_post)


def _split_progen_params(params):
    """ProGen scan_layers param tree -> (pre, stack, post) groups for the
    1F1B schedule (inverse: _join_progen_grads). The stacked 'layers'
    subtree is the pipeline; embed runs on stage 0; everything else —
    trailing gMLP blocks, final norm, logits head — runs in the last
    stage's fused loss head (all O(1) in depth)."""
    if "layers" not in params:
        raise ValueError(
            "1F1B needs the scan_layers stacked param layout "
            "(use models.progen.stack_params to convert)"
        )
    pre = {"embed": params["embed"]}
    stack = params["layers"]
    post = {k: v for k, v in params.items()
            if k not in ("embed", "layers")}
    return pre, stack, post


def _join_progen_grads(g_pre, g_stack, g_post):
    return {"embed": g_pre["embed"], "layers": g_stack, **g_post}


def make_1f1b_train_step(
    model,
    optimizer,
    *,
    mesh: Mesh,
    axis: str = "model",
    n_microbatches: int,
):
    """The production train step with forward AND backward scheduled by
    the 1F1B pipeline: same loss / accumulation / clip / masked-AdamW
    semantics as training/step.make_train_step (grads are exact — parity
    test-locked against the plain step), but a stage's live activations
    are bounded by 2*(stages-1) microbatch boundaries instead of GPipe's
    O(n_microbatches). ``config.remat`` additionally checkpoints each
    layer inside the stage recompute. Composes with data parallelism: on
    a mesh with ``data > 1`` each microbatch's rows are sharded over the
    data axis (every chip does 1/D of the work; grads psum over data)."""
    import optax
    from flax import linen as nn

    from progen_tpu.models.layers import (
        FeedForwardBlock,
        LocalAttentionBlock,
        ScaleNorm,
    )
    from progen_tpu.models.progen import UniformBlock
    from progen_tpu.ops.rotary import fixed_pos_embedding
    from progen_tpu.training.loss import cross_entropy

    c = model.config
    n_uniform = c.depth - c.global_mlp_depth
    sin, cos = fixed_pos_embedding(c.seq_len, c.dim_head)
    block = UniformBlock(c, glu=c.ff_glu)

    def fn_pre(pre, ids):
        return nn.Embed(
            c.num_tokens,
            c.dim,
            dtype=c.compute_dtype,
            param_dtype=c.params_dtype,
            name="embed",
        ).apply({"params": pre["embed"]}, ids)

    def block_fn(layer_params, h):
        h, _ = block.apply({"params": layer_params}, h, sin, cos)
        return h

    if c.remat:
        block_fn = jax.checkpoint(block_fn)

    def fn_loss(post, h, toks_mb):
        x = h
        for i in range(n_uniform, c.depth):
            use_gmlp = (c.depth - i) <= c.global_mlp_depth
            x = x + LocalAttentionBlock(c).apply(
                {"params": post[f"attn{i}"]}, x, sin, cos, None
            )
            x = x + FeedForwardBlock(
                c, glu=(not use_gmlp) and c.ff_glu, spatial_gate=use_gmlp
            ).apply({"params": post[f"ff{i}"]}, x, None)
        x = ScaleNorm(
            c.layer_norm_epsilon, c.compute_dtype, c.params_dtype
        ).apply({"params": post["ScaleNorm_0"]}, x)
        logits = nn.Dense(
            c.num_tokens,
            dtype=c.compute_dtype,
            param_dtype=c.params_dtype,
            name="to_logits",
        ).apply({"params": post["to_logits"]}, x)
        labels = toks_mb[..., 1:]
        return cross_entropy(logits.astype(jnp.float32), labels).mean()

    def train_step(state, batch):
        pre, stack, post = _split_progen_params(state.params)

        def micro(grads_acc, mb_rows):
            loss, (g_pre, g_stack, g_post) = pipeline_1f1b_loss_and_grads(
                fn_pre, block_fn, fn_loss, pre, stack, post, mb_rows,
                mesh=mesh, axis=axis, n_microbatches=n_microbatches,
            )
            grads = _join_progen_grads(g_pre, g_stack, g_post)
            grads_acc = jax.tree.map(jnp.add, grads_acc, grads)
            return grads_acc, loss

        zero_grads = jax.tree.map(jnp.zeros_like, state.params)
        grads, losses = jax.lax.scan(micro, zero_grads, batch)
        grads = jax.tree.map(lambda g: g / batch.shape[0], grads)

        updates, opt_state = optimizer.update(
            grads, state.opt_state, state.params
        )
        params = optax.apply_updates(state.params, updates)
        new_state = state.replace(
            step=state.step + 1, params=params, opt_state=opt_state
        )
        metrics = {
            "loss": losses.mean(),
            "last_micro_loss": losses[-1],
            "grad_norm": optax.global_norm(grads),
        }
        return new_state, metrics

    return train_step


def compile_1f1b_train_step(
    model,
    optimizer,
    shardings,
    mesh: Mesh,
    *,
    axis: str = "model",
    n_microbatches: int,
):
    """jit ``make_1f1b_train_step`` with explicit state/batch shardings and
    a donated state — the 1F1B twin of
    ``parallel/pipeline.compile_pipeline_train_step`` (same PIPELINE_RULES
    state layout; only the schedule differs)."""
    from progen_tpu.parallel.partition import batch_sharding

    step = make_1f1b_train_step(
        model, optimizer, mesh=mesh, axis=axis,
        n_microbatches=n_microbatches,
    )
    return jax.jit(
        step,
        in_shardings=(shardings, batch_sharding(mesh, accum_axis=True)),
        out_shardings=(shardings, None),
        donate_argnums=(0,),
    )
