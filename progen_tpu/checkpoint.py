"""Mesh-sharded checkpointing with the reference's factory interface.

Interface parity (/root/reference/progen_transformer/checkpoint.py:85-109):
``get_checkpoint_fns(path) -> (reset, get_last, save)`` with ``keep_last_n``
retention and ``ckpt_{unix_time}`` naming (lexicographic sort = latest,
checkpoint.py:27-30). Package schema parity (/root/reference/train.py:196-202):
``{next_seq_index, params, optim_state, model_config, run_id}`` — with
params/optim_state generalized to the whole TrainState so the model config
stored in the checkpoint can rebuild the model on resume, overriding the TOML
(train.py:94-100; sample.py:46-47 reconstructs purely from the checkpoint).

TPU-first deltas:
  * arrays are written per-shard through Orbax/TensorStore — each host
    writes only the shards it owns, no single-host pickle of the full model
    (the reference cloudpickles everything on one process,
    checkpoint.py:25-30; impossible at 1.2B on a v5e host);
  * the save is atomic (Orbax's tmp-dir + rename commit) and multi-host
    coordinated, so a preempted write never corrupts the latest checkpoint —
    the reference's recovery-by-restart story (SURVEY §5) needs this;
  * restore takes an abstract TrainState + shardings so every leaf lands
    directly on its mesh position (no host round-trip);
  * GCS works through the same code path (TensorStore speaks gs:// natively)
    instead of a parallel download-to-/tmp implementation
    (checkpoint.py:41-81).
"""

from __future__ import annotations

import json
import shutil
import time
from pathlib import Path
from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import orbax.checkpoint as ocp
from jax.sharding import NamedSharding

from progen_tpu import telemetry

CKPT_PREFIX = "ckpt_"
DEFAULT_KEEP_LAST_N = 500  # reference default, train.py:48


class Package(NamedTuple):
    """What one checkpoint holds — reference schema, train.py:196-202,
    plus ``train_config``: optimizer-structure-affecting run settings
    (lr schedule etc.). Resume must rebuild the optimizer EXACTLY as
    saved — a schedule mismatch changes the optax state pytree and the
    sharded restore fails structurally — so these ride the checkpoint the
    same way the model config does."""

    next_seq_index: int
    state: Any  # TrainState (params + opt_state + step)
    model_config: dict
    run_id: Optional[str]
    train_config: Optional[dict] = None


def _is_gcs(path: str) -> bool:
    return str(path).startswith("gs://")


def sharded_abstract_state(abstract_state: Any, shardings: Any) -> Any:
    """Attach shardings (a pytree prefix: one NamedSharding per flax
    Partitioned box / plain leaf — see partition.state_shardings) to an
    abstract state pytree, producing the restore template Orbax needs to
    place every shard directly on the mesh."""
    sh_leaves = jax.tree.leaves(
        shardings, is_leaf=lambda x: isinstance(x, NamedSharding)
    )
    ab_leaves, treedef = jax.tree.flatten(abstract_state)
    assert len(sh_leaves) == len(ab_leaves), "sharding/state leaf mismatch"
    return treedef.unflatten(
        jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=s)
        for l, s in zip(ab_leaves, sh_leaves)
    )


def get_checkpoint_fns(
    path: str,
    keep_last_n: int = DEFAULT_KEEP_LAST_N,
    *,
    async_save: bool = False,
) -> Tuple[Callable, Callable, Callable]:
    """(reset, get_last, save) over local or gs:// ``path``.

    save(package: Package) -> str
    get_last(abstract_state=None) -> Optional[Package]; without an abstract
        state only the metadata is loaded eagerly and ``state`` is restored
        unsharded; with one (see ``sharded_abstract_state``) every array
        restores straight to its mesh shard.
    reset() -> None: wipe the checkpoint directory (guarded by --new +
        interactive confirm at the CLI layer, train.py:85-88).

    ``async_save``: the array write overlaps subsequent training steps —
    Orbax copies device arrays to host synchronously (so the donated
    TrainState buffers are safe to reuse immediately) and commits to
    storage in the background. The ``meta.json`` finalizer runs at the
    NEXT ``save`` (or at ``save.flush()``, which the train loop calls on
    exit): until then the checkpoint has no meta.json and restore skips it
    as incomplete — the same invariant the sync path relies on for
    crash-atomicity, so a death mid-write can never be mistaken for a
    complete checkpoint.
    """
    # TensorStore requires absolute paths; the reference-parity default
    # ('./ckpts', train.py:47) arrives relative
    root = (
        ocp.path.utils.to_path(path) if _is_gcs(path) else Path(path).resolve()
    )

    def _list() -> list:
        if not _exists(root):
            return []
        return sorted(
            (p for p in root.iterdir() if p.name.startswith(CKPT_PREFIX)),
            key=lambda p: p.name,
        )

    def _exists(p) -> bool:
        try:
            return p.exists()
        except OSError:
            return False

    def reset() -> None:
        if _is_gcs(path):
            for p in _list():
                _rmtree(p)
        elif Path(path).exists():
            shutil.rmtree(path)

    def _rmtree(p) -> None:
        if isinstance(p, Path):
            shutil.rmtree(p)
        else:  # CloudPath-like
            p.rmtree()

    # async machinery: one AsyncCheckpointer reused across saves; the
    # (target, meta) awaiting its meta.json finalizer
    _async: dict = {}

    def _retain() -> None:
        """Drop complete checkpoints beyond keep_last_n (reference
        semantics, checkpoint.py:33-37) — shared by sync and async."""
        stale = _complete(_list())[:-keep_last_n] if keep_last_n else []
        for p in stale:
            _rmtree(p)

    def _finalize_pending() -> None:
        """Wait for the in-flight async array write, then publish its
        meta.json + run retention (coordinator only)."""
        import jax

        if not _async:
            return  # sync mode / nothing in flight: span-free no-op
        with telemetry.span("ckpt/finalize"):
            if "ckptr" in _async:
                _async["ckptr"].wait_until_finished()
            item = _async.pop("pending", None)
            if item is not None and jax.process_index() == 0:
                target, meta = item
                _write_text(target / "meta.json", json.dumps(meta))
                _retain()

    def _close() -> None:
        """Publish any pending save, then shut the background commit
        thread down deterministically (otherwise a non-daemon Orbax thread
        outlives the last flush and delays interpreter exit on aborts).
        Safe to call repeatedly; the next save() recreates the
        checkpointer."""
        _finalize_pending()
        ckptr = _async.pop("ckptr", None)
        if ckptr is not None:
            ckptr.close()

    def _save(package: Package) -> str:
        # unix-time naming (checkpoint.py:27-30) made collision-proof: two
        # saves within the same second get strictly increasing names, so
        # lexicographic order == save order always holds. Multi-host: every
        # process must pass the SAME path into the collective Orbax save, so
        # process 0's stamp is broadcast; meta.json and retention are
        # coordinator-only side effects.
        import jax

        _finalize_pending()  # no-op unless an async save is in flight

        stamp = int(time.time())
        existing = _list()
        if existing:
            last_stamp = int(existing[-1].name[len(CKPT_PREFIX):])
            stamp = max(stamp, last_stamp + 1)
        if jax.process_count() > 1:
            import numpy as _np
            from jax.experimental import multihost_utils

            stamp = int(
                multihost_utils.broadcast_one_to_all(_np.int64(stamp))
            )
        name = f"{CKPT_PREFIX}{stamp}"
        target = root / name
        if not _is_gcs(path) and jax.process_index() == 0:
            root.mkdir(parents=True, exist_ok=True)
        meta = {
            "next_seq_index": int(package.next_seq_index),
            "model_config": package.model_config,
            "run_id": package.run_id,
            "train_config": package.train_config,
        }
        if async_save:
            if "ckptr" not in _async:
                _async["ckptr"] = ocp.AsyncCheckpointer(
                    ocp.StandardCheckpointHandler()
                )
            # device->host copy happens before this returns (donation-safe);
            # storage commit runs in the background; meta.json publishes at
            # the next save()/flush()
            _async["ckptr"].save(
                target / "state", args=ocp.args.StandardSave(package.state)
            )
            _async["pending"] = (target, meta)
            return str(target)
        with ocp.StandardCheckpointer() as ckptr:
            ckptr.save(target / "state", package.state)  # collective
        if jax.process_index() == 0:
            # metadata written after the state commit; a checkpoint without
            # meta.json is treated as incomplete and skipped on restore
            _write_text(target / "meta.json", json.dumps(meta))
            _retain()
        return str(target)

    def save(package: Package) -> str:
        # the span (B with no E in events.jsonl = died mid-save) rides
        # the process telemetry; goodput crediting stays with the caller
        with telemetry.span("ckpt/save", async_mode=async_save):
            return _save(package)

    save.flush = _finalize_pending  # await + publish the in-flight save
    save.close = _close  # flush + stop the background commit thread

    def _complete(candidates):
        return [p for p in candidates if _exists(p / "meta.json")]

    def _get_last(abstract_state: Any = None) -> Optional[Package]:
        candidates = _complete(_list())
        if not candidates:
            return None
        last = candidates[-1]
        meta = json.loads(_read_text(last / "meta.json"))
        with ocp.StandardCheckpointer() as ckptr:
            state = ckptr.restore(last / "state", abstract_state)
        return Package(
            next_seq_index=meta["next_seq_index"],
            state=state,
            model_config=meta["model_config"],
            run_id=meta["run_id"],
            train_config=meta.get("train_config"),
        )

    def get_last(abstract_state: Any = None) -> Optional[Package]:
        with telemetry.span("ckpt/restore"):
            return _get_last(abstract_state)

    def _restore_params(abstract_params: Any = None) -> Optional[Package]:
        """Params-only restore for inference (sample CLI): skips the Adam
        moments — ~2/3 of the checkpoint bytes, which matters at 1.2B on a
        small sampling box. ``state`` in the returned Package is just the
        params pytree."""
        candidates = _complete(_list())
        if not candidates:
            return None
        last = candidates[-1]
        meta = json.loads(_read_text(last / "meta.json"))
        with ocp.Checkpointer(ocp.PyTreeCheckpointHandler()) as ckptr:
            if abstract_params is None:
                # shape/dtype skeleton from the checkpoint's own metadata,
                # restored whole onto the default device — exactly what
                # single-host inference wants
                dev = jax.sharding.SingleDeviceSharding(jax.devices()[0])
                # orbax changed metadata()'s return shape: older releases
                # (<=0.7.x) hand back the pytree itself, newer ones wrap it
                meta_obj = ckptr.metadata(last / "state")
                meta_tree = (
                    meta_obj["params"]
                    if isinstance(meta_obj, dict)
                    else meta_obj.item_metadata.tree["params"]
                )
                abstract_params = jax.tree.map(
                    lambda m: jax.ShapeDtypeStruct(
                        m.shape, m.dtype, sharding=dev
                    ),
                    meta_tree,
                )
            # explicit per-leaf restore args: a ShapeDtypeStruct sharding
            # alone is NOT forwarded to deserialization by this orbax, and
            # a checkpoint written from a mesh-sharded train state refuses
            # to restore without a concrete sharding (the "train on a pod,
            # sample on one host" path)
            restore_args = jax.tree.map(
                lambda a: ocp.ArrayRestoreArgs(sharding=a.sharding)
                if getattr(a, "sharding", None) is not None
                else ocp.RestoreArgs(),
                abstract_params,
            )
            try:
                restored = ckptr.restore(
                    last / "state",
                    args=ocp.args.PyTreeRestore(
                        item={"params": abstract_params},
                        restore_args={"params": restore_args},
                        partial_restore=True,
                    ),
                )
            except TypeError:
                # pre-0.8 orbax spells partial restore as empty transforms
                restored = ckptr.restore(
                    last / "state",
                    args=ocp.args.PyTreeRestore(
                        item={"params": abstract_params},
                        restore_args={"params": restore_args},
                        transforms={},
                    ),
                )
        return Package(
            next_seq_index=meta["next_seq_index"],
            state=restored["params"],
            model_config=meta["model_config"],
            run_id=meta["run_id"],
            train_config=meta.get("train_config"),
        )

    def restore_params(abstract_params: Any = None) -> Optional[Package]:
        with telemetry.span("ckpt/restore_params"):
            return _restore_params(abstract_params)

    get_last.restore_params = restore_params

    def peek_last() -> Optional[Package]:
        """Metadata only (state=None) — decide model config / resume point
        without paying the array restore (train.py:94-100 reads only the
        config before building the model)."""
        candidates = _complete(_list())
        if not candidates:
            return None
        meta = json.loads(_read_text(candidates[-1] / "meta.json"))
        return Package(
            next_seq_index=meta["next_seq_index"],
            state=None,
            model_config=meta["model_config"],
            run_id=meta["run_id"],
            train_config=meta.get("train_config"),
        )

    get_last.peek = peek_last  # exposed without widening the triple

    def _write_text(p, text: str) -> None:
        if isinstance(p, Path):
            p.write_text(text)
        else:
            with p.open("w") as f:
                f.write(text)

    def _read_text(p) -> str:
        if isinstance(p, Path):
            return p.read_text()
        with p.open("r") as f:
            return f.read()

    return reset, get_last, save
