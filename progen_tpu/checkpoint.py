"""Mesh-sharded checkpointing with the reference's factory interface.

Interface parity (/root/reference/progen_transformer/checkpoint.py:85-109):
``get_checkpoint_fns(path) -> (reset, get_last, save)`` with ``keep_last_n``
retention and ``ckpt_{unix_time}`` naming (lexicographic sort = latest,
checkpoint.py:27-30). Package schema parity (/root/reference/train.py:196-202):
``{next_seq_index, params, optim_state, model_config, run_id}`` — with
params/optim_state generalized to the whole TrainState so the model config
stored in the checkpoint can rebuild the model on resume, overriding the TOML
(train.py:94-100; sample.py:46-47 reconstructs purely from the checkpoint).

TPU-first deltas:
  * arrays are written per-shard through Orbax/TensorStore — each host
    writes only the shards it owns, no single-host pickle of the full model
    (the reference cloudpickles everything on one process,
    checkpoint.py:25-30; impossible at 1.2B on a v5e host);
  * the save is atomic (Orbax's tmp-dir + rename commit) and multi-host
    coordinated, so a preempted write never corrupts the latest checkpoint —
    the reference's recovery-by-restart story (SURVEY §5) needs this;
  * restore takes an abstract TrainState + shardings so every leaf lands
    directly on its mesh position (no host round-trip);
  * GCS works through the same code path (TensorStore speaks gs:// natively)
    instead of a parallel download-to-/tmp implementation
    (checkpoint.py:41-81).
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import shutil
import time
from pathlib import Path
from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import orbax.checkpoint as ocp
from jax.sharding import NamedSharding

from progen_tpu import telemetry
from progen_tpu.resilience.retry import retry_call
from progen_tpu.telemetry.registry import get_registry

CKPT_PREFIX = "ckpt_"
CORRUPT_SUFFIX = ".corrupt"
_CKPT_NAME_RE = re.compile(re.escape(CKPT_PREFIX) + r"\d+")
DEFAULT_KEEP_LAST_N = 500  # reference default, train.py:48


# ---------------------------------------------------------------------------
# Integrity manifest: per-entry digests riding meta.json
# ---------------------------------------------------------------------------
#
# A checkpoint is only as good as its worst byte: Orbax's tmp+rename
# commit protects against dying MID-write, but not against truncation,
# bit rot, or a partially-synced network filesystem discovered at
# restore time — which used to be discovered as an opaque TensorStore
# error that killed the run. The manifest records (size, sha256) for
# every file under ``state/`` at save time; restore verifies it and
# walks BACKWARD through older complete checkpoints when it fails,
# renaming the bad directory to ``ckpt_N.corrupt`` (quarantine, never
# delete — the evidence matters) instead of crashing.
#
# Local-path only: digesting a gs:// checkpoint means re-downloading it.
# Env gates: PROGEN_CKPT_DIGEST=0 skips writing manifests,
# PROGEN_CKPT_VERIFY=0 skips verification (both default on).


def _digest_enabled() -> bool:
    return os.environ.get("PROGEN_CKPT_DIGEST", "1") != "0"


def _verify_enabled() -> bool:
    return os.environ.get("PROGEN_CKPT_VERIFY", "1") != "0"


def digest_manifest(state_dir) -> Optional[dict]:
    """{relpath: [size, sha256hex]} for every file under ``state_dir``;
    None for non-local paths (CloudPath) or when digests are disabled."""
    if not _digest_enabled() or not isinstance(state_dir, Path):
        return None
    manifest = {}
    for p in sorted(state_dir.rglob("*")):
        if not p.is_file():
            continue
        h = hashlib.sha256()
        with p.open("rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
        rel = p.relative_to(state_dir).as_posix()
        manifest[rel] = [p.stat().st_size, h.hexdigest()]
    return manifest


def verify_manifest(state_dir, manifest: Optional[dict]) -> bool:
    """True when every manifest entry exists with matching size+digest.
    A legacy checkpoint (no manifest) verifies trivially; extra files on
    disk are tolerated (forward compat with Orbax layout changes)."""
    if not manifest or not isinstance(state_dir, Path):
        return True
    for rel, (size, digest) in manifest.items():
        p = state_dir / rel
        try:
            if p.stat().st_size != int(size):
                return False
            h = hashlib.sha256()
            with p.open("rb") as f:
                for chunk in iter(lambda: f.read(1 << 20), b""):
                    h.update(chunk)
            if h.hexdigest() != digest:
                return False
        except OSError:
            return False
    return True


def checkpoint_digest(ckpt_dir) -> Optional[str]:
    """Content identity of ONE checkpoint directory: sha256 over its
    meta.json integrity manifest (the per-file digests, already paid at
    save time — no re-hashing of array bytes). Two saves of identical
    weights agree; any differing byte under ``state/`` disagrees. Falls
    back to hashing the whole meta.json when the manifest was disabled
    (PROGEN_CKPT_DIGEST=0); None when meta.json is absent/unreadable
    (the save never completed)."""
    meta_path = Path(ckpt_dir) / "meta.json"
    try:
        meta = json.loads(meta_path.read_text())
    except (OSError, ValueError):
        return None
    payload = meta.get("integrity") or meta
    return hashlib.sha256(
        json.dumps(payload, sort_keys=True).encode("utf-8")
    ).hexdigest()


def digest_gauge(digest: Optional[str]) -> float:
    """The first 48 bits of a hex digest as a float gauge (exact in the
    52-bit float64 mantissa) — how a replica publishes its live
    checkpoint identity through Prometheus exposition so the deploy
    controller and the router can see fleet skew. -1.0 = unknown."""
    if not digest:
        return -1.0
    return float(int(digest[:12], 16))


class Package(NamedTuple):
    """What one checkpoint holds — reference schema, train.py:196-202,
    plus ``train_config``: optimizer-structure-affecting run settings
    (lr schedule etc.). Resume must rebuild the optimizer EXACTLY as
    saved — a schedule mismatch changes the optax state pytree and the
    sharded restore fails structurally — so these ride the checkpoint the
    same way the model config does."""

    next_seq_index: int
    state: Any  # TrainState (params + opt_state + step)
    model_config: dict
    run_id: Optional[str]
    train_config: Optional[dict] = None
    # which checkpoint directory the restore walk actually selected —
    # the hot-reload path compares this against the checkpoint it is
    # already serving (a corrupt newest quarantined by the fallback walk
    # must not be mistaken for "new weights arrived")
    path: Optional[str] = None


def _is_gcs(path: str) -> bool:
    return str(path).startswith("gs://")


def sharded_abstract_state(abstract_state: Any, shardings: Any) -> Any:
    """Attach shardings (a pytree prefix: one NamedSharding per flax
    Partitioned box / plain leaf — see partition.state_shardings) to an
    abstract state pytree, producing the restore template Orbax needs to
    place every shard directly on the mesh."""
    sh_leaves = jax.tree.leaves(
        shardings, is_leaf=lambda x: isinstance(x, NamedSharding)
    )
    ab_leaves, treedef = jax.tree.flatten(abstract_state)
    assert len(sh_leaves) == len(ab_leaves), "sharding/state leaf mismatch"
    return treedef.unflatten(
        jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=s)
        for l, s in zip(ab_leaves, sh_leaves)
    )


def get_checkpoint_fns(
    path: str,
    keep_last_n: int = DEFAULT_KEEP_LAST_N,
    *,
    async_save: bool = False,
) -> Tuple[Callable, Callable, Callable]:
    """(reset, get_last, save) over local or gs:// ``path``.

    save(package: Package) -> str
    get_last(abstract_state=None) -> Optional[Package]; without an abstract
        state only the metadata is loaded eagerly and ``state`` is restored
        unsharded; with one (see ``sharded_abstract_state``) every array
        restores straight to its mesh shard.
    reset() -> None: wipe the checkpoint directory (guarded by --new +
        interactive confirm at the CLI layer, train.py:85-88).

    ``async_save``: the array write overlaps subsequent training steps —
    Orbax copies device arrays to host synchronously (so the donated
    TrainState buffers are safe to reuse immediately) and commits to
    storage in the background. The ``meta.json`` finalizer runs at the
    NEXT ``save`` (or at ``save.flush()``, which the train loop calls on
    exit): until then the checkpoint has no meta.json and restore skips it
    as incomplete — the same invariant the sync path relies on for
    crash-atomicity, so a death mid-write can never be mistaken for a
    complete checkpoint.
    """
    # TensorStore requires absolute paths; the reference-parity default
    # ('./ckpts', train.py:47) arrives relative
    root = (
        ocp.path.utils.to_path(path) if _is_gcs(path) else Path(path).resolve()
    )

    def _list() -> list:
        if not _exists(root):
            return []
        # fullmatch excludes quarantined ``ckpt_N.corrupt`` dirs — they
        # stay on disk as evidence but never re-enter the rotation (and
        # never confuse the stamp arithmetic in _save)
        return sorted(
            (
                p
                for p in root.iterdir()
                if _CKPT_NAME_RE.fullmatch(p.name)
            ),
            key=lambda p: p.name,
        )

    def _exists(p) -> bool:
        try:
            return p.exists()
        except OSError:
            return False

    def reset() -> None:
        if _is_gcs(path):
            for p in _list():
                _rmtree(p)
        elif Path(path).exists():
            shutil.rmtree(path)

    def _rmtree(p) -> None:
        if isinstance(p, Path):
            shutil.rmtree(p)
        else:  # CloudPath-like
            p.rmtree()

    # async machinery: one AsyncCheckpointer reused across saves; the
    # (target, meta) awaiting its meta.json finalizer
    _async: dict = {}

    def _retain() -> None:
        """Drop complete checkpoints beyond keep_last_n (reference
        semantics, checkpoint.py:33-37) — shared by sync and async."""
        stale = _complete(_list())[:-keep_last_n] if keep_last_n else []
        for p in stale:
            _rmtree(p)

    def _finalize_pending() -> None:
        """Wait for the in-flight async array write, then publish its
        meta.json + run retention (coordinator only)."""
        import jax

        if not _async:
            return  # sync mode / nothing in flight: span-free no-op
        with telemetry.span("ckpt/finalize"):
            if "ckptr" in _async:
                _async["ckptr"].wait_until_finished()
            item = _async.pop("pending", None)
            if item is not None and jax.process_index() == 0:
                target, meta = item
                # arrays are fully committed now — digest them before
                # the manifest-bearing meta.json publishes the checkpoint
                meta["integrity"] = digest_manifest(target / "state")
                retry_call(
                    _write_text,
                    target / "meta.json",
                    json.dumps(meta),
                    label="ckpt/io/meta_write",
                )
                _retain()

    def _close() -> None:
        """Publish any pending save, then shut the background commit
        thread down deterministically (otherwise a non-daemon Orbax thread
        outlives the last flush and delays interpreter exit on aborts).
        Safe to call repeatedly; the next save() recreates the
        checkpointer."""
        _finalize_pending()
        ckptr = _async.pop("ckptr", None)
        if ckptr is not None:
            ckptr.close()

    def _save(package: Package) -> str:
        # unix-time naming (checkpoint.py:27-30) made collision-proof: two
        # saves within the same second get strictly increasing names, so
        # lexicographic order == save order always holds. Multi-host: every
        # process must pass the SAME path into the collective Orbax save, so
        # process 0's stamp is broadcast; meta.json and retention are
        # coordinator-only side effects.
        import jax

        _finalize_pending()  # no-op unless an async save is in flight

        stamp = int(time.time())
        existing = _list()
        if existing:
            last_stamp = int(existing[-1].name[len(CKPT_PREFIX):])
            stamp = max(stamp, last_stamp + 1)
        if jax.process_count() > 1:
            import numpy as _np
            from jax.experimental import multihost_utils

            stamp = int(
                multihost_utils.broadcast_one_to_all(_np.int64(stamp))
            )
        name = f"{CKPT_PREFIX}{stamp}"
        target = root / name
        if not _is_gcs(path) and jax.process_index() == 0:
            root.mkdir(parents=True, exist_ok=True)
        meta = {
            "next_seq_index": int(package.next_seq_index),
            "model_config": package.model_config,
            "run_id": package.run_id,
            "train_config": package.train_config,
        }
        if async_save:
            if "ckptr" not in _async:
                _async["ckptr"] = ocp.AsyncCheckpointer(
                    ocp.StandardCheckpointHandler()
                )
            # device->host copy happens before this returns (donation-safe);
            # storage commit runs in the background; meta.json publishes at
            # the next save()/flush()
            _async["ckptr"].save(
                target / "state", args=ocp.args.StandardSave(package.state)
            )
            _async["pending"] = (target, meta)
            return str(target)

        def _commit():
            # a failed earlier attempt can leave a partial target that
            # Orbax refuses to overwrite — clear it before re-trying
            state_dir = target / "state"
            if isinstance(state_dir, Path) and state_dir.exists():
                shutil.rmtree(state_dir)
            with ocp.StandardCheckpointer() as ckptr:
                ckptr.save(state_dir, package.state)  # collective

        if jax.process_count() > 1:
            _commit()  # collective op: per-host retry would deadlock
        else:
            retry_call(_commit, label="ckpt/io/save")
        if jax.process_index() == 0:
            # metadata written after the state commit; a checkpoint without
            # meta.json is treated as incomplete and skipped on restore.
            # The integrity manifest digests what actually hit storage.
            meta["integrity"] = digest_manifest(target / "state")
            retry_call(
                _write_text,
                target / "meta.json",
                json.dumps(meta),
                label="ckpt/io/meta_write",
            )
            _retain()
        return str(target)

    def save(package: Package) -> str:
        # the span (B with no E in events.jsonl = died mid-save) rides
        # the process telemetry; goodput crediting stays with the caller
        with telemetry.span("ckpt/save", async_mode=async_save):
            return _save(package)

    def _check_error() -> None:
        """Non-blocking poll of the background commit thread; the train
        loop calls this once per step so a fatal commit error surfaces at
        the NEXT step rather than the next flush (which may be minutes of
        silently-doomed training away). On failure: emit a
        ``ckpt_commit_failed`` event, drop the pending finalizer (a
        failed commit must never publish meta.json — the incomplete dir
        stays meta-less and restore skips it), retire the checkpointer
        (so the finally-path ``close()`` is a clean no-op), and re-raise
        to the step loop."""
        ckptr = _async.get("ckptr")
        if ckptr is None:
            return  # sync mode / nothing in flight
        check = getattr(ckptr, "check_for_errors", None)
        if check is None:
            return  # orbax without the poll API: flush-time surfacing
        try:
            check()
        except BaseException as e:
            get_registry().inc("ckpt_commit_failures")
            telemetry.get_telemetry().emit({
                "ev": "ckpt_commit_failed",
                "ts": time.time(),
                "error": f"{type(e).__name__}: {e}",
            })
            _async.pop("pending", None)
            bad = _async.pop("ckptr", None)
            if bad is not None:
                try:
                    bad.close()
                except Exception:
                    pass
            raise

    save.flush = _finalize_pending  # await + publish the in-flight save
    save.close = _close  # flush + stop the background commit thread
    save.check_error = _check_error  # per-step async commit health poll
    save._async = _async  # test seam: inject a failing checkpointer

    def _complete(candidates):
        return [p for p in candidates if _exists(p / "meta.json")]

    def _quarantine(p, reason: str) -> None:
        """Rename a bad checkpoint dir to ``<name>.corrupt`` so it leaves
        the rotation but stays on disk as evidence. Coordinator-only (on a
        shared filesystem every host sees the rename); best-effort — a
        failed rename just means the next walk re-discovers the same
        verdict."""
        import jax

        print(
            f"[checkpoint] quarantining {getattr(p, 'name', p)}: {reason}",
            flush=True,
        )
        get_registry().inc("ckpt_quarantines")
        telemetry.get_telemetry().emit({
            "ev": "ckpt_quarantine",
            "ts": time.time(),
            "ckpt": getattr(p, "name", str(p)),
            "reason": reason,
        })
        if jax.process_index() != 0 or not isinstance(p, Path):
            return
        try:
            p.rename(p.with_name(p.name + CORRUPT_SUFFIX))
        except OSError:
            pass

    # checkpoints whose manifest verified this process — peek_last and a
    # following get_last hash the same bytes once, not twice
    _verified: set = set()

    def _verify_candidate(cand) -> Optional[tuple]:
        """(dir, meta) when ``cand``'s manifest verifies; None after
        quarantining it otherwise."""
        try:
            meta = json.loads(
                retry_call(
                    _read_text,
                    cand / "meta.json",
                    label="ckpt/io/meta_read",
                )
            )
        except (OSError, ValueError):
            _quarantine(cand, "unreadable meta.json")
            return None
        if _verify_enabled() and cand.name not in _verified:
            if not verify_manifest(cand / "state", meta.get("integrity")):
                _quarantine(cand, "integrity manifest mismatch")
                return None
            _verified.add(cand.name)
        return cand, meta

    def _select_last() -> Optional[tuple]:
        """Newest COMPLETE checkpoint whose integrity manifest verifies,
        walking backward through older ones and quarantining failures —
        the fallback chain replacing the old newest-or-crash behavior.
        Returns (dir, meta) or None."""
        for cand in reversed(_complete(_list())):
            sel = _verify_candidate(cand)
            if sel is not None:
                return sel
        return None

    def _select_pinned(at) -> Optional[tuple]:
        """The SPECIFIC checkpoint ``at`` (a ``ckpt_<stamp>`` directory
        name, or a path whose basename is one), verified. A pin never
        falls back: when the target is missing, incomplete, or fails its
        digest walk (quarantined), the answer is None — serving some
        OTHER checkpoint under a pin would defeat the deploy
        controller's canary isolation."""
        name = os.path.basename(str(at).rstrip("/"))
        for cand in _complete(_list()):
            if cand.name == name:
                return _verify_candidate(cand)
        return None

    def _select(at=None) -> Optional[tuple]:
        return _select_last() if at is None else _select_pinned(at)

    def _get_last(abstract_state: Any = None) -> Optional[Package]:
        import jax

        sel = _select_last()
        if sel is None:
            return None
        last, meta = sel

        def _restore():
            with ocp.StandardCheckpointer() as ckptr:
                return ckptr.restore(last / "state", abstract_state)

        # a restore failure on a digest-verified checkpoint is structural
        # (template mismatch), not corruption — re-raise, don't walk: a
        # silent fallback would mask a real bug with stale weights
        if jax.process_count() > 1:
            state = _restore()  # collective: per-host retry would deadlock
        else:
            state = retry_call(_restore, label="ckpt/io/restore")
        return Package(
            next_seq_index=meta["next_seq_index"],
            state=state,
            model_config=meta["model_config"],
            run_id=meta["run_id"],
            train_config=meta.get("train_config"),
            path=str(last),
        )

    def get_last(abstract_state: Any = None) -> Optional[Package]:
        with telemetry.span("ckpt/restore"):
            return _get_last(abstract_state)

    def _restore_params(
        abstract_params: Any = None, at=None
    ) -> Optional[Package]:
        """Params-only restore for inference (sample CLI): skips the Adam
        moments — ~2/3 of the checkpoint bytes, which matters at 1.2B on a
        small sampling box. ``state`` in the returned Package is just the
        params pytree. ``at`` pins the restore to one specific checkpoint
        (no newest-walk, no fallback) — the hot-reload pin seam."""
        sel = _select(at)
        if sel is None:
            return None
        last, meta = sel
        with ocp.Checkpointer(ocp.PyTreeCheckpointHandler()) as ckptr:
            if abstract_params is None:
                # shape/dtype skeleton from the checkpoint's own metadata,
                # restored whole onto the default device — exactly what
                # single-host inference wants
                dev = jax.sharding.SingleDeviceSharding(jax.devices()[0])
                # orbax changed metadata()'s return shape: older releases
                # (<=0.7.x) hand back the pytree itself, newer ones wrap it
                meta_obj = ckptr.metadata(last / "state")
                meta_tree = (
                    meta_obj["params"]
                    if isinstance(meta_obj, dict)
                    else meta_obj.item_metadata.tree["params"]
                )
                abstract_params = jax.tree.map(
                    lambda m: jax.ShapeDtypeStruct(
                        m.shape, m.dtype, sharding=dev
                    ),
                    meta_tree,
                )
            # explicit per-leaf restore args: a ShapeDtypeStruct sharding
            # alone is NOT forwarded to deserialization by this orbax, and
            # a checkpoint written from a mesh-sharded train state refuses
            # to restore without a concrete sharding (the "train on a pod,
            # sample on one host" path)
            restore_args = jax.tree.map(
                lambda a: ocp.ArrayRestoreArgs(sharding=a.sharding)
                if getattr(a, "sharding", None) is not None
                else ocp.RestoreArgs(),
                abstract_params,
            )
            try:
                restored = ckptr.restore(
                    last / "state",
                    args=ocp.args.PyTreeRestore(
                        item={"params": abstract_params},
                        restore_args={"params": restore_args},
                        partial_restore=True,
                    ),
                )
            except TypeError:
                # pre-0.8 orbax spells partial restore as empty transforms
                restored = ckptr.restore(
                    last / "state",
                    args=ocp.args.PyTreeRestore(
                        item={"params": abstract_params},
                        restore_args={"params": restore_args},
                        transforms={},
                    ),
                )
        return Package(
            next_seq_index=meta["next_seq_index"],
            state=restored["params"],
            model_config=meta["model_config"],
            run_id=meta["run_id"],
            train_config=meta.get("train_config"),
            path=str(last),
        )

    def restore_params(
        abstract_params: Any = None, at=None
    ) -> Optional[Package]:
        with telemetry.span("ckpt/restore_params"):
            return _restore_params(abstract_params, at=at)

    get_last.restore_params = restore_params

    def peek_last(at=None) -> Optional[Package]:
        """Metadata only (state=None) — decide model config / resume point
        without paying the array restore (train.py:94-100 reads only the
        config before building the model). Runs the same verify+fallback
        walk as get_last (cached, so the bytes hash once) — otherwise the
        model could be built from a config whose checkpoint get_last later
        quarantines. ``at`` pins the peek to one specific checkpoint."""
        sel = _select(at)
        if sel is None:
            return None
        last, meta = sel
        return Package(
            next_seq_index=meta["next_seq_index"],
            state=None,
            model_config=meta["model_config"],
            run_id=meta["run_id"],
            train_config=meta.get("train_config"),
            path=str(last),
        )

    get_last.peek = peek_last  # exposed without widening the triple

    def _write_text(p, text: str) -> None:
        if isinstance(p, Path):
            p.write_text(text)
        else:
            with p.open("w") as f:
                f.write(text)

    def _read_text(p) -> str:
        if isinstance(p, Path):
            return p.read_text()
        with p.open("r") as f:
            return f.read()

    return reset, get_last, save
