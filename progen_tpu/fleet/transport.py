"""Length-prefixed framed TCP transport for the serving fleet.

Newline-JSONL over a unix socket needs no framing: the kernel delivers
whole writes to one host and a torn final line is the writer's crash
signature. TCP gives neither guarantee to strangers — any process that
can reach the port can write bytes at it — so the fleet wire wraps
every JSONL line in a binary envelope the receiver can validate before
parsing a single byte of payload:

    magic(2B)=b"PG" | version(1B)=1 | auth_len(1B) | payload_len(4B BE)
    | auth[auth_len] | payload[payload_len]

The payload of a frame is EXACTLY the UTF-8 JSON line the unix-socket
transport would carry — the framing is transparent above this module,
which is what keeps TCP streams bit-identical to unix-socket streams
(test-locked by the fleet kill-matrix) and journal/replay/handoff
working unchanged over either wire.

Enforcement, all before payload parse:

  * bad magic / unknown version — the peer is not speaking this
    protocol (or the stream lost sync): the frame is dropped and the
    connection is condemned (``FrameError``); resync is hopeless once
    the length prefix can't be trusted;
  * auth mismatch — every frame carries the shared fleet token
    (``PROGEN_FLEET_TOKEN``); a frame with the wrong token is dropped
    and the connection condemned. Not cryptography — a fence against
    accidental cross-fleet dials and port scans; TLS is the ROADMAP
    follow-up;
  * oversized frame — ``payload_len`` above ``max_frame`` is rejected
    WITHOUT buffering the payload (a 4GB length prefix must not
    allocate 4GB);
  * idle timeout — a connection that has produced no bytes for
    ``idle_timeout`` seconds is closed by its owner loop (half-open
    TCP peers hold sockets forever; unix sockets never needed this).

Torn frames are the normal case, not an error: ``FrameDecoder`` is a
byte-stream accumulator that yields complete payloads and keeps the
tail buffered across ``feed()`` calls, so a frame split across any
number of reads reassembles exactly (the serve kill-matrix SIGKILLs a
peer mid-frame and the survivor must neither crash nor mis-parse).

Every dropped frame leaves an ``{"ev": "frame_drop", "reason": ...}``
record (grammar owned HERE, linted by PGL006) plus a ``frame_drops``
counter — a wire that silently eats frames is indistinguishable from a
healthy one until requests go missing.

Chaos sites (``PROGEN_CHAOS``, resilience/chaos.py):

  * ``transport/accept`` — fires in the listener's accept path: the
    connection is accepted and immediately dropped (a flaky fronting
    LB); ``kill@N`` dies in accept;
  * ``transport/frame``  — fires per decoded frame: the frame is
    dropped and the connection condemned (a corrupted/truncated frame
    on the wire); the router treats the condemned link as replica-down
    and runs the journal-ownership handoff.
"""

from __future__ import annotations

import os
import socket
import struct
import time
from typing import Callable, List, Optional, Tuple

from progen_tpu.resilience.chaos import ChaosError, maybe_inject

MAGIC = b"PG"
VERSION = 1
_HEADER = struct.Struct("!2sBBI")
HEADER_BYTES = _HEADER.size  # 8
# request/event lines are small; resume payloads carry at most a few
# thousand token ids. 1 MiB is ~100x headroom, and small enough that a
# hostile length prefix can't balloon the receive buffer.
DEFAULT_MAX_FRAME = 1 << 20
_MAX_AUTH = 255

# frame_drop reasons (free-form field, but kept to this set in-tree so
# the drop records stay greppable)
DROP_BAD_MAGIC = "bad_magic"
DROP_BAD_VERSION = "bad_version"
DROP_BAD_AUTH = "bad_auth"
DROP_OVERSIZED = "oversized"
DROP_CHAOS = "chaos"
DROP_IDLE = "idle_timeout"


class FrameError(Exception):
    """Framing violation: the byte stream can no longer be trusted and
    the connection must be dropped (length-prefixed protocols cannot
    resync past a corrupt prefix)."""

    def __init__(self, reason: str, detail: str = ""):
        self.reason = reason
        super().__init__(f"{reason}{': ' + detail if detail else ''}")


def fleet_token() -> bytes:
    """The shared fleet auth token (``PROGEN_FLEET_TOKEN``), as frame
    envelope bytes. Empty (the default) means an open fleet — both
    sides must agree, exactly like an empty password would."""
    tok = os.environ.get("PROGEN_FLEET_TOKEN", "")
    return tok.encode("utf-8")[:_MAX_AUTH]


def _record_drop(reason: str, **attrs) -> None:
    """One drop record + counter per rejected frame. Lazy imports and a
    broad except, chaos.py-style: the transport must keep condemning
    bad peers even with telemetry torn down."""
    try:
        from progen_tpu import telemetry
        from progen_tpu.telemetry.registry import get_registry

        get_registry().inc("frame_drops")
        rec = {"ev": "frame_drop", "ts": time.time(), "reason": reason}
        rec.update({k: v for k, v in attrs.items() if v is not None})
        telemetry.get_telemetry().emit(rec)
    except Exception:
        pass


def encode_frame(payload, auth: Optional[bytes] = None) -> bytes:
    """One JSONL line (str or bytes) -> wire frame. ``auth=None`` reads
    the process-wide fleet token."""
    if isinstance(payload, str):
        payload = payload.encode("utf-8")
    if auth is None:
        auth = fleet_token()
    if len(auth) > _MAX_AUTH:
        raise ValueError(f"auth token too long ({len(auth)} > {_MAX_AUTH})")
    header = _HEADER.pack(MAGIC, VERSION, len(auth), len(payload))
    return header + auth + payload


class FrameDecoder:
    """Stateful byte-stream -> payload-line decoder. ``feed()`` returns
    every COMPLETE payload in arrival order and buffers any torn tail;
    a framing violation records the drop and raises ``FrameError`` (the
    caller owns the socket and must close it)."""

    def __init__(self, auth: Optional[bytes] = None,
                 max_frame: int = DEFAULT_MAX_FRAME,
                 peer: Optional[str] = None):
        self._auth = fleet_token() if auth is None else auth
        self.max_frame = int(max_frame)
        self.peer = peer
        self._buf = b""
        self.frames_in = 0

    @property
    def buffered(self) -> int:
        """Bytes of torn frame waiting for the rest of their read."""
        return len(self._buf)

    def _condemn(self, reason: str, detail: str = "") -> None:
        _record_drop(reason, peer=self.peer)
        self._buf = b""
        raise FrameError(reason, detail)

    def feed(self, data: bytes) -> List[str]:
        self._buf += data
        out: List[str] = []
        while len(self._buf) >= HEADER_BYTES:
            magic, version, auth_len, payload_len = _HEADER.unpack(
                self._buf[:HEADER_BYTES]
            )
            if magic != MAGIC:
                self._condemn(DROP_BAD_MAGIC, repr(magic))
            if version != VERSION:
                self._condemn(DROP_BAD_VERSION, str(version))
            if payload_len > self.max_frame:
                # reject on the prefix alone: the payload is never
                # buffered, so a hostile length can't balloon memory
                self._condemn(
                    DROP_OVERSIZED,
                    f"{payload_len} > max_frame {self.max_frame}",
                )
            total = HEADER_BYTES + auth_len + payload_len
            if len(self._buf) < total:
                break  # torn frame: wait for the next read
            auth = self._buf[HEADER_BYTES:HEADER_BYTES + auth_len]
            payload = self._buf[HEADER_BYTES + auth_len:total]
            if auth != self._auth:
                self._condemn(DROP_BAD_AUTH)
            try:
                maybe_inject("transport/frame")
            except ChaosError:
                self._condemn(DROP_CHAOS)
            self._buf = self._buf[total:]
            self.frames_in += 1
            out.append(payload.decode("utf-8", errors="replace"))
        return out


def parse_hostport(text: str) -> Tuple[str, int]:
    """``HOST:PORT`` -> (host, port); bare ``:PORT``/``PORT`` bind all
    interfaces loopback-first (``127.0.0.1``)."""
    text = text.strip()
    host, sep, port = text.rpartition(":")
    if not sep:
        host, port = "", text
    try:
        p = int(port)
        if not 0 <= p <= 65535:
            raise ValueError
    except ValueError:
        raise ValueError(f"bad HOST:PORT {text!r}") from None
    return host or "127.0.0.1", p


def connect_tcp(host: str, port: int, timeout: float = 2.0) -> socket.socket:
    """Dial one fleet peer; returns a NON-blocking connected socket
    (the same contract ReplicaLink.connect leaves a unix socket in)."""
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.settimeout(timeout)
    try:
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        s.connect((host, port))
    except BaseException:
        s.close()
        raise
    s.setblocking(False)
    return s


class FramedConnection:
    """One framed peer: socket + decoder + idle accounting. The owner
    loop selects on ``fileno()``, calls ``recv_lines()`` when readable,
    ``send_line()`` to answer, and ``idle_expired()`` on its tick."""

    def __init__(self, sock: socket.socket, auth: Optional[bytes] = None,
                 max_frame: int = DEFAULT_MAX_FRAME,
                 idle_timeout: float = 0.0,
                 clock: Callable[[], float] = time.monotonic,
                 peer: Optional[str] = None):
        sock.setblocking(False)
        self.sock: Optional[socket.socket] = sock
        self._auth = fleet_token() if auth is None else auth
        self._decoder = FrameDecoder(self._auth, max_frame, peer=peer)
        self.idle_timeout = float(idle_timeout)
        self._clock = clock
        self.last_rx = clock()
        self.peer = peer

    def fileno(self) -> int:
        assert self.sock is not None
        return self.sock.fileno()

    def send_line(self, line: str) -> None:
        """Frame + send one JSONL line. Bounded blocking send, the
        ReplicaLink.send discipline: a peer that can't drain a few KB
        in 5s is down, and a partial frame would corrupt the stream
        anyway — the raised OSError tells the owner to drop us."""
        assert self.sock is not None
        data = encode_frame(line, self._auth)
        self.sock.settimeout(5.0)
        try:
            self.sock.sendall(data)
        finally:
            if self.sock is not None:
                self.sock.setblocking(False)

    def recv_lines(self) -> Tuple[List[str], bool]:
        """Drain the socket: (complete payload lines, eof). A framing
        violation reads as EOF — the record is already written by the
        decoder, and a condemned connection and a closed one get the
        same treatment from every owner."""
        if self.sock is None:
            return [], True
        chunks: List[bytes] = []
        eof = False
        while True:
            try:
                data = self.sock.recv(65536)
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                data = b""
            if not data:
                eof = True
                break
            chunks.append(data)
        lines: List[str] = []
        if chunks:
            self.last_rx = self._clock()
            try:
                lines = self._decoder.feed(b"".join(chunks))
            except FrameError:
                return lines, True
        return lines, eof

    def idle_expired(self, now: Optional[float] = None) -> bool:
        """True once this peer has been silent past ``idle_timeout``
        (0 = never). Records the drop exactly once; the owner closes."""
        if self.idle_timeout <= 0 or self.sock is None:
            return False
        now = self._clock() if now is None else now
        if now - self.last_rx <= self.idle_timeout:
            return False
        _record_drop(DROP_IDLE, peer=self.peer)
        return True

    def close(self) -> None:
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
        self.sock = None


class FramedListener:
    """TCP listener producing ``FramedConnection`` peers. ``port=0``
    binds an ephemeral port; the bound port is ``self.port`` (printed
    by the CLIs so tests and operators can dial it)."""

    def __init__(self, host: str, port: int,
                 auth: Optional[bytes] = None,
                 max_frame: int = DEFAULT_MAX_FRAME,
                 idle_timeout: float = 0.0, backlog: int = 16):
        self._auth = fleet_token() if auth is None else auth
        self.max_frame = int(max_frame)
        self.idle_timeout = float(idle_timeout)
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((host, port))
        srv.listen(backlog)
        srv.setblocking(False)
        self.sock = srv
        self.host, self.port = srv.getsockname()[:2]

    def fileno(self) -> int:
        return self.sock.fileno()

    def accept(self) -> Optional[FramedConnection]:
        """One accept; None when nothing is waiting or chaos dropped
        the dial (``transport/accept`` — the connection is accepted
        then immediately closed, a flaky fronting LB)."""
        try:
            conn, addr = self.sock.accept()
        except (BlockingIOError, InterruptedError):
            return None
        except OSError:
            return None
        try:
            maybe_inject("transport/accept")
        except ChaosError:
            try:
                from progen_tpu.telemetry.registry import get_registry

                get_registry().inc("accept_drops")
            except Exception:
                pass
            conn.close()
            return None
        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        peer = f"{addr[0]}:{addr[1]}" if isinstance(addr, tuple) else None
        return FramedConnection(
            conn, self._auth, self.max_frame, self.idle_timeout, peer=peer
        )

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass
