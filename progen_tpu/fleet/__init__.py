"""Fleet layer: network transport + control plane for the serving fleet.

The serving stack below this package speaks newline-JSONL over stdin
or unix sockets, which pins the router, its replicas, and every client
to one machine. This package is the internet-scale leg:

  * ``transport.py`` — a length-prefixed binary framing layer (magic +
    version + auth-token envelope + JSON payload) served over TCP by
    ``progen-tpu-serve --tcp`` and ``progen-tpu-router --listen_tcp``,
    and dialed by ``--replica tcp=HOST:PORT`` specs. The payload of
    every frame is exactly the JSONL line the unix-socket path carries,
    so streams are bit-identical across the two wires and journal /
    replay / handoff work unchanged over TCP.
  * ``autoscaler.py`` — a policy engine over the fleet collector's
    ring TSDB: queue depth, slot occupancy and latency quantiles from
    the merged fleet series drive scale-up/scale-down decisions with
    hysteresis, cooldowns and min/max bounds, executed against the
    router's ``--spawn``/``--fleet_dir`` self-managed fleet.

Deliberately jax-free: framing and scaling policy are host-side
concerns, testable and startable without a backend.
"""

from progen_tpu.fleet.autoscaler import (
    ACTION_DOWN,
    ACTION_HOLD,
    ACTION_UP,
    Autoscaler,
    Decision,
    ScalingPolicy,
    load_policy,
)
from progen_tpu.fleet.transport import (
    DEFAULT_MAX_FRAME,
    FrameDecoder,
    FrameError,
    FramedConnection,
    FramedListener,
    connect_tcp,
    encode_frame,
    fleet_token,
    parse_hostport,
)

__all__ = [
    "ACTION_DOWN",
    "ACTION_HOLD",
    "ACTION_UP",
    "Autoscaler",
    "Decision",
    "ScalingPolicy",
    "load_policy",
    "DEFAULT_MAX_FRAME",
    "FrameDecoder",
    "FrameError",
    "FramedConnection",
    "FramedListener",
    "connect_tcp",
    "encode_frame",
    "fleet_token",
    "parse_hostport",
]
