"""TSDB-driven fleet autoscaler: merged metrics in, scale decisions out.

MegaScale's observability lesson (PAPERS.md) applied to control: fleet
decisions should be driven by the aggregated metrics stream, not by
whatever process happens to notice pressure first. The PR 12 collector
already maintains exactly that stream — per-source ``ev:"sample"``
records in a ring TSDB, folded by ``fleet_series`` into one series
with reset-safe counter sums, max/sum gauges and merged latency
quantiles — and this module is its first control-plane consumer.

Each tick the :class:`Autoscaler` reads the TSDB (``TsdbReader`` —
read-only, never races the collector), takes the LATEST fleet point,
and runs pure policy math (:func:`evaluate_policy`, jax-free and
clock-free, unit-tested directly):

  * scale UP when queue pressure (``queue_depth_sum`` across router +
    replicas) or a latency objective (fleet ``ttft_s`` p95 / ``itl_s``
    p99) is above its high-water mark;
  * scale DOWN when the queue is below its low-water mark and every
    latency objective is comfortable — the gap between the two
    watermarks IS the hysteresis band (a fleet sitting between them
    holds, so the scaler cannot flap on a boundary load);
  * a breach must SUSTAIN for ``up_sustain``/``down_sustain``
    consecutive ticks before acting (one bursty scrape is noise);
  * after any action the matching cooldown (``up_cooldown_s`` /
    ``down_cooldown_s``) gates the next one — spawn cost and drain
    cost are asymmetric, so the two directions get separate clocks;
  * ``min_replicas``/``max_replicas`` bound the target; no data, or a
    latest point older than ``stale_after_s``, always holds (scaling
    on a dead collector's last opinion would be flying blind).

Decisions land as ``{"ev": "scale", "action": up|down|hold, ...}``
records (grammar owned HERE, linted by PGL006), edge-triggered: every
up/down is recorded, holds only when their reason changes — a 2s tick
must not bury the trace in steady-state holds.

Execution is the caller's job (cli/router.py): the router owns its
``--spawn``/``--fleet_dir`` fleet, spawns scale-ups with ``--replay``
and drains scale-downs before reaping. Chaos site
``autoscaler/decide`` fires at the top of each decide tick.

Policy knobs load from a flat ``[autoscaler]`` TOML table
(``configs/serving/autoscaler.toml`` is the shipped example).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, List, Optional, Tuple

from progen_tpu.resilience.chaos import maybe_inject

# the scale-record action alphabet (PGL006-enforced)
ACTION_UP = "up"
ACTION_DOWN = "down"
ACTION_HOLD = "hold"

# hold/action reasons, bounded so the CI smoke and summarize can grep
REASON_NO_DATA = "no_data"
REASON_STALE_DATA = "stale_data"
REASON_QUEUE_HIGH = "queue_high"
REASON_TTFT_HIGH = "ttft_p95_high"
REASON_ITL_HIGH = "itl_p99_high"
REASON_QUEUE_LOW = "queue_low"
REASON_SUSTAIN = "sustaining"
REASON_COOLDOWN = "cooldown"
REASON_AT_MAX = "at_max_replicas"
REASON_AT_MIN = "at_min_replicas"
REASON_STEADY = "steady"


@dataclasses.dataclass(frozen=True)
class ScalingPolicy:
    """Autoscaler knobs; defaults are smoke-scale, not production."""

    min_replicas: int = 1
    max_replicas: int = 4
    # queue watermarks: total queued across router + replicas
    # (queue_depth_sum on the fleet series). The gap is the hysteresis
    # band — high must stay strictly above low.
    queue_high: float = 8.0
    queue_low: float = 1.0
    # latency high-water marks; 0 disables the signal
    ttft_p95_high_s: float = 0.0
    itl_p99_high_s: float = 0.0
    # consecutive breaching ticks required before acting
    up_sustain: int = 2
    down_sustain: int = 3
    # seconds after the last action before the next one may fire
    up_cooldown_s: float = 20.0
    down_cooldown_s: float = 60.0
    # a latest fleet point older than this holds (collector dead/stuck)
    stale_after_s: float = 15.0
    # the caller's decide cadence (cli/router.py reads it)
    interval_s: float = 2.0
    # max queued/in-flight requests the router migrates onto a replica
    # that just turned HEALTHY (serving/router.py rebalance bound)
    rebalance_max: int = 4

    def __post_init__(self):
        if self.min_replicas < 0 or self.max_replicas < self.min_replicas:
            raise ValueError(
                f"need 0 <= min_replicas <= max_replicas, got "
                f"{self.min_replicas}..{self.max_replicas}"
            )
        if self.queue_high <= self.queue_low:
            raise ValueError(
                f"queue_high ({self.queue_high}) must exceed queue_low "
                f"({self.queue_low}) — the gap is the hysteresis band"
            )
        if self.up_sustain < 1 or self.down_sustain < 1:
            raise ValueError("sustain counts must be >= 1")


def load_policy(path) -> ScalingPolicy:
    """Flat ``[autoscaler]`` TOML table -> policy; unknown keys raise
    (a typo'd knob silently at its default is a misconfigured fleet)."""
    from progen_tpu.config import load_toml_config

    raw = load_toml_config(str(path))
    table = raw.get("autoscaler", raw)
    if not isinstance(table, dict):
        raise ValueError(f"{path}: [autoscaler] is not a table")
    names = {f.name for f in dataclasses.fields(ScalingPolicy)}
    unknown = set(table) - names
    if unknown:
        raise ValueError(
            f"{path}: unknown autoscaler key(s) {sorted(unknown)}"
        )
    return ScalingPolicy(**table)


@dataclasses.dataclass
class Decision:
    """One decide-tick verdict. ``target`` is the replica count the
    fleet should converge to (== current on hold)."""

    action: str
    target: int
    reason: str
    current: int
    signals: Dict[str, float] = dataclasses.field(default_factory=dict)


def extract_signals(vals: Dict[str, float]) -> Dict[str, float]:
    """The fleet-series keys the policy reads, pulled into one flat
    dict (absent signals are simply not present — evaluate_policy
    treats missing latency signals as 'comfortable')."""
    out: Dict[str, float] = {}
    q = vals.get("queue_depth_sum", vals.get("queue_depth"))
    if q is not None:
        out["queue"] = float(q)
    occ = vals.get("slot_occupancy_sum", vals.get("slot_occupancy"))
    if occ is not None:
        out["slot_occupancy"] = float(occ)
    ttft = vals.get("ttft_s_p95_s")
    if ttft is not None:
        out["ttft_p95_s"] = float(ttft)
    itl = vals.get("itl_s_p99_s")
    if itl is not None:
        out["itl_p99_s"] = float(itl)
    for k in ("replicas_live", "replicas_total", "fleet_up"):
        if k in vals:
            out[k] = float(vals[k])
    return out


def _pressure(policy: ScalingPolicy,
              signals: Dict[str, float]) -> Tuple[int, str]:
    """(direction, reason): +1 scale-up pressure, -1 scale-down
    pressure, 0 in the hysteresis band."""
    queue = signals.get("queue", 0.0)
    ttft = signals.get("ttft_p95_s")
    itl = signals.get("itl_p99_s")
    if queue > policy.queue_high:
        return 1, REASON_QUEUE_HIGH
    if policy.ttft_p95_high_s > 0 and ttft is not None \
            and ttft > policy.ttft_p95_high_s:
        return 1, REASON_TTFT_HIGH
    if policy.itl_p99_high_s > 0 and itl is not None \
            and itl > policy.itl_p99_high_s:
        return 1, REASON_ITL_HIGH
    if queue < policy.queue_low:
        return -1, REASON_QUEUE_LOW
    return 0, REASON_STEADY


def evaluate_policy(policy: ScalingPolicy, current: int,
                    signals: Optional[Dict[str, float]], age_s: float,
                    streak: Tuple[int, int],
                    since_up_s: float, since_down_s: float,
                    ) -> Tuple[Decision, Tuple[int, int]]:
    """Pure policy math: one tick's verdict plus the updated
    (direction, length) breach streak. ``signals=None`` means no fleet
    point exists. ``since_up_s`` is seconds since the last scale-up
    (gates the next up); ``since_down_s`` is seconds since the last
    action in EITHER direction — a fresh spawn relieving the queue must
    not trigger an immediate drain of the replica it just paid for
    (``inf`` when never)."""
    sig = signals or {}

    def hold(reason: str) -> Decision:
        return Decision(ACTION_HOLD, current, reason, current, sig)

    if signals is None:
        return hold(REASON_NO_DATA), (0, 0)
    if age_s > policy.stale_after_s:
        return hold(REASON_STALE_DATA), (0, 0)
    direction, reason = _pressure(policy, signals)
    last_dir, length = streak
    length = length + 1 if direction == last_dir else 1
    streak = (direction, length)
    if direction == 0:
        return hold(REASON_STEADY), streak
    if direction > 0:
        if current >= policy.max_replicas:
            return hold(REASON_AT_MAX), streak
        if length < policy.up_sustain:
            return hold(REASON_SUSTAIN), streak
        if since_up_s < policy.up_cooldown_s:
            return hold(REASON_COOLDOWN), streak
        return (
            Decision(ACTION_UP, current + 1, reason, current, sig),
            streak,
        )
    if current <= policy.min_replicas:
        return hold(REASON_AT_MIN), streak
    if length < policy.down_sustain:
        return hold(REASON_SUSTAIN), streak
    if since_down_s < policy.down_cooldown_s:
        return hold(REASON_COOLDOWN), streak
    return (
        Decision(ACTION_DOWN, current - 1, reason, current, sig),
        streak,
    )


class Autoscaler:
    """Stateful decide loop over a TSDB reader. The caller ticks
    ``decide(n_current)`` on its own cadence and executes the returned
    decision; hysteresis streaks and cooldown clocks live here."""

    def __init__(self, policy: ScalingPolicy, reader=None,
                 clock: Callable[[], float] = time.time,
                 emit=None):
        self.policy = policy
        self.reader = reader
        self._clock = clock
        self._emit = emit
        self._streak: Tuple[int, int] = (0, 0)
        self._last_up: Optional[float] = None
        self._last_down: Optional[float] = None
        self._last_hold_reason: Optional[str] = None

    # -- input ------------------------------------------------------------

    def _latest_point(self) -> Optional[Tuple[float, Dict[str, float]]]:
        """Latest aggregated fleet point from the TSDB, or None."""
        from progen_tpu.telemetry.collector import fleet_series

        if self.reader is None:
            return None
        samples = [
            rec for rec in self.reader.read()
            if rec.get("ev") == "sample"
        ]
        series = fleet_series(samples)
        return series[-1] if series else None

    # -- output -----------------------------------------------------------

    def _record(self, decision: Decision, now: float) -> None:
        """Edge-triggered scale records: every up/down, holds only on a
        reason change — the trace shows transitions, not steady state."""
        if decision.action == ACTION_HOLD:
            if decision.reason == self._last_hold_reason:
                return
            self._last_hold_reason = decision.reason
        else:
            self._last_hold_reason = None
        try:
            from progen_tpu import telemetry

            rec = {
                "ev": "scale", "ts": now,
                "action": decision.action,
                "reason": decision.reason,
                "current": int(decision.current),
                "target": int(decision.target),
            }
            for k, v in decision.signals.items():
                rec[k] = round(float(v), 6)
            telemetry.get_telemetry().emit(rec)
        except Exception:
            pass
        if self._emit is not None:
            self._emit(decision)

    # -- the tick ---------------------------------------------------------

    def decide(self, current: int,
               now: Optional[float] = None) -> Decision:
        """One policy tick against the TSDB's latest fleet point.
        Chaos site ``autoscaler/decide`` fires first — a transient
        fault here must cost one tick, never the fleet (the caller
        catches ChaosError and skips)."""
        maybe_inject("autoscaler/decide")
        now = self._clock() if now is None else now
        point = self._latest_point()
        signals: Optional[Dict[str, float]] = None
        age_s = float("inf")
        if point is not None:
            t, vals = point
            signals = extract_signals(vals)
            age_s = max(0.0, now - t)
        last_any = max(
            (t for t in (self._last_up, self._last_down)
             if t is not None),
            default=None,
        )
        decision, self._streak = evaluate_policy(
            self.policy, int(current), signals, age_s, self._streak,
            (float("inf") if self._last_up is None
             else now - self._last_up),
            (float("inf") if last_any is None else now - last_any),
        )
        if decision.action == ACTION_UP:
            self._last_up = now
        elif decision.action == ACTION_DOWN:
            self._last_down = now
        self._record(decision, now)
        return decision


def read_scale_records(path) -> List[dict]:
    """All ``ev:"scale"`` records in an events JSONL (what the CI
    smoke and tests assert against)."""
    from progen_tpu.telemetry.trace import iter_jsonl

    return [r for r in iter_jsonl(path) if r.get("ev") == "scale"]
