"""The deploy controller: one state machine, one action per tick.

Phases of a candidate checkpoint (all resumable via the ledger,
``deploy/ledger.py``):

  1. **observe** — the newest complete checkpoint that is not the
     fleet checkpoint and was never rolled back becomes the candidate;
     the ``observed`` record snapshots its digest and the fleet's live
     ttft p95 from the collector's TSDB (the latency baseline).
  2. **canary** (chaos site ``deploy/canary``) — write the candidate's
     name into the canary replica's ``reload.pin``; the replica's
     pinned-reload path (digest walk, tree-compat check, between-step
     ``commit_params``) answers through ``reload.pin.ack``. A rejected
     or timed-out pin rolls back; nothing else in the fleet has
     touched the new weights yet.
  3. **probe** (``deploy/probe``) — score the held-out probe FASTA
     with the batch scorer (``workloads/scoring.py``), resumable via
     its output-shard dedupe, into ``deploy_dir/probes/<ckpt>/``; the
     fleet checkpoint is probed the same way first, so the ppl
     baseline is owned and bit-reproducible, not scraped. Token-
     weighted ppl above ``max_ppl_regression_pct`` over baseline —
     or live ttft above ``max_ttft_regression_pct`` over the observed
     snapshot — rolls back.
  4. **promote** (``deploy/promote``) — pin the remaining replicas one
     at a time, each ``promote`` record appended after its pin write;
     the next replica is only pinned once the previous acked. The
     replica applies the swap between decode steps: no drain, no
     dropped requests, no recompiles.
  5. **converged** — every replica acked the candidate: it is the
     fleet checkpoint.

  * **rollback** (``deploy/rollback``) — any failure re-pins ALL
    replicas to the fleet checkpoint, appends a ``rollback`` record,
    and fires a ``deploy_rollback`` alert through the AlertSink
    (edge-dedup makes the webhook exactly-once even across controller
    restarts, which re-fire the alert from the replayed ledger).

A fresh ledger **adopts**: the newest verified checkpoint is declared
the fleet baseline and every replica pinned to it — start the
controller before publishing candidates, so no replica's newest-wins
watcher ever self-upgrades past the canary gate.

The ledger drives idempotence, the pin/ack files ground truth: a
restarted controller re-pins nothing already pinned (``Replica.pin``
is a no-op on equal content), never re-runs a completed probe, and
re-promotes only replicas whose ack is not yet on the candidate.
"""

from __future__ import annotations

import dataclasses
import glob
import json
import math
import os
import time
from pathlib import Path
from typing import Callable, Dict, List, Optional

from progen_tpu.deploy.ledger import (
    DeployLedger,
    LedgerState,
    fold,
    read_ledger,
    replay_state,
)
from progen_tpu.telemetry.spans import span
from progen_tpu.telemetry.trace import iter_jsonl

# the fleet-series key the ttft guard reads (collector.fleet_series)
TTFT_KEY = "ttft_s_p95_s"


@dataclasses.dataclass(frozen=True)
class DeployPolicy:
    """Deploy knobs; defaults are smoke-scale, not production."""

    interval_s: float = 2.0
    # canary replica name; "" = the first replica (sorted by name)
    canary: str = ""
    # candidate probe ppl may exceed baseline by at most this percent
    max_ppl_regression_pct: float = 1.0
    probe_batch_size: int = 8
    # conditioning tag prepended to probe sequences (FASTA grammar)
    probe_context: str = ""
    # live fleet ttft p95 may exceed the observed-time snapshot by at
    # most this percent while the canary serves (0 = guard off)
    max_ttft_regression_pct: float = 0.0
    # a canary/promote pin unanswered for this long rolls back — a
    # wedged replica must not stall the deploy pipeline forever
    ack_timeout_s: float = 120.0

    def __post_init__(self):
        if self.interval_s <= 0:
            raise ValueError("interval_s must be > 0")
        if self.ack_timeout_s <= 0:
            raise ValueError("ack_timeout_s must be > 0")
        if self.max_ppl_regression_pct < 0:
            raise ValueError("max_ppl_regression_pct must be >= 0")
        if self.max_ttft_regression_pct < 0:
            raise ValueError("max_ttft_regression_pct must be >= 0")
        if self.probe_batch_size < 1:
            raise ValueError("probe_batch_size must be >= 1")


def load_deploy_policy(path) -> DeployPolicy:
    """Flat ``[deploy]`` TOML table -> policy; unknown keys raise (a
    typo'd knob silently at its default is a canary gate that is not
    in force)."""
    from progen_tpu.config import load_toml_config

    raw = load_toml_config(str(path))
    table = raw.get("deploy", raw)
    if not isinstance(table, dict):
        raise ValueError(f"{path}: [deploy] is not a table")
    names = {f.name for f in dataclasses.fields(DeployPolicy)}
    unknown = set(table) - names
    if unknown:
        raise ValueError(
            f"{path}: unknown deploy key(s) {sorted(unknown)}"
        )
    return DeployPolicy(**table)


class Replica:
    """One replica's control seam: its ``reload.pin`` file (written
    here, honored by serve's ``--reload_pin`` poll) and the
    ``reload.pin.ack`` the replica answers through. The ack — not the
    ledger, not a prom scrape — is the authority on what a pin did."""

    def __init__(self, name: str, path):
        self.name = str(name)
        self.dir = Path(path)
        self.pin_path = self.dir / "reload.pin"
        self.ack_path = self.dir / "reload.pin.ack"

    def pinned(self) -> Optional[str]:
        try:
            content = self.pin_path.read_text().strip()
        except OSError:
            return None
        return content or None

    def pin(self, ckpt: str) -> bool:
        """Atomic pin write; a no-op (False) when already pinned to
        ``ckpt`` — the replay-idempotence seam."""
        if self.pinned() == ckpt:
            return False
        self.dir.mkdir(parents=True, exist_ok=True)
        tmp = self.pin_path.with_name(self.pin_path.name + ".tmp")
        with tmp.open("w") as f:
            f.write(ckpt + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.pin_path)
        return True

    def ack(self) -> Optional[dict]:
        try:
            return json.loads(self.ack_path.read_text())
        except (OSError, ValueError):
            return None

    def ack_for(self, ckpt: str) -> Optional[dict]:
        a = self.ack()
        return a if a is not None and a.get("pin") == ckpt else None

    def on(self, ckpt: str) -> bool:
        a = self.ack_for(ckpt)
        return bool(a and a.get("status") == "committed")

    def rejected(self, ckpt: str) -> Optional[str]:
        """The rejection reason when the replica rejected this pin."""
        a = self.ack_for(ckpt)
        if a and a.get("status") == "rejected":
            return str(a.get("reason", "rejected"))
        return None


def probe_stats(out_dir) -> dict:
    """Token-weighted perplexity over the scorer's output shards.
    Summation runs in sorted-id order over the deduped union, so the
    result is bit-identical no matter how many restarts (and fresh
    shards) the scoring took."""
    rows: Dict[str, dict] = {}
    pattern = os.path.join(str(out_dir), "scores-*.jsonl")
    for path in sorted(glob.glob(pattern)):
        for rec in iter_jsonl(path):
            if "id" in rec:
                rows[str(rec["id"])] = rec
    total_nll = 0.0
    total_tok = 0
    for rid in sorted(rows):
        rec = rows[rid]
        total_nll += float(rec["nll"]) * int(rec["n_tokens"])
        total_tok += int(rec["n_tokens"])
    ppl = math.exp(total_nll / total_tok) if total_tok else float("inf")
    return {"ppl": ppl, "n": len(rows), "tokens": total_tok}


class DeployController:
    """See module doc. ``tick()`` performs at most one action and
    returns its ledger op (or None when waiting/idle)."""

    def __init__(
        self,
        checkpoint_path,
        replicas: List[Replica],
        deploy_dir,
        policy: Optional[DeployPolicy] = None,
        *,
        probe_fasta: Optional[str] = None,
        reader=None,
        alerts=None,
        clock: Callable[[], float] = time.time,
    ):
        from progen_tpu.checkpoint import get_checkpoint_fns

        if not replicas:
            raise ValueError("deploy controller needs >= 1 replica")
        self.checkpoint_path = str(checkpoint_path)
        self.replicas = sorted(replicas, key=lambda r: r.name)
        self.policy = policy or DeployPolicy()
        self.deploy_dir = Path(deploy_dir)
        self.probe_fasta = probe_fasta
        self.reader = reader
        self.alerts = alerts
        self._clock = clock
        self._get_last = get_checkpoint_fns(self.checkpoint_path)[1]
        names = {r.name for r in self.replicas}
        if self.policy.canary and self.policy.canary not in names:
            raise ValueError(
                f"canary {self.policy.canary!r} not in replicas "
                f"{sorted(names)}"
            )
        self.canary = next(
            r for r in self.replicas
            if not self.policy.canary or r.name == self.policy.canary
        )
        self.state: LedgerState = replay_state(
            read_ledger(self.deploy_dir / "deploy.jsonl")
        )
        self.ledger = DeployLedger(self.deploy_dir / "deploy.jsonl")
        # replay re-fires rollback alerts: the sink's edge-dedup
        # suppresses any already delivered, so the webhook stays
        # exactly-once while a kill between ledger append and alert
        # emit still cannot lose the page
        if self.alerts is not None:
            for rec in self.state.rollbacks:
                self.alerts.deploy_rollback(
                    rec.get("ckpt", ""), rec.get("reason", "")
                )

    def close(self) -> None:
        self.ledger.close()

    # -- ledger -----------------------------------------------------------

    def _append(self, op: str, ckpt: str, **fields) -> dict:
        rec = self.ledger.append(
            op, ckpt, ts=self._clock(), **fields
        )
        fold(self.state, rec)
        return rec

    # -- inputs -----------------------------------------------------------

    def _newest_complete(self) -> Optional[str]:
        from progen_tpu.checkpoint import _CKPT_NAME_RE

        root = Path(self.checkpoint_path)
        try:
            names = sorted(
                p.name for p in root.iterdir()
                if _CKPT_NAME_RE.fullmatch(p.name)
                and (p / "meta.json").exists()
            )
        except OSError:
            return None
        return names[-1] if names else None

    def _digest(self, ckpt: str) -> Optional[str]:
        from progen_tpu.checkpoint import checkpoint_digest

        return checkpoint_digest(
            os.path.join(self.checkpoint_path, ckpt)
        )

    def _fleet_ttft(self) -> Optional[float]:
        """Latest fleet ttft p95 from the collector's TSDB, or None."""
        if self.reader is None:
            return None
        from progen_tpu.telemetry.collector import fleet_series

        samples = [
            rec for rec in self.reader.read()
            if rec.get("ev") == "sample"
        ]
        series = fleet_series(samples)
        if not series:
            return None
        value = series[-1][1].get(TTFT_KEY)
        return None if value is None else float(value)

    # -- the tick ---------------------------------------------------------

    def tick(self) -> Optional[str]:
        """One action per call: observe/canary/probe/promote/rollback/
        converged, or None while waiting (acks) or idle."""
        if self.state.fleet is None:
            return self._adopt()
        newest = self._newest_complete()
        if (
            newest is not None
            and newest != self.state.fleet
            and newest not in self.state.failed
            and newest != self.state.candidate
            and newest > (self.state.candidate or "")
        ):
            return self._observe(newest)
        if self.state.candidate is None:
            self._enforce_fleet_pins()
            return None
        return self._advance(self.state.candidate)

    def _adopt(self) -> Optional[str]:
        """Fresh ledger: the newest verified checkpoint IS the fleet
        baseline — pin everyone to it before any candidate can be
        observed, so no replica's newest-wins watcher outruns the
        canary gate."""
        pkg = self._get_last.peek()
        if pkg is None:
            return None
        ckpt = Path(pkg.path).name
        for replica in self.replicas:
            replica.pin(ckpt)
        self._append("observed", ckpt, digest=self._digest(ckpt),
                     adopted=True)
        self._append("converged", ckpt, digest=self._digest(ckpt),
                     adopted=True)
        return "converged"

    def _observe(self, ckpt: str) -> str:
        fields = {"digest": self._digest(ckpt)}
        ttft = self._fleet_ttft()
        if ttft is not None:
            fields["baseline_ttft_p95_s"] = round(ttft, 6)
        self._append("observed", ckpt, **fields)
        return "observed"

    def _advance(self, cand: str) -> Optional[str]:
        now = self._clock()
        # -- canary ---------------------------------------------------
        if cand not in self.state.canaried:
            with span("deploy/canary", ckpt=cand):
                self.canary.pin(cand)
                self._append("canary", cand, replica=self.canary.name)
            return "canary"
        reason = self.canary.rejected(cand)
        if reason is not None:
            return self._rollback(cand, f"canary_rejected:{reason}")
        if not self.canary.on(cand):
            armed = float(self.state.canaried[cand].get("ts", now))
            if now - armed > self.policy.ack_timeout_s:
                return self._rollback(cand, "canary_timeout")
            return None  # waiting on the canary's ack
        # -- probe + verdict ------------------------------------------
        if self.probe_fasta is not None:
            baseline = self.state.probes.get(self.state.fleet)
            if baseline is None:
                stats = self._probe(self.state.fleet)
                self._append("probe", self.state.fleet, **stats)
                return "probe"
            if cand not in self.state.probes:
                try:
                    stats = self._probe(cand)
                except Exception as exc:
                    return self._rollback(
                        cand, f"probe_failed:{type(exc).__name__}"
                    )
                self._append("probe", cand, **stats)
                return "probe"
            verdict = self._verdict(cand)
            if verdict is not None:
                return self._rollback(cand, verdict)
        # -- promote (rolling, one replica per tick) ------------------
        told = self.state.promoted.get(cand, {})
        for replica in self.replicas:
            if replica is self.canary or replica.on(cand):
                continue
            reason = replica.rejected(cand)
            if reason is not None:
                return self._rollback(
                    cand, f"promote_rejected:{replica.name}:{reason}"
                )
            rec = told.get(replica.name)
            if rec is None:
                with span("deploy/promote", ckpt=cand,
                          replica=replica.name):
                    replica.pin(cand)
                    self._append("promote", cand, replica=replica.name)
                return "promote"
            if now - float(rec.get("ts", now)) > \
                    self.policy.ack_timeout_s:
                return self._rollback(
                    cand, f"promote_timeout:{replica.name}"
                )
            return None  # waiting on this replica's ack
        # -- converged ------------------------------------------------
        self._append("converged", cand, digest=self._digest(cand))
        return "converged"

    def _verdict(self, cand: str) -> Optional[str]:
        """Rollback reason, or None when the candidate passes."""
        base = self.state.probes[self.state.fleet]
        trial = self.state.probes[cand]
        limit = float(base["ppl"]) * (
            1.0 + self.policy.max_ppl_regression_pct / 100.0
        )
        if float(trial["ppl"]) > limit:
            return (
                f"ppl_regression:{float(trial['ppl']):.6g}"
                f">{limit:.6g}"
            )
        if self.policy.max_ttft_regression_pct > 0:
            snap = self.state.observed.get(cand, {}).get(
                "baseline_ttft_p95_s"
            )
            live = self._fleet_ttft()
            if snap is not None and live is not None:
                lim = float(snap) * (
                    1.0 + self.policy.max_ttft_regression_pct / 100.0
                )
                if live > lim:
                    return f"ttft_regression:{live:.6g}>{lim:.6g}"
        return None

    def _probe(self, ckpt: str) -> dict:
        """Score the probe FASTA on ``ckpt`` into its own output dir.
        Resumable: a controller killed mid-probe re-enters here and the
        scorer's shard dedupe skips everything durably scored."""
        from progen_tpu.config import ProGenConfig
        from progen_tpu.models.progen import ProGen
        from progen_tpu.workloads import fasta_records, run_batch_score

        with span("deploy/probe", ckpt=ckpt):
            pkg = self._get_last.restore_params(at=ckpt)
            if pkg is None:
                raise RuntimeError(f"checkpoint {ckpt} not restorable")
            model = ProGen(ProGenConfig.from_dict(pkg.model_config))
            out_dir = str(self.deploy_dir / "probes" / ckpt)
            run_batch_score(
                model, pkg.state,
                fasta_records(
                    self.probe_fasta, self.policy.probe_context
                ),
                out_dir,
                batch_size=self.policy.probe_batch_size,
                logprobs=False, resume=True,
            )
            return probe_stats(out_dir)

    def _rollback(self, cand: str, reason: str) -> str:
        with span("deploy/rollback", ckpt=cand):
            for replica in self.replicas:
                replica.pin(self.state.fleet)
            self._append(
                "rollback", cand, to=self.state.fleet, reason=reason
            )
        if self.alerts is not None:
            self.alerts.deploy_rollback(cand, reason)
        return "rollback"

    def _enforce_fleet_pins(self) -> None:
        """Idle safety net: with no candidate in flight every replica
        belongs on the fleet checkpoint — re-assert the pins (no-op
        writes when already there), which also completes a rollback a
        SIGKILL interrupted between pin writes."""
        if self.state.fleet is None:
            return
        for replica in self.replicas:
            replica.pin(self.state.fleet)
