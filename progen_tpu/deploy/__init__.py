"""Continuous deployment: canary, promote, rollback — zero drops.

The train-to-serve loop closer (``progen-tpu-deploy``): watch the
checkpoint dir the trainer writes, canary each new checkpoint on ONE
replica through the digest-verify + pinned-reload chain, score a
held-out probe set on it with the batch scorer, compare against the
fleet baseline (own probe of the fleet checkpoint, plus live ttft from
the collector's TSDB), then promote replica-by-replica or roll back.
Every decision is a fsync'd ``ev:"deploy"`` ledger record the
controller replays on start — SIGKILL at any phase resumes
idempotently. See ``deploy/controller.py``.
"""

from progen_tpu.deploy.controller import (
    DeployController,
    DeployPolicy,
    Replica,
    load_deploy_policy,
    probe_stats,
)
from progen_tpu.deploy.ledger import (
    DEPLOY_OPS,
    DeployLedger,
    LedgerState,
    fold,
    read_ledger,
    replay_state,
)

__all__ = [
    "DEPLOY_OPS",
    "DeployController",
    "DeployLedger",
    "DeployPolicy",
    "LedgerState",
    "fold",
    "Replica",
    "load_deploy_policy",
    "probe_stats",
    "read_ledger",
    "replay_state",
]
