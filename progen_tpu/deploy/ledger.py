"""The deploy ledger: fsync'd ``ev:"deploy"`` records + replay.

The controller's ONLY durable state is ``deploy.jsonl`` — one JSON
line per decision, fsync'd before the call returns, so a SIGKILL at
any phase loses at most the action it had not yet recorded (and every
action is idempotent, so re-running it is safe). ``ev:"deploy"``
records are built only here (PGL006 owns the grammar), op from:

  * ``observed``  — a new complete checkpoint appeared (the record
    carries its digest and a TSDB latency snapshot as the live
    baseline);
  * ``canary``    — the canary replica was pinned to it;
  * ``probe``     — a probe-set scoring completed (pure measurement:
    token-weighted ppl, counts — the verdict lives in what follows);
  * ``promote``   — one non-canary replica was pinned to it (rolling:
    one record per replica);
  * ``rollback``  — the candidate was reverted; every replica re-pinned
    to the fleet checkpoint; the candidate is never retried;
  * ``converged`` — every replica acked the checkpoint: it IS the
    fleet checkpoint now.

``replay_state`` folds a ledger back into the controller's working
state: the fleet checkpoint is the last ``converged``, the candidate
is the last ``observed`` not yet converged or rolled back, completed
probes are never re-run, and per-replica ``promote`` records say who
was already told — a restarted controller re-pins nothing already
pinned and resumes mid-pipeline.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Set

from progen_tpu.telemetry.spans import get_telemetry
from progen_tpu.telemetry.trace import iter_jsonl

DEPLOY_OPS = (
    "observed", "canary", "probe", "promote", "rollback", "converged"
)


class DeployLedger:
    """Append-only fsync'd JSONL writer; every record is mirrored to
    the telemetry sink so a tracker sees deploy decisions alongside
    everything else."""

    def __init__(self, path):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._f = open(self.path, "a", encoding="utf-8")

    def append(self, op: str, ckpt: str, **fields) -> dict:
        if op not in DEPLOY_OPS:
            raise ValueError(f"unknown deploy op {op!r}")
        rec = {
            "ev": "deploy",
            "ts": float(fields.pop("ts", None) or time.time()),
            "op": op,
            "ckpt": str(ckpt),
            **fields,
        }
        self._f.write(json.dumps(rec) + "\n")
        self._f.flush()
        os.fsync(self._f.fileno())
        get_telemetry().emit(rec)
        return rec

    def close(self) -> None:
        self._f.close()


def read_ledger(path) -> List[dict]:
    """All ``ev:"deploy"`` records, oldest first (torn tail skipped)."""
    p = Path(path)
    if not p.exists():
        return []
    return [r for r in iter_jsonl(p) if r.get("ev") == "deploy"]


@dataclasses.dataclass
class LedgerState:
    """The controller's working state, foldable from the ledger."""

    fleet: Optional[str] = None  # last converged checkpoint name
    fleet_digest: Optional[str] = None
    candidate: Optional[str] = None  # observed, not yet settled
    canaried: Dict[str, dict] = dataclasses.field(default_factory=dict)
    probes: Dict[str, dict] = dataclasses.field(default_factory=dict)
    # ckpt -> {replica name: promote record} (who was already told)
    promoted: Dict[str, Dict[str, dict]] = dataclasses.field(
        default_factory=dict
    )
    failed: Set[str] = dataclasses.field(default_factory=set)
    observed: Dict[str, dict] = dataclasses.field(default_factory=dict)
    rollbacks: List[dict] = dataclasses.field(default_factory=list)


def fold(st: LedgerState, rec: dict) -> LedgerState:
    """Apply ONE ledger record to the state — shared by the startup
    replay and the controller's live appends, so a restarted controller
    reconstructs exactly the state a surviving one would hold."""
    op = rec.get("op")
    ckpt = str(rec.get("ckpt", ""))
    if op == "observed":
        st.observed[ckpt] = rec
        if ckpt not in st.failed and ckpt != st.fleet:
            st.candidate = ckpt
    elif op == "canary":
        st.canaried[ckpt] = rec
    elif op == "probe":
        st.probes[ckpt] = rec
    elif op == "promote":
        st.promoted.setdefault(ckpt, {})[
            str(rec.get("replica", ""))
        ] = rec
    elif op == "rollback":
        st.failed.add(ckpt)
        st.rollbacks.append(rec)
        if st.candidate == ckpt:
            st.candidate = None
    elif op == "converged":
        st.fleet = ckpt
        st.fleet_digest = rec.get("digest")
        if st.candidate == ckpt:
            st.candidate = None
    return st


def replay_state(records: Iterable[dict]) -> LedgerState:
    """Fold ledger records (oldest first) into a :class:`LedgerState`.
    Pure — the controller applies it, then re-verifies against the
    live pin/ack files before acting (the files, not the ledger, are
    the authority on what each replica is actually serving)."""
    st = LedgerState()
    for rec in records:
        fold(st, rec)
    return st
