"""Experiment tracking: wandb-compatible interface, local-first backends.

Capability parity (/root/reference/train.py:24-28,135-150,193,211,222):
``init`` with resume-by-run-id, scalar logging (loss / valid_loss), config
attachment (num_params), HTML-rendered samples via a Jinja2 template, and a
disabled mode (``--wandb_off`` -> ``mode='disabled'``, train.py:143).

Backends:
  * ``WandbTracker`` — used when the wandb package exists (it is not in this
    image; the class stays import-guarded);
  * ``JsonlTracker`` — default: metrics appended as JSON lines under
    ``{dir}/{run_id}/metrics.jsonl``, HTML artifacts as files; greppable and
    sufficient for loss-curve comparison against the reference;
  * ``NoopTracker`` — the reference's disabled mode.

Only process 0 should construct a real tracker (partition.is_coordinator);
`make_tracker` enforces that itself.
"""

from __future__ import annotations

import json
import threading
import time
import uuid
from pathlib import Path
from typing import Optional

from progen_tpu.parallel.partition import is_coordinator

try:  # template parity with train.py:28; fallback keeps jinja2 optional
    from jinja2 import Template

    _SAMPLE_TMPL = Template(
        "<i>{{prime_str}}</i><br/><br/>"
        '<div style="overflow-wrap: break-word;">{{sampled_str}}</div>'
    )

    def render_sample_html(prime_str: str, sampled_str: str) -> str:
        return _SAMPLE_TMPL.render(
            prime_str=prime_str, sampled_str=sampled_str
        )

except ImportError:  # pragma: no cover

    def render_sample_html(prime_str: str, sampled_str: str) -> str:
        return (
            f"<i>{prime_str}</i><br/><br/>"
            f'<div style="overflow-wrap: break-word;">{sampled_str}</div>'
        )


class NoopTracker:
    run_id: Optional[str] = None

    def log(self, metrics: dict, step: Optional[int] = None) -> None:
        pass

    def log_event(self, record: dict) -> None:
        pass

    def log_html(self, name: str, html: str, step: Optional[int] = None) -> None:
        pass

    def set_config(self, config: dict) -> None:
        pass

    def finish(self) -> None:
        pass


class JsonlTracker(NoopTracker):
    def __init__(self, project: str, run_id: Optional[str], dir: str):
        self.run_id = run_id or uuid.uuid4().hex[:8]
        self.path = Path(dir) / project / self.run_id
        self.path.mkdir(parents=True, exist_ok=True)
        self._metrics = (self.path / "metrics.jsonl").open("a")
        self._events = None  # opened on first span; most runs have none
        # the watchdog thread, async-checkpoint paths, and retry hooks
        # all emit through log_event concurrently with the train loop's
        # log(); the lock makes every write+flush one critical section
        # so JSONL lines can never tear or interleave. REENTRANT: the
        # serve CLI's second-signal handler logs through this same
        # tracker and a signal can land while the main thread holds the
        # lock mid-write — a plain Lock would deadlock the exit path. A
        # reentrant write can interleave into the interrupted line, but
        # iter_jsonl skips (and counts) torn lines by contract.
        self._lock = threading.RLock()

    def log(self, metrics: dict, step: Optional[int] = None) -> None:
        rec = {"_time": time.time(), **metrics}
        if step is not None:
            rec["_step"] = step
        with self._lock:
            if self._metrics.closed:
                raise ValueError("tracker is finished")
            self._metrics.write(json.dumps(rec) + "\n")
            self._metrics.flush()

    def log_event(self, record: dict) -> None:
        """Span/watchdog records -> events.jsonl beside metrics.jsonl,
        same crash-safety discipline (flush per line). Raises ValueError
        after ``finish()`` — telemetry sinks treat that as detach."""
        with self._lock:
            if self._events is None:
                if self._metrics.closed:
                    raise ValueError("tracker is finished")
                self._events = (self.path / "events.jsonl").open("a")
            self._events.write(json.dumps(record) + "\n")
            self._events.flush()

    def log_html(self, name: str, html: str, step: Optional[int] = None) -> None:
        suffix = f"_{step}" if step is not None else ""
        (self.path / f"{name}{suffix}.html").write_text(html)

    def set_config(self, config: dict) -> None:
        (self.path / "config.json").write_text(json.dumps(config, default=str))

    def finish(self) -> None:
        with self._lock:
            self._metrics.close()
            if self._events is not None:
                self._events.close()


class WandbTracker(NoopTracker):  # exercised via a mock module in-suite
    def __init__(self, project: str, run_id: Optional[str]):
        import wandb

        self._wandb = wandb
        self._run = wandb.init(
            project=project,
            id=run_id,
            resume="allow" if run_id else None,
        )
        self.run_id = self._run.id

    def log(self, metrics: dict, step: Optional[int] = None) -> None:
        self._wandb.log(metrics, step=step)

    def log_html(self, name: str, html: str, step: Optional[int] = None) -> None:
        self._wandb.log({name: self._wandb.Html(html)}, step=step)

    def set_config(self, config: dict) -> None:
        self._run.config.update(config, allow_val_change=True)

    def finish(self) -> None:
        self._run.finish()


def make_tracker(
    project: str,
    run_id: Optional[str] = None,
    *,
    disabled: bool = False,
    dir: str = "./runs",
) -> NoopTracker:
    """Tracker factory. Disabled, or on any process but 0 -> Noop
    (reference logs from its single process; multi-host must gate)."""
    if disabled or not is_coordinator():
        return NoopTracker()
    try:
        import wandb  # noqa: F401

        return WandbTracker(project, run_id)
    except ImportError:
        return JsonlTracker(project, run_id, dir)
