"""Token shift: half the feature channels are delayed one position.

Reference: /root/reference/progen_transformer/progen.py:43-46 — split features
in half, shift the first half one step along the sequence (pad front, drop
last), re-concatenate. Batch-first here: operates on (..., n, d).
"""

from __future__ import annotations

import jax.numpy as jnp


def shift_tokens(x: jnp.ndarray, shift_state: jnp.ndarray | None = None):
    """x: (..., n, d). Returns same shape.

    If `shift_state` is given (shape (..., 1, d//2 rounded like array_split)),
    it is used as the value shifted into position 0 instead of zeros — the
    hook incremental decoding uses to carry the previous token's features.
    """
    # np.array_split(x, 2) puts the extra column in the first half for odd d.
    d = x.shape[-1]
    split = d - d // 2
    x_shift, x_pass = x[..., :split], x[..., split:]
    if shift_state is None:
        shift_state = jnp.zeros_like(x_shift[..., :1, :])
    x_shift = jnp.concatenate((shift_state, x_shift[..., :-1, :]), axis=-2)
    return jnp.concatenate((x_shift, x_pass), axis=-1)
